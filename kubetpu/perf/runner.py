"""scheduler_perf runner — drive the REAL scheduler loop through an op list.

The reference harness (test/integration/scheduler_perf/scheduler_perf.go:756)
boots apiserver+etcd+scheduler in one process, feeds API objects, and
measures SchedulingThroughput at bind time. Here the same op lists drive the
full kubetpu ``Scheduler`` — queue (backoff/hints), cache/snapshot, encode,
device greedy scan, async dispatcher — through its informer seam; no HTTP
hop, same semantics.

Threading note: the Scheduler is single-owner (informer callbacks + loop on
one thread), so churn is injected *synchronously* between cycles on the
loop thread, clocked by elapsed wall time against the op's
``intervalMilliseconds`` — equivalent to the reference's churn goroutine
observed at cycle boundaries.

Throughput definition: measured-phase scheduled pods / measured-phase wall
seconds — the average the reference's threshold selector asserts on
(scheduler_perf.go:352-359 "SchedulingThroughput / Average"; collector
util.go:468 samples scheduled-pod deltas every second and averages).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from ..api import types as t
from ..framework import config as C
from ..metrics.scheduler_metrics import window_quantile_ms
from ..sched.scheduler import Scheduler
from . import workloads as W


def round_latency_ms(v: float | None) -> float | None:
    """THE latency rounding for bench artifacts (2 decimals) — one place,
    used by WorkloadResult.to_json AND bench.py's stage lines, so a
    benchdiff between a runner emission and a bench emission never sees
    phantom rounding deltas."""
    return None if v is None else round(float(v), 2)


def measured_p99_ms(sched: "Scheduler", prom_base: dict | None) -> float | None:
    """p99 of pod_scheduling_sli_duration_seconds in MILLISECONDS, scoped
    to the measured window (the ``_begin_measured_phase`` baseline): a
    large init phase must not dominate the reported p99s. Shared by both
    run modes; the staged percentiles apply the same scoping per stage."""
    if prom_base is None:
        return None
    return window_quantile_ms(
        sched.metrics.prom.pod_scheduling_sli_duration,
        prom_base.get("sli_duration"),
        0.99,
    )


@dataclass
class WorkloadResult:
    case_name: str
    workload_name: str
    threshold: float | None
    measure_pods: int
    scheduled: int
    duration_s: float
    throughput: float                 # pods/s, the SchedulingThroughput avg
    vs_threshold: float | None        # throughput / threshold
    attempts: int
    cycles: int
    p99_attempt_latency_ms: float | None = None
    threshold_note: str = ""          # derivation of a scaled threshold
    # device-traffic view of the measured phase (from the per-cycle TPU
    # records): cycle rate, ACTUAL host→device bytes per cycle vs what a
    # residency-less encode would have shipped, resident-state size, and
    # how many pipelined cycles were replayed for parity
    cycles_per_sec: float | None = None
    transfer_bytes_per_cycle: float | None = None
    batch_bytes_per_cycle: float | None = None
    resident_bytes: int = 0
    compile_misses: int = 0
    pipeline_replays: int = 0
    # host-encode view of the measured phase: encode-span wall per cycle,
    # its share of the scheduling-cycle wall (the r05 trace showed 86% —
    # the tentpole's target is ≤ 40%), and the encode-cache hit rate
    encode_ms_per_cycle: float | None = None
    encode_wall_frac: float | None = None
    encode_cache_hit_rate: float | None = None
    # API-plane view of the measured phase (fullstack only for rpcs): HTTP
    # round trips per scheduled pod — the tentpole's acceptance metric —
    # plus the dispatcher's mean bulk micro-batch size and error count
    rpcs_per_scheduled_pod: float | None = None
    dispatcher_batch_mean: float | None = None
    dispatcher_errors: int = 0
    # mesh-sharded assignment (parallel.mesh): device count + mesh shape the
    # run was sharded over ((), 1 = single device) and the cross-shard
    # reduction probe — MULTICHIP records must carry their own context
    n_devices: int = 1
    mesh_shape: tuple = ()
    collective_wall_s: float | None = None
    # post-run metric snapshot (SchedulerMetricsRegistry.snapshot): p50/p99
    # from the histograms + schedule_attempts by result — every BENCH json
    # carries its own diagnosis
    metrics_snapshot: dict | None = None
    # per-pod staged latency attribution, measured-window scoped
    # (sched.flightrecorder → scheduler_e2e_scheduling_duration_seconds):
    # {stage: {"p50": ms, "p99": ms}} for queue_wait/encode/kernel/
    # dispatch/bind_rtt/e2e (+ api_ingest/informer through the full stack)
    staged_latency_ms: dict | None = None
    # SustainedChurn soak gate: p99 e2e of the measured window's first vs
    # second half + the flatness verdict (ROADMAP item 2's "p99 flat for
    # minutes" evidence)
    soak: dict | None = None
    # flight recorder + per-pod tracing state for this run (the <5%
    # overhead budget's on/off comparison key)
    flight_recorder: bool = True
    # wire-protocol view of the measured phase (fullstack only): the codec
    # request bodies actually NEGOTIATED to ("binary" means the server
    # confirmed the dialect — a fallback shows up as "json" here, not as a
    # silently slow run), apiserver payload bytes per scheduled pod, and
    # how many extra concurrent watchers hammered the fan-out path
    wire_codec: str = ""
    wire_bytes_per_pod: float | None = None
    watch_fanout: int = 0
    # active-active federation (sched.federation; --replicas N
    # --partition hash|race|lease): replica count, partition mode, total
    # CAS-bind conflicts + conflict rate (conflicted attempts / all bind
    # attempts), binding_parity (store-verified pods bound exactly once —
    # must equal measure_pods for a lossless run), lease transitions, and
    # the replica-kill recovery time (kill → survivors re-absorbed the
    # dead replica's partition and every pod bound)
    replicas: int = 1
    partition: str = ""
    conflicts: int = 0
    conflict_rate: float | None = None
    binding_parity: int | None = None
    lease_transitions: int = 0
    recovery_s: float | None = None
    # telemetry-plane view when a run exported to a collector
    # (--telemetry): ingested span totals and the drop counter the
    # TelemetryOverhead gate asserts stayed zero
    telemetry: dict | None = None
    # anomaly-sentinel view when a run rode the sentinel (--sentinel):
    # lifecycle stats (evaluations/fired/bundles), the per-alert final
    # states, clean (nothing fired — the false-positive gate), and in
    # spike mode the injected-stall fire→bundle→resolve verdict
    sentinel: dict | None = None
    # multi-process deployment view (run_workload_multiprocess): how many
    # REAL OS processes carried the run (apiserver + schedulers +
    # collector + watch drivers), each child's peak RSS / CPU seconds /
    # restart count from the supervisor's /proc sampling, and how many
    # supervisor respawns fired mid-run — 0 processes = in-process mode
    n_processes: int = 0
    child_stats: dict | None = None
    restarts: int = 0
    # replicated read plane (run_workload_multiprocess with
    # ``apiservers`` > 1): how many apiservers carried the run (1 leader
    # + N-1 followers; the watch fan-out load round-robins over the
    # followers) and the PEAK follower replication lag sampled over the
    # measured window — the read plane's honesty counter: a follower may
    # serve a slightly old rv, never a wrong one, and this is how old
    # "slightly" got under load
    apiservers: int = 1
    follower_lag_ms: float | None = None
    follower_lag_records: int | None = None
    # chained replication shipping (``--replication-chain``): follower i
    # tails follower i-1 instead of the leader, so the leader's egress is
    # ONE follower's worth regardless of fan-out — the rung records the
    # topology it ran and the leader's apiserver_replication_bytes_total
    # over the run (the egress claim's evidence)
    replication_chain: bool = False
    leader_replication_bytes: float | None = None
    # --- trace-shaped workloads (run_workload_trace) ---------------------
    # admission-latency SLO: p50/p99 of enqueue→bind over every pod the
    # trace created, judged against the profile's declared budget — the
    # scale-frontier metric benchdiff gates (slo_ok = p99 <= budget)
    admission_p50_ms: float | None = None
    admission_p99_ms: float | None = None
    slo_budget_ms: float | None = None
    slo_ok: bool | None = None
    # host-memory ceiling of the stage: max RSS sampled per cycle during
    # the measured window (benchdiff gates +50% AND >256MB absolute)
    peak_rss_bytes: int = 0
    # the stage hit its wall budget and emitted a TRUNCATED-but-parseable
    # record instead of eating the whole bench wall (the 100k-node rungs)
    truncated: bool = False
    # trace bookkeeping: events replayed / pods created / deleted by the
    # trace / still unbound at the end / node count when it finished, and
    # the encode-cache re-encode accounting (scoped-invalidation evidence)
    trace_stats: dict | None = None
    # --- packing frontier (PR 19) ----------------------------------------
    # utilization-vs-throughput evidence, engine-agnostic so the three-way
    # PackingComparison ladder reads the same keys from every rung:
    # distinct nodes carrying the measured pods once the run settled, the
    # fraction of high-priority (priority > 0) measured pods that actually
    # bound, and — packing cycles only — the warm-started solver's mean
    # projection-loop iterations per measured cycle + the weight tensor
    # that produced the frontier (reproducible from the JSON alone)
    nodes_used_at_steady_state: int | None = None
    priority_slo_hit_rate: float | None = None
    solver_iters_per_cycle: float | None = None
    packing_weights: dict | None = None
    # --- node-topology axis (PR 20) --------------------------------------
    # slice-level fragmentation evidence on labeled fleets: the topology
    # mode the run used, total labeled TPU slices, how many were FULLY
    # free when the trace settled (benchdiff gates a drop), the fraction
    # of labeled slices left partially occupied (0 = perfectly defragged,
    # benchdiff gates drift), and the p99 quorum→admitted gang latency
    # from scheduler_gang_admission_duration_seconds
    topology: str = "off"
    slices_total: int | None = None
    slices_free_at_steady_state: int | None = None
    fragmentation_index: float | None = None
    gang_admission_p99_ms: float | None = None
    # artifact paths written next to the bench JSON when tracing is on:
    # chrome trace, /metrics text, device-side cycle records
    artifacts: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        out = {
            "case": self.case_name,
            "workload": self.workload_name,
            "metric": "SchedulingThroughput/Average",
            "value": round(self.throughput, 1),
            "unit": "pods/s",
            "scheduled": self.scheduled,
            "measure_pods": self.measure_pods,
            "duration_s": round(self.duration_s, 3),
            "attempts": self.attempts,
            "cycles": self.cycles,
        }
        if self.threshold is not None:
            out["threshold"] = self.threshold
            out["vs_baseline"] = round(self.vs_threshold, 2)
        if self.threshold_note:
            out["threshold_note"] = self.threshold_note
        if self.p99_attempt_latency_ms is not None:
            out["p99_attempt_latency_ms"] = round_latency_ms(
                self.p99_attempt_latency_ms
            )
        if self.cycles_per_sec is not None:
            out["cycles_per_sec"] = round(self.cycles_per_sec, 2)
        if self.transfer_bytes_per_cycle is not None:
            out["transfer_bytes_per_cycle"] = round(self.transfer_bytes_per_cycle)
        if self.batch_bytes_per_cycle is not None:
            out["batch_bytes_per_cycle"] = round(self.batch_bytes_per_cycle)
        if self.resident_bytes:
            out["resident_bytes"] = self.resident_bytes
        if self.pipeline_replays:
            out["pipeline_replays"] = self.pipeline_replays
        if self.encode_ms_per_cycle is not None:
            out["encode_ms_per_cycle"] = round(self.encode_ms_per_cycle, 2)
        if self.encode_wall_frac is not None:
            out["encode_wall_frac"] = round(self.encode_wall_frac, 3)
        if self.encode_cache_hit_rate is not None:
            out["encode_cache_hit_rate"] = round(self.encode_cache_hit_rate, 4)
        if self.rpcs_per_scheduled_pod is not None:
            out["rpcs_per_scheduled_pod"] = round(self.rpcs_per_scheduled_pod, 4)
        if self.dispatcher_batch_mean is not None:
            out["dispatcher_batch_mean"] = round(self.dispatcher_batch_mean, 1)
        if self.dispatcher_errors:
            out["dispatcher_errors"] = self.dispatcher_errors
        if self.mesh_shape:
            out["n_devices"] = self.n_devices
            out["mesh_shape"] = list(self.mesh_shape)
            if self.collective_wall_s is not None:
                out["collective_wall_s"] = round(self.collective_wall_s, 6)
        if self.staged_latency_ms is not None:
            out["staged_latency_ms"] = self.staged_latency_ms
        if self.soak is not None:
            out["soak"] = self.soak
        if not self.flight_recorder:
            out["flight_recorder"] = False
        if self.wire_codec:
            out["wire_codec"] = self.wire_codec
        if self.wire_bytes_per_pod is not None:
            out["wire_bytes_per_pod"] = round(self.wire_bytes_per_pod, 1)
        if self.watch_fanout:
            out["watch_fanout"] = self.watch_fanout
        if self.replicas > 1 or self.partition:
            out["replicas"] = self.replicas
            out["partition"] = self.partition
            out["conflicts"] = self.conflicts
            if self.conflict_rate is not None:
                out["conflict_rate"] = round(self.conflict_rate, 4)
            if self.binding_parity is not None:
                out["binding_parity"] = self.binding_parity
            if self.lease_transitions:
                out["lease_transitions"] = self.lease_transitions
            if self.recovery_s is not None:
                out["recovery_s"] = round(self.recovery_s, 3)
        if self.admission_p99_ms is not None:
            out["admission_p99_ms"] = round_latency_ms(self.admission_p99_ms)
            if self.admission_p50_ms is not None:
                out["admission_p50_ms"] = round_latency_ms(
                    self.admission_p50_ms
                )
        if self.slo_budget_ms is not None:
            out["slo_budget_ms"] = self.slo_budget_ms
            out["slo_ok"] = self.slo_ok
        if self.peak_rss_bytes:
            out["peak_rss_bytes"] = self.peak_rss_bytes
        if self.truncated:
            out["truncated"] = True
        if self.trace_stats is not None:
            out["trace"] = self.trace_stats
        if self.telemetry is not None:
            out["telemetry"] = self.telemetry
        if self.sentinel is not None:
            out["sentinel"] = self.sentinel
        if self.n_processes:
            out["n_processes"] = self.n_processes
            out["restarts"] = self.restarts
            if self.child_stats is not None:
                out["child_stats"] = self.child_stats
        if self.nodes_used_at_steady_state is not None:
            out["nodes_used_at_steady_state"] = self.nodes_used_at_steady_state
        if self.priority_slo_hit_rate is not None:
            out["priority_slo_hit_rate"] = round(self.priority_slo_hit_rate, 4)
        if self.solver_iters_per_cycle is not None:
            out["solver_iters_per_cycle"] = round(self.solver_iters_per_cycle, 2)
        if self.packing_weights is not None:
            out["packing_weights"] = self.packing_weights
        if self.topology and self.topology != "off":
            out["topology"] = self.topology
        if self.slices_total is not None:
            out["slices_total"] = self.slices_total
        if self.slices_free_at_steady_state is not None:
            out["slices_free_at_steady_state"] = (
                self.slices_free_at_steady_state
            )
        if self.fragmentation_index is not None:
            out["fragmentation_index"] = round(self.fragmentation_index, 4)
        if self.gang_admission_p99_ms is not None:
            out["gang_admission_p99_ms"] = round_latency_ms(
                self.gang_admission_p99_ms
            )
        if self.metrics_snapshot is not None:
            out["metrics"] = self.metrics_snapshot
        if self.artifacts:
            out["artifacts"] = self.artifacts
        return out


def dump_diagnosis_artifacts(
    sched: "Scheduler", artifacts_dir: str, prefix: str
) -> dict[str, str]:
    """Write the run's diagnosis artifacts next to the bench JSON: the
    cycle trace as Perfetto-loadable Chrome-trace JSON, a /metrics text
    snapshot, and the device-side per-cycle counter records (joined to the
    trace spans by cycle id). Returns {artifact: path}."""
    import json as _json
    import os

    os.makedirs(artifacts_dir, exist_ok=True)
    base = os.path.join(artifacts_dir, prefix)
    trace_path = sched.tracer.dump_chrome_trace(base + ".trace.json")
    metrics_path = base + ".metrics.prom"
    with open(metrics_path, "w") as f:
        f.write(sched.metrics_text())
    cycles_path = base + ".tpu_cycles.json"
    with open(cycles_path, "w") as f:
        _json.dump(sched.metrics.tpu.records_json(), f)
    return {
        "trace": trace_path,
        "metrics": metrics_path,
        "tpu_cycles": cycles_path,
    }


class _Client:
    """API-server stand-in: dispatcher calls land here; bind/delete feed the
    informer handlers back on the loop thread via a pending queue (the
    watch-event delivery the reference gets from the apiserver)."""

    def __init__(self) -> None:
        self.sched: Scheduler | None = None
        self.bound: list[tuple[str, str]] = []
        import collections
        self._events: "collections.deque" = collections.deque()
        # bind-time counts per namespace: the throughput collector's view
        # (scheduler_perf measures SchedulingThroughput at bind, scoped to
        # the measured op's pods — churn/preemption traffic must not count)
        self.bound_by_ns: "collections.Counter" = collections.Counter()

    def bind(self, pod: t.Pod, node_name: str) -> None:
        self.bound.append((pod.name, node_name))
        self.bound_by_ns[pod.namespace] += 1
        self._events.append(("update", pod, pod.with_node(node_name)))

    def bulk_bind(self, pairs) -> list:
        # direct mode has no RPC to amortize; accepting the micro-batch
        # keeps the dispatch shape (and its batch-size stats) identical to
        # fullstack
        for pod, node_name in pairs:
            self.bind(pod, node_name)
        return [None] * len(pairs)

    def delete_pod(self, pod: t.Pod, reason: str = "") -> None:
        self._events.append(("delete", pod, None))

    def patch_status(self, pod: t.Pod, reason: str, message: str = "") -> None:
        pass

    def nominate(self, pod: t.Pod, node_name: str) -> None:
        pass

    def deliver(self) -> None:
        """Drain informer events on the loop thread."""
        while True:
            try:
                kind, a, b = self._events.popleft()
            except IndexError:
                return
            if kind == "update":
                self.sched.on_pod_update(a, b)
            else:
                self.sched.on_pod_delete(a)


def _begin_measured_phase(sched, warmup: bool, warm_pods):
    """Optionally compile the measured phase's device program (the full
    bucket ladder, so remainder batches hit the compile cache too), then
    snapshot the metric counters (and the histograms, via a prom baseline)
    so the measurement AND the embedded metrics snapshot are scoped to the
    same window — a large init phase must not dominate the reported p99s."""
    if warmup:
        sched.warmup(warm_pods)
    # measured-window baseline for the replay counter (init-phase churn —
    # PV/namespace creation — replays in-flight init cycles and must not
    # pollute the measured-phase evidence)
    sched._measure_replays0 = sched.metrics.pipeline_replays
    # encode-cache hit/miss baseline: the init/warmup misses (first sight
    # of every template) must not dilute the steady-state hit rate
    if sched.encode_cache is not None:
        kinds = ("filter", "score", "request")
        sched._measure_cache0 = (
            sum(sched.encode_cache.hits[k] for k in kinds),
            sum(sched.encode_cache.misses[k] for k in kinds),
        )
    # dispatcher baseline: mean bulk batch size + errors scoped to the
    # measured phase, not the init churn
    sched._measure_disp0 = sched.dispatcher.stats()
    # measured-window start on the lifecycle clock (perf_counter): the
    # soak stage splits the flight recorder's e2e samples at this
    # window's midpoint
    sched._measure_t0_pc = time.perf_counter()
    return (
        sched.metrics.schedule_attempts,
        sched.metrics.cycles,
        sched.metrics.prom.snapshot_baseline(),
    )


def _encode_stats(sched, cycles0: int) -> dict:
    """Measured-phase host-encode summary from the cycle trace spans
    (scoped by cycle id) + the encode-cache counters."""
    out = dict(
        encode_ms_per_cycle=None, encode_wall_frac=None,
        encode_cache_hit_rate=None,
    )
    spans = sched.tracer.recent(1 << 30)
    enc_s = [
        s.duration_s for s in spans
        if s.name == "encode" and s.attrs.get("cycle", 0) > cycles0
    ]
    cyc_s = [
        s.duration_s for s in spans
        if s.name == "scheduling-cycle" and s.attrs.get("cycle", 0) > cycles0
    ]
    if enc_s:
        out["encode_ms_per_cycle"] = 1000.0 * sum(enc_s) / len(enc_s)
    if enc_s and cyc_s and sum(cyc_s) > 0:
        out["encode_wall_frac"] = sum(enc_s) / sum(cyc_s)
    if sched.encode_cache is not None:
        kinds = ("filter", "score", "request")
        h = sum(sched.encode_cache.hits[k] for k in kinds)
        m = sum(sched.encode_cache.misses[k] for k in kinds)
        h0, m0 = getattr(sched, "_measure_cache0", (0, 0))
        dh, dm = h - h0, m - m0
        if dh + dm:
            out["encode_cache_hit_rate"] = dh / (dh + dm)
    return out


def _staged_and_soak(sched, prom_base) -> dict:
    """Measured-window staged percentiles + the SustainedChurn soak split
    (both None when the flight recorder is off or nothing bound)."""
    out = dict(
        staged_latency_ms=None, soak=None,
        flight_recorder=sched.flight_recorder is not None,
    )
    if sched.flight_recorder is None:
        return out
    out["staged_latency_ms"] = sched.metrics.prom.staged_percentiles(
        prom_base
    )
    t0 = getattr(sched, "_measure_t0_pc", None)
    if t0 is not None:
        out["soak"] = sched.flight_recorder.soak_split(
            t0, time.perf_counter()
        )
    return out


def _mesh_stats(sched) -> dict:
    """Mesh context of the run (device count / shape / collective probe) —
    stamped into every record so multichip numbers are self-describing."""
    shape = sched.mesh_shape
    n = 1
    for d in shape:
        n *= d
    return dict(
        n_devices=n,
        mesh_shape=shape,
        collective_wall_s=sched._collective_wall_s,
    )


def _dispatcher_stats(sched) -> dict:
    """Measured-phase dispatcher summary: mean bulk micro-batch size and
    API-write error count (deltas against the ``_begin_measured_phase``
    baseline)."""
    stats = sched.dispatcher.stats()
    base = getattr(sched, "_measure_disp0", None) or {}
    d_batches = stats["batches"] - base.get("batches", 0)
    d_calls = stats["batched_calls"] - base.get("batched_calls", 0)
    return dict(
        dispatcher_batch_mean=(d_calls / d_batches) if d_batches else None,
        dispatcher_errors=stats["errors"] - base.get("errors", 0),
    )


def _device_traffic_stats(sched, cycles0: int, duration: float) -> dict:
    """Measured-phase device-traffic summary from the per-cycle TPU
    records (joined to the window by cycle id)."""
    recs = [r for r in sched.metrics.tpu.records if r.cycle > cycles0]
    out = dict(
        cycles_per_sec=None, transfer_bytes_per_cycle=None,
        batch_bytes_per_cycle=None, resident_bytes=0,
        compile_misses=sum(1 for r in recs if r.compile_miss),
        pipeline_replays=(
            sched.metrics.pipeline_replays
            - getattr(sched, "_measure_replays0", 0)
        ),
    )
    if recs:
        out["transfer_bytes_per_cycle"] = (
            sum(r.transfer_bytes for r in recs) / len(recs)
        )
        out["batch_bytes_per_cycle"] = (
            sum(r.batch_bytes for r in recs) / len(recs)
        )
        out["resident_bytes"] = max(r.resident_bytes for r in recs)
        if duration > 0:
            out["cycles_per_sec"] = len(recs) / duration
    return out


def _packing_stats(sched, cycles0: int, bound, created) -> dict:
    """Packing-frontier evidence (engine-agnostic keys, PR 19):

    - ``nodes_used_at_steady_state``: distinct nodes carrying the MEASURED
      pods (name prefix ``measure-``) at the end of the run — the
      utilization half of the frontier, comparable across engines.
    - ``priority_slo_hit_rate``: among measured pods created with
      priority > 0, the fraction that actually bound (None when the
      workload has no priority tiers).
    - ``solver_iters_per_cycle``: mean packing-solver iterations over the
      measured cycles' device records (None for greedy/batched — they
      never stamp ``solver_iters``).
    - ``packing_weights``: the weight tensor behind the run, so a
      measured frontier is reproducible from its JSON alone.

    ``bound`` is an iterable of (pod_name, node_name); ``created`` an
    iterable of created Pod objects."""
    bound = list(bound)
    measured_nodes = {
        node for name, node in bound if name.startswith("measure-")
    }
    out: dict = dict(
        nodes_used_at_steady_state=(
            len(measured_nodes) if measured_nodes else None
        ),
        priority_slo_hit_rate=None,
        solver_iters_per_cycle=None,
        packing_weights=None,
    )
    bound_names = {name for name, _ in bound}
    high = [p for p in created
            if p.priority > 0 and p.name.startswith("measure-")]
    if high:
        out["priority_slo_hit_rate"] = (
            sum(1 for p in high if p.name in bound_names) / len(high)
        )
    iters = [
        r.solver_iters for r in sched.metrics.tpu.records
        if r.cycle > cycles0 and r.solver_iters is not None
    ]
    if iters:
        out["solver_iters_per_cycle"] = sum(iters) / len(iters)
    eng = getattr(sched, "_assign_device", None)
    weights = getattr(eng, "weights", None)
    if weights is not None and hasattr(weights, "to_json"):
        out["packing_weights"] = weights.to_json()
    return out


@dataclass
class _Deleter:
    """deletePodsOp with skipWaitToCompletion: drain a namespace's created
    pods at ``per_second`` between cycles (each delete fires the
    AssignedPodDelete event through the queue)."""

    pods: list
    per_second: int
    started_at: float = -1.0
    deleted: int = 0

    def maybe_fire(self, sched: Scheduler, now: float) -> None:
        if self.started_at < 0:
            self.started_at = now
        due = int((now - self.started_at) * self.per_second)
        while self.deleted < min(due, len(self.pods)):
            sched.on_pod_delete(self.pods[self.deleted])
            self.deleted += 1


@dataclass
class _Churn:
    op: W.ChurnOp
    namespace: str
    next_at: float = 0.0
    seq: int = 0
    live: list = field(default_factory=list)   # recreate-mode pool

    def maybe_fire(self, sched: Scheduler, now: float) -> None:
        while now >= self.next_at:
            self.next_at = (self.next_at or now) + self.op.interval_ms / 1000.0
            if self.op.mode == "recreate" and self.op.number and (
                len(self.live) >= self.op.number
            ):
                victim = self.live.pop(0)
                sched.on_pod_delete(victim)
            pod = self.op.template(f"churn-{self.seq}", self.namespace)
            self.seq += 1
            sched.on_pod_add(pod)
            if self.op.mode == "recreate":
                self.live.append(pod)


@dataclass
class _FsChurn:
    """churnOp through the REST stack: interfering pods are created (and
    in recreate mode deleted) via the remote store, so the scheduler sees
    them through the informer seam — the informer→invalidate→re-encode
    path end to end, exactly the reference's churn goroutine shape."""

    op: W.ChurnOp
    namespace: str
    remote: object
    bulk: bool = True
    next_at: float = 0.0
    seq: int = 0
    live: list = field(default_factory=list)   # recreate-mode pool (keys)

    def maybe_fire(self, now: float) -> None:
        from ..client.informers import PODS

        creates: list[tuple[str, t.Pod]] = []
        while now >= self.next_at:
            self.next_at = (self.next_at or now) + self.op.interval_ms / 1000.0
            if self.op.mode == "recreate" and self.op.number and (
                len(self.live) >= self.op.number
            ):
                # a catch-up burst can wrap past ``number``: the victim may
                # still be sitting in the unflushed create queue — flush
                # first so every popped key exists before its delete
                if creates:
                    _bulk_create(self.remote, PODS, creates, bulk=self.bulk)
                    creates = []
                victim = self.live.pop(0)
                try:
                    self.remote.delete(PODS, victim)
                except Exception:
                    pass   # already bound+mutated or gone — churn goes on
            pod = self.op.template(f"churn-{self.seq}", self.namespace)
            self.seq += 1
            key = f"{self.namespace}/{pod.name}"
            creates.append((key, pod))
            if self.op.mode == "recreate":
                self.live.append(key)
        # everything due this fire rides one bulk create (a stalled loop
        # catching up pays one RPC, not one per missed interval)
        _bulk_create(self.remote, PODS, creates, bulk=self.bulk)


def _bulk_create(
    remote, kind: str, items: "list[tuple[str, object]]",
    bulk: bool = True, chunk: int = 256,
) -> None:
    """Create ``items`` through the REST store — one bulk request per
    ``chunk`` when the store has the bulk verb (the perf runner's
    create-path RPC amortization), falling back to per-object creates
    (and always for ``bulk=False``, the escape hatch's single-op path)."""
    if bulk and len(items) > 1 and hasattr(remote, "bulk"):
        from ..store.memstore import bulk_result_error

        for i in range(0, len(items), chunk):
            ops = [
                {"op": "create", "key": k, "object": o}
                for k, o in items[i:i + chunk]
            ]
            for res in remote.bulk(kind, ops):
                err = bulk_result_error(res)
                if err is not None:
                    raise err
        return
    for k, o in items:
        remote.create(kind, k, o)


@dataclass
class _FsDeleter:
    """deletePodsOp through the REST stack: drain a namespace's created
    pods at ``per_second`` via remote deletes (each one becomes an
    AssignedPodDelete informer event for the scheduler)."""

    keys: list
    per_second: int
    remote: object
    started_at: float = -1.0
    deleted: int = 0

    def maybe_fire(self, now: float) -> None:
        from ..client.informers import PODS

        if self.started_at < 0:
            self.started_at = now
        due = int((now - self.started_at) * self.per_second)
        while self.deleted < min(due, len(self.keys)):
            try:
                self.remote.delete(PODS, self.keys[self.deleted])
            except Exception:
                pass
            self.deleted += 1


def run_workload(
    case: W.TestCase | str,
    workload: W.Workload | str,
    profile: C.Profile | None = None,
    max_batch: int = 1024,
    timeout_s: float = 1800.0,
    engine: str = "greedy",
    stall_s: float = 15.0,
    warmup: bool = True,
    artifacts_dir: str | None = None,
    pipeline: bool = False,
    encode_cache: bool = True,
    bulk: bool = True,
    mesh=None,
    flight_recorder: bool = True,
) -> WorkloadResult:
    """Execute one (test case, workload) pair and return the measurement.
    ``engine`` selects the assignment engine ("greedy" scan or "batched"
    rounds); ``stall_s`` is how long zero progress must persist before a
    phase gives up (must exceed the queue's max backoff, default 10 s, or
    backed-off pods read as stalls). ``warmup`` compiles the measured
    phase's device programs — the whole bucket ladder — before its clock
    starts (via ``Scheduler.warmup``; no scheduling-state mutation) — a
    long-lived scheduler compiles once at startup, so measured throughput
    is steady-state, like the reference's precompiled binary. ``pipeline``
    runs the two-stage pipelined cycle with the device-resident node block
    (Scheduler(pipeline=True)). ``artifacts_dir`` dumps the run's
    Chrome-trace JSON, /metrics snapshot, and device-side cycle records
    there (see ``dump_diagnosis_artifacts``). ``encode_cache`` toggles the
    event-time template-keyed encode cache (``--encode-cache off`` escape
    hatch — cached and fresh encodes are bit-identical). ``bulk`` toggles
    the dispatcher's cycle-boundary micro-batching (``--bulk off`` escape
    hatch — the off path is pod-for-pod identical). ``mesh`` shards the
    node axis over a device mesh (Scheduler(mesh=…): None/"off", "auto",
    "on", or a jax.sharding.Mesh) — bit-identical assignments, N-chip
    capacity. ``flight_recorder`` toggles the scheduling flight recorder +
    per-pod staged latency attribution (``--flight-recorder off`` is the
    overhead escape hatch; the bench's FlightRecorderOverhead line records
    the measured on/off cost)."""
    if isinstance(case, str):
        case = W.TEST_CASES[case]
    if isinstance(workload, str):
        workload = next(w for w in case.workloads if w.name == workload)
    params = dict(workload.params)

    client = _Client()
    sched = Scheduler(
        client, profile=profile or C.Profile(), max_batch=max_batch,
        engine=engine, pipeline=pipeline, encode_cache=encode_cache,
        bulk=bulk, mesh=mesh, flight_recorder=flight_recorder,
        feature_gates=dict(case.feature_gates) if case.feature_gates else None,
    )
    client.sched = sched
    sched.enable_preemption()

    churns: list[_Churn] = []
    deleters: list[_Deleter] = []
    created_by_ns: dict[str, list[t.Pod]] = {}
    measured = 0
    duration = 0.0
    attempts0 = cycles0 = 0
    prom_base = None
    op_ns_counter = 0

    def settle(target: int, namespaces: tuple[str, ...] = ()) -> tuple[int, float]:
        """Run cycles until ``target`` pods of the op's ``namespaces`` are
        BOUND (or stall). Churn fires between cycles; its pods bind in
        their own namespaces and never count toward the op's target (the
        reference scopes SchedulingThroughput to the measured pods too).
        Returns (bound, wall seconds)."""

        def bound_now() -> int:
            return sum(client.bound_by_ns[ns] for ns in namespaces)

        start = bound_now()
        done = 0
        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        last_progress = t0
        while done < target:
            now = time.perf_counter()
            if now > deadline:
                break
            for ch in churns:
                ch.maybe_fire(sched, now)
            for d in deleters:
                d.maybe_fire(sched, now)
            res = sched.schedule_batch()
            client.deliver()
            before = done
            done = bound_now() - start
            if done == before and res["scheduled"] == 0:
                # pods may simply be in backoff (max 10 s by default): only
                # a sustained quiet period is a real stall
                if now - last_progress > stall_s:
                    break
                time.sleep(0.005)
            else:
                last_progress = now
        return done, time.perf_counter() - t0

    created_nodes: list[str] = []
    for op_i, op in enumerate(case.ops):
        if isinstance(op, W.CreateNodesOp):
            n = op.count or params[op.count_param]
            factory = op.template or W.node_default
            for i in range(n):
                node = factory(i, op.zones)
                created_nodes.append(node.name)
                sched.on_node_add(node)
        elif isinstance(op, W.CreateNamespacesOp):
            # namespace objects carry labels for affinity namespaceSelectors
            n = params[op.count_param] if op.count_param else op.count
            for i in range(n):
                sched.on_namespace_add(t.Namespace(
                    name=f"{op.prefix}-{i}", labels=op.labels,
                ))
        elif isinstance(op, W.CreateServiceOp):
            sched.on_service_add(t.Service(
                name=op.name, namespace=op.namespace, selector=op.selector,
            ))
        elif isinstance(op, W.DeletePodsOp):
            deleters.append(_Deleter(
                pods=list(created_by_ns.get(op.namespace, ())),
                per_second=op.per_second,
            ))
        elif isinstance(op, W.CreatePodSetsOp):
            count = params[op.count_param]
            per = params[op.pods_param]
            template = op.template or case.default_pod_template
            total_sets = 0
            for g in range(count):
                ns = f"{op.prefix}-{g}"
                for j in range(per):
                    pod = template(f"set-{op_i}-{g}-{j}", ns)
                    created_by_ns.setdefault(ns, []).append(pod)
                    sched.on_pod_add(pod)
                    total_sets += 1
            settle(total_sets, tuple(
                f"{op.prefix}-{g}" for g in range(count)
            ))
        elif isinstance(op, W.CreatePodGroupsOp):
            from ..api.wrappers import make_pod_group

            groups = params[op.count_param]
            min_count = params[op.min_count_param]
            for g in range(groups):
                sched.on_pod_group_add(make_pod_group(
                    f"{op.prefix}-{g}", namespace=f"{op.prefix}-0",
                    min_count=min_count,
                ))
        elif isinstance(op, W.CreatePodsWithPVsOp):
            from ..api.wrappers import make_pod

            count = params[op.count_param]
            ns = op.namespace or f"pv-{op_i}"
            if op.collect_metrics:
                # warmup shape: plain pods (the PVC mask is a static-sig
                # column; shapes match the measured batch)
                attempts0, cycles0, prom_base = _begin_measured_phase(
                    sched, warmup,
                    [
                        make_pod(f"warmup-pv-{j}", namespace=ns,
                                 cpu_milli=100, memory=500 * 1024**2)
                        for j in range(min(count, sched.max_batch))
                    ],
                )
            for j in range(count):
                pv_name = f"{ns}-pv-{j}"
                sched.on_pv_add(t.PersistentVolume(
                    name=pv_name, driver=op.driver,
                    access_modes=("ReadOnlyMany",), capacity=1024**3,
                    claim_ref=f"{ns}/{ns}-claim-{j}",
                ))
                sched.on_pvc_add(t.PersistentVolumeClaim(
                    name=f"{ns}-claim-{j}", namespace=ns,
                    volume_name=pv_name, access_modes=("ReadOnlyMany",),
                    request=1024**3,
                ))
                sched.on_pod_add(make_pod(
                    f"pvpod-{op_i}-{j}", namespace=ns, cpu_milli=100,
                    memory=500 * 1024**2, creation_index=j,
                    pvcs=(f"{ns}-claim-{j}",),
                ))
            done, secs = settle(count, (ns,))
            if op.collect_metrics:
                measured += done
                duration += secs
        elif isinstance(op, W.CreateExtendedResourcePodsOp):
            from ..api.wrappers import make_pod

            count = params[op.count_param]
            ns = op.namespace
            if op.collect_metrics:
                attempts0, cycles0, prom_base = _begin_measured_phase(
                    sched, warmup,
                    [
                        make_pod(
                            f"warmup-ext-{j}", namespace=ns,
                            requests={f"foo.com/bar-{j}": 1},
                        )
                        for j in range(min(count, sched.max_batch))
                    ],
                )
            for j in range(count):
                sched.on_pod_add(make_pod(
                    f"extpod-{j}", namespace=ns, creation_index=j,
                    requests={f"foo.com/bar-{j}": 1},
                ))
            done, secs = settle(count, (ns,))
            if op.collect_metrics:
                measured += done
                duration += secs
        elif isinstance(op, W.CreateGangPodsOp):
            from ..api.wrappers import make_pod

            groups = params[op.count_param]
            per = params[op.multiplier_param]
            count = groups * per
            if op.collect_metrics:
                # group-lane shapes: one coalesced batch of plain pods
                attempts0, cycles0, prom_base = _begin_measured_phase(
                    sched, warmup,
                    [
                        make_pod(
                            f"warmup-gang-{j}", namespace=op.namespace,
                            cpu_milli=100, memory=100 * 1024**2,
                        )
                        for j in range(min(count, sched.max_batch))
                    ],
                )
            for j in range(count):
                sched.on_pod_add(make_pod(
                    f"gangpod-{j}", namespace=op.namespace,
                    cpu_milli=100, memory=100 * 1024**2,
                    scheduling_group=f"{op.prefix}-{j // per}",
                    creation_index=j,
                ))
            done, secs = settle(count, (op.namespace,))
            if op.collect_metrics:
                measured += done
                duration += secs
        elif isinstance(op, W.CreateResourceDriverOp):
            sched.on_device_class_add(t.DeviceClass(
                name=op.class_name,
                selectors=(t.CELSelector(
                    f'device.driver == "{op.driver}"'
                ),),
            ))
            per_node = params[op.max_claims_param]
            for node_name in created_nodes:
                if not node_name.startswith(op.node_prefix):
                    continue
                sched.on_resource_slice_add(t.ResourceSlice(
                    name=f"slice-{node_name}", driver=op.driver,
                    pool=node_name, node_name=node_name,
                    devices=tuple(
                        t.Device(name=f"device-{d}")
                        for d in range(per_node)
                    ),
                ))
        elif isinstance(op, W.CreateClaimPodsOp):
            from ..api.wrappers import make_pod

            count = params[op.count_param]
            ns = op.namespace

            def claim_pod(name: str, ns: str = ns, op=op) -> t.Pod:
                sched.on_resource_claim_add(t.ResourceClaim(
                    name=f"{name}-claim", namespace=ns,
                    uid=f"{ns}/{name}-claim",
                    requests=(t.DeviceRequest(
                        name="req-0", device_class_name=op.class_name,
                    ),),
                ))
                return make_pod(
                    name, namespace=ns, claims=(f"{name}-claim",),
                )

            if op.collect_metrics:
                attempts0, cycles0, prom_base = _begin_measured_phase(
                    sched, warmup,
                    [
                        claim_pod(f"warmup-dra-{j}")
                        for j in range(min(count, sched.max_batch))
                    ],
                )
            for j in range(count):
                pod = claim_pod(f"drapod-{op_i}-{j}")
                created_by_ns.setdefault(ns, []).append(pod)
                sched.on_pod_add(pod)
            done, secs = settle(count, (ns,))
            if op.collect_metrics:
                measured += done
                duration += secs
        elif isinstance(op, W.ChurnOp):
            churns.append(_Churn(op=op, namespace=f"churn-{len(churns)}"))
        elif isinstance(op, W.BarrierOp):
            sched.run_until_idle()
            client.deliver()
        elif isinstance(op, W.CreatePodsOp):
            count = params[op.count_param]
            template = op.template or case.default_pod_template
            ns = op.namespace or f"namespace-{op_ns_counter}"
            op_ns_counter += 1
            # the op index keeps names unique when several createPods ops
            # share one namespace (MixedSchedulingBasePod does)
            prefix = f"{'measure' if op.collect_metrics else 'init'}-{op_i}"
            if op.collect_metrics:
                attempts0, cycles0, prom_base = _begin_measured_phase(
                    sched, warmup,
                    [
                        template(f"warmup-{op_i}-{j}", ns)
                        for j in range(min(count, sched.max_batch))
                    ],
                )
            for j in range(count):
                pod = template(f"{prefix}-{ns}-{j}", ns)
                created_by_ns.setdefault(ns, []).append(pod)
                sched.on_pod_add(pod)
            if op.skip_wait:
                continue
            done, secs = settle(count, (ns,))
            if op.collect_metrics:
                measured += done
                duration += secs
        else:
            raise TypeError(f"unknown op {op!r}")

    sched.dispatcher.sync()
    client.deliver()
    sched._drain_bind_completions()
    # p99 from the pod_scheduling_sli_duration_seconds HISTOGRAM, scoped to
    # the measured phase (measured_p99_ms — the shared window-scoping
    # helper; histogram_quantile estimation)
    lat = measured_p99_ms(sched, prom_base)
    artifacts: dict[str, str] = {}
    if artifacts_dir is not None:
        artifacts = dump_diagnosis_artifacts(
            sched, artifacts_dir,
            f"{case.name}_{workload.name}_{engine}",
        )
    throughput = measured / duration if duration > 0 else 0.0
    traffic = _device_traffic_stats(sched, cycles0, duration)
    result = WorkloadResult(
        case_name=case.name,
        workload_name=workload.name,
        threshold=workload.threshold,
        threshold_note=workload.threshold_note,
        **traffic,
        **_packing_stats(
            sched, cycles0, client.bound,
            [p for pods in created_by_ns.values() for p in pods],
        ),
        **_encode_stats(sched, cycles0),
        **_dispatcher_stats(sched),
        **_mesh_stats(sched),
        **_staged_and_soak(sched, prom_base),
        measure_pods=sum(
            params[op.count_param]
            for op in case.ops
            if isinstance(op, W.CreatePodsOp) and op.collect_metrics
        ) + sum(
            params[op.count_param] * params[op.multiplier_param]
            for op in case.ops
            if isinstance(op, W.CreateGangPodsOp) and op.collect_metrics
        ) + sum(
            params[op.count_param]
            for op in case.ops
            if isinstance(
                op, (W.CreatePodsWithPVsOp, W.CreateExtendedResourcePodsOp,
                     W.CreateClaimPodsOp)
            ) and op.collect_metrics
        ),
        scheduled=measured,
        duration_s=duration,
        throughput=throughput,
        vs_threshold=(
            throughput / workload.threshold if workload.threshold else None
        ),
        attempts=sched.metrics.schedule_attempts - attempts0,
        cycles=sched.metrics.cycles - cycles0,
        p99_attempt_latency_ms=lat,
        metrics_snapshot=sched.metrics.prom.snapshot(baseline=prom_base),
        artifacts=artifacts,
    )
    sched.close()
    return result


class _RssSampler:
    """Per-stage peak-RSS tracker: samples /proc/self/statm once per
    scheduling cycle (a few µs) and keeps the max. Stage-local on purpose
    — ru_maxrss is process-monotone and would attribute an earlier 100k
    stage's peak to every later record."""

    def __init__(self) -> None:
        self.peak = 0
        self._page = 4096
        self._f = None
        try:
            self._page = os.sysconf("SC_PAGE_SIZE")
            self._f = open("/proc/self/statm", "rb")
        except (OSError, ValueError, AttributeError):
            pass    # no procfs: sample() falls back to the monotone
            #         ru_maxrss (coarser semantics beat a zero)

    def sample(self) -> int:
        if self._f is not None:
            self._f.seek(0)
            rss = int(self._f.read().split()[1]) * self._page
        else:
            import resource

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        if rss > self.peak:
            self.peak = rss
        return rss

    def close(self) -> None:
        if self._f is not None:
            self._f.close()


class _TraceDirectDriver:
    """Direct-mode I/O for the trace replay: events land straight on the
    scheduler's informer handlers; bind times come off the in-process
    client."""

    def __init__(self, sched, client) -> None:
        self.sched = sched
        self.client = client
        self._nodes: dict[str, t.Node] = {}

    def add_node(self, node: t.Node) -> None:
        self._nodes[node.name] = node
        self.sched.on_node_add(node)

    def drain_node(self, name: str) -> None:
        node = self._nodes.pop(name, None)
        if node is not None:
            self.sched.on_node_delete(node)

    def create_pod(self, pod: t.Pod) -> None:
        self.sched.on_pod_add(pod)

    def delete_pod(self, key: str, pod: t.Pod) -> None:
        self.sched.on_pod_delete(pod)

    def create_group(self, ev) -> None:
        from ..api.wrappers import make_pod_group

        self.sched.on_pod_group_add(make_pod_group(
            ev.name, namespace=ev.namespace, min_count=ev.min_count,
        ))

    def pump(self) -> bool:
        self.client.deliver()
        return False

    def bind_times(self) -> dict:
        return self.client.bind_times

    def close(self) -> None:
        pass


class _TraceFullstackDriver:
    """Fullstack I/O for the trace replay: pod/node events go through the
    REST apiserver (bulk creates per tick) and come back through the
    informer seam — enqueue→bind spans the whole control plane. PodGroups
    have no REST kind; group events land on the scheduler directly (the
    one documented direct injection)."""

    def __init__(self, sched, remote, informers, client) -> None:
        self.sched = sched
        self.remote = remote
        self.informers = informers
        self.client = client

    def add_node(self, node: t.Node) -> None:
        from ..client.informers import NODES

        self.remote.create(NODES, node.name, node)

    def drain_node(self, name: str) -> None:
        from ..client.informers import NODES

        try:
            self.remote.delete(NODES, name)
        except Exception:
            pass

    def create_pod(self, pod: t.Pod) -> None:
        from ..client.informers import PODS

        self.remote.create(PODS, f"{pod.namespace}/{pod.name}", pod)

    def delete_pod(self, key: str, pod: t.Pod) -> None:
        from ..client.informers import PODS

        try:
            self.remote.delete(PODS, key)
        except Exception:
            pass    # already gone / rebound — the trace goes on

    def create_group(self, ev) -> None:
        from ..api.wrappers import make_pod_group

        self.sched.on_pod_group_add(make_pod_group(
            ev.name, namespace=ev.namespace, min_count=ev.min_count,
        ))

    def pump(self) -> bool:
        return bool(self.informers.pump())

    def bind_times(self) -> dict:
        return self.client.bind_times

    def close(self) -> None:
        pass


def run_workload_trace(
    profile,
    mode: str = "direct",
    engine: str = "greedy",
    max_batch: int = 128,
    timeout_s: float = 600.0,
    stall_s: float = 15.0,
    warmup: bool = True,
    speed: float = 1.0,
    wall_budget_s: float | None = None,
    encode_cache: bool = True,
    scoped_invalidation: bool = True,
    wire: str = "binary",
    artifacts_dir: str | None = None,
    sentinel: bool = False,
    sentinel_spike: bool = False,
    spike_stall_s: float = 6.0,
    topology: str = "off",
) -> WorkloadResult:
    """Replay a ``workloads.TraceProfile`` against the real scheduler loop
    and measure the admission-latency SLO: p50/p99 of enqueue→bind over
    every pod the trace created, judged against the profile's declared
    budget (``slo_ok``), plus per-stage peak RSS, device-resident bytes,
    and the encode-cache re-encode accounting — the scale-frontier record
    shape.

    ``mode``: "direct" (events on the informer handlers — the engine-bound
    number) or "fullstack" (through the REST apiserver + informers —
    enqueue→bind spans the control plane). ``speed`` scales the trace
    clock (2.0 = replay twice as fast). ``wall_budget_s``: hard stage wall
    — when exceeded the replay stops firing, the settle is skipped, and
    the record is emitted TRUNCATED but parseable (a hung 100k-node rung
    must never eat the whole bench wall). ``scoped_invalidation=False``
    pins the encode cache's pre-PR-14 full-epoch flush (the A/B control
    the node-wave evidence is measured against).

    ``sentinel=True`` rides the anomaly sentinel on the loop with the
    profile's DECLARED ``slo_budget_ms`` as the burn-rate budget — the
    honest venue for the admission-SLO rule, because paced arrivals keep
    a clean replay inside budget (bulk-create workloads blow any fixed
    budget on tail queue-wait alone). ``sentinel_spike=True`` injects a
    one-shot ``spike_stall_s`` scheduler stall a third of the way
    through the replay: the loop keeps firing trace arrivals but skips
    the scheduling cycle, so the backlog accrues REAL admission latency
    — the record's ``sentinel.spike`` verdict carries the
    fire→bundle→resolve acceptance.

    ``topology``: the scheduler's ``--topology`` mode. On a profile with
    ``slices > 0`` every node carries the shared rack/slice grammar and
    the record gains the slice-level fragmentation evidence
    (slices_total / slices_free_at_steady_state / fragmentation_index)
    plus gang_admission_p99_ms from the gang-admission histogram."""
    from ..sched.scheduler import Scheduler
    from . import workloads as W

    if isinstance(profile, str):
        profile = W.TRACE_PROFILES[profile]
    events = profile.events()

    sentinel_obj = None
    if sentinel or sentinel_spike:
        from ..telemetry.rules import fast_rules
        from ..telemetry.sentinel import Sentinel as _Sentinel

        sentinel_obj = _Sentinel(
            rules=fast_rules(),
            slo_budget_ms=profile.slo_budget_ms,
            interval_s=0.25,
        )

    srv = remote = informers = None
    if mode == "direct":
        client = _TraceClient()
        sched = Scheduler(
            client, profile=C.Profile(), max_batch=max_batch, engine=engine,
            encode_cache=encode_cache,
            feature_gates={"GenericWorkload": True, "GangScheduling": True},
            sentinel=sentinel_obj if sentinel_obj is not None else False,
            topology=topology,
        )
        client.sched = sched
        driver = _TraceDirectDriver(sched, client)
    elif mode == "fullstack":
        from ..apiserver import APIServer, RemoteStore
        from ..client import SchedulerInformers
        from ..client.informers import NODES

        srv = APIServer().start()
        remote = RemoteStore(srv.url, wire=wire)
        client = _make_trace_store_client(remote)
        sched = Scheduler(
            client, profile=C.Profile(), max_batch=max_batch, engine=engine,
            encode_cache=encode_cache,
            feature_gates={"GenericWorkload": True, "GangScheduling": True},
            sentinel=sentinel_obj if sentinel_obj is not None else False,
            topology=topology,
        )
        informers = SchedulerInformers(remote, sched)
        informers.start()
        driver = _TraceFullstackDriver(sched, remote, informers, client)
    else:
        raise ValueError(f"unknown trace mode {mode!r}")
    if sched.encode_cache is not None and not scoped_invalidation:
        sched.encode_cache.scoped = False
    sched.enable_preemption()

    rss = _RssSampler()
    created_at: dict[str, float] = {}
    deleted: set[str] = set()
    pods_by_key: dict[str, t.Pod] = {}
    truncated = False
    try:
        # initial cluster
        slices = getattr(profile, "slices", 0)
        if mode == "direct":
            for i in range(profile.nodes):
                driver.add_node(W.node_default(i, profile.zones, slices))
        else:
            nodes = [
                W.node_default(i, profile.zones, slices)
                for i in range(profile.nodes)
            ]
            _bulk_create(
                remote, NODES, [(nd.name, nd) for nd in nodes],
            )
            driver.pump()
        if warmup:
            sched.warmup([
                W.build_trace_pod(W.TraceEvent(
                    0.0, "create_pod", f"warm-{j}", "trace-warm",
                ))
                for j in range(min(max_batch, 64))
            ])
        attempts0, cycles0, prom_base = _begin_measured_phase(
            sched, False, [],
        )
        rss.sample()

        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        wall_deadline = (
            t0 + wall_budget_s if wall_budget_s is not None else None
        )
        i = 0
        last_progress = t0
        bound_prev = 0
        spike = {"stall_s": spike_stall_s, "until": None,
                 "start_wall": None, "end_wall": None}
        spike_armed = sentinel_spike

        def live_unbound() -> int:
            bt = driver.bind_times()
            return sum(
                1 for k in created_at if k not in deleted and k not in bt
            )

        while True:
            now = time.perf_counter()
            if wall_deadline is not None and now > wall_deadline:
                truncated = True
                break
            if now > deadline:
                truncated = True
                break
            trace_now = (now - t0) * speed
            fired = 0
            while i < len(events) and events[i].at_s <= trace_now:
                ev = events[i]
                i += 1
                fired += 1
                if ev.kind == "create_pod":
                    pod = W.build_trace_pod(ev)
                    key = f"{ev.namespace}/{ev.name}"
                    created_at[key] = time.perf_counter()
                    pods_by_key[key] = pod
                    driver.create_pod(pod)
                elif ev.kind == "delete_pod":
                    key = f"{ev.namespace}/{ev.name}"
                    deleted.add(key)
                    pod = pods_by_key.get(key)
                    if pod is not None:
                        driver.delete_pod(key, pod)
                elif ev.kind == "add_node":
                    driver.add_node(
                        make_trace_node(ev.name, profile.zones, slices)
                    )
                elif ev.kind == "drain_node":
                    driver.drain_node(ev.name)
                elif ev.kind == "create_group":
                    driver.create_group(ev)
            if spike_armed and i >= max(1, len(events) // 3):
                spike_armed = False
                spike["start_wall"] = time.time()
                spike["until"] = now + spike["stall_s"]
            stalled = (
                spike["until"] is not None and spike["end_wall"] is None
            )
            if stalled and now >= spike["until"]:
                spike["end_wall"] = time.time()
                stalled = False
            moved = driver.pump()
            if stalled:
                # the injected scheduler stall: arrivals keep landing (the
                # pump above) while the cycle is skipped — the backlog
                # accrues REAL admission latency, which is what makes the
                # burn-rate fire distinguishable from a clean replay
                res = {"scheduled": 0}
                time.sleep(0.002)
            else:
                res = sched.schedule_batch()
                driver.pump()
                sched.dispatcher.sync()
                sched._drain_bind_completions()
            rss.sample()
            bound_now = len(driver.bind_times())
            progressed = (
                fired or moved or res["scheduled"]
                or bound_now > bound_prev
            )
            bound_prev = bound_now
            if i >= len(events):
                # replay done: settle until every live pod bound or stall
                if live_unbound() == 0:
                    break
                if progressed:
                    last_progress = now
                elif now - last_progress > stall_s:
                    break
                else:
                    time.sleep(0.002)
            elif progressed:
                last_progress = now
            else:
                # idle until the next event is due (bounded nap)
                time.sleep(min(0.002, max(0.0, (
                    events[i].at_s / speed + t0 - now
                ))))
        duration = time.perf_counter() - t0
        sched.dispatcher.sync()
        driver.pump()
        sched._drain_bind_completions()
        sentinel_report = None
        if sentinel_obj is not None:
            sentinel_report = _sentinel_settle(
                sentinel_obj,
                spike if spike["end_wall"] is not None else None,
            )

        # admission latencies: enqueue→bind per created pod
        bt = driver.bind_times()
        lats = [
            (bt[k] - created_at[k]) * 1000.0
            for k in created_at if k in bt
        ]
        p50 = float(np.percentile(lats, 50)) if lats else None
        p99 = float(np.percentile(lats, 99)) if lats else None
        unbound = live_unbound()
        ec = sched.encode_cache
        trace_stats = {
            "profile": profile.name,
            "seed": profile.seed,
            "events": len(events),
            "fired": i,
            "created": len(created_at),
            "deleted": len(deleted),
            "unbound": unbound,
            "nodes_final": sched.cache.update_snapshot().num_nodes(),
            "samples": len(lats),
        }
        if ec is not None:
            st = ec.stats()
            trace_stats["encode_rebuilt_bytes"] = st["rebuilt_bytes"]
            trace_stats["encode_extended_bytes"] = st["extended_bytes"]
            trace_stats["encode_scoped_extensions"] = st["scoped_extensions"]
            trace_stats["encode_scoped_removals"] = st["scoped_removals"]
            trace_stats["encode_compacted_bytes"] = st["compacted_bytes"]
            trace_stats["encode_invalidations"] = st["invalidations"]
            trace_stats["scoped_invalidation"] = bool(ec.scoped)
        artifacts: dict[str, str] = {}
        if artifacts_dir is not None and not truncated:
            artifacts = dump_diagnosis_artifacts(
                sched, artifacts_dir,
                f"Trace_{profile.name}_{mode}_{engine}",
            )
        measured = len(lats)
        throughput = measured / duration if duration > 0 else 0.0
        traffic = _device_traffic_stats(sched, cycles0, duration)
        topo_stats = _trace_topology_stats(sched)
        return WorkloadResult(
            case_name=f"Trace_{profile.name}",
            workload_name=(
                f"{profile.nodes}Nodes" + ("" if mode == "direct"
                                           else "_fullstack")
            ),
            threshold=None,
            **traffic,
            **_encode_stats(sched, cycles0),
            **_dispatcher_stats(sched),
            **_mesh_stats(sched),
            **_staged_and_soak(sched, prom_base),
            # trace pods are not measure-prefixed: only the solver-side
            # packing stats (iters/weights) populate here; nodes_final in
            # trace_stats already carries the utilization story
            **_packing_stats(sched, cycles0, [], []),
            measure_pods=len(created_at),
            scheduled=measured,
            duration_s=duration,
            throughput=throughput,
            vs_threshold=None,
            attempts=sched.metrics.schedule_attempts - attempts0,
            cycles=sched.metrics.cycles - cycles0,
            p99_attempt_latency_ms=measured_p99_ms(sched, prom_base),
            admission_p50_ms=p50,
            admission_p99_ms=p99,
            slo_budget_ms=profile.slo_budget_ms,
            slo_ok=(
                p99 is not None and p99 <= profile.slo_budget_ms
                and unbound == 0 and not truncated
            ),
            peak_rss_bytes=rss.peak,
            truncated=truncated,
            sentinel=sentinel_report,
            topology=topology,
            slices_total=topo_stats.get("slices_total"),
            slices_free_at_steady_state=topo_stats.get(
                "slices_free_at_steady_state"
            ),
            fragmentation_index=topo_stats.get("fragmentation_index"),
            gang_admission_p99_ms=topo_stats.get("gang_admission_p99_ms"),
            trace_stats=trace_stats,
            metrics_snapshot=sched.metrics.prom.snapshot(baseline=prom_base),
            artifacts=artifacts,
        )
    finally:
        rss.close()
        sched.close()
        if srv is not None:
            srv.close()


def _trace_topology_stats(sched) -> dict:
    """Slice-level fragmentation at trace end, computed host-side from
    the FINAL snapshot in one pass over node infos: a slice is FREE when
    no pod sits anywhere on it; fragmentation_index is the share of
    labeled slices left PARTIALLY occupied (some nodes busy, some free —
    the state that blocks future aligned gangs). gang_admission_p99_ms
    comes from the gang-admission histogram when any gang admitted.
    Empty dict on an unlabeled fleet with no gang observations."""
    from ..state.topology import SLICE_KEY

    snap = sched.cache.update_snapshot()
    occupancy: dict[str, list[int]] = {}
    for info in snap.nodes.values():
        val = info.node.labels_dict().get(SLICE_KEY)
        if val is not None:
            occupancy.setdefault(val, []).append(len(info.pods))
    out: dict = {}
    if occupancy:
        total = len(occupancy)
        free = sum(
            1 for counts in occupancy.values()
            if not any(c > 0 for c in counts)
        )
        partial = sum(
            1 for counts in occupancy.values()
            if any(c > 0 for c in counts) and any(c == 0 for c in counts)
        )
        out["slices_total"] = total
        out["slices_free_at_steady_state"] = free
        out["fragmentation_index"] = partial / total
    h = sched.metrics.prom.gang_admission_duration.merged()
    if h.total:
        out["gang_admission_p99_ms"] = h.quantile(0.99) * 1000.0
    return out


def make_trace_node(
    name: str, zones: tuple[str, ...] = (), slices: int = 0
) -> t.Node:
    """A wave node: default scheduler-perf shape under the trace's own
    name (drains address nodes by name). Zone assignment uses a STABLE
    hash — builtin hash() is salted per process, which would break the
    trace determinism contract across runs. Rack/slice labels come from
    the same ``trace_topology_labels`` grammar as the initial fleet."""
    import zlib

    from ..api.wrappers import make_node

    labels = {W.HOSTNAME_KEY: name}
    if zones:
        labels[W.ZONE_KEY] = zones[zlib.crc32(name.encode()) % len(zones)]
    labels.update(W.trace_topology_labels(name, slices))
    return make_node(
        name, cpu_milli=4000, memory=32 * 1024**3, pods=110, labels=labels,
    )


def _make_trace_store_client(remote):
    """Fullstack trace client: StoreClient + per-pod bind wall stamps
    (dispatcher workers bind concurrently, hence the lock)."""
    import threading

    from ..client import StoreClient

    class _C(StoreClient):
        def __init__(self, store) -> None:
            super().__init__(store)
            self.bind_times: dict[str, float] = {}
            self._bt_lock = threading.Lock()

        def bind(self, pod, node_name) -> None:
            super().bind(pod, node_name)
            with self._bt_lock:
                self.bind_times.setdefault(
                    f"{pod.namespace}/{pod.name}", time.perf_counter()
                )

        def bulk_bind(self, pairs) -> list:
            errs = super().bulk_bind(pairs)
            now = time.perf_counter()
            with self._bt_lock:
                for (pod, _node), err in zip(pairs, errs):
                    if err is None:
                        self.bind_times.setdefault(
                            f"{pod.namespace}/{pod.name}", now
                        )
            return errs

    return _C(remote)


class _TraceClient(_Client):
    """Direct-mode client that stamps per-pod bind wall times (the
    admission-latency denominator)."""

    def __init__(self) -> None:
        super().__init__()
        self.bind_times: dict[str, float] = {}

    def bind(self, pod: t.Pod, node_name: str) -> None:
        super().bind(pod, node_name)
        self.bind_times.setdefault(
            f"{pod.namespace}/{pod.name}", time.perf_counter()
        )


class _WatchFanout:
    """N extra concurrent pod watchers against the apiserver — the heavy
    fan-out load of a big cluster (hundreds of kubelets/controllers each
    holding a watch). Each watcher is its own RemoteStore connection on
    its own thread, long-polling the pods bucket; a compaction relists
    and resumes. The load is the POINT (every store write wakes every
    watcher, each draining the same events — the serialize-once body ring
    pays one encode for all of them), so the threads run for the whole
    workload and stop at teardown."""

    def __init__(self, url: str, wire: str, n: int) -> None:
        import threading

        from ..apiserver import RemoteStore
        from ..client.informers import PODS

        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

        def loop() -> None:
            try:
                rs = RemoteStore(url, wire=wire)
                w = rs.watch(PODS, 0)
                # 2s long-poll: a write still wakes the watcher instantly
                # through the store's condition variable — the timeout
                # only bounds IDLE churn (hundreds of watchers at 0.5s
                # would burn the host on empty polls, starving the very
                # scheduler the fan-out is supposed to load)
                w.poll_timeout_s = 2.0
                while not self._stop.is_set():
                    try:
                        w.poll()
                    except Exception:
                        if self._stop.is_set():
                            return
                        # compacted cursor or transient transport error:
                        # re-anchor at the current head and keep watching
                        try:
                            _items, rv = rs.list(PODS)
                            w = rs.watch(PODS, rv)
                            w.poll_timeout_s = 2.0
                        except Exception:
                            time.sleep(0.05)
            except Exception:
                pass    # a dead extra watcher must not kill the bench

        for _ in range(n):
            t = threading.Thread(target=loop, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


def _sentinel_settle(sentinel, spike: "dict | None",
                     resolve_timeout_s: float = 30.0) -> dict:
    """Post-run sentinel settle: keep evaluating on the real clock until
    every alert that fired has resolved (the recovery half of the
    fire→resolve acceptance — the rule windows slide past the spike and
    the clean streak closes the lifecycle), then fold the evidence into
    the record. ``spike`` carries the injected stall's wall window; with
    it the report adds the fire-latency / bundle-coverage verdicts the
    SentinelSpike bench stage asserts."""
    import time as _time

    deadline = _time.monotonic() + resolve_timeout_s
    while _time.monotonic() < deadline:
        sentinel.evaluate()
        snap = sentinel.alerts_json()
        if snap["firing"] == 0 and snap["pending"] == 0:
            break
        _time.sleep(sentinel.interval_s)
    out = dict(sentinel.stats())
    snap = sentinel.alerts_json()
    out["alerts"] = [
        {k: a[k] for k in ("rule", "state", "severity", "fires", "value")}
        for a in snap["alerts"]
    ]
    # the zero-false-positive assert for the clean (no-spike) run
    out["clean"] = out["fired_total"] == 0
    if spike is not None:
        target = next(
            (a for a in snap["alerts"]
             if a["rule"] == "admission-slo-burn"),
            None,
        )
        verdict: dict = {
            "stall_s": round(spike["end_wall"] - spike["start_wall"], 3),
            "fired": target is not None and target["fires"] > 0,
            "resolved": target is not None
            and target["state"] == "resolved",
        }
        if target is not None and target.get("fired_at_wall"):
            lat = target["fired_at_wall"] - spike["end_wall"]
            verdict["fire_latency_s"] = round(lat, 3)
            # "within one evaluation interval" — of the bad events
            # becoming VISIBLE, which is one recovery cycle after the
            # stall ends: the backlog's first bind wave (a full-batch
            # encode + dispatch) has to land in the histogram before a
            # single bad observation exists. Two intervals of cadence
            # slack + a 3s bind-wave allowance
            verdict["fired_within_interval"] = (
                lat <= 2 * sentinel.interval_s + 3.0
            )
        bundle = next(
            (b for b in sentinel.bundles_payload()
             if (b.get("trigger") or {}).get("rule")
             == "admission-slo-burn"),
            None,
        )
        verdict["bundle_captured"] = bundle is not None
        if bundle is not None:
            # the trace slice looks back trace_window_s from capture:
            # a capture this close to the stall has the stall in-frame
            verdict["bundle_covers_stall"] = (
                bundle["captured_wall"] - spike["end_wall"]
                <= sentinel.trace_window_s
            )
            verdict["bundle_sections"] = sorted(
                (bundle.get("sections") or {}).keys()
            )
        out["spike"] = verdict
    return out


def run_workload_full_stack(
    case: W.TestCase | str,
    workload: W.Workload | str,
    profile: C.Profile | None = None,
    max_batch: int = 1024,
    timeout_s: float = 1800.0,
    engine: str = "greedy",
    stall_s: float = 15.0,
    warmup: bool = True,
    artifacts_dir: str | None = None,
    pipeline: bool = False,
    encode_cache: bool = True,
    bulk: bool = True,
    mesh=None,
    flight_recorder: bool = True,
    wire: str = "binary",
    watch_fanout: int = 0,
    telemetry: bool = False,
    sentinel: bool = False,
    sentinel_spike: bool = False,
) -> WorkloadResult:
    """The same measurement through the FULL STACK: an in-process REST
    apiserver + RemoteStore + informers + dispatcher binds over HTTP —
    the reference harness's shape (scheduler_perf boots a real apiserver
    and measures through it, test/integration/scheduler_perf/util.go:96).
    Supports createNodes/createNamespaces/createPods/barrier PLUS churn
    and pod-delete recycling (churnOp / deletePodsOp create and delete
    through the REST store, so the informer→invalidate→re-encode path is
    exercised end to end) — SchedulingBasic, the quadratic affinity/
    spreading cases, and the churn workloads; richer ops (PV/DRA/gang)
    still raise.

    The direct-vs-full-stack delta is the apiserver tax: run both modes on
    one workload to measure what the REST hop costs.

    ``wire`` selects the negotiated wire codec ("binary" default, "json"
    the escape hatch — bindings are pod-for-pod identical); the record
    embeds the codec actually negotiated plus wire_bytes_per_pod.
    ``watch_fanout`` adds N extra concurrent pod watchers (the big-
    cluster fan-out load the serialize-once body ring exists for).
    ``telemetry`` runs the FULL telemetry plane alongside the workload —
    a real HTTP collector, traceparent stamped on every RPC, both
    processes' exporters on their 1 s cadence — so the
    TelemetryOverhead_* comparison measures the whole tax, not a
    cut-down one; the result carries the collector's span totals and
    drop counter.
    ``sentinel`` rides the anomaly sentinel (telemetry.sentinel) on the
    scheduler's cycle boundary with bench-scaled rule windows
    (rules.fast_rules) — the SentinelOverhead_* pair's "on" half; the
    result carries its lifecycle stats (``clean`` = nothing fired).
    ``sentinel_spike`` additionally injects a one-shot scheduling stall
    mid-measured-phase and reports the fire→bundle→resolve verdict
    (the acceptance scenario — NOT a judged throughput row)."""
    import collections

    from ..apiserver import APIServer, RemoteStore
    from ..client import SchedulerInformers, StoreClient
    from ..client.informers import NAMESPACES, NODES, PODS

    if isinstance(case, str):
        case = W.TEST_CASES[case]
    if isinstance(workload, str):
        workload = next(w for w in case.workloads if w.name == workload)
    params = dict(workload.params)
    supported = (
        W.CreateNodesOp, W.CreateNamespacesOp, W.CreatePodsOp, W.BarrierOp,
        W.ChurnOp, W.DeletePodsOp,
    )
    for op in case.ops:
        if not isinstance(op, supported):
            raise NotImplementedError(
                f"full-stack mode does not drive {type(op).__name__}"
            )

    srv = APIServer().start()
    remote = RemoteStore(srv.url, wire=wire, traceparent=telemetry)
    fanout = (
        _WatchFanout(srv.url, wire, watch_fanout) if watch_fanout else None
    )
    coll_srv = None
    exporters: list = []
    if telemetry:
        from ..telemetry.collector import CollectorServer
        from ..telemetry.exporter import TelemetryExporter

        coll_srv = CollectorServer().start()
        exporters.append(TelemetryExporter(
            coll_srv.url, process="apiserver-bench",
            component="apiserver", tracer=srv.tracer,
            metrics_fn=srv.metrics_text,
        ).start())

    class _CountingClient(StoreClient):
        def __init__(self, store) -> None:
            import threading

            super().__init__(store)
            self.bound_by_ns: collections.Counter = collections.Counter()
            self.bound_pairs: list[tuple[str, str]] = []
            self._count_lock = threading.Lock()   # dispatcher workers bind
            #                                       concurrently

        def bind(self, pod, node_name) -> None:
            super().bind(pod, node_name)
            with self._count_lock:
                self.bound_by_ns[pod.namespace] += 1
                self.bound_pairs.append((pod.name, node_name))

        def bulk_bind(self, pairs) -> list:
            errs = super().bulk_bind(pairs)
            with self._count_lock:
                for (pod, node), err in zip(pairs, errs):
                    # failed ops fall back through bind(), which counts
                    if err is None:
                        self.bound_by_ns[pod.namespace] += 1
                        self.bound_pairs.append((pod.name, node))
            return errs

    client = _CountingClient(remote)
    sentinel_obj = None
    if sentinel or sentinel_spike:
        from ..telemetry.rules import fast_rules
        from ..telemetry.sentinel import Sentinel as _Sentinel

        # bench-scaled windows (seconds, not minutes) so the lifecycle
        # completes inside a bench stage; the declared budget only
        # exists in spike mode — a clean run keeps the admission burn
        # rule dormant and judges the budget-less rules (outlier,
        # cache-collapse) for false positives instead
        sentinel_obj = _Sentinel(
            rules=fast_rules(),
            slo_budget_ms=250.0 if sentinel_spike else None,
            interval_s=0.25,
        )
    sched = Scheduler(
        client, profile=profile or C.Profile(), max_batch=max_batch,
        engine=engine, pipeline=pipeline, encode_cache=encode_cache,
        bulk=bulk, mesh=mesh, flight_recorder=flight_recorder,
        feature_gates=dict(case.feature_gates) if case.feature_gates else None,
        sentinel=sentinel_obj if sentinel_obj is not None else False,
    )
    if telemetry:
        from ..telemetry.exporter import TelemetryExporter

        remote.set_tracer(sched.tracer)
        fr = sched.flight_recorder
        exporters.append(TelemetryExporter(
            coll_srv.url, process="scheduler-bench",
            component="scheduler", tracer=sched.tracer,
            metrics_fn=sched.metrics_text,
            flight_fn=(
                (lambda: fr.records_json(limit=512))
                if fr is not None else None
            ),
        ).start())
    informers = SchedulerInformers(remote, sched, bulk=bulk)
    informers.start()

    measured = 0
    duration = 0.0
    attempts0 = cycles0 = 0
    prom_base = None
    op_ns_counter = 0
    requests0 = 0
    rpcs_total = 0        # measured-phase apiserver round trips
    wire0 = 0
    wire_total = 0        # measured-phase apiserver payload bytes
    churns: list[_FsChurn] = []
    deleters: list[_FsDeleter] = []
    created_keys_by_ns: dict[str, list[str]] = {}
    created_pods: list[t.Pod] = []
    # one-shot injected stall (sentinel_spike): armed when the MEASURED
    # phase starts, fired once a third of its pods have bound — the
    # backlogged pods then bind with e2e latencies past the declared
    # budget, which is exactly the bad-event burst the admission
    # burn-rate rule exists to catch
    spike = {"armed": False, "stall_s": 0.75,
             "start_wall": None, "end_wall": None}

    def settle(target: int, namespaces: tuple[str, ...]) -> tuple[int, float]:
        def bound_now() -> int:
            return sum(client.bound_by_ns[ns] for ns in namespaces)

        start = bound_now()
        done = 0
        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        last_progress = t0
        while done < target:
            now = time.perf_counter()
            if now > deadline:
                break
            if spike["armed"] and done >= target // 3:
                spike["armed"] = False
                spike["start_wall"] = time.time()
                time.sleep(spike["stall_s"])
                spike["end_wall"] = time.time()
            for ch in churns:
                ch.maybe_fire(now)
            for d in deleters:
                d.maybe_fire(now)
            moved = informers.pump()
            res = sched.schedule_batch()
            sched.dispatcher.sync()
            sched._drain_bind_completions()
            before = done
            done = bound_now() - start
            if done == before and res["scheduled"] == 0 and not moved:
                if now - last_progress > stall_s:
                    break
                time.sleep(0.005)
            else:
                last_progress = now
        return done, time.perf_counter() - t0

    try:
        for op_i, op in enumerate(case.ops):
            if isinstance(op, W.CreateNodesOp):
                n = op.count or params[op.count_param]
                factory = op.template or W.node_default
                nodes = [factory(i, op.zones) for i in range(n)]
                _bulk_create(
                    remote, NODES, [(nd.name, nd) for nd in nodes], bulk=bulk,
                )
            elif isinstance(op, W.CreateNamespacesOp):
                n = params[op.count_param] if op.count_param else op.count
                _bulk_create(remote, NAMESPACES, [
                    (f"{op.prefix}-{i}", t.Namespace(
                        name=f"{op.prefix}-{i}", labels=op.labels,
                    ))
                    for i in range(n)
                ], bulk=bulk)
            elif isinstance(op, W.BarrierOp):
                informers.pump()
                sched.run_until_idle()
            elif isinstance(op, W.ChurnOp):
                churns.append(_FsChurn(
                    op=op, namespace=f"churn-{len(churns)}", remote=remote,
                    bulk=bulk,
                ))
            elif isinstance(op, W.DeletePodsOp):
                deleters.append(_FsDeleter(
                    keys=list(created_keys_by_ns.get(op.namespace, ())),
                    per_second=op.per_second, remote=remote,
                ))
            elif isinstance(op, W.CreatePodsOp):
                count = params[op.count_param]
                template = op.template or case.default_pod_template
                ns = op.namespace or f"namespace-{op_ns_counter}"
                op_ns_counter += 1
                prefix = (
                    f"{'measure' if op.collect_metrics else 'init'}-{op_i}"
                )
                informers.pump()
                if op.collect_metrics:
                    attempts0, cycles0, prom_base = _begin_measured_phase(
                        sched, warmup,
                        [
                            template(f"warmup-{op_i}-{j}", ns)
                            for j in range(min(count, sched.max_batch))
                        ],
                    )
                    requests0 = srv.metrics.total_requests()
                    wire0 = srv.metrics.wire_bytes_total()
                    if sentinel_spike:
                        spike["armed"] = True
                items = []
                for j in range(count):
                    pod = template(f"{prefix}-{ns}-{j}", ns)
                    key = f"{ns}/{pod.name}"
                    created_keys_by_ns.setdefault(ns, []).append(key)
                    created_pods.append(pod)
                    items.append((key, pod))
                _bulk_create(remote, PODS, items, bulk=bulk)
                if op.skip_wait:
                    continue
                done, secs = settle(count, (ns,))
                if op.collect_metrics:
                    measured += done
                    duration += secs
                    # everything the measured phase cost the API plane:
                    # pod creates, informer polls, binds, status patches
                    rpcs_total += srv.metrics.total_requests() - requests0
                    wire_total += srv.metrics.wire_bytes_total() - wire0
        informers.pump()
        sched.dispatcher.sync()
        sched._drain_bind_completions()
        sentinel_report = None
        if sentinel_obj is not None:
            sentinel_report = _sentinel_settle(
                sentinel_obj,
                spike if spike["end_wall"] is not None else None,
            )
    finally:
        if fanout is not None:
            fanout.stop()
        telemetry_stats = None
        for exp in exporters:
            exp.close()         # final flush so span totals are complete
        if coll_srv is not None:
            col = coll_srv.collector
            telemetry_stats = {
                "spans": col.spans_total,
                "spans_dropped": col.spans_dropped,
                "processes": len(col.summary()["processes"]),
            }
            coll_srv.close()
        sched.close()
        srv.close()

    lat = measured_p99_ms(sched, prom_base)
    artifacts: dict[str, str] = {}
    if artifacts_dir is not None:
        artifacts = dump_diagnosis_artifacts(
            sched, artifacts_dir,
            f"{case.name}_{workload.name}_{engine}_fullstack",
        )
    throughput = measured / duration if duration > 0 else 0.0
    traffic = _device_traffic_stats(sched, cycles0, duration)
    return WorkloadResult(
        case_name=case.name,
        workload_name=workload.name + "_fullstack",
        threshold=workload.threshold,
        threshold_note=workload.threshold_note,
        **traffic,
        **_encode_stats(sched, cycles0),
        **_dispatcher_stats(sched),
        **_mesh_stats(sched),
        **_staged_and_soak(sched, prom_base),
        **_packing_stats(sched, cycles0, client.bound_pairs, created_pods),
        rpcs_per_scheduled_pod=(
            rpcs_total / measured if measured else None
        ),
        wire_codec=remote.wire_codec,
        wire_bytes_per_pod=(
            wire_total / measured if measured else None
        ),
        watch_fanout=watch_fanout,
        measure_pods=sum(
            params[op.count_param]
            for op in case.ops
            if isinstance(op, W.CreatePodsOp) and op.collect_metrics
        ),
        scheduled=measured,
        duration_s=duration,
        throughput=throughput,
        vs_threshold=(
            throughput / workload.threshold if workload.threshold else None
        ),
        attempts=sched.metrics.schedule_attempts - attempts0,
        cycles=sched.metrics.cycles - cycles0,
        p99_attempt_latency_ms=lat,
        telemetry=telemetry_stats,
        sentinel=sentinel_report,
        metrics_snapshot=sched.metrics.prom.snapshot(baseline=prom_base),
        artifacts=artifacts,
    )


def run_workload_federated(
    case: W.TestCase | str,
    workload: W.Workload | str,
    replicas: int = 2,
    partition: str = "race",
    profile: C.Profile | None = None,
    max_batch: int = 1024,
    timeout_s: float = 1800.0,
    engine: str = "greedy",
    stall_s: float = 15.0,
    warmup: bool = True,
    bulk: bool = True,
    flight_recorder: bool = True,
    partitions: int | None = None,
    kill_replica_at: float | None = None,
) -> WorkloadResult:
    """The fullstack measurement under ACTIVE-ACTIVE FEDERATION: N full
    scheduler replicas (each with its own RemoteStore connection, informer
    bundle and dispatcher) race one in-process REST apiserver, each on its
    own loop thread — the ``--replicas N --partition hash|race|lease``
    deployment mode (sched.federation). ``replicas=1`` is the scaling
    ladder's baseline (one scheduler through the identical harness).

    ``kill_replica_at`` (0..1): when that fraction of the measured pods
    has bound, the highest-index replica is killed mid-bench; the
    measurement then ALSO reports ``recovery_s`` — kill → every remaining
    pod bound by the survivors (the dead replica's partition re-absorbed).

    Reported federation evidence: ``conflicts`` / ``conflict_rate``
    (CAS-bind 409 losses + fenced stale-owner binds over all bind
    attempts), ``binding_parity`` (store-verified count of measured pods
    bound exactly once — the CAS store makes twice impossible, so parity
    == measure_pods means none lost either), and ``lease_transitions``.
    Supports the createNodes/createNamespaces/createPods/barrier op set
    (SchedulingBasic's shape); richer ops raise."""
    import threading as _threading

    from ..apiserver import APIServer, RemoteStore
    from ..client import StoreClient
    from ..client.informers import NAMESPACES, NODES, PODS
    from ..sched.federation import SchedulerFederation

    if isinstance(case, str):
        case = W.TEST_CASES[case]
    if isinstance(workload, str):
        workload = next(w for w in case.workloads if w.name == workload)
    params = dict(workload.params)
    supported = (
        W.CreateNodesOp, W.CreateNamespacesOp, W.CreatePodsOp, W.BarrierOp,
    )
    for op in case.ops:
        if not isinstance(op, supported):
            raise NotImplementedError(
                f"federated mode does not drive {type(op).__name__}"
            )

    srv = APIServer().start()
    admin = RemoteStore(srv.url)

    # one bound-count board shared by every replica's client: the monitor
    # thread reads it, dispatcher worker threads of N replicas write it
    board_lock = _threading.Lock()
    bound_by_ns: dict[str, int] = {}

    class _BoardClient(StoreClient):
        def bind(self, pod, node_name) -> None:
            super().bind(pod, node_name)
            with board_lock:
                bound_by_ns[pod.namespace] = (
                    bound_by_ns.get(pod.namespace, 0) + 1
                )

        def bulk_bind(self, pairs) -> list:
            errs = super().bulk_bind(pairs)
            with board_lock:
                for (pod, _node), err in zip(pairs, errs):
                    if err is None:
                        bound_by_ns[pod.namespace] = (
                            bound_by_ns.get(pod.namespace, 0) + 1
                        )
            return errs

    fed = SchedulerFederation(
        lambda i: RemoteStore(srv.url),
        replicas=replicas,
        partition=partition,
        partitions=partitions,
        scheduler_kwargs=dict(
            profile=profile or C.Profile(), max_batch=max_batch,
            engine=engine, bulk=bulk, flight_recorder=flight_recorder,
            feature_gates=(
                dict(case.feature_gates) if case.feature_gates else None
            ),
        ),
        client_factory=lambda s: _BoardClient(s),
        informer_bulk=bulk,
    )

    def bound_now(namespaces: tuple[str, ...]) -> int:
        with board_lock:
            return sum(bound_by_ns.get(ns, 0) for ns in namespaces)

    measured = 0
    duration = 0.0
    requests0 = 0
    rpcs_total = 0
    attempts0 = cycles0 = 0
    recovery_s: float | None = None
    killed = False
    parity: int | None = None
    measure_namespaces: tuple[str, ...] = ()
    op_ns_counter = 0
    stop = _threading.Event()
    threads: list = []

    def settle(
        target: int, namespaces: tuple[str, ...], allow_kill: bool = False,
    ) -> tuple[int, float]:
        """Monitor the shared board until ``target`` pods of
        ``namespaces`` bound (the replica threads do the work), firing the
        mid-bench kill when requested. The kill arms ONLY in the measured
        phase (``allow_kill``) — an init-phase settle must not consume it,
        or recovery would measure the init tail and the whole measured
        phase would run a replica short."""
        nonlocal recovery_s, killed
        start = bound_now(namespaces)
        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        last_progress = t0
        done = 0
        t_kill = None
        kill_at = (
            int(kill_replica_at * target)
            if (kill_replica_at is not None and allow_kill) else None
        )
        while done < target:
            now = time.perf_counter()
            if now > deadline:
                break
            before = done
            done = bound_now(namespaces) - start
            if (
                kill_at is not None and not killed and done >= kill_at
                and len(fed.live()) > 1
            ):
                idx = fed.live()[-1].index
                fed.kill(idx, close=False)
                killed = True
                t_kill = now
            if done > before:
                last_progress = now
            elif now - last_progress > stall_s:
                break
            else:
                time.sleep(0.005)
        t_end = time.perf_counter()
        if t_kill is not None and done >= target:
            recovery_s = t_end - t_kill
        return done, t_end - t0

    try:
        for op_i, op in enumerate(case.ops):
            if isinstance(op, W.CreateNodesOp):
                n = op.count or params[op.count_param]
                factory = op.template or W.node_default
                nodes = [factory(i, op.zones) for i in range(n)]
                _bulk_create(
                    admin, NODES, [(nd.name, nd) for nd in nodes], bulk=bulk,
                )
            elif isinstance(op, W.CreateNamespacesOp):
                n = params[op.count_param] if op.count_param else op.count
                _bulk_create(admin, NAMESPACES, [
                    (f"{op.prefix}-{i}", t.Namespace(
                        name=f"{op.prefix}-{i}", labels=op.labels,
                    ))
                    for i in range(n)
                ], bulk=bulk)
            elif isinstance(op, W.BarrierOp):
                continue   # phases already settle to completion below
            elif isinstance(op, W.CreatePodsOp):
                count = params[op.count_param]
                template = op.template or case.default_pod_template
                ns = op.namespace or f"namespace-{op_ns_counter}"
                op_ns_counter += 1
                prefix = (
                    f"{'measure' if op.collect_metrics else 'init'}-{op_i}"
                )
                if not threads:
                    # first pod op: sync + (optionally) compile every
                    # replica BEFORE its loop thread exists — warmup and
                    # the loop must share the single-owner thread
                    fed.start()
                    for h in fed.live():
                        h.informers.pump()
                        if warmup:
                            h.sched.warmup([
                                template(f"warmup-{op_i}-{j}", ns)
                                for j in range(
                                    min(count, h.sched.max_batch)
                                )
                            ])
                    threads = fed.run_threads(stop)
                if op.collect_metrics:
                    # accumulate: a case may carry several measured ops,
                    # and parity must count every measured namespace
                    measure_namespaces = measure_namespaces + (ns,)
                    attempts0 = sum(
                        h.sched.metrics.schedule_attempts
                        for h in fed.handles
                    )
                    cycles0 = sum(
                        h.sched.metrics.cycles for h in fed.handles
                    )
                    requests0 = srv.metrics.total_requests()
                items = []
                for j in range(count):
                    pod = template(f"{prefix}-{ns}-{j}", ns)
                    items.append((f"{ns}/{pod.name}", pod))
                _bulk_create(admin, PODS, items, bulk=bulk)
                if op.skip_wait:
                    continue
                done, secs = settle(
                    count, (ns,), allow_kill=op.collect_metrics,
                )
                if op.collect_metrics:
                    measured += done
                    duration += secs
                    rpcs_total += srv.metrics.total_requests() - requests0
        # store-verified binding parity: every measured pod bound exactly
        # once (the CAS bind makes twice impossible; parity ==
        # measure_pods means none were lost to a dead replica or a
        # conflict loop either). Inside the try: the server must still be
        # up, and a failed parity read should surface, not mask.
        stop.set()
        for th in threads:
            th.join(timeout=10)
        if measure_namespaces:
            items, _rv = admin.list(PODS)
            parity = sum(
                1 for key, pod in items
                if pod.node_name
                and key.split("/", 1)[0] in measure_namespaces
            )
    finally:
        # teardown runs on EVERY path — an exception mid-ladder must not
        # leak the apiserver thread/socket into the rest of the bench
        stop.set()
        for th in threads:
            th.join(timeout=10)
        for h in fed.handles:
            if not h.alive:
                fed.close_replica(h.index)
        fed.close()
        srv.close()

    throughput = measured / duration if duration > 0 else 0.0
    return WorkloadResult(
        case_name=case.name,
        workload_name=(
            f"{workload.name}_fullstack_{replicas}sched_{partition}"
        ),
        threshold=workload.threshold,
        threshold_note=workload.threshold_note,
        measure_pods=sum(
            params[op.count_param]
            for op in case.ops
            if isinstance(op, W.CreatePodsOp) and op.collect_metrics
        ),
        scheduled=measured,
        duration_s=duration,
        throughput=throughput,
        vs_threshold=(
            throughput / workload.threshold if workload.threshold else None
        ),
        attempts=sum(
            h.sched.metrics.schedule_attempts for h in fed.handles
        ) - attempts0,
        cycles=sum(h.sched.metrics.cycles for h in fed.handles) - cycles0,
        rpcs_per_scheduled_pod=(
            rpcs_total / measured if measured else None
        ),
        flight_recorder=flight_recorder,
        replicas=replicas,
        partition=partition,
        conflicts=fed.conflicts(),
        conflict_rate=fed.conflict_rate(),
        binding_parity=parity,
        lease_transitions=fed.lease_transitions(),
        recovery_s=recovery_s,
    )


def _scrape_metrics(url: str):
    """Parse one component's /metrics scrape (None on any failure — a
    restarting replica mid-scrape must not kill the run; the caller
    reports what it could read)."""
    import urllib.request

    from ..metrics.textparse import parse_prometheus_text

    try:
        with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                    timeout=10) as resp:
            return parse_prometheus_text(resp.read().decode())
    except Exception:
        return None


def _replication_status(url: str, timeout: float = 2.0) -> dict | None:
    """One apiserver's /replication/status page (None on any failure —
    a follower mid-crash or mid-election must not kill the sampler)."""
    import json as _json
    import urllib.request

    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/replication/status", timeout=timeout,
        ) as resp:
            return _json.loads(resp.read().decode())
    except Exception:
        return None


def _sum_samples(parsed, name: str, **labels) -> float:
    """Sum of every sample of family ``name`` whose label set contains
    ``labels`` (a sum() over a PromQL instant selector)."""
    if parsed is None:
        return 0.0
    want = {(k, str(v)) for k, v in labels.items()}
    return sum(
        s.value for s in parsed.samples(name)
        if s.name == name and want <= set(s.labels)
    )


class ParityError(AssertionError):
    """The store-verified exactly-once binding check failed: a measured
    pod is unbound (lost to a dead replica / conflict loop) after the run
    claimed completion. Raised — never just a field — so a lossy mp run
    FAILS its bench stage and benchdiff treats it as a regression."""


def run_workload_multiprocess(
    case: W.TestCase | str,
    workload: W.Workload | str,
    replicas: int = 2,
    apiservers: int = 1,
    partition: str = "race",
    wire: str = "binary",
    engine: str = "greedy",
    max_batch: int = 1024,
    timeout_s: float = 1800.0,
    stall_s: float = 30.0,
    bulk: bool = True,
    persistence: str | None = None,
    telemetry: bool = False,
    watch_fanout: int = 0,
    fanout_procs: int = 0,
    kill_replica_at: float | None = None,
    restart: str = "on-failure:2",
    replication_chain: bool = False,
    child_env: dict | None = None,
) -> WorkloadResult:
    """THE honest deployment shape: apiserver + N scheduler replicas
    (+ optional collector and watch-fanout drivers) as REAL OS processes
    under the launch supervisor (``kubetpu.launch.Cluster``) — no shared
    GIL, components talk ONLY through the apiserver, exactly the
    reference's independent-binaries layer map. The measuring parent
    drives the op list through an admin RemoteStore and observes binding
    progress from the STORE (not from in-process counters it cannot
    have), then joins through ``Supervisor.join`` with the store-verified
    exactly-once parity check — a parity miss raises ``ParityError`` and
    fails the stage, never just a field.

    ``kill_replica_at`` (0..1): at that fraction of the measured pods
    bound, the last replica is SIGKILLed; the supervisor's ``restart``
    policy respawns it (the respawned process re-federates — hash
    re-adopts its rank's backlog via the informer relist, lease
    re-acquires through the shared store) and ``recovery_s`` measures
    kill → every measured pod bound.

    ``apiservers`` > 1 stands up the replicated read plane (1 leader +
    N-1 follower apiservers; the Cluster round-robins the watch fan-out
    drivers over the followers, leaving the leader to its writers) and
    samples each follower's peak replication lag over the measured
    window into ``follower_lag_ms`` / ``follower_lag_records``.
    ``replication_chain`` wires follower i to tail follower i-1 instead
    of the leader; the run records the leader's replication egress bytes
    either way (``leader_replication_bytes``) so the chained-vs-star
    delta is a stage-to-stage comparison, not an inference.

    Evidence scraped over HTTP before shutdown: apiserver request/wire
    deltas for the measured window, per-replica federation conflicts +
    schedule attempts from the diagnostics pages (counters of the
    CURRENTLY live processes — a restarted replica restarts its
    counters; ``restarts`` says when that happened), and per-child peak
    RSS / CPU seconds from the supervisor's /proc sampling.

    Supports the createNodes/createNamespaces/createPods/barrier op set
    (the fullstack SchedulingBasic shape); richer ops raise."""
    import os as _os

    from ..apiserver import RemoteStore
    from ..client.informers import NAMESPACES, NODES, PODS
    from ..launch import Cluster

    if isinstance(case, str):
        case = W.TEST_CASES[case]
    if isinstance(workload, str):
        workload = next(w for w in case.workloads if w.name == workload)
    params = dict(workload.params)
    supported = (
        W.CreateNodesOp, W.CreateNamespacesOp, W.CreatePodsOp, W.BarrierOp,
    )
    for op in case.ops:
        if not isinstance(op, supported):
            raise NotImplementedError(
                f"multi-process mode does not drive {type(op).__name__}"
            )
    if kill_replica_at is not None and replicas < 2:
        raise ValueError("--kill-replica-at requires --replicas >= 2")

    import kubetpu as _pkg

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(
        _pkg.__file__
    )))
    cluster = Cluster(
        replicas=replicas, apiservers=apiservers, partition=partition,
        wire=wire, engine=engine,
        max_batch=max_batch, persistence=persistence,
        telemetry=("collector" if telemetry else "off"),
        fanout_procs=fanout_procs, fanout_watchers=watch_fanout,
        restart=restart, replication_chain=replication_chain,
        env=child_env, cwd=repo_root,
    )
    measured = 0
    duration = 0.0
    measure_target = 0
    recovery_s: float | None = None
    killed = False
    requests0 = wire0 = 0.0
    rpcs_total = wire_total = 0.0
    measure_namespaces: tuple[str, ...] = ()
    op_ns_counter = 0
    # peak follower replication lag over the measured window (the read
    # plane's honesty counter) — sampled from /replication/status at most
    # every ``_LAG_SAMPLE_S`` inside the settle loop
    _LAG_SAMPLE_S = 0.4
    lag_peak: dict[str, float] = {}
    lag_last_sample = [0.0]

    def sample_follower_lag() -> None:
        if apiservers < 2:
            return
        now = time.perf_counter()
        if now - lag_last_sample[0] < _LAG_SAMPLE_S:
            return
        lag_last_sample[0] = now
        for url in cluster.api_urls[1:]:
            st = _replication_status(url)
            if not st:
                continue
            lag_peak["ms"] = max(
                lag_peak.get("ms", 0.0), float(st.get("lagMs") or 0.0)
            )
            lag_peak["records"] = max(
                lag_peak.get("records", 0.0),
                float(st.get("lagRecords") or 0.0),
            )

    cluster.start()
    try:
        admin = RemoteStore(cluster.api_url, wire=wire)

        def bound_now(namespaces: tuple[str, ...]) -> int:
            items, _rv = admin.list(PODS)
            return sum(
                1 for key, pod in items
                if pod.node_name and key.split("/", 1)[0] in namespaces
            )

        def settle(
            target: int, namespaces: tuple[str, ...], start: int,
            allow_kill: bool = False,
        ) -> tuple[int, float]:
            """``start`` is the namespaces' bound count captured BEFORE
            the creating bulk RPCs: the scheduler processes run
            concurrently with the chunked create, so pods from chunk 1
            can already be bound when settle begins — a baseline taken
            here would make ``target`` unreachable and every mp
            throughput row would silently absorb a full stall wait."""
            nonlocal recovery_s, killed
            t0 = time.perf_counter()
            deadline = t0 + timeout_s
            last_progress = t0
            done = 0
            t_kill = None
            kill_at = (
                int(kill_replica_at * target)
                if (kill_replica_at is not None and allow_kill) else None
            )
            while done < target:
                now = time.perf_counter()
                if now > deadline:
                    break
                before = done
                done = bound_now(namespaces) - start
                if kill_at is not None and not killed and done >= kill_at:
                    cluster.kill_replica(len(cluster.schedulers) - 1)
                    killed = True
                    t_kill = time.perf_counter()
                sample_follower_lag()
                if done > before:
                    last_progress = now
                elif now - last_progress > stall_s:
                    break
                else:
                    time.sleep(0.1)
            t_end = time.perf_counter()
            if t_kill is not None and done >= target:
                recovery_s = t_end - t_kill
            return done, t_end - t0

        for op_i, op in enumerate(case.ops):
            if isinstance(op, W.CreateNodesOp):
                n = op.count or params[op.count_param]
                factory = op.template or W.node_default
                nodes = [factory(i, op.zones) for i in range(n)]
                _bulk_create(
                    admin, NODES, [(nd.name, nd) for nd in nodes], bulk=bulk,
                )
            elif isinstance(op, W.CreateNamespacesOp):
                n = params[op.count_param] if op.count_param else op.count
                _bulk_create(admin, NAMESPACES, [
                    (f"{op.prefix}-{i}", t.Namespace(
                        name=f"{op.prefix}-{i}", labels=op.labels,
                    ))
                    for i in range(n)
                ], bulk=bulk)
            elif isinstance(op, W.BarrierOp):
                continue   # phases settle to completion below
            elif isinstance(op, W.CreatePodsOp):
                count = params[op.count_param]
                template = op.template or case.default_pod_template
                ns = op.namespace or f"namespace-{op_ns_counter}"
                op_ns_counter += 1
                prefix = (
                    f"{'measure' if op.collect_metrics else 'init'}-{op_i}"
                )
                if op.collect_metrics:
                    measure_namespaces = measure_namespaces + (ns,)
                    measure_target += count
                    api_metrics = _scrape_metrics(cluster.api_url)
                    requests0 = _sum_samples(
                        api_metrics, "apiserver_request_total"
                    )
                    wire0 = _sum_samples(
                        api_metrics, "apiserver_wire_bytes_total"
                    )
                start = bound_now((ns,))   # BEFORE the creates — see settle
                items = []
                for j in range(count):
                    pod = template(f"{prefix}-{ns}-{j}", ns)
                    items.append((f"{ns}/{pod.name}", pod))
                _bulk_create(admin, PODS, items, bulk=bulk)
                if op.skip_wait:
                    continue
                done, secs = settle(
                    count, (ns,), start, allow_kill=op.collect_metrics,
                )
                if op.collect_metrics:
                    measured += done
                    duration += secs
                    api_metrics = _scrape_metrics(cluster.api_url)
                    rpcs_total += _sum_samples(
                        api_metrics, "apiserver_request_total"
                    ) - requests0
                    wire_total += _sum_samples(
                        api_metrics, "apiserver_wire_bytes_total"
                    ) - wire0

        # federation evidence off the live replicas' diagnostics pages
        # (scraped BEFORE the join stops them)
        conflicts = 0.0
        attempts = 0.0
        lease_transitions = 0.0
        for diag_url in cluster.scheduler_diag_urls():
            parsed = _scrape_metrics(diag_url)
            conflicts += _sum_samples(
                parsed, "scheduler_federation_conflicts_total"
            )
            attempts += _sum_samples(
                parsed, "scheduler_schedule_attempts_total",
                result="scheduled",
            )
            lease_transitions += _sum_samples(
                parsed, "scheduler_federation_lease_transitions_total"
            )
        wire_codec = admin.wire_codec
        n_processes = cluster.n_processes()
        restarts = cluster.supervisor.restarts_total()
        leader_rep_bytes: float | None = None
        if apiservers > 1:
            leader_rep_bytes = _sum_samples(
                _scrape_metrics(cluster.api_url),
                "apiserver_replication_bytes_total",
            )

        parity_read: dict[str, int] = {}

        def verify_parity() -> None:
            """The join contract: store-verified exactly-once binding of
            EVERY measured pod, checked while the apiserver still serves.
            (The CAS bind makes bound-twice impossible, so parity ==
            target means none were lost to a dead replica or a conflict
            loop either.) The count READ from the store is what the
            record carries — never a value derived from the target."""
            parity = bound_now(measure_namespaces)
            parity_read["bound"] = parity
            if parity != measure_target:
                raise ParityError(
                    f"binding parity miss: {parity}/{measure_target} "
                    f"measured pods bound "
                    f"(replicas={replicas}, partition={partition}, "
                    f"killed={killed}, restarts={restarts})"
                )

        cluster.join(verify=verify_parity if measure_namespaces else None)
        child_stats = cluster.supervisor.child_stats()
    finally:
        cluster.shutdown()

    throughput = measured / duration if duration > 0 else 0.0
    return WorkloadResult(
        case_name=case.name,
        workload_name=(
            f"{workload.name}_mp_{replicas}sched_{partition}"
            + (f"_{apiservers}api" if apiservers > 1 else "")
        ),
        threshold=workload.threshold,
        threshold_note=workload.threshold_note,
        measure_pods=measure_target,
        scheduled=measured,
        duration_s=duration,
        throughput=throughput,
        vs_threshold=(
            throughput / workload.threshold if workload.threshold else None
        ),
        attempts=int(attempts),
        cycles=0,
        rpcs_per_scheduled_pod=(
            rpcs_total / measured if measured else None
        ),
        wire_codec=wire_codec,
        wire_bytes_per_pod=(
            wire_total / measured if measured else None
        ),
        watch_fanout=watch_fanout,
        replicas=replicas,
        partition=partition,
        conflicts=int(conflicts),
        conflict_rate=(conflicts / attempts) if attempts else 0.0,
        lease_transitions=int(lease_transitions),
        binding_parity=parity_read.get("bound"),   # the store-READ count
        #                   (join raised ParityError on any miss, so a
        #                    record only exists when it equals the target)
        recovery_s=recovery_s,
        n_processes=n_processes,
        child_stats=child_stats,
        restarts=restarts,
        apiservers=apiservers,
        follower_lag_ms=lag_peak.get("ms"),
        follower_lag_records=(
            int(lag_peak["records"]) if "records" in lag_peak else None
        ),
        replication_chain=replication_chain,
        leader_replication_bytes=leader_rep_bytes,
    )


def run_list_scaling(
    n_nodes: int = 5000,
    relists: int = 8,
    page_limit: int | None = None,
    wire: str = "binary",
    wall_budget_s: float = 120.0,
) -> dict:
    """The read plane's LIST-at-scale evidence (the ``ListScaling_*``
    bench rungs): one apiserver over a store pre-loaded with ``n_nodes``
    nodes, then ``relists`` full paged walks through a RemoteStore — the
    exact informer-relist path (limit/continue pages pinned to one
    snapshot rv, per-page retry budget, serialize-once item bytes).

    Reports the per-relist wall p50/p99 (``list_p99_ms`` is what
    benchdiff gates), the wire bytes and page count per relist off the
    client's relist accounting, the max single page ever shipped, and
    one unpaged-GET wall for the before/after context. Every walk is
    parity-checked against the node count — a paged walk that dropped or
    duplicated a key raises (a correctness failure must fail the stage,
    never land as a slow-but-green number). ``wall_budget_s`` caps the
    stage: a rung that can't finish its relists returns a TRUNCATED but
    parseable record carrying the walks it did complete."""
    from ..apiserver import APIServer, RemoteStore
    from ..client.informers import NODES
    from ..store.memstore import MemStore

    store = MemStore()
    srv = APIServer(store).start()
    try:
        rs = RemoteStore(srv.url, wire=wire)
        limit = rs.LIST_PAGE_LIMIT if page_limit is None else page_limit
        nodes = [W.node_default(i) for i in range(n_nodes)]
        _bulk_create(rs, NODES, [(nd.name, nd) for nd in nodes])

        walls_ms: list[float] = []
        stats0 = dict(rs.relist_stats)
        t0 = time.perf_counter()
        truncated = False
        for _ in range(relists):
            if time.perf_counter() - t0 > wall_budget_s:
                truncated = True
                break
            t_walk = time.perf_counter()
            items, rv = rs.list(NODES, limit=limit)
            walls_ms.append((time.perf_counter() - t_walk) * 1000.0)
            keys = {k for k, _ in items}
            if len(items) != n_nodes or len(keys) != n_nodes:
                raise AssertionError(
                    f"paged walk parity miss: {len(items)} items / "
                    f"{len(keys)} distinct keys over {n_nodes} nodes "
                    f"(rv={rv})"
                )
        done = len(walls_ms)
        pages = rs.relist_stats["pages"] - stats0["pages"]
        total_bytes = rs.relist_stats["bytes"] - stats0["bytes"]
        # snapshot BEFORE the unpaged baseline below — limit=0 rides the
        # same walk accounting as one giant page and would clobber the max
        max_page_bytes = rs.relist_stats["max_page_bytes"]
        unpaged_ms = None
        if not truncated and time.perf_counter() - t0 <= wall_budget_s:
            t_walk = time.perf_counter()
            rs.list(NODES, limit=0)     # the legacy single-GET baseline
            unpaged_ms = (time.perf_counter() - t_walk) * 1000.0
        return {
            "nodes": n_nodes,
            "page_limit": limit,
            "relists": done,
            "list_p50_ms": round_latency_ms(
                float(np.percentile(walls_ms, 50)) if walls_ms else None
            ),
            "list_p99_ms": round_latency_ms(
                float(np.percentile(walls_ms, 99)) if walls_ms else None
            ),
            "pages_per_relist": round(pages / done, 2) if done else None,
            "bytes_per_relist": round(total_bytes / done) if done else None,
            "max_page_bytes": max_page_bytes,
            "unpaged_ms": round_latency_ms(unpaged_ms),
            "wire_codec": rs.wire_codec,
            "parity_ok": True,
            "truncated": truncated,
        }
    finally:
        srv.close()


def run_trace_multiprocess(
    profile,
    replicas: int = 2,
    partition: str = "lease",
    wire: str = "binary",
    engine: str = "greedy",
    max_batch: int = 128,
    timeout_s: float = 600.0,
    stall_s: float = 30.0,
    speed: float = 1.0,
    wall_budget_s: float | None = None,
    handover_at: float | None = 0.5,
    restart: str = "on-failure:2",
    child_env: dict | None = None,
) -> WorkloadResult:
    """Replay a trace profile against the REAL multi-process federation
    (ROADMAP 5b): apiserver + ``replicas`` scheduler processes under the
    launch supervisor, pod arrivals paced by the trace clock through an
    admin RemoteStore, admission latency measured enqueue→bind from the
    STORE's observed bindings (polled over the paged list walk — bind
    timestamps carry up to one poll interval of quantization, well under
    the seconds-scale SLO budgets these records are judged against).

    ``handover_at`` (0..1 of the trace clock, lease/hash modes): at that
    point the LAST scheduler replica is SIGKILLed mid-trace — the
    supervisor's restart policy respawns it and its keyspace rides a
    lease handover — so ``admission_p99_ms`` spans a forced handover,
    which is the record's whole point: the SLO price of losing a
    federated scheduler under live trace load. ``recovery_s`` is
    kill → every live trace pod bound.

    Supports create_pod/delete_pod/add_node/drain_node events (gang
    create_group has no REST kind and needs the in-process seam —
    those profiles raise)."""
    import os as _os

    from ..apiserver import RemoteStore
    from ..client.informers import NODES, PODS
    from ..launch import Cluster

    if isinstance(profile, str):
        profile = W.TRACE_PROFILES[profile]
    events = profile.events()
    unsupported = {e.kind for e in events} - {
        "create_pod", "delete_pod", "add_node", "drain_node",
    }
    if unsupported:
        raise NotImplementedError(
            f"multi-process trace replay does not drive {unsupported}"
        )
    if handover_at is not None and replicas < 2:
        raise ValueError("handover_at requires replicas >= 2")
    trace_len_s = events[-1].at_s if events else 0.0

    import kubetpu as _pkg

    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(
        _pkg.__file__
    )))
    cluster = Cluster(
        replicas=replicas, partition=partition, wire=wire, engine=engine,
        max_batch=max_batch, restart=restart, env=child_env,
        cwd=repo_root,
    )
    cluster.start()
    truncated = False
    killed = False
    t_kill: float | None = None
    recovery_s: float | None = None
    created_at: dict[str, float] = {}
    deleted: set[str] = set()
    bind_time: dict[str, float] = {}
    try:
        admin = RemoteStore(cluster.api_url, wire=wire)
        nodes = [W.node_default(i, profile.zones,
                                getattr(profile, "slices", 0))
                 for i in range(profile.nodes)]
        _bulk_create(admin, NODES, [(nd.name, nd) for nd in nodes])

        _POLL_S = 0.05
        poll_last = [0.0]

        def poll_bound(now: float, force: bool = False) -> int:
            """Stamp bind times for newly-bound trace pods off a store
            list (rides the paged walk). Throttled — the poll is the
            measurement's read load, not a busy loop."""
            if not force and now - poll_last[0] < _POLL_S:
                return 0
            poll_last[0] = now
            items, _rv = admin.list(PODS)
            stamp = time.perf_counter()
            fresh = 0
            for key, pod in items:
                if pod.node_name and key in created_at \
                        and key not in bind_time:
                    bind_time[key] = stamp
                    fresh += 1
            return fresh

        def live_unbound() -> int:
            return sum(
                1 for k in created_at
                if k not in deleted and k not in bind_time
            )

        t0 = time.perf_counter()
        deadline = t0 + timeout_s
        wall_deadline = (
            t0 + wall_budget_s if wall_budget_s is not None else None
        )
        i = 0
        last_progress = t0
        while True:
            now = time.perf_counter()
            if (wall_deadline is not None and now > wall_deadline) \
                    or now > deadline:
                truncated = True
                break
            trace_now = (now - t0) * speed
            fired = 0
            while i < len(events) and events[i].at_s <= trace_now:
                ev = events[i]
                i += 1
                fired += 1
                if ev.kind == "create_pod":
                    key = f"{ev.namespace}/{ev.name}"
                    admin.create(PODS, key, W.build_trace_pod(ev))
                    created_at[key] = time.perf_counter()
                elif ev.kind == "delete_pod":
                    key = f"{ev.namespace}/{ev.name}"
                    deleted.add(key)
                    try:
                        admin.delete(PODS, key)
                    except Exception:
                        pass    # already gone / rebound — the trace goes on
                elif ev.kind == "add_node":
                    admin.create(NODES, ev.name,
                                 make_trace_node(
                                     ev.name, profile.zones,
                                     getattr(profile, "slices", 0)))
                elif ev.kind == "drain_node":
                    try:
                        admin.delete(NODES, ev.name)
                    except Exception:
                        pass
            if (
                handover_at is not None and not killed
                and trace_now >= handover_at * trace_len_s
            ):
                cluster.kill_replica(len(cluster.schedulers) - 1)
                killed = True
                t_kill = time.perf_counter()
            fresh = poll_bound(now)
            progressed = bool(fired or fresh)
            if i >= len(events):
                if live_unbound() == 0:
                    break
                if progressed:
                    last_progress = now
                elif now - last_progress > stall_s:
                    break
                else:
                    time.sleep(0.02)
            elif progressed:
                last_progress = now
            else:
                time.sleep(min(0.02, max(0.0, (
                    events[i].at_s / speed + t0 - now
                ))))
        poll_bound(time.perf_counter(), force=True)
        t_end = time.perf_counter()
        duration = t_end - t0
        unbound = live_unbound()
        if t_kill is not None and unbound == 0:
            recovery_s = t_end - t_kill

        conflicts = 0.0
        attempts = 0.0
        lease_transitions = 0.0
        for diag_url in cluster.scheduler_diag_urls():
            parsed = _scrape_metrics(diag_url)
            conflicts += _sum_samples(
                parsed, "scheduler_federation_conflicts_total"
            )
            attempts += _sum_samples(
                parsed, "scheduler_schedule_attempts_total",
                result="scheduled",
            )
            lease_transitions += _sum_samples(
                parsed, "scheduler_federation_lease_transitions_total"
            )
        wire_codec = admin.wire_codec
        n_processes = cluster.n_processes()
        restarts = cluster.supervisor.restarts_total()

        def verify_parity() -> None:
            if live_unbound():
                raise ParityError(
                    f"binding parity miss: {live_unbound()} live trace "
                    f"pods unbound (replicas={replicas}, "
                    f"partition={partition}, killed={killed}, "
                    f"restarts={restarts})"
                )

        # a clean full replay joins on the strict store-verified parity;
        # a truncated/stalled one records its unbound count honestly via
        # slo_ok=False instead of turning an SLO record into a crash
        cluster.join(
            verify=verify_parity if (not truncated and unbound == 0)
            else None
        )
        child_stats = cluster.supervisor.child_stats()
    finally:
        cluster.shutdown()

    lats = [
        (bind_time[k] - created_at[k]) * 1000.0
        for k in created_at if k in bind_time
    ]
    p50 = float(np.percentile(lats, 50)) if lats else None
    p99 = float(np.percentile(lats, 99)) if lats else None
    throughput = len(lats) / duration if duration > 0 else 0.0
    return WorkloadResult(
        case_name=f"TraceFederation_{profile.name}",
        workload_name=(
            f"{profile.nodes}Nodes_mp_{replicas}sched_{partition}"
        ),
        threshold=None,
        measure_pods=len(created_at),
        scheduled=len(lats),
        duration_s=duration,
        throughput=throughput,
        vs_threshold=None,
        attempts=int(attempts),
        cycles=0,
        wire_codec=wire_codec,
        replicas=replicas,
        partition=partition,
        conflicts=int(conflicts),
        conflict_rate=(conflicts / attempts) if attempts else 0.0,
        lease_transitions=int(lease_transitions),
        binding_parity=len(bind_time),
        recovery_s=recovery_s,
        n_processes=n_processes,
        child_stats=child_stats,
        restarts=restarts,
        admission_p50_ms=p50,
        admission_p99_ms=p99,
        slo_budget_ms=profile.slo_budget_ms,
        slo_ok=(
            p99 is not None and p99 <= profile.slo_budget_ms
            and unbound == 0 and not truncated
        ),
        truncated=truncated,
        trace_stats={
            "profile": profile.name,
            "seed": profile.seed,
            "events": len(events),
            "fired": i,
            "created": len(created_at),
            "deleted": len(deleted),
            "unbound": unbound,
            "samples": len(lats),
            "handover": killed,
            "handover_at_s": (
                round(t_kill - t0, 3) if t_kill is not None else None
            ),
        },
    )


def run_crash_recovery(
    n_nodes: int = 5000,
    n_pods: int = 50000,
    watchers: int = 200,
    bind_frac: float = 0.5,
    wal_fsync: bool = True,
    wal_wire: str = "binary",
    dirpath: str | None = None,
) -> dict:
    """The durable-store recovery bench (ROADMAP item 2's scenario): build
    a 5k-node / 50k-pod cluster in a WAL-backed store (bulk writes — the
    group-commit path), bind ``bind_frac`` of the pods, then CRASH the
    process (the store is abandoned un-closed, exactly what a kill leaves
    behind) and measure:

    - ``recovery_s``: wall time for a fresh store to replay snapshot+tail
      with resourceVersion continuity;
    - ``relist_storm_s``: ``watchers`` reconnecting watchers each taking a
      BOUNDED relist from a pre-crash cursor (the tail events only, off
      the repopulated ring) — plus the 410 full-relist cost one
      compacted-cursor watcher pays, for contrast;
    - ``binding_parity``: store-verified pods bound EXACTLY once after
      recovery (must equal the pre-crash bind count — the exactly-once
      check the federation bench also asserts)."""
    import shutil
    import tempfile

    from ..api.wrappers import make_node, make_pod
    from ..client.informers import NODES, PODS
    from ..store.memstore import MemStore

    own_dir = dirpath is None
    dirpath = dirpath or tempfile.mkdtemp(prefix="kubetpu-wal-bench-")
    try:
        st = MemStore(persistence=dirpath, wal_fsync=wal_fsync,
                      wal_wire=wal_wire)
        t_pop0 = time.perf_counter()
        chunk = 512
        for i in range(0, n_nodes, chunk):
            st.bulk(NODES, [
                {"op": "create", "key": f"node-{j}",
                 "object": make_node(f"node-{j}")}
                for j in range(i, min(i + chunk, n_nodes))
            ])
        for i in range(0, n_pods, chunk):
            st.bulk(PODS, [
                {"op": "create", "key": f"bench/pod-{j}",
                 "object": make_pod(f"pod-{j}", namespace="bench")}
                for j in range(i, min(i + chunk, n_pods))
            ])
        n_bound = int(n_pods * bind_frac)
        for i in range(0, n_bound, chunk):
            keys = [f"bench/pod-{j}" for j in range(i, min(i + chunk, n_bound))]
            gets = st.bulk(PODS, [{"op": "get", "key": k} for k in keys])
            st.bulk(PODS, [
                {"op": "update", "key": k,
                 "object": g["object"].with_node(f"node-{j % n_nodes}"),
                 "expect_rv": g["resourceVersion"]}
                for j, (k, g) in enumerate(zip(keys, gets))
            ])
        populate_s = time.perf_counter() - t_pop0
        pre_rv = st.resource_version
        wal_stats = st.wal_stats()
        # CRASH: abandon the store un-closed — in-memory state dies, the
        # flushed log is what a killed process leaves on disk
        del st

        t0 = time.perf_counter()
        st2 = MemStore(persistence=dirpath, wal_fsync=wal_fsync,
                       wal_wire=wal_wire)
        recovery_s = time.perf_counter() - t0
        info = st2.recovery_info
        assert st2.resource_version == pre_rv, (
            f"rv continuity broken: {st2.resource_version} != {pre_rv}"
        )
        # exactly-once binding parity, store-verified (keys are unique by
        # construction — the CAS store makes bound-twice impossible, so
        # parity == the pre-crash bind count means none lost either)
        parity = sum(
            1 for _k, pod in st2.list(PODS)[0] if pod.node_name
        )
        # hard gate, like the rv assert above: a recovery that loses
        # bindings must FAIL the stage (benchdiff treats an errored
        # metric as a regression), never emit a green line with
        # parity_ok=false that nothing gates on
        assert parity == n_bound, (
            f"binding parity broken after recovery: {parity} != {n_bound}"
        )
        # the relist storm: every reconnecting watcher resumes from a
        # pre-crash cursor inside the replayed tail — a BOUNDED relist
        cursor = max(info.snapshot_rv, pre_rv - 1000)
        t1 = time.perf_counter()
        delivered = 0
        for _ in range(watchers):
            events, _cur = st2._events_since(PODS, cursor)
            delivered += len(events)
        relist_storm_s = time.perf_counter() - t1
        # contrast: what ONE watcher whose cursor predates the compaction
        # horizon pays after its 410 — a full list of the bucket
        t2 = time.perf_counter()
        full_items, _rv = st2.list(PODS)
        full_relist_s = time.perf_counter() - t2
        st2.close()
        return {
            "n_nodes": n_nodes,
            "n_pods": n_pods,
            "bound": n_bound,
            "binding_parity": parity,
            "parity_ok": parity == n_bound,
            "rv": pre_rv,
            "populate_s": round(populate_s, 3),
            "recovery_s": round(recovery_s, 3),
            "recovered_writes_per_s": round(
                (info.snapshot_objects + info.replayed) / recovery_s, 1
            ) if recovery_s > 0 else None,
            "snapshot_rv": info.snapshot_rv,
            "snapshot_objects": info.snapshot_objects,
            "replayed": info.replayed,
            "truncated_bytes": info.truncated_bytes,
            "watchers": watchers,
            "relist_storm_s": round(relist_storm_s, 4),
            "relist_events_delivered": delivered,
            "full_relist_objects": len(full_items),
            "full_relist_s": round(full_relist_s, 4),
            "wal_fsync": wal_fsync,
            "wal_wire": wal_wire,
            "wal_records": (wal_stats or {}).get("records_appended"),
            "wal_bytes": (wal_stats or {}).get("bytes_appended"),
            "wal_fsyncs": (wal_stats or {}).get("fsyncs"),
        }
    finally:
        if own_dir:
            shutil.rmtree(dirpath, ignore_errors=True)


def run_replicated_failover(
    n_nodes: int = 5000,
    n_pods: int = 50000,
    apiservers: int = 3,
    bind_frac: float = 0.5,
    wire: str = "binary",
    lease_duration_s: float = 0.5,
    timeout_s: float = 300.0,
    serve_timeout_s: float = 60.0,
    child_env: dict | None = None,
) -> dict:
    """The replicated read plane's failover-by-log-position bench — the
    hot-standby answer to ``run_crash_recovery``'s cold restart, on the
    SAME 5k-node / 50k-pod durability shape but with every process REAL
    (1 leader + N-1 follower apiservers under the launch supervisor):

    - drive the write storm (bulk creates + CAS binds of ``bind_frac`` of
      the pods) through the leader over HTTP while a sampler thread reads
      each follower's ``/replication/status`` — the PEAK ``lagMs`` /
      ``lagRecords`` under the storm is ``follower_lag_ms`` /
      ``follower_lag_records`` (the read plane's honesty counter);
    - wait for every follower to catch the leader's rv, then SIGKILL the
      leader (restart policy "never" — nobody respawns it);
    - ``failover_to_serving_s``: kill → a follower won the writer lease
      by log position AND serves a successful full list AND accepts a
      probe write. This is the number the cold ``recovery_s`` wall is
      judged against — a hot standby that already holds the state must
      beat a process that replays the WAL from disk;
    - binding parity, store-verified on the NEW leader: every CAS-bound
      pod bound exactly once across the failover (a miss raises — the
      stage fails, never a green line nothing gates on).

    The lease is tuned short (``lease_duration_s``) so the measurement is
    the protocol — position probe, epoch-fenced CAS — not a lazy lease
    expiry."""
    import os as _os
    import threading as _threading

    import kubetpu as _pkg

    from ..api.wrappers import make_node, make_pod
    from ..apiserver import RemoteStore
    from ..client.informers import NODES, PODS
    from ..launch import Cluster
    from ..store.memstore import bulk_result_error

    if apiservers < 2:
        raise ValueError("failover needs at least one follower apiserver")
    repo_root = _os.path.dirname(_os.path.dirname(_os.path.abspath(
        _pkg.__file__
    )))
    cluster = Cluster(
        replicas=0, apiservers=apiservers, wire=wire,
        lease_duration_s=lease_duration_s, env=child_env, cwd=repo_root,
    )
    lag_peak = {"ms": 0.0, "records": 0}
    samples = [0]
    stop = _threading.Event()

    def _checked_bulk(admin, kind, ops):
        for res in admin.bulk(kind, ops):
            err = bulk_result_error(res)
            if err is not None:
                raise err

    cluster.start()
    try:
        leader_url = cluster.api_url
        follower_urls = list(cluster.api_urls[1:])

        def _sampler() -> None:
            while not stop.wait(0.3):
                for u in follower_urls:
                    st = _replication_status(u)
                    if not st:
                        continue
                    samples[0] += 1
                    lag_peak["ms"] = max(
                        lag_peak["ms"], float(st.get("lagMs") or 0.0)
                    )
                    lag_peak["records"] = max(
                        lag_peak["records"],
                        int(st.get("lagRecords") or 0),
                    )

        sampler = _threading.Thread(target=_sampler, daemon=True)
        sampler.start()
        admin = RemoteStore(leader_url, wire=wire)
        # ---- the write storm: the durability shape, through the leader
        chunk = 512
        t_pop0 = time.perf_counter()
        for i in range(0, n_nodes, chunk):
            _checked_bulk(admin, NODES, [
                {"op": "create", "key": f"node-{j}",
                 "object": make_node(f"node-{j}")}
                for j in range(i, min(i + chunk, n_nodes))
            ])
        for i in range(0, n_pods, chunk):
            _checked_bulk(admin, PODS, [
                {"op": "create", "key": f"bench/pod-{j}",
                 "object": make_pod(f"pod-{j}", namespace="bench")}
                for j in range(i, min(i + chunk, n_pods))
            ])
        n_bound = int(n_pods * bind_frac)
        for i in range(0, n_bound, chunk):
            keys = [
                f"bench/pod-{j}" for j in range(i, min(i + chunk, n_bound))
            ]
            gets = admin.bulk(PODS, [{"op": "get", "key": k} for k in keys])
            _checked_bulk(admin, PODS, [
                {"op": "update", "key": k,
                 "object": g["object"].with_node(
                     f"node-{int(k.rsplit('-', 1)[1]) % n_nodes}"
                 ),
                 "expect_rv": g["resourceVersion"]}
                for k, g in zip(keys, gets)
            ])
        populate_s = time.perf_counter() - t_pop0
        pre_rv = int(
            (_replication_status(leader_url) or {}).get("resourceVersion")
            or 0
        )
        if pre_rv <= 0:
            raise RuntimeError("leader /replication/status unreadable")
        # ---- every follower caught up: the failover measures the
        # protocol, not residual shipping
        t_catch0 = time.perf_counter()
        deadline = t_catch0 + timeout_s
        while True:
            rvs = [
                int((_replication_status(u) or {}).get("resourceVersion")
                    or 0)
                for u in follower_urls
            ]
            if all(rv >= pre_rv for rv in rvs):
                break
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"followers never caught rv {pre_rv}: {rvs}"
                )
            time.sleep(0.05)
        catch_up_s = time.perf_counter() - t_catch0
        stop.set()
        sampler.join(timeout=5)
        # the bound set, read from the READ plane (a follower), pre-kill
        items, _rv = RemoteStore(follower_urls[0], wire=wire).list(PODS)
        pre_bound = sum(1 for _k, pod in items if pod.node_name)
        assert pre_bound == n_bound, (
            f"follower read plane lost binds pre-kill: "
            f"{pre_bound} != {n_bound}"
        )
        # ---- SIGKILL the leader; measure kill -> a follower SERVES
        cluster.supervisor.kill("apiserver")
        t0 = time.perf_counter()
        serve_deadline = t0 + serve_timeout_s
        new_leader = None
        while time.perf_counter() < serve_deadline and new_leader is None:
            for u in follower_urls:
                st = _replication_status(u)
                if st and st.get("role") == "leader":
                    new_leader = u
                    break
            if new_leader is None:
                time.sleep(0.02)
        if new_leader is None:
            raise RuntimeError(
                f"no follower promoted within {serve_timeout_s}s"
            )
        elected_s = time.perf_counter() - t0
        admin2 = RemoteStore(new_leader, wire=wire)
        post_bound = -1
        post_rv = 0
        while time.perf_counter() < serve_deadline:
            try:
                items2, post_rv = admin2.list(PODS)
                post_bound = sum(
                    1 for _k, pod in items2 if pod.node_name
                )
                break
            except Exception:
                time.sleep(0.02)
        probe_ok = False
        attempt = 0
        while time.perf_counter() < serve_deadline and not probe_ok:
            try:
                admin2.create(
                    PODS, f"failover/probe-{attempt}",
                    make_pod(f"probe-{attempt}", namespace="failover"),
                )
                probe_ok = True
            except Exception:
                attempt += 1
                time.sleep(0.02)
        failover_to_serving_s = time.perf_counter() - t0
        if not probe_ok:
            raise RuntimeError(
                f"new leader {new_leader} never accepted the probe write "
                f"within {serve_timeout_s}s"
            )
        # hard gates, run_crash_recovery-style: a failover that lost
        # bindings or rv continuity FAILS the stage
        assert post_bound == n_bound, (
            f"binding parity broken across failover: "
            f"{post_bound} != {n_bound}"
        )
        assert post_rv >= pre_rv, (
            f"rv continuity broken across failover: "
            f"{post_rv} < {pre_rv}"
        )
        # the epoch fence lands at the lease CAS, which completes just
        # after the role flip that let the probe through — wait briefly
        # so the record carries the fenced epoch, without gating the
        # serving wall on it
        new_st = _replication_status(new_leader) or {}
        fence_deadline = time.perf_counter() + 5.0
        while (
            not new_st.get("promotions")
            and time.perf_counter() < fence_deadline
        ):
            time.sleep(0.05)
            new_st = _replication_status(new_leader) or new_st
        return {
            "n_nodes": n_nodes,
            "n_pods": n_pods,
            "apiservers": apiservers,
            "bound": n_bound,
            "binding_parity": post_bound,
            "parity_ok": post_bound == n_bound,
            "rv": pre_rv,
            "new_leader_rv": post_rv,
            "populate_s": round(populate_s, 3),
            "catch_up_s": round(catch_up_s, 3),
            "elected_s": round(elected_s, 3),
            "failover_to_serving_s": round(failover_to_serving_s, 3),
            "follower_lag_ms": round(lag_peak["ms"], 3),
            "follower_lag_records": lag_peak["records"],
            "lag_samples": samples[0],
            "lease_duration_s": lease_duration_s,
            "epoch": new_st.get("epoch"),
            "promotions": new_st.get("promotions"),
        }
    finally:
        stop.set()
        cluster.shutdown()


def run_wal_overhead(
    n_writes: int = 20000,
    chunk: int = 256,
    wal_fsync: bool = True,
    wal_wire: str = "binary",
) -> dict:
    """Steady-state WAL cost: the SAME bulk create+bind write sequence
    against a persistent store and a memory-only one; the throughput
    ratio (and ``wal_overhead_frac``) is the price of durability —
    benchdiff-gated so a WAL hot-path regression trips CI."""
    import shutil
    import tempfile

    from ..api.wrappers import make_pod
    from ..client.informers import PODS
    from ..store.memstore import MemStore

    def drive(store) -> float:
        t0 = time.perf_counter()
        for i in range(0, n_writes, chunk):
            keys = [f"ns/p-{j}" for j in range(i, min(i + chunk, n_writes))]
            store.bulk(PODS, [
                {"op": "create", "key": k,
                 "object": make_pod(k.split("/", 1)[1], namespace="ns")}
                for k in keys
            ])
            gets = store.bulk(PODS, [{"op": "get", "key": k} for k in keys])
            store.bulk(PODS, [
                {"op": "update", "key": k,
                 "object": g["object"].with_node("node-0"),
                 "expect_rv": g["resourceVersion"]}
                for k, g in zip(keys, gets)
            ])
        return time.perf_counter() - t0

    dirpath = tempfile.mkdtemp(prefix="kubetpu-wal-bench-")
    try:
        st_on = MemStore(persistence=dirpath, wal_fsync=wal_fsync,
                         wal_wire=wal_wire)
        on_s = drive(st_on)
        stats = st_on.wal_stats()
        st_on.close()
    finally:
        shutil.rmtree(dirpath, ignore_errors=True)
    st_off = MemStore()
    off_s = drive(st_off)
    writes = 2 * n_writes           # one create + one bind per pod
    on_rate = writes / on_s if on_s > 0 else 0.0
    off_rate = writes / off_s if off_s > 0 else 0.0
    return {
        "writes": writes,
        "chunk": chunk,
        "wal_fsync": wal_fsync,
        "wal_wire": wal_wire,
        "on_writes_per_s": round(on_rate, 1),
        "off_writes_per_s": round(off_rate, 1),
        "throughput_ratio": round(on_rate / off_rate, 4) if off_rate else None,
        "wal_overhead_frac": (
            round(max(0.0, 1.0 - on_rate / off_rate), 4) if off_rate else None
        ),
        "wal_bytes_per_write": (
            round(stats["bytes_appended"] / writes, 1) if stats else None
        ),
        "wal_fsyncs": stats["fsyncs"] if stats else None,
        # the durability tax's latency shape, not just its throughput
        # cost: p99 of the group-commit fsync (store_wal_fsync_duration_
        # seconds — the same histogram the apiserver's /metrics exposes)
        "fsync_p99_ms": stats["fsync_p99_ms"] if stats else None,
    }


def run_label(label: str = "performance", **kwargs) -> list[WorkloadResult]:
    """Run every workload carrying ``label`` (the reference's label selector,
    e.g. -perf-scheduling-label-filter=performance)."""
    out = []
    for case in W.TEST_CASES.values():
        for wl in case.workloads:
            if label in wl.labels:
                out.append(run_workload(case, wl, **kwargs))
    return out
