"""scheduler_perf analog: op-list workloads driving the real scheduler loop
(test/integration/scheduler_perf)."""

from .runner import (
    WorkloadResult,
    run_label,
    run_workload,
    run_workload_federated,
    run_workload_full_stack,
    run_workload_multiprocess,
    run_workload_trace,
)
from .workloads import (
    TEST_CASES,
    TRACE_PROFILES,
    TestCase,
    TraceProfile,
    Workload,
)

__all__ = [
    "TEST_CASES",
    "TRACE_PROFILES",
    "TestCase",
    "TraceProfile",
    "Workload",
    "WorkloadResult",
    "run_label",
    "run_workload",
    "run_workload_federated",
    "run_workload_full_stack",
    "run_workload_multiprocess",
    "run_workload_trace",
]
