"""scheduler_perf analog: op-list workloads driving the real scheduler loop
(test/integration/scheduler_perf)."""

from .runner import (
    WorkloadResult,
    run_label,
    run_workload,
    run_workload_federated,
    run_workload_full_stack,
    run_workload_multiprocess,
)
from .workloads import TEST_CASES, TestCase, Workload

__all__ = [
    "TEST_CASES",
    "TestCase",
    "Workload",
    "WorkloadResult",
    "run_label",
    "run_workload",
    "run_workload_federated",
    "run_workload_full_stack",
    "run_workload_multiprocess",
]
