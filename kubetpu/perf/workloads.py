"""scheduler_perf workload definitions — op lists + object templates.

Mirrors the reference harness's shape
(test/integration/scheduler_perf/scheduler_perf.go:756
RunBenchmarkPerfScheduling; ops in operations.go; per-topic
performance-config.yaml files): a *test case* is an op-list template
(createNodes/createNamespaces/createPods/churn/barrier) plus named
*workloads* binding the ``$param`` counts and the SchedulingThroughput
threshold asserted by CI. Templates reproduce the reference's YAML pod/node
templates (test/integration/scheduler_perf/templates/*.yaml) as factory
functions.

The measured metric is the reference's SchedulingThroughput: scheduled pods
per second over the collect-metrics phase (scheduler_perf.go:352-359 selects
``SchedulingThroughput / Average``; util.go:468 throughputCollector).
"""

from __future__ import annotations

import math

from dataclasses import dataclass, field, replace
from typing import Callable, Mapping

import numpy as np

from ..api import types as t
from ..api.wrappers import make_node, make_pod, pod_affinity_term, spread_constraint
from ..state.topology import RACK_KEY, SLICE_KEY

ZONE_KEY = "topology.kubernetes.io/zone"
HOSTNAME_KEY = "kubernetes.io/hostname"

# ---------------------------------------------------------------------------
# object templates (templates/*.yaml analogs)
# ---------------------------------------------------------------------------


def trace_topology_labels(name: str, slices: int) -> dict[str, str]:
    """The ONE rack/TPU-slice label grammar every node generator shares
    (initial fleet, autoscaler wave nodes, tests): a stable crc32 of the
    node name picks the slice — builtin hash() is salted per process,
    which would break the trace determinism contract — and racks group
    four slices each. ``slices <= 0`` means an unlabeled fleet (the
    ``--topology auto`` parity case)."""
    if slices <= 0:
        return {}
    import zlib

    s = zlib.crc32(name.encode()) % slices
    return {SLICE_KEY: f"slice-{s:03d}", RACK_KEY: f"rack-{s // 4:02d}"}


def node_default(
    i: int, zones: tuple[str, ...] = (), slices: int = 0
) -> t.Node:
    """templates/node-default.yaml: 4 cpu / 32Gi / 110 pods, plus the
    labelNodePrepareStrategy zone label (round-robin over ``zones``), the
    kubelet-maintained hostname label, and — when ``slices`` — the shared
    rack/TPU-slice grammar (trace_topology_labels)."""
    name = f"scheduler-perf-{i}"
    labels = {HOSTNAME_KEY: name}
    if zones:
        labels[ZONE_KEY] = zones[i % len(zones)]
    labels.update(trace_topology_labels(name, slices))
    return make_node(
        name, cpu_milli=4000, memory=32 * 1024**3, pods=110, labels=labels
    )


_POD_REQ = dict(cpu_milli=100, memory=500 * 1024**2)  # 100m / 500Mi


def pod_default(name: str, namespace: str) -> t.Pod:
    """templates/pod-default.yaml."""
    return make_pod(name, namespace=namespace, **_POD_REQ)


def pod_with_pod_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-affinity.yaml: color=blue, required zone
    affinity to color=blue across sched-0/sched-1."""
    term = pod_affinity_term(
        ZONE_KEY, match_labels={"color": "blue"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        affinity=t.Affinity(pod_affinity=t.PodAffinity(required=(term,))),
        **_POD_REQ,
    )


def pod_with_pod_anti_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-anti-affinity.yaml: color=green, required
    hostname anti-affinity to color=green."""
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "green"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "green"},
        affinity=t.Affinity(pod_anti_affinity=t.PodAffinity(required=(term,))),
        **_POD_REQ,
    )


def pod_anti_affinity_label_only(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-anti-affinity-label.yaml: carries color=green
    (matching the init pods' anti-affinity) but no constraint of its own."""
    return make_pod(
        name, namespace=namespace, labels={"color": "green"}, **_POD_REQ
    )


def pod_with_preferred_pod_affinity(name: str, namespace: str) -> t.Pod:
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "red"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "red"},
        affinity=t.Affinity(pod_affinity=t.PodAffinity(
            preferred=(t.WeightedPodAffinityTerm(1, term),)
        )),
        **_POD_REQ,
    )


def pod_with_preferred_pod_anti_affinity(name: str, namespace: str) -> t.Pod:
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "yellow"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "yellow"},
        affinity=t.Affinity(pod_anti_affinity=t.PodAffinity(
            preferred=(t.WeightedPodAffinityTerm(1, term),)
        )),
        **_POD_REQ,
    )


def pod_with_topology_spreading(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-topology-spreading.yaml: maxSkew 5 / zone /
    DoNotSchedule over color=blue."""
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        spread=(spread_constraint(
            5, ZONE_KEY,
            when=t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE,
            match_labels={"color": "blue"},
        ),),
        **_POD_REQ,
    )


def pod_with_preferred_topology_spreading(name: str, namespace: str) -> t.Pod:
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        spread=(spread_constraint(
            5, ZONE_KEY,
            when=t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY,
            match_labels={"color": "blue"},
        ),),
        **_POD_REQ,
    )


def pod_with_node_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-node-affinity.yaml: required zone In [zone1,zone2]."""
    from ..api.wrappers import node_affinity_required, req_in

    return make_pod(
        name, namespace=namespace,
        affinity=node_affinity_required(
            t.NodeSelectorTerm(match_expressions=(req_in(ZONE_KEY, "zone1", "zone2"),))
        ),
        **_POD_REQ,
    )


def pod_high_priority_large_cpu(name: str, namespace: str) -> t.Pod:
    """templates/pod-high-priority-large-cpu.yaml: priority 10, 9 cpu."""
    return make_pod(
        name, namespace=namespace, priority=10,
        cpu_milli=9000, memory=500 * 1024**2,
    )


def pod_low_priority(name: str, namespace: str) -> t.Pod:
    """templates/pod-low-priority.yaml: 900m/500Mi, priority 0 — four of
    them fill 3.6 of a node's 4 cpu (the PreemptionAsync setup)."""
    return make_pod(
        name, namespace=namespace, cpu_milli=900, memory=500 * 1024**2,
    )


def pod_high_priority_3cpu(name: str, namespace: str) -> t.Pod:
    """templates/pod-high-priority.yaml: priority 10, 3 cpu — must preempt
    3 of 4 low-priority pods to fit."""
    return make_pod(
        name, namespace=namespace, priority=10,
        cpu_milli=3000, memory=500 * 1024**2,
    )


def light_pod(name: str, namespace: str) -> t.Pod:
    """templates/light-pod.yaml: no resource requests."""
    return make_pod(name, namespace=namespace)


def gated_pod(name: str, namespace: str) -> t.Pod:
    """templates/gated-pod.yaml: held by a scheduling gate forever."""
    return make_pod(name, namespace=namespace, gates=("test.k8s.io/hold",))


def pod_with_label(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-label.yaml: a labeled pod with no constraints of
    its own — exercises the profile's DEFAULT spread constraints path."""
    return make_pod(
        name, namespace=namespace, labels={"foo": "bar"}, **_POD_REQ,
    )


#: the bin-pack workload's deterministic 10-slot size/priority cycle,
#: keyed by the pod's trailing ``-{j}`` index: one 2-cpu latency pod
#: (priority 10), two 1-cpu services (priority 5), three 500m and four
#: 100m batch fillers (priority 0). One full cycle requests 5.9 cpu —
#: ~1.5 of a 4-cpu node when packed tight, but a spreading scorer happily
#: smears it over many part-empty nodes, which is exactly the frontier
#: the PackingComparison ladder measures.
_BINPACK_SLOTS: tuple[tuple[int, int], ...] = (
    (2000, 10),
    (1000, 5), (1000, 5),
    (500, 0), (500, 0), (500, 0),
    (100, 0), (100, 0), (100, 0), (100, 0),
)


def pod_binpack(name: str, namespace: str) -> t.Pod:
    """The skewed-size + priority-tier bin-pack template (PR 19): the
    pod's shape is a pure function of its trailing index, so the workload
    is identical across engines and runs — any nodes-used delta is the
    engine's doing, not the draw's."""
    try:
        j = int(name.rsplit("-", 1)[-1])
    except ValueError:
        j = 0
    cpu, priority = _BINPACK_SLOTS[j % len(_BINPACK_SLOTS)]
    return make_pod(
        name, namespace=namespace, priority=priority,
        cpu_milli=cpu, memory=500 * 1024**2,
    )


def node_with_extended_resource(i: int, zones: tuple[str, ...] = ()) -> t.Node:
    """templates/node-with-extended-resource.yaml: each node advertises ONE
    unit of a PER-NODE-UNIQUE extended resource (foo.com/bar-{i}) — the
    DRA-extended-resource scheduling shape."""
    return make_node(
        f"ext-node-{i}", cpu_milli=4000, memory=32 * 1024**3, pods=110,
        labels={"node-with-extended-resource": "true"},
        extended={f"foo.com/bar-{i}": 1},
    )


@dataclass(frozen=True)
class CreateExtendedResourcePodsOp:
    """createPods with templates/pod-with-extended-resource.yaml: pod i
    requests foo.com/bar-{i}: 1 — each pod fits exactly one node."""

    count_param: str = "measurePods"
    collect_metrics: bool = False
    namespace: str = "test"


DAEMONSET_NODE = "scheduler-perf-node"


def node_with_name(_: int = 0, zones: tuple[str, ...] = ()) -> t.Node:
    """templates/node-with-name.yaml: one named node with a 90000-pod
    allowance — the daemonset / gated cases funnel every pod onto it."""
    return make_node(
        DAEMONSET_NODE, cpu_milli=4000, memory=32 * 1024**3, pods=90000,
        labels={HOSTNAME_KEY: DAEMONSET_NODE},
    )


def daemonset_pod(name: str, namespace: str) -> t.Pod:
    """templates/daemonset-pod.yaml: required node affinity on
    matchFields metadata.name = scheduler-perf-node, no requests."""
    term = t.NodeSelectorTerm(match_fields=(
        t.Requirement("metadata.name", t.Operator.IN, (DAEMONSET_NODE,)),
    ))
    return make_pod(
        name, namespace=namespace,
        affinity=t.Affinity(node_affinity=t.NodeAffinity(
            required=t.NodeSelector(terms=(term,))
        )),
    )


def pod_preferred_anti_affinity_ns_selector(name: str, namespace: str) -> t.Pod:
    """templates/pod-preferred-anti-affinity-ns-selector.yaml: color=green,
    preferred hostname anti-affinity to color=green across namespaces
    labeled team=devops."""
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "green"},
        namespace_selector=t.LabelSelector(match_labels=(("team", "devops"),)),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "green"},
        affinity=t.Affinity(pod_anti_affinity=t.PodAffinity(
            preferred=(t.WeightedPodAffinityTerm(1, term),)
        )),
        **_POD_REQ,
    )


# ---------------------------------------------------------------------------
# op list (operations.go analogs)
# ---------------------------------------------------------------------------

PodTemplate = Callable[[str, str], t.Pod]


@dataclass(frozen=True)
class CreateNodesOp:
    """operations.go:205 createNodesOp (+ labelNodePrepareStrategy).
    ``count`` > 0 overrides ``count_param`` (the YAML ``count:`` form);
    ``template`` overrides the default node factory (nodeTemplatePath)."""

    count_param: str = "initNodes"
    zones: tuple[str, ...] = ()
    count: int = 0
    template: Callable[[int, tuple[str, ...]], t.Node] | None = None


@dataclass(frozen=True)
class CreateNamespacesOp:
    """operations.go createNamespacesOp. ``labels`` models
    namespaceTemplatePath (templates/namespace-with-labels.yaml);
    ``count_param`` overrides ``count`` when set."""

    prefix: str = "sched"
    count: int = 2
    count_param: str = ""
    labels: tuple[tuple[str, str], ...] = ()


@dataclass(frozen=True)
class CreateServiceOp:
    """createAny with a Service template (templates/service.yaml:
    selector foo=bar) — feeds the DefaultSelector for default spread."""

    namespace: str = "service-ns"
    name: str = "service"
    selector: tuple[tuple[str, str], ...] = (("foo", "bar"),)


@dataclass(frozen=True)
class DeletePodsOp:
    """operations.go deletePodsOp: gradually delete the pods previously
    created in ``namespace`` at ``per_second``, while later ops run
    (skipWaitToCompletion) — each delete fires an AssignedPodDelete event
    through the queue."""

    namespace: str
    per_second: int = 50


@dataclass(frozen=True)
class CreatePodSetsOp:
    """operations.go createPodSetsOp: for i in 0..count: createPods into
    namespace ``{prefix}-{i}``."""

    count_param: str = "initNamespaces"
    pods_param: str = "initPodsPerNamespace"
    prefix: str = "init-ns"
    template: PodTemplate | None = None


@dataclass(frozen=True)
class CreatePodsOp:
    """operations.go:295 createPodsOp. ``skip_wait`` = the YAML
    skipWaitToCompletion (gated pods never schedule; don't settle)."""

    count_param: str = "initPods"
    template: PodTemplate | None = None     # None → case default
    collect_metrics: bool = False
    namespace: str | None = None            # None → unique per-op namespace
    skip_wait: bool = False


@dataclass(frozen=True)
class CreatePodGroupsOp:
    """operations.go createAny with a PodGroup template
    (podgroup/gangscheduling/performance-config.yaml:18 + its
    templates/podgroup.yaml: gangs gang-0..gang-(n-1), each with
    minCount = podsPerGroup)."""

    count_param: str = "initPodGroups"
    min_count_param: str = "podsPerGroup"
    prefix: str = "gang"


@dataclass(frozen=True)
class CreateGangPodsOp:
    """createPods with countMultiplierParam (performance-config.yaml:28 +
    templates/gang-pod.yaml): pod i references gang-(i // podsPerGroup);
    100m cpu / 100Mi, like the reference template."""

    count_param: str = "initPodGroups"
    multiplier_param: str = "podsPerGroup"
    prefix: str = "gang"
    collect_metrics: bool = True
    namespace: str = "gang-0"


@dataclass(frozen=True)
class CreatePodsWithPVsOp:
    """createPods with persistentVolumeTemplatePath /
    persistentVolumeClaimTemplatePath (volumes/performance-config.yaml:55
    SchedulingInTreePVs, :142 SchedulingCSIPVs): each pod gets its own
    bound PV+PVC pair (templates/pv-aws.yaml + templates/pvc.yaml —
    ReadOnlyMany, 1Gi, bind-completed)."""

    count_param: str = "measurePods"
    collect_metrics: bool = False
    driver: str = ""                        # CSI driver name ("" = in-tree)
    namespace: str | None = None


def node_with_dra(i: int, zones: tuple[str, ...] = ()) -> t.Node:
    """templates/node-with-dra-test-driver.yaml: a default node named to
    match the driver op's ``nodes: scheduler-perf-dra-*`` selector."""
    name = f"scheduler-perf-dra-{i}"
    return make_node(
        name, cpu_milli=4000, memory=32 * 1024**3, pods=110,
        labels={HOSTNAME_KEY: name},
    )


@dataclass(frozen=True)
class CreateResourceDriverOp:
    """operations.go createResourceDriverOp (dra/performance-config.yaml
    ``createResourceDriver``): publish the DRA driver's DeviceClass plus one
    ResourceSlice with ``maxClaimsPerNodeParam`` devices per node matching
    ``node_prefix`` (the reference's ``nodes: scheduler-perf-dra-*``
    selector; test driver shape: templates/deviceclass.yaml + per-node
    slices)."""

    driver: str = "test-driver.cdi.k8s.io"
    class_name: str = "test-class"
    max_claims_param: str = "maxClaimsPerNode"
    node_prefix: str = "scheduler-perf-dra-"


@dataclass(frozen=True)
class CreateClaimPodsOp:
    """createPods with a ResourceClaimTemplate
    (dra/performance-config.yaml SchedulingWithResourceClaimTemplate:
    templates/resourceclaimtemplate.yaml + pod-with-claim-template.yaml):
    each pod gets its OWN ResourceClaim instance — one request, one device
    of ``class_name`` — exactly what the resourceclaim controller stamps
    from the template."""

    count_param: str = "measurePods"
    class_name: str = "test-class"
    collect_metrics: bool = False
    namespace: str = "dra-test"


@dataclass(frozen=True)
class ChurnOp:
    """operations.go:518 churnOp — create (or recreate) interfering objects
    at an interval while the measured phase runs."""

    mode: str = "create"                    # create | recreate
    template: PodTemplate = pod_high_priority_large_cpu
    interval_ms: int = 500
    number: int = 0                         # recreate pool size (0 = unbounded)


@dataclass(frozen=True)
class BarrierOp:
    """operations.go:574 barrierOp — wait until all created pods scheduled."""


Op = object  # union of the five ops above


@dataclass(frozen=True)
class Workload:
    name: str
    params: Mapping[str, int]
    threshold: float | None = None          # SchedulingThroughput floor
    labels: tuple[str, ...] = ()
    # Documented derivation when ``threshold`` is NOT a verbatim reference
    # floor (the reduced-shape CPU-fallback workloads): how the floor was
    # scaled from the full-shape reference number, so ``vs_baseline`` is
    # never null and never silently flattering
    threshold_note: str = ""


@dataclass(frozen=True)
class TestCase:
    name: str
    ops: tuple
    workloads: tuple[Workload, ...]
    default_pod_template: PodTemplate = pod_default
    source: str = ""                        # reference config citation
    # per-case featureGates block (performance-config.yaml featureGates:)
    feature_gates: tuple[tuple[str, bool], ...] = ()


# ---------------------------------------------------------------------------
# registry — the BASELINE.md rows (thresholds from the reference configs)
# ---------------------------------------------------------------------------

TEST_CASES: dict[str, TestCase] = {}


def _case(tc: TestCase) -> TestCase:
    TEST_CASES[tc.name] = tc
    return tc


_case(TestCase(
    name="SchedulingBasic",
    source="misc/performance-config.yaml:20",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000},
                 threshold=680, threshold_note=(
                     "5k floor kept verbatim: per-pod cost of the linear "
                     "workload is ~flat in node count (the reference "
                     "subsamples via numFeasibleNodesToFind), so its 500-"
                     "node throughput is >= the 5k floor")),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 10000},
                 threshold=680, labels=("performance",)),
        # the wire-protocol fullstack ladder (ROADMAP item 2): 1k/2k/5k
        # nodes driven THROUGH the REST apiserver with heavy watch
        # fan-out — the control-plane-bound shapes the binary codec +
        # native body ring exist for. Thresholds keep the reference 5k
        # floor verbatim (the 500Nodes note: per-pod cost of the linear
        # workload is ~flat in node count).
        Workload("1000Nodes",
                 {"initNodes": 1000, "initPods": 300, "measurePods": 800},
                 threshold=680, threshold_note=(
                     "5k floor kept verbatim: per-pod cost of the linear "
                     "workload is ~flat in node count"),
                 labels=("wire",)),
        Workload("2000Nodes",
                 {"initNodes": 2000, "initPods": 300, "measurePods": 800},
                 threshold=680, threshold_note=(
                     "5k floor kept verbatim: per-pod cost of the linear "
                     "workload is ~flat in node count"),
                 labels=("wire",)),
        Workload("5000Nodes_1000Pods",
                 {"initNodes": 5000, "initPods": 300, "measurePods": 1000},
                 threshold=680, labels=("wire",)),
        # the mesh-sharded tier (ROADMAP item 1): a cluster one chip's HBM
        # and FLOPs can't hold comfortably — run with mesh on/off for the
        # ShardingComparison evidence (the reference config tops out at 5k;
        # the floor is kept verbatim, see the 500Nodes note)
        Workload("15000Nodes",
                 {"initNodes": 15000, "initPods": 1000, "measurePods": 5000},
                 threshold=680, threshold_note=(
                     "no reference row at 15k nodes; the 5k-node floor "
                     "(680) is kept verbatim — per-pod cost of the linear "
                     "workload is ~flat in node count"),
                 labels=("multichip",)),
    ),
))

_case(TestCase(
    name="SchedulingPodAntiAffinity",
    source="affinity/performance-config.yaml:20",
    default_pod_template=pod_with_pod_anti_affinity,
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 100, "measurePods": 400}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=180, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPodMatchingAntiAffinity",
    source="affinity/performance-config.yaml:60",
    default_pod_template=pod_with_pod_anti_affinity,
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", template=pod_anti_affinity_label_only,
                     collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 100, "measurePods": 400}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 5000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPodAffinity",
    source="affinity/performance-config.yaml:96 (threshold 70 — the hardest quadratic workload)",
    default_pod_template=pod_with_pod_affinity,
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000},
                 threshold=700, threshold_note=(
                     "70 pods/s 5k floor x10: the quadratic PreScore cost "
                     "scales ~linearly with node count, so at 1/10 the "
                     "nodes the reference would run ~10x its floor — the "
                     "scaled floor keeps vs_baseline conservative")),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=70, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingNodeAffinity",
    source="affinity/performance-config.yaml SchedulingNodeAffinity",
    default_pod_template=pod_with_node_affinity,
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreatePodsOp("initPods"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000}),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 10000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="TopologySpreading",
    source="topology_spreading/performance-config.yaml:19",
    ops=(
        CreateNodesOp("initNodes", zones=("moon-1", "moon-2", "moon-3")),
        CreatePodsOp("initPods", template=pod_default),
        CreatePodsOp("measurePods", template=pod_with_topology_spreading,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 1000, "measurePods": 1000},
                 threshold=4600, threshold_note=(
                     "460 pods/s 5k floor x10: segment-sum PreScore cost "
                     "scales ~linearly with node count (see "
                     "SchedulingPodAffinity scaling note)")),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=460, labels=("performance",)),
    ),
))

_case(TestCase(
    name="PreferredTopologySpreading",
    source="topology_spreading/performance-config.yaml:64",
    ops=(
        CreateNodesOp("initNodes", zones=("moon-1", "moon-2", "moon-3")),
        CreatePodsOp("initPods", template=pod_default),
        CreatePodsOp("measurePods",
                     template=pod_with_preferred_topology_spreading,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 1000, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=340, labels=("performance",)),
    ),
))

_case(TestCase(
    name="MixedSchedulingBasePod",
    source="affinity/performance-config.yaml MixedSchedulingBasePod",
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreateNamespacesOp("sched", 1),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_pod_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_pod_anti_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_preferred_pod_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_preferred_pod_anti_affinity,
                     namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 200, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 2000, "measurePods": 5000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingInTreePVs",
    source="volumes/performance-config.yaml:55 (threshold 290)",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsWithPVsOp("initPods"),
        CreatePodsWithPVsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "initPods": 5, "measurePods": 10}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=290, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingCSIPVs",
    source="volumes/performance-config.yaml:142 (threshold 100)",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsWithPVsOp("initPods", driver="ebs.csi.aws.com"),
        CreatePodsWithPVsOp("measurePods", driver="ebs.csi.aws.com",
                            collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "initPods": 5, "measurePods": 10}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=100, labels=("performance",)),
    ),
))

_case(TestCase(
    name="GangScheduling",
    source="podgroup/gangscheduling/performance-config.yaml:7 (no thresholds yet — new suite)",
    feature_gates=(("GenericWorkload", True), ("GangScheduling", True)),
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("gang", 1),
        CreatePodGroupsOp("initPodGroups", "podsPerGroup"),
        CreateGangPodsOp("initPodGroups", "podsPerGroup",
                         collect_metrics=True),
    ),
    workloads=(
        Workload("10Nodes_3Gangs",
                 {"initNodes": 10, "initPodGroups": 3, "podsPerGroup": 3}),
        Workload("100Nodes_10Gangs",
                 {"initNodes": 100, "initPodGroups": 10, "podsPerGroup": 3}),
        Workload("5000Nodes_1000Gangs_3000Pods",
                 {"initNodes": 5000, "initPodGroups": 1000, "podsPerGroup": 3},
                 labels=("performance",)),
        Workload("5000Nodes_3Gangs_3000Pods_1000PerGroup",
                 {"initNodes": 5000, "initPodGroups": 3, "podsPerGroup": 1000},
                 labels=("performance",)),
    ),
))

_case(TestCase(
    name="Unschedulable",
    source="misc/performance-config.yaml:252",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods"),
        ChurnOp(mode="create", template=pod_high_priority_large_cpu,
                interval_ms=200),
        CreatePodsOp("measurePods", template=pod_default,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes/10Init/1kPods",
                 {"initNodes": 500, "initPods": 10, "measurePods": 1000}),
        Workload("5kNodes/100Init/10kPods",
                 {"initNodes": 5000, "initPods": 100, "measurePods": 10000},
                 threshold=590, labels=("performance",)),
    ),
))

_case(TestCase(
    name="PreemptionAsync",
    source="misc/performance-config.yaml:186 (threshold 570)",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods", template=pod_low_priority),
        ChurnOp(mode="create", template=pod_high_priority_3cpu,
                interval_ms=200),
        CreatePodsOp("measurePods", template=pod_default,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "initPods": 20, "measurePods": 5}),
        Workload("500Nodes",
                 {"initNodes": 500, "initPods": 2000, "measurePods": 500}),
        Workload("5000Nodes",
                 {"initNodes": 5000, "initPods": 20000, "measurePods": 5000},
                 threshold=570, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingDaemonset",
    source="misc/performance-config.yaml:91 (threshold 1100)",
    default_pod_template=daemonset_pod,
    ops=(
        # one named node receives every pod; the default nodes exist only
        # to be filtered out (the reference's PreFilterResult scenario)
        CreateNodesOp(count=1, template=node_with_name),
        CreateNodesOp("initNodes"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "measurePods": 10}),
        Workload("15000Nodes", {"initNodes": 15000, "measurePods": 30000},
                 threshold=1100, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingWhileGated",
    source="misc/performance-config.yaml:365 (threshold 910)",
    default_pod_template=light_pod,
    ops=(
        CreateNodesOp(count=1, template=node_with_name),
        # pods that stay gated to the end of the test
        CreatePodsOp("gatedPods", template=gated_pod, namespace="gated",
                     skip_wait=True),
        # pods that get scheduled then gradually deleted, generating
        # AssignedPodDelete events the queue must absorb
        CreatePodsOp("deletingPods", namespace="deleting"),
        DeletePodsOp(namespace="deleting", per_second=50),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("1Node_10GatedPods",
                 {"gatedPods": 10, "deletingPods": 10, "measurePods": 10}),
        Workload("1Node_10000GatedPods",
                 {"gatedPods": 10000, "deletingPods": 20000,
                  "measurePods": 20000},
                 threshold=910, labels=("performance",)),
    ),
))

_case(TestCase(
    name="DefaultTopologySpreading",
    source="topology_spreading/performance-config.yaml:104 (threshold 160 at 50k; "
           "a service's selector drives the DEFAULT spread constraints)",
    default_pod_template=pod_with_label,
    ops=(
        CreateNodesOp("initNodes", zones=("moon-1", "moon-2", "moon-3")),
        CreateServiceOp(namespace="service-ns"),
        CreatePodsOp("initPods", template=pod_default),
        CreatePodsOp("measurePods", collect_metrics=True,
                     namespace="service-ns"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 1000, "measurePods": 1000}),
        Workload("5000Nodes_50000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 50000},
                 threshold=160, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPreferredAntiAffinityWithNSSelector",
    source="affinity/performance-config.yaml:391",
    default_pod_template=pod_preferred_anti_affinity_ns_selector,
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("init-ns", count_param="initNamespaces",
                           labels=(("team", "devops"),)),
        CreateNamespacesOp("measure-ns", count=1,
                           labels=(("team", "devops"),)),
        CreatePodSetsOp("initNamespaces", "initPodsPerNamespace",
                        prefix="init-ns"),
        CreatePodsOp("measurePods", collect_metrics=True,
                     namespace="measure-ns-0"),
    ),
    workloads=(
        Workload("10Nodes",
                 {"initNodes": 10, "initPodsPerNamespace": 2,
                  "initNamespaces": 2, "measurePods": 10}),
        Workload("500Nodes",
                 {"initNodes": 500, "initPodsPerNamespace": 4,
                  "initNamespaces": 10, "measurePods": 100},
                 labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingWithExtendedResource",
    source="misc/performance-config.yaml:452 (threshold 180)",
    ops=(
        CreateNodesOp("nodesWithoutExtendedResource"),
        CreateNodesOp("nodesWithExtendedResource",
                      template=node_with_extended_resource),
        CreateExtendedResourcePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("fast", {"nodesWithExtendedResource": 10,
                          "nodesWithoutExtendedResource": 1,
                          "measurePods": 10}),
        Workload("5000pods_5000nodes",
                 {"nodesWithExtendedResource": 5000,
                  "nodesWithoutExtendedResource": 0, "measurePods": 5000},
                 threshold=180, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingWithResourceClaimTemplate",
    source="dra/performance-config.yaml:58 (threshold 56, 'typically above 70')",
    feature_gates=(("DynamicResourceAllocation", True),),
    ops=(
        CreateNodesOp("nodesWithoutDRA"),
        CreateNodesOp("nodesWithDRA", template=node_with_dra),
        CreateResourceDriverOp(),
        CreateClaimPodsOp("initPods", namespace="init"),
        CreateClaimPodsOp("measurePods", collect_metrics=True,
                          namespace="test"),
    ),
    workloads=(
        Workload("fast", {"nodesWithDRA": 1, "nodesWithoutDRA": 1,
                          "initPods": 0, "measurePods": 10,
                          "maxClaimsPerNode": 10}),
        Workload("5000pods_500nodes",
                 {"nodesWithDRA": 500, "nodesWithoutDRA": 0,
                  "initPods": 2500, "measurePods": 2500,
                  "maxClaimsPerNode": 10},
                 threshold=56, labels=("performance",)),
    ),
))

# ---------------------------------------------------------------------------
# Trace-shaped workloads (ROADMAP item 5 / the PR-14 scale frontier)
#
# Uniform createPods op-lists never exercise what Tesserae (2508.04953) and
# "Priority Matters" (2511.08373) judge schedulers on: time-varying,
# multi-tenant load. A *trace* is a seeded, DETERMINISTIC event stream —
# (trace-clock offset, op) tuples the runner replays against the real
# scheduler loop, measuring an admission-latency SLO (p99 enqueue→bind vs a
# declared budget) instead of only steady-state throughput. Four generators:
#
# - diurnal_burst_trace: a sinusoidal diurnal arrival curve with flash-crowd
#   bursts layered on top (queue-wait spikes are the point);
# - node_wave_trace: autoscaler-style node ADD waves that later DRAIN, under
#   a steady pod trickle (exercises the append-incremental encode + scoped
#   cache extension + incremental reshard at scale);
# - rolling_update_trace: delete+create trains over a standing fleet (the
#   informer→invalidate→re-encode path under realistic update storms);
# - multitenant_trace: priority tiers + gangs + spread constraints arriving
#   INTERLEAVED (the mixed-tenant shape single-template cases never hit).
#
# Determinism contract: same (generator, seed, params) → identical event
# tuple, asserted in tier-1 — replay TIMING is wall-clock, the op sequence
# is not.
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TraceEvent:
    """One trace op. ``at_s`` is the trace-clock offset; the runner fires
    every event whose offset has elapsed before each scheduling cycle."""

    at_s: float
    kind: str                   # create_pod|delete_pod|add_node|drain_node|create_group
    name: str = ""
    namespace: str = "trace"
    template: str = "default"   # build_trace_pod dispatch key
    priority: int = 0
    group: str = ""             # scheduling group (gang members)
    min_count: int = 0          # gang quorum (create_group)


_TRACE_REQ = dict(cpu_milli=100, memory=500 * 1024**2)


def build_trace_pod(ev: TraceEvent) -> t.Pod:
    """Materialize a trace create_pod event. Templates are deliberately few
    (controller-stamped workloads share specs — the encode cache's bet):
    ``default`` (pod-default shape), ``tiny`` (no requests), ``spread``
    (zone maxSkew-5 DoNotSchedule over color=blue), ``prio`` (default shape
    carrying the event's priority), ``gang`` (member of ``ev.group``)."""
    if ev.template == "tiny":
        return make_pod(ev.name, namespace=ev.namespace,
                        priority=ev.priority)
    if ev.template == "spread":
        return make_pod(
            ev.name, namespace=ev.namespace, labels={"color": "blue"},
            priority=ev.priority,
            spread=(spread_constraint(
                5, ZONE_KEY,
                when=t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE,
                match_labels={"color": "blue"},
            ),),
            **_TRACE_REQ,
        )
    if ev.template == "gang":
        return make_pod(
            ev.name, namespace=ev.namespace, priority=ev.priority,
            scheduling_group=ev.group, **_TRACE_REQ,
        )
    # "default" / "prio"
    return make_pod(ev.name, namespace=ev.namespace, priority=ev.priority,
                    **_TRACE_REQ)


def _sorted_events(events: list) -> tuple:
    """Stable total order: trace time, then name (ties must not depend on
    generator emit order — determinism is the contract)."""
    return tuple(sorted(events, key=lambda e: (e.at_s, e.kind, e.name)))


def diurnal_burst_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    base_rate: float = 20.0,
    peak_rate: float = 120.0,
    bursts: int = 2,
    burst_pods: int = 150,
    burst_width_s: float = 1.0,
    namespace: str = "trace",
) -> tuple:
    """One diurnal cycle: Poisson arrivals at rate λ(t) = base + (peak −
    base)·½(1 − cos 2πt/T), plus ``bursts`` flash crowds of ``burst_pods``
    each landing inside ``burst_width_s`` at seeded times in the middle
    80% of the trace."""
    rng = np.random.default_rng(seed)
    events: list[TraceEvent] = []
    seq = 0
    for sec in range(int(duration_s)):
        lam = base_rate + (peak_rate - base_rate) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * sec / duration_s)
        )
        n = int(rng.poisson(lam))
        for k in range(n):
            events.append(TraceEvent(
                at_s=sec + (k + 0.5) / (n + 1), kind="create_pod",
                name=f"d-{seq}", namespace=namespace,
            ))
            seq += 1
    starts = np.sort(rng.uniform(
        0.1 * duration_s, 0.9 * duration_s, size=bursts
    ))
    for b, t0 in enumerate(starts):
        for k in range(burst_pods):
            events.append(TraceEvent(
                at_s=float(t0) + burst_width_s * k / max(burst_pods, 1),
                kind="create_pod", name=f"burst-{b}-{k}",
                namespace=namespace,
            ))
    return _sorted_events(events)


def node_wave_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    pod_rate: float = 40.0,
    waves: int = 2,
    wave_nodes: int = 64,
    ramp_s: float = 2.0,
    drain: bool = True,
    namespace: str = "trace",
) -> tuple:
    """Steady pod trickle at ``pod_rate`` (uniform spacing — the wave is the
    variable, not the arrivals) + ``waves`` autoscaler waves: each adds
    ``wave_nodes`` nodes spread over ``ramp_s``, and — when ``drain`` —
    deletes them again in the trace's final quarter. Wave k's nodes are
    named ``wave-{k}-{i}`` so shape tests (and the drain) can address
    them."""
    rng = np.random.default_rng(seed)
    events: list[TraceEvent] = []
    total_pods = int(duration_s * pod_rate)
    for j in range(total_pods):
        events.append(TraceEvent(
            at_s=j / pod_rate, kind="create_pod", name=f"w-{j}",
            namespace=namespace,
        ))
    # wave starts inside the first half so their capacity matters to the
    # trailing arrivals; jittered but seeded
    starts = np.sort(rng.uniform(
        0.1 * duration_s, 0.5 * duration_s, size=waves
    ))
    for w, t0 in enumerate(starts):
        for i in range(wave_nodes):
            events.append(TraceEvent(
                at_s=float(t0) + ramp_s * i / max(wave_nodes, 1),
                kind="add_node", name=f"wave-{w}-{i}",
            ))
        if drain:
            t_drain = 0.75 * duration_s + w
            for i in range(wave_nodes):
                events.append(TraceEvent(
                    at_s=t_drain + ramp_s * i / max(wave_nodes, 1),
                    kind="drain_node", name=f"wave-{w}-{i}",
                ))
    return _sorted_events(events)


def rolling_update_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    fleet: int = 200,
    trains: int = 4,
    train_size: int = 50,
    namespace: str = "trace",
) -> tuple:
    """A standing fleet of ``fleet`` pods (created over the first second),
    then ``trains`` rolling-update trains: train k deletes ``train_size``
    pods (round-robin over the fleet) and recreates them at the next
    version — the delete+create storm a Deployment rollout feeds the
    scheduler."""
    rng = np.random.default_rng(seed)
    events: list[TraceEvent] = []
    version = [0] * fleet
    for i in range(fleet):
        events.append(TraceEvent(
            at_s=i / max(fleet, 1), kind="create_pod",
            name=f"roll-{i}-v0", namespace=namespace,
        ))
    # trains fire between 20% and 90% of the trace, jittered but seeded
    starts = np.sort(rng.uniform(
        0.2 * duration_s, 0.9 * duration_s, size=trains
    ))
    for k, t0 in enumerate(starts):
        for j in range(train_size):
            i = (k * train_size + j) % fleet
            v = version[i]
            at = float(t0) + j * 0.01
            events.append(TraceEvent(
                at_s=at, kind="delete_pod", name=f"roll-{i}-v{v}",
                namespace=namespace,
            ))
            events.append(TraceEvent(
                at_s=at + 0.005, kind="create_pod",
                name=f"roll-{i}-v{v + 1}", namespace=namespace,
            ))
            version[i] = v + 1
    return _sorted_events(events)


def multitenant_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    rate: float = 40.0,
    gangs: int = 6,
    gang_size: int = 4,
    namespace: str = "trace",
) -> tuple:
    """The mixed-tenant profile: arrivals at ``rate`` are drawn (seeded)
    from three tenant classes — latency-sensitive high-priority pods
    (priority 10), batch pods (priority 0), and spread-constrained service
    pods — while ``gangs`` gang groups (quorum ``gang_size``) arrive at
    seeded times with their members trickling in. Priority tiers, gangs
    and spread constraints are live SIMULTANEOUSLY, which is the point."""
    rng = np.random.default_rng(seed)
    events: list[TraceEvent] = []
    total = int(duration_s * rate)
    classes = rng.choice(3, size=total, p=(0.3, 0.5, 0.2))
    for j in range(total):
        at = j / rate
        cls = int(classes[j])
        if cls == 0:
            events.append(TraceEvent(
                at_s=at, kind="create_pod", name=f"hi-{j}",
                namespace=namespace, template="prio", priority=10,
            ))
        elif cls == 1:
            events.append(TraceEvent(
                at_s=at, kind="create_pod", name=f"batch-{j}",
                namespace=namespace,
            ))
        else:
            events.append(TraceEvent(
                at_s=at, kind="create_pod", name=f"svc-{j}",
                namespace=namespace, template="spread",
            ))
    starts = np.sort(rng.uniform(
        0.1 * duration_s, 0.8 * duration_s, size=gangs
    ))
    for g, t0 in enumerate(starts):
        events.append(TraceEvent(
            at_s=float(t0), kind="create_group", name=f"gang-{g}",
            namespace=namespace, min_count=gang_size,
        ))
        for m in range(gang_size):
            events.append(TraceEvent(
                at_s=float(t0) + 0.05 * (m + 1), kind="create_pod",
                name=f"gang-{g}-m{m}", namespace=namespace,
                template="gang", priority=5, group=f"gang-{g}",
            ))
    return _sorted_events(events)


def train_serve_churn_trace(
    seed: int = 0,
    duration_s: float = 30.0,
    serve_rate: float = 30.0,
    gangs: int = 8,
    gang_size: int = 4,
    gang_lifetime_s: float = 8.0,
    churn: float = 0.3,
    namespace: str = "trace",
) -> tuple:
    """Mixed train+serve churn: latency-sensitive SERVE pods arrive at
    ``serve_rate`` (a seeded fraction ``churn`` of them is deleted a few
    seconds later — rolling serve churn), while TRAIN gangs (quorum
    ``gang_size``) arrive at seeded times and DEPART ``gang_lifetime_s``
    later, members deleted. On a sliced fleet the scheduling question is
    whether departed train gangs leave their slices FULLY free at steady
    state, or scattered serve pods keep every slice partially occupied —
    the fragmentation-over-time evidence."""
    rng = np.random.default_rng(seed)
    events: list[TraceEvent] = []
    total = int(duration_s * serve_rate)
    kill = rng.random(total) < churn
    lifetimes = rng.uniform(2.0, 6.0, size=total)
    for j in range(total):
        at = j / serve_rate
        events.append(TraceEvent(
            at_s=at, kind="create_pod", name=f"serve-{j}",
            namespace=namespace, template="prio", priority=8,
        ))
        if kill[j]:
            events.append(TraceEvent(
                at_s=min(at + float(lifetimes[j]), 0.95 * duration_s),
                kind="delete_pod", name=f"serve-{j}", namespace=namespace,
            ))
    starts = np.sort(rng.uniform(
        0.1 * duration_s, 0.6 * duration_s, size=gangs
    ))
    for g, t0 in enumerate(starts):
        events.append(TraceEvent(
            at_s=float(t0), kind="create_group", name=f"train-{g}",
            namespace=namespace, min_count=gang_size,
        ))
        for m in range(gang_size):
            events.append(TraceEvent(
                at_s=float(t0) + 0.05 * (m + 1), kind="create_pod",
                name=f"train-{g}-m{m}", namespace=namespace,
                template="gang", priority=5, group=f"train-{g}",
            ))
            end = float(t0) + gang_lifetime_s + 0.05 * m
            if end < 0.9 * duration_s:
                events.append(TraceEvent(
                    at_s=end, kind="delete_pod", name=f"train-{g}-m{m}",
                    namespace=namespace,
                ))
    return _sorted_events(events)


@dataclass(frozen=True)
class TraceProfile:
    """A named trace shape: generator + params + initial cluster size +
    the admission SLO budget its record is judged against. ``events()`` is
    the deterministic op sequence; ``scaled()`` derives bench rungs (the
    50k/100k ladder) without re-declaring the shape. ``slices > 0`` stamps
    every node (initial fleet AND wave nodes — one grammar,
    trace_topology_labels) with rack/TPU-slice labels so the scenario can
    run with the node-topology axis engaged."""

    name: str
    gen: Callable[..., tuple]
    params: Mapping
    nodes: int
    slo_budget_ms: float
    seed: int = 0
    zones: tuple[str, ...] = ("zone-a", "zone-b", "zone-c")
    slices: int = 0
    description: str = ""

    def events(self) -> tuple:
        return self.gen(seed=self.seed, **dict(self.params))

    def scaled(self, suffix: str, nodes: int | None = None,
               slo_budget_ms: float | None = None, **param_overrides
               ) -> "TraceProfile":
        params = dict(self.params)
        params.update(param_overrides)
        return replace(
            self,
            name=f"{self.name}-{suffix}",
            params=params,
            nodes=nodes if nodes is not None else self.nodes,
            slo_budget_ms=(
                slo_budget_ms if slo_budget_ms is not None
                else self.slo_budget_ms
            ),
        )


TRACE_PROFILES: dict[str, TraceProfile] = {}


def _trace(p: TraceProfile) -> TraceProfile:
    TRACE_PROFILES[p.name] = p
    return p


_trace(TraceProfile(
    name="diurnal-burst",
    gen=diurnal_burst_trace,
    params=dict(duration_s=30.0, base_rate=20.0, peak_rate=120.0,
                bursts=2, burst_pods=150),
    nodes=5000,
    slo_budget_ms=4000.0,
    description="sinusoidal diurnal arrivals + flash-crowd bursts "
                "(flash-crowd admission p99 vs budget)",
))

_trace(TraceProfile(
    name="node-wave",
    gen=node_wave_trace,
    params=dict(duration_s=30.0, pod_rate=40.0, waves=2, wave_nodes=64,
                ramp_s=2.0),
    nodes=5000,
    slo_budget_ms=3000.0,
    description="autoscaler add/drain node waves under a steady pod "
                "trickle (incremental reshard + scoped cache extension)",
))

_trace(TraceProfile(
    name="rolling-update",
    gen=rolling_update_trace,
    params=dict(duration_s=30.0, fleet=200, trains=4, train_size=50),
    nodes=2000,
    slo_budget_ms=3000.0,
    description="delete+create trains over a standing fleet "
                "(rollout storms through the informer path)",
))

_trace(TraceProfile(
    name="multitenant",
    gen=multitenant_trace,
    params=dict(duration_s=30.0, rate=40.0, gangs=6, gang_size=4),
    nodes=2000,
    slo_budget_ms=5000.0,
    slices=32,
    description="priority tiers + gangs + spread constraints interleaved "
                "(the mixed-tenant admission shape) on a sliced fleet",
))

_trace(TraceProfile(
    name="train-serve-churn",
    gen=train_serve_churn_trace,
    params=dict(duration_s=30.0, serve_rate=30.0, gangs=8, gang_size=4,
                gang_lifetime_s=8.0, churn=0.3),
    nodes=512,
    slo_budget_ms=5000.0,
    slices=16,
    description="mixed train gangs + serve churn on a sliced fleet "
                "(topology on vs off: do train departures leave slices "
                "fully free?)",
))

_trace(TraceProfile(
    name="slice-fragmentation",
    gen=train_serve_churn_trace,
    params=dict(duration_s=30.0, serve_rate=20.0, gangs=10, gang_size=4,
                gang_lifetime_s=6.0, churn=0.5),
    nodes=256,
    slo_budget_ms=5000.0,
    slices=16,
    description="fragmentation-over-time: heavy gang arrival/departure "
                "churn — slices_free_at_steady_state is the gated metric",
))

_trace(TraceProfile(
    name="gang-contention",
    gen=multitenant_trace,
    params=dict(duration_s=20.0, rate=60.0, gangs=12, gang_size=6),
    nodes=128,
    slo_budget_ms=8000.0,
    slices=8,
    description="gang admission latency under contention: many gangs "
                "racing a small sliced fleet against a dense pod stream "
                "(gang_admission_p99_ms is the gated metric)",
))


_case(TestCase(
    name="BinPacking",
    source="PR 19: utilization-vs-throughput frontier workload (no "
           "reference config — skewed sizes + priority tiers built for "
           "the PackingComparison three-engine ladder)",
    default_pod_template=pod_binpack,
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        # no pods/s threshold: the workload's verdict is the benchdiff
        # frontier — nodes_used_at_steady_state and priority_slo_hit_rate
        # against the greedy baseline, not a reference throughput floor
        Workload("200Nodes",
                 {"initNodes": 200, "initPods": 50, "measurePods": 300}),
        Workload("1000Nodes_3000Pods",
                 {"initNodes": 1000, "initPods": 200, "measurePods": 3000},
                 labels=("performance", "packing")),
    ),
))

_case(TestCase(
    name="SchedulingWithMixedChurn",
    source="misc/performance-config.yaml:327",
    ops=(
        CreateNodesOp("initNodes"),
        ChurnOp(mode="recreate", template=pod_high_priority_large_cpu,
                interval_ms=1000, number=1),
        CreatePodsOp("measurePods", template=pod_default,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("1000Nodes", {"initNodes": 1000, "measurePods": 1000},
                 threshold=710, threshold_note=(
                     "5k floor kept verbatim: like SchedulingBasic, the "
                     "per-pod cost of the linear churn workload is ~flat "
                     "in node count, so the 1000-node throughput is >= "
                     "the 5k floor")),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "measurePods": 10000},
                 threshold=710, labels=("performance",)),
    ),
))
