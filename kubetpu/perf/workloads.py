"""scheduler_perf workload definitions — op lists + object templates.

Mirrors the reference harness's shape
(test/integration/scheduler_perf/scheduler_perf.go:756
RunBenchmarkPerfScheduling; ops in operations.go; per-topic
performance-config.yaml files): a *test case* is an op-list template
(createNodes/createNamespaces/createPods/churn/barrier) plus named
*workloads* binding the ``$param`` counts and the SchedulingThroughput
threshold asserted by CI. Templates reproduce the reference's YAML pod/node
templates (test/integration/scheduler_perf/templates/*.yaml) as factory
functions.

The measured metric is the reference's SchedulingThroughput: scheduled pods
per second over the collect-metrics phase (scheduler_perf.go:352-359 selects
``SchedulingThroughput / Average``; util.go:468 throughputCollector).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Mapping

from ..api import types as t
from ..api.wrappers import make_node, make_pod, pod_affinity_term, spread_constraint

ZONE_KEY = "topology.kubernetes.io/zone"
HOSTNAME_KEY = "kubernetes.io/hostname"

# ---------------------------------------------------------------------------
# object templates (templates/*.yaml analogs)
# ---------------------------------------------------------------------------


def node_default(i: int, zones: tuple[str, ...] = ()) -> t.Node:
    """templates/node-default.yaml: 4 cpu / 32Gi / 110 pods, plus the
    labelNodePrepareStrategy zone label (round-robin over ``zones``) and the
    kubelet-maintained hostname label."""
    name = f"scheduler-perf-{i}"
    labels = {HOSTNAME_KEY: name}
    if zones:
        labels[ZONE_KEY] = zones[i % len(zones)]
    return make_node(
        name, cpu_milli=4000, memory=32 * 1024**3, pods=110, labels=labels
    )


_POD_REQ = dict(cpu_milli=100, memory=500 * 1024**2)  # 100m / 500Mi


def pod_default(name: str, namespace: str) -> t.Pod:
    """templates/pod-default.yaml."""
    return make_pod(name, namespace=namespace, **_POD_REQ)


def pod_with_pod_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-affinity.yaml: color=blue, required zone
    affinity to color=blue across sched-0/sched-1."""
    term = pod_affinity_term(
        ZONE_KEY, match_labels={"color": "blue"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        affinity=t.Affinity(pod_affinity=t.PodAffinity(required=(term,))),
        **_POD_REQ,
    )


def pod_with_pod_anti_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-anti-affinity.yaml: color=green, required
    hostname anti-affinity to color=green."""
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "green"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "green"},
        affinity=t.Affinity(pod_anti_affinity=t.PodAffinity(required=(term,))),
        **_POD_REQ,
    )


def pod_anti_affinity_label_only(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-pod-anti-affinity-label.yaml: carries color=green
    (matching the init pods' anti-affinity) but no constraint of its own."""
    return make_pod(
        name, namespace=namespace, labels={"color": "green"}, **_POD_REQ
    )


def pod_with_preferred_pod_affinity(name: str, namespace: str) -> t.Pod:
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "red"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "red"},
        affinity=t.Affinity(pod_affinity=t.PodAffinity(
            preferred=(t.WeightedPodAffinityTerm(1, term),)
        )),
        **_POD_REQ,
    )


def pod_with_preferred_pod_anti_affinity(name: str, namespace: str) -> t.Pod:
    term = pod_affinity_term(
        HOSTNAME_KEY, match_labels={"color": "yellow"},
        namespaces=("sched-1", "sched-0"),
    )
    return make_pod(
        name, namespace=namespace, labels={"color": "yellow"},
        affinity=t.Affinity(pod_anti_affinity=t.PodAffinity(
            preferred=(t.WeightedPodAffinityTerm(1, term),)
        )),
        **_POD_REQ,
    )


def pod_with_topology_spreading(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-topology-spreading.yaml: maxSkew 5 / zone /
    DoNotSchedule over color=blue."""
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        spread=(spread_constraint(
            5, ZONE_KEY,
            when=t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE,
            match_labels={"color": "blue"},
        ),),
        **_POD_REQ,
    )


def pod_with_preferred_topology_spreading(name: str, namespace: str) -> t.Pod:
    return make_pod(
        name, namespace=namespace, labels={"color": "blue"},
        spread=(spread_constraint(
            5, ZONE_KEY,
            when=t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY,
            match_labels={"color": "blue"},
        ),),
        **_POD_REQ,
    )


def pod_with_node_affinity(name: str, namespace: str) -> t.Pod:
    """templates/pod-with-node-affinity.yaml: required zone In [zone1,zone2]."""
    from ..api.wrappers import node_affinity_required, req_in

    return make_pod(
        name, namespace=namespace,
        affinity=node_affinity_required(
            t.NodeSelectorTerm(match_expressions=(req_in(ZONE_KEY, "zone1", "zone2"),))
        ),
        **_POD_REQ,
    )


def pod_high_priority_large_cpu(name: str, namespace: str) -> t.Pod:
    """templates/pod-high-priority-large-cpu.yaml: priority 10, 9 cpu."""
    return make_pod(
        name, namespace=namespace, priority=10,
        cpu_milli=9000, memory=500 * 1024**2,
    )


# ---------------------------------------------------------------------------
# op list (operations.go analogs)
# ---------------------------------------------------------------------------

PodTemplate = Callable[[str, str], t.Pod]


@dataclass(frozen=True)
class CreateNodesOp:
    """operations.go:205 createNodesOp (+ labelNodePrepareStrategy)."""

    count_param: str = "initNodes"
    zones: tuple[str, ...] = ()


@dataclass(frozen=True)
class CreateNamespacesOp:
    """operations.go createNamespacesOp."""

    prefix: str = "sched"
    count: int = 2


@dataclass(frozen=True)
class CreatePodsOp:
    """operations.go:295 createPodsOp."""

    count_param: str = "initPods"
    template: PodTemplate | None = None     # None → case default
    collect_metrics: bool = False
    namespace: str | None = None            # None → unique per-op namespace


@dataclass(frozen=True)
class CreatePodGroupsOp:
    """operations.go createAny with a PodGroup template
    (podgroup/gangscheduling/performance-config.yaml:18 + its
    templates/podgroup.yaml: gangs gang-0..gang-(n-1), each with
    minCount = podsPerGroup)."""

    count_param: str = "initPodGroups"
    min_count_param: str = "podsPerGroup"
    prefix: str = "gang"


@dataclass(frozen=True)
class CreateGangPodsOp:
    """createPods with countMultiplierParam (performance-config.yaml:28 +
    templates/gang-pod.yaml): pod i references gang-(i // podsPerGroup);
    100m cpu / 100Mi, like the reference template."""

    count_param: str = "initPodGroups"
    multiplier_param: str = "podsPerGroup"
    prefix: str = "gang"
    collect_metrics: bool = True
    namespace: str = "gang-0"


@dataclass(frozen=True)
class CreatePodsWithPVsOp:
    """createPods with persistentVolumeTemplatePath /
    persistentVolumeClaimTemplatePath (volumes/performance-config.yaml:55
    SchedulingInTreePVs, :142 SchedulingCSIPVs): each pod gets its own
    bound PV+PVC pair (templates/pv-aws.yaml + templates/pvc.yaml —
    ReadOnlyMany, 1Gi, bind-completed)."""

    count_param: str = "measurePods"
    collect_metrics: bool = False
    driver: str = ""                        # CSI driver name ("" = in-tree)
    namespace: str | None = None


@dataclass(frozen=True)
class ChurnOp:
    """operations.go:518 churnOp — create (or recreate) interfering objects
    at an interval while the measured phase runs."""

    mode: str = "create"                    # create | recreate
    template: PodTemplate = pod_high_priority_large_cpu
    interval_ms: int = 500
    number: int = 0                         # recreate pool size (0 = unbounded)


@dataclass(frozen=True)
class BarrierOp:
    """operations.go:574 barrierOp — wait until all created pods scheduled."""


Op = object  # union of the five ops above


@dataclass(frozen=True)
class Workload:
    name: str
    params: Mapping[str, int]
    threshold: float | None = None          # SchedulingThroughput floor
    labels: tuple[str, ...] = ()


@dataclass(frozen=True)
class TestCase:
    name: str
    ops: tuple
    workloads: tuple[Workload, ...]
    default_pod_template: PodTemplate = pod_default
    source: str = ""                        # reference config citation
    # per-case featureGates block (performance-config.yaml featureGates:)
    feature_gates: tuple[tuple[str, bool], ...] = ()


# ---------------------------------------------------------------------------
# registry — the BASELINE.md rows (thresholds from the reference configs)
# ---------------------------------------------------------------------------

TEST_CASES: dict[str, TestCase] = {}


def _case(tc: TestCase) -> TestCase:
    TEST_CASES[tc.name] = tc
    return tc


_case(TestCase(
    name="SchedulingBasic",
    source="misc/performance-config.yaml:20",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000}),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 10000},
                 threshold=680, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPodAntiAffinity",
    source="affinity/performance-config.yaml:20",
    default_pod_template=pod_with_pod_anti_affinity,
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 100, "measurePods": 400}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=180, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPodMatchingAntiAffinity",
    source="affinity/performance-config.yaml:60",
    default_pod_template=pod_with_pod_anti_affinity,
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", template=pod_anti_affinity_label_only,
                     collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 100, "measurePods": 400}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 5000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingPodAffinity",
    source="affinity/performance-config.yaml:96 (threshold 70 — the hardest quadratic workload)",
    default_pod_template=pod_with_pod_affinity,
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreateNamespacesOp("sched", 2),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True, namespace="sched-1"),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=70, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingNodeAffinity",
    source="affinity/performance-config.yaml SchedulingNodeAffinity",
    default_pod_template=pod_with_node_affinity,
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreatePodsOp("initPods"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 500, "measurePods": 1000}),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 10000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="TopologySpreading",
    source="topology_spreading/performance-config.yaml:19",
    ops=(
        CreateNodesOp("initNodes", zones=("moon-1", "moon-2", "moon-3")),
        CreatePodsOp("initPods", template=pod_default),
        CreatePodsOp("measurePods", template=pod_with_topology_spreading,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 1000, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=460, labels=("performance",)),
    ),
))

_case(TestCase(
    name="PreferredTopologySpreading",
    source="topology_spreading/performance-config.yaml:64",
    ops=(
        CreateNodesOp("initNodes", zones=("moon-1", "moon-2", "moon-3")),
        CreatePodsOp("initPods", template=pod_default),
        CreatePodsOp("measurePods",
                     template=pod_with_preferred_topology_spreading,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 1000, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 5000, "measurePods": 5000},
                 threshold=340, labels=("performance",)),
    ),
))

_case(TestCase(
    name="MixedSchedulingBasePod",
    source="affinity/performance-config.yaml MixedSchedulingBasePod",
    ops=(
        CreateNodesOp("initNodes", zones=("zone1",)),
        CreateNamespacesOp("sched", 1),
        CreatePodsOp("initPods", namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_pod_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_pod_anti_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_preferred_pod_affinity,
                     namespace="sched-0"),
        CreatePodsOp("initPods", template=pod_with_preferred_pod_anti_affinity,
                     namespace="sched-0"),
        CreatePodsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes", {"initNodes": 500, "initPods": 200, "measurePods": 1000}),
        Workload("5000Nodes_5000Pods",
                 {"initNodes": 5000, "initPods": 2000, "measurePods": 5000},
                 threshold=540, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingInTreePVs",
    source="volumes/performance-config.yaml:55 (threshold 290)",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsWithPVsOp("initPods"),
        CreatePodsWithPVsOp("measurePods", collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "initPods": 5, "measurePods": 10}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=290, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingCSIPVs",
    source="volumes/performance-config.yaml:142 (threshold 100)",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsWithPVsOp("initPods", driver="ebs.csi.aws.com"),
        CreatePodsWithPVsOp("measurePods", driver="ebs.csi.aws.com",
                            collect_metrics=True),
    ),
    workloads=(
        Workload("5Nodes", {"initNodes": 5, "initPods": 5, "measurePods": 10}),
        Workload("5000Nodes_2000Pods",
                 {"initNodes": 5000, "initPods": 1000, "measurePods": 2000},
                 threshold=100, labels=("performance",)),
    ),
))

_case(TestCase(
    name="GangScheduling",
    source="podgroup/gangscheduling/performance-config.yaml:7 (no thresholds yet — new suite)",
    feature_gates=(("GenericWorkload", True), ("GangScheduling", True)),
    ops=(
        CreateNodesOp("initNodes"),
        CreateNamespacesOp("gang", 1),
        CreatePodGroupsOp("initPodGroups", "podsPerGroup"),
        CreateGangPodsOp("initPodGroups", "podsPerGroup",
                         collect_metrics=True),
    ),
    workloads=(
        Workload("10Nodes_3Gangs",
                 {"initNodes": 10, "initPodGroups": 3, "podsPerGroup": 3}),
        Workload("100Nodes_10Gangs",
                 {"initNodes": 100, "initPodGroups": 10, "podsPerGroup": 3}),
        Workload("5000Nodes_1000Gangs_3000Pods",
                 {"initNodes": 5000, "initPodGroups": 1000, "podsPerGroup": 3},
                 labels=("performance",)),
        Workload("5000Nodes_3Gangs_3000Pods_1000PerGroup",
                 {"initNodes": 5000, "initPodGroups": 3, "podsPerGroup": 1000},
                 labels=("performance",)),
    ),
))

_case(TestCase(
    name="Unschedulable",
    source="misc/performance-config.yaml:252",
    ops=(
        CreateNodesOp("initNodes"),
        CreatePodsOp("initPods"),
        ChurnOp(mode="create", template=pod_high_priority_large_cpu,
                interval_ms=200),
        CreatePodsOp("measurePods", template=pod_default,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("500Nodes/10Init/1kPods",
                 {"initNodes": 500, "initPods": 10, "measurePods": 1000}),
        Workload("5kNodes/100Init/10kPods",
                 {"initNodes": 5000, "initPods": 100, "measurePods": 10000},
                 threshold=590, labels=("performance",)),
    ),
))

_case(TestCase(
    name="SchedulingWithMixedChurn",
    source="misc/performance-config.yaml:327",
    ops=(
        CreateNodesOp("initNodes"),
        ChurnOp(mode="recreate", template=pod_high_priority_large_cpu,
                interval_ms=1000, number=1),
        CreatePodsOp("measurePods", template=pod_default,
                     collect_metrics=True),
    ),
    workloads=(
        Workload("1000Nodes", {"initNodes": 1000, "measurePods": 1000}),
        Workload("5000Nodes_10000Pods",
                 {"initNodes": 5000, "measurePods": 10000},
                 threshold=710, labels=("performance",)),
    ),
))
