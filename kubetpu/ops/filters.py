"""Filter kernels — boolean masks over ``(pods, nodes)``.

The reference runs Filter plugins per (pod, node) inside a chunked
parallel-for (``findNodesThatPassFilters``, pkg/scheduler/schedule_one.go:771,
``parallelize/parallelism.go:68``). Here every predicate is a vectorized
tensor op producing the full ``(P, N)`` mask in one XLA program; the
label/taint predicates were already folded into ``PodBatch.static_mask`` by
the encoder. The *dynamic* filters — ones that depend on state that evolves
as the batch assigns pods — are NodeResourcesFit (below) and NodePorts
(interned port triples × conflict matrix, evaluated in
``framework.runtime.feasible_and_scores``).

All kernels are shape-polymorphic in P and N and contain no Python control
flow on traced values, so they jit/vmap/shard_map cleanly.
"""

from __future__ import annotations

import jax.numpy as jnp


def resource_fit_mask(
    pod_requests: jnp.ndarray,    # (P, R) int64, exact requests (not NonZero)
    alloc: jnp.ndarray,           # (N, R) int64
    requested: jnp.ndarray,       # (N, R) int64, exact requested on node
    pod_count: jnp.ndarray,       # (N,) int32
    allowed_pods: jnp.ndarray,    # (N,) int32
) -> jnp.ndarray:
    """NodeResourcesFit Filter (noderesources/fit.go:647 fitsRequest):

    - per resource: infeasible when ``req > 0 and req > allocatable - used``
    - pod count: infeasible when ``len(pods) + 1 > allowedPodNumber``
    Returns (P, N) bool.
    """
    free = alloc - requested                                  # (N, R)
    req = pod_requests[:, None, :]                            # (P, 1, R)
    ok = (req == 0) | (req <= free[None, :, :])               # (P, N, R)
    mask = jnp.all(ok, axis=-1)                               # (P, N)
    room = (pod_count + 1) <= allowed_pods                    # (N,)
    return mask & room[None, :]


def resource_fit_mask_single(
    pod_request: jnp.ndarray,     # (R,) int64
    alloc: jnp.ndarray,           # (N, R)
    requested: jnp.ndarray,       # (N, R)
    pod_count: jnp.ndarray,       # (N,)
    allowed_pods: jnp.ndarray,    # (N,)
) -> jnp.ndarray:
    """(N,) variant used inside the greedy scan (one pod per step)."""
    free = alloc - requested
    ok = (pod_request[None, :] == 0) | (pod_request[None, :] <= free)
    return jnp.all(ok, axis=-1) & ((pod_count + 1) <= allowed_pods)
