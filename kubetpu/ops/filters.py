"""Filter kernels — boolean masks over ``(pods, nodes)``.

The reference runs Filter plugins per (pod, node) inside a chunked
parallel-for (``findNodesThatPassFilters``, pkg/scheduler/schedule_one.go:771,
``parallelize/parallelism.go:68``). Here every predicate is a vectorized
tensor op producing the full ``(P, N)`` mask in one XLA program; the
label/taint predicates were already folded into ``PodBatch.static_mask`` by
the encoder. The *dynamic* filters — ones that depend on state that evolves
as the batch assigns pods — are NodeResourcesFit (below) and NodePorts
(interned port triples × conflict matrix, evaluated in
``framework.runtime.feasible_and_scores``).

All kernels are shape-polymorphic in P and N and contain no Python control
flow on traced values, so they jit/vmap/shard_map cleanly.
"""

from __future__ import annotations

import jax.numpy as jnp


def resource_fit_mask(
    pod_requests: jnp.ndarray,    # (P, R) int64, exact requests (not NonZero)
    alloc: jnp.ndarray,           # (N, R) int64
    requested: jnp.ndarray,       # (N, R) int64, exact requested on node
    pod_count: jnp.ndarray,       # (N,) int32
    allowed_pods: jnp.ndarray,    # (N,) int32
) -> jnp.ndarray:
    """NodeResourcesFit Filter (noderesources/fit.go:647 fitsRequest):

    - per resource: infeasible when ``req > 0 and req > allocatable - used``
    - pod count: infeasible when ``len(pods) + 1 > allowedPodNumber``
    Returns (P, N) bool.
    """
    free = alloc - requested                                  # (N, R)
    req = pod_requests[:, None, :]                            # (P, 1, R)
    ok = (req == 0) | (req <= free[None, :, :])               # (P, N, R)
    mask = jnp.all(ok, axis=-1)                               # (P, N)
    room = (pod_count + 1) <= allowed_pods                    # (N,)
    return mask & room[None, :]


def resource_fit_mask_nominated(
    pod_requests: jnp.ndarray,    # (P, R) int64
    alloc: jnp.ndarray,           # (N, R)
    requested: jnp.ndarray,       # (N, R)
    pod_count: jnp.ndarray,       # (N,)
    allowed_pods: jnp.ndarray,    # (N,)
    gate: jnp.ndarray,            # (P, G) bool — nomination applies to pod p
    g_node: jnp.ndarray,          # (G,) int32 nominated node index (-1 none)
    g_req: jnp.ndarray,           # (G, R) int64 nominated pod requests
) -> jnp.ndarray:
    """NodeResourcesFit with nominator reservations
    (RunFilterPluginsWithNominatedPods' fit dimension): pod p additionally
    sees ``Σ_g gate[p,g]·requests[g]`` charged to g's nominated node. The
    (P,N,R) intermediate is never materialized — one (P,N) plane per
    resource (R is a small static constant)."""
    n = alloc.shape[0]
    onehot = (g_node[:, None] == jnp.arange(n, dtype=g_node.dtype))  # (G, N)
    gate_f = gate.astype(jnp.float64)
    extra_cnt = jnp.einsum("pg,gn->pn", gate.astype(jnp.int32),
                           onehot.astype(jnp.int32))
    mask = (pod_count[None, :] + 1 + extra_cnt) <= allowed_pods[None, :]
    free = alloc - requested                                         # (N, R)
    for r in range(alloc.shape[1]):
        plane = (onehot * g_req[:, r][:, None]).astype(jnp.float64)  # (G, N)
        # the s64 contraction is not in TPU's X64-rewrite vocabulary; f64
        # sums of integers < 2^53 are exact, so the dot runs in f64 and
        # converts back (resource quantities are far below 2^53)
        extra_r = jnp.einsum("pg,gn->pn", gate_f, plane).astype(jnp.int64)
        req_r = pod_requests[:, r][:, None]                          # (P, 1)
        mask = mask & ((req_r == 0) | (req_r <= free[None, :, r] - extra_r))
    return mask


def resource_fit_mask_single(
    pod_request: jnp.ndarray,     # (R,) int64
    alloc: jnp.ndarray,           # (N, R)
    requested: jnp.ndarray,       # (N, R)
    pod_count: jnp.ndarray,       # (N,)
    allowed_pods: jnp.ndarray,    # (N,)
) -> jnp.ndarray:
    """(N,) variant used inside the greedy scan (one pod per step)."""
    free = alloc - requested
    ok = (pod_request[None, :] == 0) | (pod_request[None, :] <= free)
    return jnp.all(ok, axis=-1) & ((pod_count + 1) <= allowed_pods)
