"""InterPodAffinity device kernels.

Reference checks (pkg/scheduler/framework/plugins/interpodaffinity/):
- Filter (filtering.go:364-419): existing-pods anti-affinity (any node label
  pair with count > 0 → infeasible), incoming anti-affinity (count > 0 at the
  node's domain for any term → infeasible), incoming affinity (every term's
  count > 0 where all term keys exist; self-affinity escape when the global
  map is empty and the pod matches its own terms, filtering.go:414).
- Score (scoring.go:240): Σ over topology maps at the node's values, then
  min-max normalize over filtered nodes (scoring.go:258):
  ``int64(100 · (s − min) / (max − min))``, 0 when max == min.

All counts live in the carried ``sums (R, D)`` state (interned count rows ×
topology domains — see state.podaffinity); the kernels are pure gathers.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100


def _slot_counts(pa, sums, rid):
    """(N,) count at each node's domain for one row id (0 where key absent;
    garbage-safe for rid < 0 — callers gate on validity)."""
    r = jnp.maximum(rid, 0)
    dom = pa.node_domain[r]
    return jnp.where(dom >= 0, sums[r][jnp.maximum(dom, 0)], 0)


def affinity_filter_pod(pa, sums, fa_rows, fa_self, ra_rows, ea_rows):
    """(N,) bool for ONE pod. ``fa_rows (CA,)``, ``ra_rows (CR,)``,
    ``ea_rows (CE,)`` are the pod's row-id slots (−1 unused); every kernel
    cost is O(slots × N), independent of the global row count."""
    n = pa.node_domain.shape[1]

    # incoming required affinity (satisfyPodAffinity)
    keys_ok = jnp.ones(n, dtype=bool)
    pods_exist = jnp.ones(n, dtype=bool)
    set_total = jnp.int64(0)
    any_fa = jnp.any(fa_rows >= 0)
    for c in range(fa_rows.shape[0]):
        rid = fa_rows[c]
        valid = rid >= 0
        r = jnp.maximum(rid, 0)
        cnt = _slot_counts(pa, sums, rid)
        keys_ok = keys_ok & jnp.where(valid, pa.has_key[r], True)
        pods_exist = pods_exist & jnp.where(valid, cnt > 0, True)
        set_total = set_total + jnp.where(valid, jnp.sum(sums[r]), 0)
    escape = (set_total == 0) & fa_self
    fa_ok = jnp.where(any_fa, keys_ok & (pods_exist | escape), True)

    # incoming required anti-affinity (satisfyPodAntiAffinity)
    ra_ok = jnp.ones(n, dtype=bool)
    for c in range(ra_rows.shape[0]):
        rid = ra_rows[c]
        valid = rid >= 0
        r = jnp.maximum(rid, 0)
        cnt = _slot_counts(pa, sums, rid)
        ra_ok = ra_ok & jnp.where(valid, ~(pa.has_key[r] & (cnt > 0)), True)

    # existing pods' anti-affinity (satisfyExistingPodsAntiAffinity): only
    # rows whose term matches this pod are in its ea slots
    affected = jnp.zeros(n, dtype=bool)
    for c in range(ea_rows.shape[0]):
        rid = ea_rows[c]
        valid = rid >= 0
        cnt = _slot_counts(pa, sums, rid)
        affected = affected | jnp.where(valid, cnt > 0, False)

    return fa_ok & ra_ok & ~affected


def affinity_score_pod(pa, sums, score_rows, score_vals, mask):
    """(N,) int64 normalized InterPodAffinity score for ONE pod given its
    feasibility row. ``score_rows/score_vals (CS,)`` are the pod's weighted
    row slots."""
    n = pa.node_domain.shape[1]
    raw = jnp.zeros(n, dtype=jnp.int64)
    for c in range(score_rows.shape[0]):
        rid = score_rows[c]
        valid = rid >= 0
        cnt = _slot_counts(pa, sums, rid)
        raw = raw + jnp.where(valid, score_vals[c] * cnt, 0)
    big = jnp.iinfo(jnp.int64).max
    mn = jnp.min(jnp.where(mask, raw, big))
    mx = jnp.max(jnp.where(mask, raw, -big))
    diff = mx - mn
    f = (
        MAX_NODE_SCORE
        * (raw - mn).astype(jnp.float64)
        / jnp.maximum(diff, 1).astype(jnp.float64)
    )
    out = jnp.where(diff > 0, f.astype(jnp.int64), 0)
    return jnp.where(mask, out, 0)
