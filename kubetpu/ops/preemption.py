"""Preemption victim-search kernels.

TPU re-expression of the reference's dry-run preemption
(pkg/scheduler/framework/preemption/preemption.go:404 DryRunPreemption +
pkg/scheduler/framework/plugins/defaultpreemption/default_preemption.go:252
SelectVictimsOnNode): instead of sampling a random candidate subset and
simulating nodes one goroutine at a time, every node's victim selection runs
as one vmapped program — exhaustive over all candidate nodes, which can only
improve on the reference's sampled search (same per-node semantics, strictly
larger candidate pool).

Per-node semantics mirrored exactly:

1. potential victims = pods with priority < preemptor's
   (default_preemption.go:396 isPreemptionAllowed)
2. preemptor must fit with ALL of them removed (:302) — fit here covers the
   victim-*dependent* filters (NodeResourcesFit, NodePorts, pod count);
   victim-independent filters are the caller-supplied ``potential`` mask
3. PDB violation marking walks victims in MoreImportantPod order
   (:315 filterPodsWithPDBViolation; util.MoreImportantPod = higher
   priority first, earlier start time breaks ties)
4. reprieve: violating victims first, then non-violating, each in importance
   order; a victim is reprieved iff the preemptor still fits with it back
   (:316-343)
5. node choice = pickOneNodeForPreemption's lexicographic refinement
   (preemption.go:311): fewest PDB violations → lowest highest-victim
   priority → lowest summed priority (+2^31 per victim) → fewest victims →
   latest earliest-start-time among highest-priority victims → first node.

Scope note (documented divergence): like the reference — which refuses to
resolve inter-pod-affinity-to-victims for performance (:297-301) — the
in-kernel re-check covers resources/count/ports. Nodes whose failure
involved hard spread/inter-pod-affinity are conservatively excluded by the
caller's ``potential`` mask (never nominates an invalid node; may miss
nodes that victim removal would have fixed).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# plain ints: jnp scalars here would initialize the backend at
# IMPORT time (a CLI process must stay device-free until its loop);
# ints weak-promote to i64 identically inside jit
I64_MIN = -(2**62)
I64_MAX = 2**62
PRIO_OFFSET = 2**31  # preemption.go:339 MaxInt32+1 shift


def _fits(pod_req, alloc, req_state, count_state, allowed, wants_conf, port_counts):
    """Does the preemptor fit this node state? NodeResourcesFit semantics
    (req==0 passes; fit.go fitsRequest) + pod count + NodePorts conflict
    against live port-usage counts."""
    ok_r = jnp.all((pod_req == 0) | (pod_req <= alloc - req_state))
    ok_c = (count_state + 1) <= allowed
    ok_p = ~jnp.any(wants_conf & (port_counts > 0))
    return ok_r & ok_c & ok_p


def select_victims_node(
    pod_req,        # (R,) int64 — preemptor exact requests
    pod_prio,       # () int64
    wants_conf,     # (Kp,) bool — preemptor port triples × conflict matrix
    alloc,          # (R,) int64
    requested,      # (R,) int64
    pod_count,      # () int32
    allowed,        # () int32
    v_valid,        # (K,) bool
    v_prio,         # (K,) int64
    v_start,        # (K,) int64
    v_req,          # (K, R) int64
    v_ports,        # (K, Kp) int8
    v_pdb,          # (K, D) bool
    port_counts,    # (Kp,) int32
    pdb_allowed,    # (D,) int64
):
    """One node's SelectVictimsOnNode. Returns
    ``(ok, victims (K,) bool, n_pdb_viol, max_prio, sum_prio, n_victims,
    earliest_start)`` — stats feed pick_node. vmap over the node axis."""
    K = v_valid.shape[0]
    eligible = v_valid & (v_prio < pod_prio)
    has_eligible = jnp.any(eligible)
    e64 = eligible.astype(jnp.int64)

    # state with every eligible victim removed
    base_req = requested - jnp.sum(e64[:, None] * v_req, axis=0)
    base_count = pod_count - jnp.sum(eligible)
    base_ports = port_counts - jnp.sum(
        e64[:, None] * v_ports.astype(jnp.int64), axis=0
    ).astype(port_counts.dtype)
    fits_base = _fits(
        pod_req, alloc, base_req, base_count, allowed, wants_conf, base_ports
    )

    # importance order: priority desc, start asc; ineligible slots last
    imp_key = jnp.where(eligible, -v_prio, I64_MAX)
    slot = jnp.arange(K, dtype=jnp.int32)
    _, _, by_importance = jax.lax.sort(
        (imp_key, v_start, slot), num_keys=2
    )

    # PDB violation flags, walking importance order
    def pdb_step(allowed_d, k):
        matched = v_pdb[k] & eligible[k]
        allowed_d = allowed_d - matched.astype(jnp.int64)
        violating = jnp.any(matched & (allowed_d < 0))
        return allowed_d, (k, violating)

    _, (order_k, order_viol) = jax.lax.scan(pdb_step, pdb_allowed, by_importance)
    violating = jnp.zeros(K, dtype=bool).at[order_k].set(order_viol)

    # reprieve order: violating group first, then importance within group
    grp_key = jnp.where(violating, jnp.int64(0), jnp.int64(1))
    grp_key = jnp.where(eligible, grp_key, jnp.int64(2))
    _, _, _, reprieve_order = jax.lax.sort(
        (grp_key, imp_key, v_start, slot), num_keys=3
    )

    def reprieve_step(carry, k):
        req_s, cnt_s, ports_s, victims, n_viol = carry
        try_req = req_s + v_req[k]
        try_cnt = cnt_s + 1
        try_ports = ports_s + v_ports[k].astype(ports_s.dtype)
        fits = _fits(
            pod_req, alloc, try_req, try_cnt, allowed, wants_conf, try_ports
        )
        take = eligible[k] & fits          # reprieved: stays on the node
        req_s = jnp.where(take, try_req, req_s)
        cnt_s = jnp.where(take, try_cnt, cnt_s)
        ports_s = jnp.where(take, try_ports, ports_s)
        is_victim = eligible[k] & ~fits
        victims = victims.at[k].set(is_victim)
        n_viol = n_viol + (is_victim & violating[k]).astype(jnp.int64)
        return (req_s, cnt_s, ports_s, victims, n_viol), None

    init = (
        base_req, base_count, base_ports,
        jnp.zeros(K, dtype=bool), jnp.int64(0),
    )
    (_, _, _, victims, n_pdb_viol), _ = jax.lax.scan(
        reprieve_step, init, reprieve_order
    )

    n_victims = jnp.sum(victims).astype(jnp.int64)
    ok = has_eligible & fits_base & (n_victims > 0)
    max_prio = jnp.max(jnp.where(victims, v_prio, I64_MIN))
    sum_prio = jnp.sum(jnp.where(victims, v_prio + PRIO_OFFSET, 0))
    highest = victims & (v_prio == max_prio)
    earliest_start = jnp.min(jnp.where(highest, v_start, I64_MAX))
    return ok, victims, n_pdb_viol, max_prio, sum_prio, n_victims, earliest_start


def pick_node(ok, n_pdb_viol, max_prio, sum_prio, n_victims, earliest_start):
    """pickOneNodeForPreemption (preemption.go:311): iterative lexicographic
    refinement over score functions, first node breaking any remaining tie.
    Returns chosen node index (int32) or -1 when no candidate."""
    any_ok = jnp.any(ok)
    cands = ok
    # maximize each score in turn, keeping only argmax ties
    for score in (
        -n_pdb_viol,            # fewest PDB violations
        -max_prio,              # lowest highest-victim priority
        -sum_prio,              # lowest summed (shifted) priorities
        -n_victims,             # fewest victims
        earliest_start,         # latest earliest-start of highest-prio victims
    ):
        best = jnp.max(jnp.where(cands, score, I64_MIN))
        cands = cands & (score == best)
    idx = jnp.argmax(cands).astype(jnp.int32)   # first remaining candidate
    return jnp.where(any_ok, idx, jnp.int32(-1))


# Donated: ``potential`` (N,) bool aliases the ``ok`` output and ``v_valid``
# (N, K) bool aliases the ``victims`` output — both are built fresh on every
# call (the potential mask is computed per pod; v_valid is re-uploaded from
# the host victim tensors), so invalidating them is safe and the two largest
# bool outputs reuse their input buffers instead of allocating. The other
# inputs either persist across preempt() calls (alloc, requests, the
# host-mirrored usage state) or cannot alias any output shape/dtype —
# donating those would draw "donated buffers were not usable" warnings,
# which the test suite asserts never happen.
@partial(jax.jit, donate_argnums=(3, 9))
def dry_run_preemption(
    pod_req, pod_prio, wants_conf, potential,
    alloc, requested, pod_count, allowed, port_counts,
    v_valid, v_prio, v_start, v_req, v_ports, v_pdb, pdb_allowed,
):
    """All nodes at once: vmapped SelectVictimsOnNode gated by the caller's
    ``potential`` (N,) mask (nodes whose failure preemption could resolve —
    preemption.go:180 NodesForStatusCode(Unschedulable)), then pick_node.

    Returns ``(node_idx, victims (N, K) bool, ok (N,) bool, n_pdb (N,))`` —
    victims row of the chosen node is the preemption plan; host maps slots
    back to pod uids. ``ok``/``n_pdb`` expose the full candidate set so the
    host can re-pick after extender ProcessPreemption trims candidates
    (extender.go ProcessPreemption → preemption.go callExtenders).
    """
    ok, victims, n_pdb, max_p, sum_p, n_v, early = jax.vmap(
        lambda a, r, c, al, vv, vp, vs, vr, vpo, vpd, pc: select_victims_node(
            pod_req, pod_prio, wants_conf,
            a, r, c, al, vv, vp, vs, vr, vpo, vpd, pc, pdb_allowed,
        )
    )(alloc, requested, pod_count, allowed,
      v_valid, v_prio, v_start, v_req, v_ports, v_pdb, port_counts)
    ok = ok & potential
    node_idx = pick_node(ok, n_pdb, max_p, sum_p, n_v, early)
    return node_idx, victims, ok, n_pdb


# --------------------------------------------------------------------------
# gang mode (topology-aware): evict ONE whole gang, not per-pod victims
# --------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("params", "engine"))
def dry_run_gang_preemption(
    b, params, candidate_masks, freed_req, freed_count, engine="greedy",
):
    """Gang mode of the dry-run: each candidate is "evict one low-priority
    gang and offer its CONTIGUOUS SLICE as the node set". ``candidate_masks``
    is (C, N) bool — the full slice the victim gang occupies; ``freed_req``
    (C, N, R) / ``freed_count`` (C, N) are the resources/pod counts the
    eviction returns. The preemptor gang's whole assignment engine runs
    under each hypothesis (vmapped — all C candidates in one program, the
    same exhaustive-search upgrade the per-pod dry run makes over the
    reference's sampled candidates), so admission is judged by the REAL
    filters + scores, not a resource-sum approximation.

    Returns ``(counts (C,) int32, alignment (C,) int32)`` — pods the
    preemptor would schedule under each eviction, and the slice-alignment
    of that proposal (``ops.topology.alignment_score``).
    """
    import dataclasses

    if engine == "batched":
        from ..assign.batched import batched_assign_device as assign
    else:
        from ..assign.greedy import greedy_assign_device as assign

    def one(mask, fr, fc):
        nodes = dataclasses.replace(
            b.nodes,
            requested=jnp.maximum(b.nodes.requested - fr, 0),
            nonzero_requested=jnp.maximum(b.nodes.nonzero_requested - fr, 0),
            pod_count=jnp.maximum(b.nodes.pod_count - fc, 0),
            node_valid=b.node_valid & mask,
        )
        bb = dataclasses.replace(b, nodes=nodes)
        assignments, _ = assign(bb, params)
        if b.topology is not None:
            from .topology import alignment_score

            align, _, _ = alignment_score(
                assignments, b.pod_valid,
                b.topology.slice_id, b.topology.num_slices,
            )
        else:
            align = jnp.int32(0)
        count = jnp.sum(
            (assignments >= 0) & b.pod_valid
        ).astype(jnp.int32)
        return count, align

    return jax.vmap(one)(candidate_masks, freed_req, freed_count)
