"""Score kernels — int64 scores in [0, 100] over ``(pods, nodes)``.

The reference computes scores per node inside ``RunScorePlugins``
(framework/runtime/framework.go:1351): parallel per-node Score, then
NormalizeScore, then multiply by plugin weight and sum. Each kernel here
produces the *raw* per-plugin score tensor; normalization and weighting live
in ``normalize`` / the framework runtime so the composition order matches the
reference exactly.

Integer arithmetic is int64 end-to-end where the reference uses int64 —
truncating (floor, since values are non-negative) division included.
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_NODE_SCORE = 100


def _weighted_mean(
    per_res: jnp.ndarray,      # (P, N, R) int64 per-resource scores
    pod_req: jnp.ndarray,      # (P, R) int64 — pod's request (participation rule)
    cap: jnp.ndarray,          # (1, N, R) int64 allocatable
    weights: jnp.ndarray,      # (R,) int64
    is_scalar: jnp.ndarray,    # (R,) bool
    require_positive_score: bool = False,
    round_half_up: bool = False,
) -> jnp.ndarray:
    """The shared weight-accumulation rule of the resource strategies
    (resource_allocation.go:180 skip rules + each strategy's weightSum loop):
    a resource participates when weight > 0, node allocatable > 0, and — for
    extended/scalar resources — the pod requests it. RequestedToCapacityRatio
    additionally requires the per-resource score to be > 0 and rounds the
    final mean half-up (math.Round) instead of truncating."""
    participate = (
        (weights[None, None, :] > 0)
        & (cap > 0)
        & (~is_scalar[None, None, :] | (pod_req[:, None, :] > 0))
    )
    if require_positive_score:
        participate = participate & (per_res > 0)
    w = jnp.where(participate, weights[None, None, :], 0)
    num = jnp.sum(per_res * w, axis=-1)
    den = jnp.sum(w, axis=-1)
    if round_half_up:
        out = (2 * num + den) // jnp.maximum(2 * den, 1)
    else:
        out = num // jnp.maximum(den, 1)
    return jnp.where(den > 0, out, 0)


def least_allocated_score(
    pod_nonzero: jnp.ndarray,     # (P, R) int64 — NonZero view (100mCPU/200MiB defaults)
    node_nonzero: jnp.ndarray,    # (N, R) int64 — sum of NonZero requests on node
    alloc: jnp.ndarray,           # (N, R) int64
    weights: jnp.ndarray,         # (R,) int64 — 0 for resources not scored
    is_scalar: jnp.ndarray,       # (R,) bool — extended resources (skip when pod req 0)
) -> jnp.ndarray:
    """LeastAllocated strategy (noderesources/least_allocated.go:31):

        per-resource: ((capacity - requested) * 100) // capacity,
                      0 if capacity == 0 or requested > capacity
        node score:   Σ(score_i * w_i) // Σ(w_i)   over participating resources

    A resource participates when its weight > 0, node allocatable > 0, and —
    for extended/scalar resources — the pod actually requests it
    (resource_allocation.go:180 calculateNodeAllocatableRequest skip rules).
    Returns (P, N) int64.
    """
    cap = alloc[None, :, :]                                   # (1, N, R)
    requested = node_nonzero[None, :, :] + pod_nonzero[:, None, :]  # (P, N, R)
    safe_cap = jnp.maximum(cap, 1)
    per_res = jnp.where(
        (cap > 0) & (requested <= cap),
        ((cap - requested) * MAX_NODE_SCORE) // safe_cap,
        0,
    )                                                         # (P, N, R)
    return _weighted_mean(per_res, pod_nonzero, cap, weights, is_scalar)


def most_allocated_score(
    pod_nonzero: jnp.ndarray,
    node_nonzero: jnp.ndarray,
    alloc: jnp.ndarray,
    weights: jnp.ndarray,
    is_scalar: jnp.ndarray,
) -> jnp.ndarray:
    """MostAllocated strategy (noderesources/most_allocated.go):
    per-resource ``(min(requested, capacity) * 100) // capacity`` (requests can
    exceed capacity because of NonZero defaults), 0 when capacity == 0.
    Weighted mean as in LeastAllocated."""
    cap = alloc[None, :, :]
    requested = node_nonzero[None, :, :] + pod_nonzero[:, None, :]
    safe_cap = jnp.maximum(cap, 1)
    clamped = jnp.minimum(requested, cap)  # requested > capacity clamps to max score
    per_res = jnp.where(cap > 0, (clamped * MAX_NODE_SCORE) // safe_cap, 0)
    return _weighted_mean(per_res, pod_nonzero, cap, weights, is_scalar)


def _trunc_div(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Go's int64 division truncates toward zero; Python's // floors. Segment
    slopes in a decreasing shape make the numerator negative, so match Go."""
    q = jnp.abs(a) // jnp.maximum(jnp.abs(b), 1)
    return jnp.where((a < 0) ^ (b < 0), -q, q)


def broken_linear(p: jnp.ndarray, xs: jnp.ndarray, ys: jnp.ndarray) -> jnp.ndarray:
    """helper.BuildBrokenLinearFunction (plugins/helper/shape_score.go):
    exact int64 piecewise-linear bracket. ``xs`` strictly increasing."""
    b = xs.shape[0]
    idx = jnp.searchsorted(xs, p, side="left")        # first i with xs[i] >= p
    hi = jnp.clip(idx, 0, b - 1)
    lo = jnp.clip(idx - 1, 0, b - 1)
    x0, y0, x1, y1 = xs[lo], ys[lo], xs[hi], ys[hi]
    interp = y0 + _trunc_div((y1 - y0) * (p - x0), x1 - x0)
    out = jnp.where(idx == 0, ys[0], interp)
    return jnp.where(idx >= b, ys[-1], out)


def requested_to_capacity_ratio_score(
    pod_nonzero: jnp.ndarray,
    node_nonzero: jnp.ndarray,
    alloc: jnp.ndarray,
    weights: jnp.ndarray,
    is_scalar: jnp.ndarray,
    shape_utilization: jnp.ndarray,  # (B,) int64 — bracket x points, 0..100, increasing
    shape_score: jnp.ndarray,        # (B,) int64 — bracket y, PRE-SCALED ×10 to 0..100
) -> jnp.ndarray:
    """RequestedToCapacityRatio strategy (noderesources/requested_to_capacity_ratio.go
    buildRequestedToCapacityRatioScorerFunction), exact int64 semantics:

    - utilization = requested*100//capacity; capacity==0 or overflow → 100
    - per-resource score = broken-linear(shape) at that utilization
    - a resource's weight counts only when its score > 0
    - node score = round(Σ(score·w) / Σw), half away from zero (math.Round)

    Shape y-values arrive pre-scaled ×(MaxNodeScore/MaxCustomPriorityScore)=×10
    by the config layer, as the reference's New() does.
    """
    cap = alloc[None, :, :]
    requested = node_nonzero[None, :, :] + pod_nonzero[:, None, :]
    safe_cap = jnp.maximum(cap, 1)
    util = jnp.where(
        (cap > 0) & (requested <= cap),
        (requested * MAX_NODE_SCORE) // safe_cap,
        MAX_NODE_SCORE,
    )
    per_res = broken_linear(util, shape_utilization, shape_score)
    return _weighted_mean(
        per_res, pod_nonzero, cap, weights, is_scalar,
        require_positive_score=True, round_half_up=True,
    )


def _balanced_std(frac: jnp.ndarray, present: jnp.ndarray) -> jnp.ndarray:
    """std over the participating fractions, with the reference's case split
    (balanced_allocation.go): exactly 2 → |f1-f2|/2; >2 → population std;
    <2 → 0. ``frac`` (..., R) float, ``present`` (..., R) bool."""
    n = jnp.sum(present, axis=-1)
    total = jnp.sum(jnp.where(present, frac, 0.0), axis=-1)
    mean = total / jnp.maximum(n, 1)
    var = jnp.sum(
        jnp.where(present, (frac - mean[..., None]) ** 2, 0.0), axis=-1
    ) / jnp.maximum(n, 1)
    std_many = jnp.sqrt(var)
    # two-resource shortcut: |f1 - f2| / 2 over the two present entries.
    # sum of |f_i - mean| over 2 entries == |f1 - f2|; /2 matches.
    absdev = jnp.sum(jnp.where(present, jnp.abs(frac - mean[..., None]), 0.0), axis=-1)
    std_two = absdev / 2.0
    return jnp.where(n == 2, std_two, jnp.where(n > 2, std_many, 0.0))


def balanced_allocation_score(
    pod_requests: jnp.ndarray,    # (P, R) int64 — exact requests (useRequested=true)
    node_requested: jnp.ndarray,  # (N, R) int64 — exact requested on node
    alloc: jnp.ndarray,           # (N, R) int64
    weights: jnp.ndarray,         # (R,) int64 — which resources participate (>0)
    is_scalar: jnp.ndarray,       # (R,) bool
    float_dtype=jnp.float64,
) -> jnp.ndarray:
    """NodeResourcesBalancedAllocation (balanced_allocation.go:248
    balancedResourceScorer):

        score = 50 + (50 + score_with_pod - score_without_pod) / 2

    where each side is ``int64((1 - std(fractions)) * 100)`` and fractions are
    ``min(requested/allocatable, 1)`` over participating resources. Best-effort
    pods (all participating requests zero) are skipped (→ 0) by PreScore.
    Returns (P, N) int64.
    """
    cap = alloc[None, :, :].astype(float_dtype)
    present = (
        (weights[None, None, :] > 0)
        & (alloc[None, :, :] > 0)
        & (~is_scalar[None, None, :] | (pod_requests[:, None, :] > 0))
    )                                                          # (P, N, R)
    with_pod = (node_requested[None, :, :] + pod_requests[:, None, :]).astype(float_dtype)
    without_pod = jnp.broadcast_to(
        node_requested[None, :, :].astype(float_dtype), with_pod.shape
    )
    safe_cap = jnp.maximum(cap, 1.0)
    f_with = jnp.minimum(with_pod / safe_cap, 1.0)
    f_without = jnp.minimum(without_pod / safe_cap, 1.0)
    score_with = ((1.0 - _balanced_std(f_with, present)) * MAX_NODE_SCORE).astype(jnp.int64)
    score_without = ((1.0 - _balanced_std(f_without, present)) * MAX_NODE_SCORE).astype(jnp.int64)
    score = MAX_NODE_SCORE // 2 + (MAX_NODE_SCORE // 2 + score_with - score_without) // 2
    # best-effort skip: all participating pod requests are zero
    best_effort = jnp.all(
        (pod_requests == 0) | (weights[None, :] == 0), axis=-1
    )                                                          # (P,)
    return jnp.where(best_effort[:, None], 0, score)


def default_normalize(raw: jnp.ndarray, reverse: bool = False) -> jnp.ndarray:
    """helper.DefaultNormalizeScore (plugins/helper/normalize_score.go:27),
    vectorized over the pod axis: per pod, scale [0, max] → [0, 100]
    (integer division), optionally reversed. raw: (P, N) int64."""
    mx = jnp.max(raw, axis=-1, keepdims=True)                 # (P, 1)
    scaled = jnp.where(mx > 0, (MAX_NODE_SCORE * raw) // jnp.maximum(mx, 1), 0)
    if reverse:
        # maxCount == 0 with reverse=true → all scores become maxPriority.
        scaled = MAX_NODE_SCORE - scaled
    return scaled


def image_locality_score(
    sum_scores: jnp.ndarray,      # (P, N) int64 — Σ scaled image sizes present on node
    image_count: jnp.ndarray,     # (P,) int32 — number of image sources in pod spec
) -> jnp.ndarray:
    """ImageLocality (imagelocality/image_locality.go:96 calculatePriority):
    clamp sumScores to [minThreshold, maxContainerThreshold*imageCount] and
    scale to [0, 100]. minThreshold = 23 MiB, maxContainerThreshold = 1000 MiB
    (image_locality.go:34-35)."""
    min_threshold = 23 * 1024 * 1024
    max_container_threshold = 1000 * 1024 * 1024
    max_threshold = max_container_threshold * image_count.astype(jnp.int64)[:, None]
    s = jnp.clip(sum_scores, min_threshold, jnp.maximum(max_threshold, min_threshold))
    denom = jnp.maximum(max_threshold - min_threshold, 1)
    return MAX_NODE_SCORE * (s - min_threshold) // denom
