"""Slice-alignment kernels over the dense topology coordinates.

All functions are pure jnp over ``(P,)`` assignment vectors and ``(N,)``
coordinate columns — zero per-pod Python. The central trick: with dense
slice ids in ``[0, S]`` (``S`` = unlabeled bucket) a gang's per-slice
member counts are ONE scatter-add, and from those counts both alignment
(same-slice concentration, Σ c_s²) and the cross-slice cut (pairs of
gang members split across slices, G² − Σ c_s² up to a factor 2) fall
out without materializing any (P, P) pairwise matrix.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp


def slice_counts(
    assignments: jnp.ndarray,
    pod_valid: jnp.ndarray,
    slice_id: jnp.ndarray,
    num_slices: int,
) -> jnp.ndarray:
    """(S+1,) int32 — assigned pods per slice (last bucket = unlabeled).

    ``assignments`` is the engine's (P,) node index (-1 unassigned);
    unassigned/padded pods land in the unlabeled bucket with weight 0.
    """
    assigned = (assignments >= 0) & pod_valid
    # clip the -1 sentinel before the gather; its weight is already 0
    node = jnp.clip(assignments, 0, slice_id.shape[0] - 1)
    sl = jnp.where(assigned, slice_id[node], num_slices)
    return (
        jnp.zeros(num_slices + 1, dtype=jnp.int32)
        .at[sl]
        .add(assigned.astype(jnp.int32))
    )


def alignment_score(
    assignments: jnp.ndarray,
    pod_valid: jnp.ndarray,
    slice_id: jnp.ndarray,
    num_slices: int,
) -> "tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]":
    """``(alignment, cut, slices_used)`` for one candidate placement.

    alignment = Σ_s c_s² over LABELED slices — maximal when the whole
    gang shares one slice; cut = G_labeled² − alignment ∝ cross-slice
    member pairs (the DCN traffic proxy); slices_used counts labeled
    slices the gang touches (the fragmentation footprint). All int32
    scalars, comparable across vmapped candidates.
    """
    counts = slice_counts(assignments, pod_valid, slice_id, num_slices)
    labeled = counts[:num_slices] if num_slices else counts[:0]
    align = jnp.sum(labeled * labeled).astype(jnp.int32)
    g = jnp.sum(labeled).astype(jnp.int32)
    cut = g * g - align
    used = jnp.sum((labeled > 0).astype(jnp.int32))
    return align, cut, used


def slice_occupancy(
    requested: jnp.ndarray,
    node_valid: jnp.ndarray,
    slice_id: jnp.ndarray,
    num_slices: int,
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Per-slice occupancy from the node resource rows.

    Returns ``(active, sizes)``: (S+1,) bool — slice has ANY requested
    resource on a valid node — and (S+1,) int32 valid-node counts. The
    packing objective reads these to price "opening" a fully-free slice
    (fragmentation) vs landing in an already-active one (alignment).
    """
    busy = (jnp.sum(requested, axis=1) > 0) & node_valid
    busy_per = (
        jnp.zeros(num_slices + 1, dtype=jnp.int32)
        .at[slice_id]
        .add(busy.astype(jnp.int32))
    )
    sizes = (
        jnp.zeros(num_slices + 1, dtype=jnp.int32)
        .at[slice_id]
        .add(node_valid.astype(jnp.int32))
    )
    return busy_per > 0, sizes


@partial(jax.jit, static_argnames=("num_slices",))
def free_slices(
    requested: jnp.ndarray,
    node_valid: jnp.ndarray,
    slice_id: jnp.ndarray,
    num_slices: int,
) -> jnp.ndarray:
    """int32 — labeled slices with ≥1 valid node and ZERO requested
    resources anywhere (the bench's ``slices_free_at_steady_state``)."""
    active, sizes = slice_occupancy(requested, node_valid, slice_id, num_slices)
    labeled_active = active[:num_slices] if num_slices else active[:0]
    labeled_sizes = sizes[:num_slices] if num_slices else sizes[:0]
    return jnp.sum(((~labeled_active) & (labeled_sizes > 0)).astype(jnp.int32))
