from . import filters, scores  # noqa: F401
