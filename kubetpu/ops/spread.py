"""PodTopologySpread device kernels.

Reference semantics (pkg/scheduler/framework/plugins/podtopologyspread/):
- Filter (filtering.go:314): per DoNotSchedule constraint,
  ``matchNum + selfMatch − minMatch > maxSkew`` → infeasible; nodes missing
  the topology key are infeasible outright. ``minMatch`` is the minimum
  per-domain match count over counted domains, treated as 0 when
  ``len(domains) < minDomains`` (filtering.go:55 minMatchNum).
- Score (scoring.go:199): per ScheduleAnyway constraint,
  ``cnt·log(size+2) + (maxSkew−1)`` summed over constraints, rounded; then
  the plugin's own NormalizeScore (scoring.go:229):
  ``MaxNodeScore·(max+min−s)/max`` over scored nodes, ignored → 0,
  max==0 → MaxNodeScore.

All kernels take the carried per-(signature, node) match-count state
(``counts``) so in-batch assignments (greedy scan) reproduce the reference's
updateWithPod (filtering.go:181) exactly. Per-domain sums are segment-sums of
``counts`` over the interned domain ids; domain id −1 (node ineligible /
value not counted) routes to a scratch segment and reads back matchNum 0 via
the Go-map-miss convention.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_NODE_SCORE = 100
_BIG = jnp.iinfo(jnp.int32).max


def _domain_sums(counts_s, eligible_s, node_domain_s, num_domains_total):
    """(D+1,) per-domain match sums for one signature; slot D is the −1
    scratch bucket."""
    seg = jnp.where(node_domain_s >= 0, node_domain_s, num_domains_total)
    vals = jnp.where(eligible_s, counts_s, 0)
    return jax.ops.segment_sum(vals, seg, num_segments=num_domains_total + 1)


def spread_filter_pod(st, counts, sig_idx, action, max_skew, min_domains, self_match):
    """(N,) bool feasibility for ONE pod's hard constraints.

    ``st`` is the device SpreadTensors pytree; ``counts`` the (S, N) carried
    state; the remaining args are the pod's (C,) constraint-slot rows.
    """
    n = st.eligible.shape[1]
    d = st.domain_present.shape[1]
    ok = jnp.ones(n, dtype=bool)
    C = sig_idx.shape[0]
    for c in range(C):  # C is a small static bound; unrolled
        sid = sig_idx[c]
        valid = (sid >= 0) & (action[c] == 0)
        s = jnp.maximum(sid, 0)
        elig = st.eligible[s]
        dom = st.node_domain[s]
        sums = _domain_sums(counts[s], elig, dom, d)          # (D+1,)
        present = st.domain_present[s]
        min_match = jnp.min(jnp.where(present, sums[:d], _BIG))
        min_match = jnp.where(
            st.num_domains[s] < min_domains[c], 0, min_match
        )
        match_num = jnp.where(dom >= 0, sums[jnp.where(dom >= 0, dom, d)], 0)
        skew_ok = (match_num + self_match[c] - min_match) <= max_skew[c]
        ok_c = st.has_key[s] & skew_ok
        ok = ok & jnp.where(valid, ok_c, True)
    return ok


def spread_score_pod(
    st, counts, sig_idx, action, max_skew, ignored, mask
):
    """(N,) int64 normalized spread score for ONE pod.

    ``mask`` is the pod's final feasibility row (the reference scores only
    nodes that passed Filter); ``ignored`` its soft-ignored row.
    """
    n = st.eligible.shape[1]
    d = st.domain_present.shape[1]
    scored = mask & ~ignored
    raw = jnp.zeros(n, dtype=jnp.float64)
    C = sig_idx.shape[0]
    for c in range(C):
        sid = sig_idx[c]
        valid = (sid >= 0) & (action[c] == 1)
        s = jnp.maximum(sid, 0)
        elig = st.eligible[s]
        dom = st.node_domain[s]
        sums = _domain_sums(counts[s], elig, dom, d)
        # per-node count: hostname constraints read the node's own count
        # (scoring.go:217), others the node's domain sum
        cnt_node = jnp.where(
            st.is_hostname[s],
            counts[s].astype(jnp.int64),
            jnp.where(dom >= 0, sums[jnp.where(dom >= 0, dom, d)], 0),
        )
        # topology size over *scored* nodes (initPreScoreState topoSize /
        # filteredNodes−ignored for hostname)
        seg = jnp.where(dom >= 0, dom, d)
        present_scored = (
            jax.ops.segment_max(
                scored.astype(jnp.int32), seg, num_segments=d + 1
            )[:d]
            > 0
        )
        size = jnp.where(
            st.is_hostname[s],
            jnp.sum(scored),
            jnp.sum(present_scored),
        )
        weight = jnp.log(size.astype(jnp.float64) + 2.0)
        contrib = cnt_node.astype(jnp.float64) * weight + (
            max_skew[c].astype(jnp.float64) - 1.0
        )
        raw = raw + jnp.where(valid & st.has_key[s], contrib, 0.0)
    score = jnp.round(raw).astype(jnp.int64)                  # (N,)

    # NormalizeScore (scoring.go:229) over scored nodes
    min_s = jnp.min(jnp.where(scored, score, jnp.iinfo(jnp.int64).max))
    max_s = jnp.max(jnp.where(scored, score, 0))
    normalized = jnp.where(
        max_s == 0,
        jnp.int64(MAX_NODE_SCORE),
        MAX_NODE_SCORE * (max_s + min_s - score) // jnp.maximum(max_s, 1),
    )
    # A pod with no soft constraints Skips the plugin entirely
    # (scoring.go:149 PreScore returns Skip) — 0, not the max==0 branch.
    any_soft = jnp.any((sig_idx >= 0) & (action == 1))
    return jnp.where(any_soft & scored, normalized, 0)
