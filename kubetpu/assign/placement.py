"""Device-parallel placement search for pod-group (gang) scheduling.

The reference evaluates candidate placements SEQUENTIALLY: for each
placement it restricts the snapshot, runs the per-pod algorithm, reverts,
and finally scores the successful placements
(pkg/scheduler/schedule_one_podgroup.go:632 podGroupSchedulingPlacementAlgorithm,
framework/plugins/topologyaware/topology_placement.go:61 GeneratePlacements).

TPU-native re-design: a placement is a ``(N,)`` node mask; all D candidate
placements are stacked into a ``(D, N)`` tensor and the WHOLE search runs as
one device program — ``vmap`` of the assignment engine over the placement
axis. Every placement's simulation is independent (the reference reverts
between them), so the vmap is semantically exact, and the D sequential
snapshot-restrict/simulate/revert rounds become one batched program.

Placement selection (findBestPlacement, schedule_one_podgroup.go:706) uses
PlacementScore plugins; the in-tree scorer is PodGroupPodsCount
(plugins/podgrouppodscount/podgroup_pods_count.go:52 — scheduled + proposed
count, min-max normalized). With one scorer, normalization is monotone, so
argmax of the raw count picks the same placement; ties break on the FIRST
placement in generation order (deterministic) where the reference picks a
random tie (score.Randomizer) — same documented tie-break budget as the
greedy scan's first-max-node rule.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from ..framework import runtime as rt


@partial(jax.jit, static_argnames=("params", "engine"))
def placement_assign_device(
    b: rt.DeviceBatch,
    params: rt.ScoreParams,
    placement_masks: jnp.ndarray,     # (D, N) bool — candidate node subsets
    engine: str = "greedy",
):
    """Run the assignment engine once per placement, all on device.

    Returns ``(assignments (D, P) int32, counts (D,) int32, alignment
    (D,) int32)`` where ``counts[d]`` is how many batch pods placement d
    schedules (the ProposedAssignments count the placement scorer
    consumes) and ``alignment[d]`` is the slice-alignment score of the
    proposal (Σ c_s² over the topology coordinates — ``ops.topology``).
    Alignment is all-zero when the batch carries no topology block, so
    count-first selection is unchanged on a topology-off build.
    """
    if engine == "batched":
        from .batched import batched_assign_device as assign
    else:
        from .greedy import greedy_assign_device as assign

    def one(mask):
        bb = dataclasses.replace(
            b,
            nodes=dataclasses.replace(
                b.nodes, node_valid=b.node_valid & mask
            ),
        )
        assignments, _ = assign(bb, params)
        if b.topology is not None:
            from ..ops.topology import alignment_score

            align, _, _ = alignment_score(
                assignments, b.pod_valid,
                b.topology.slice_id, b.topology.num_slices,
            )
        else:
            align = jnp.int32(0)
        return assignments, align

    assignments, alignment = jax.vmap(one)(placement_masks)   # (D, P), (D,)
    counts = jnp.sum(
        (assignments >= 0) & b.pod_valid[None, :], axis=1
    ).astype(jnp.int32)
    return assignments, counts, alignment
