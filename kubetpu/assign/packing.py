"""Constraint-based packing engine — cluster-level objectives on device.

The greedy scan and the batched rounds both optimize *per-cycle placement*:
each pod lands on its own best-scoring node and the cluster-level outcome
(how many nodes carry the workload, which priorities got admitted) is
whatever falls out. This third engine inverts that: it solves a penalized
LP-relaxation of the bin-pack over the same device-resident
``(pods × nodes × resources)`` tensors, maximizing

    priority-weighted admission  −  α·nodes-opened  −  β·fragmentation

as a fixed-point projection loop. "Priority Matters" (arXiv:2511.08373)
poses the same objective as a constraint program solved on the host; here
the relaxation runs as rounds of a ``jax.lax.while_loop`` so one cycle is
still a single fixed-shape device program, mesh-shardable on the node axis
exactly like the other two engines.

Mechanics per round (the batched engine's skeleton, rescored):

1. ``feasible_and_scores`` gives the EXACT hard-constraint mask (fits,
   taints, affinity, ports, nominations — relaxation never touches it) and
   the profile score.
2. The **packing utility** replaces the raw score as the argmax key:
   normalized profile score (tiebreak weight) minus the α penalty for
   landing on a still-empty node, minus the β emptiness of the target (a
   best-fit pull toward already-full nodes), minus a per-node dual price
   λ_n. The weights live in ONE ``(K,)`` device tensor
   (:class:`PackingWeights`) — the future learned-scoring hook
   (arXiv:2603.10545): a tuning loop perturbs a tensor, not code.
3. **Priority-ordered acceptance**: of the pods that chose a node, the
   highest-priority (queue order within a tier) is admitted — capacity
   checked exactly, one per node per round, commit-prefix semantics like
   the batched engine so every round provably progresses. This is where
   "priority-weighted admission" is enforced, not just scored: when
   capacity is scarce the high tiers win the contested slots.
4. **Dual ascent**: λ_n rises where this round's choices collided
   (``log1p(choosers−1)`` steps, clipped below the α opening penalty so
   pricing spreads pods across OPEN nodes but never pushes them to open a
   new one). λ is the relaxation's memory of contention.

**Warm start** is the perf claim: λ persists across cycles in a
device-resident :class:`~kubetpu.framework.runtime.PackingSolverState`
block beside ``ResidentNodeState`` (donated back into the solver each
cycle, DS001-safe). On a churn-steady cluster the previous cycle's prices
already encode where contention lives, so the first rounds don't pile onto
the same nodes and the loop converges in a handful of iterations instead
of from-scratch — measured as ``solver_iters_per_cycle`` in the perf
runner, never asserted.

The engine returns the identical ``(assignments, 7-slot final_state)``
contract, so gang atomicity (podgroup machinery), preemption, nomination
and binding ride through unchanged; ``--engine greedy``/``batched`` remain
bit-identical escape hatches.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ..framework import runtime as rt
from .batched import I64_MIN

# fixed-point scale for the float packing utility before it enters the
# int64 banded tie-spread argmax (20 fractional bits; utilities are O(1))
_UTIL_SCALE = float(1 << 20)


@dataclass(frozen=True)
class PackingWeights:
    """Objective weights, host-side view of the ``(K,)`` device tensor.

    ``score_weight``    — profile score (row-normalized) as tiebreak pull.
    ``priority_weight`` — per-priority-point admission bonus in the
                          OBJECTIVE (admission order uses raw priority).
    ``alpha_open``      — penalty for placing on a node with zero pods.
    ``beta_frag``       — penalty ∝ target-node emptiness (best-fit pull).
    ``dual_step``       — λ ascent step per ``log1p`` overflow unit.
    ``dual_decay``      — per-cycle multiplicative λ decay (forgets stale
                          contention; 0 disables warm-start entirely).
    ``tie_band``        — utility width within which nodes count as TIED
                          and pods fan across them by rank. The solver
                          emits EQUALIZATION prices (λ_j that level the
                          used nodes' penalized utilities, the LP-dual
                          fixed-point property), so a warm λ pulls last
                          cycle's used set into one band and the next
                          solve spreads in round one instead of replaying
                          the band-by-band descent — the warm-start lever.
    ``lam_cap_frac``    — λ clip ceiling as a fraction of ``alpha_open``
                          (bounds how much history a price can carry; set
                          above the biggest utility gap equalization must
                          bridge).
    ``slice_frag``      — penalty for landing on a node whose TPU slice is
                          currently fully free (opening it fragments a
                          slice a future aligned gang could have taken
                          whole). Inert without a topology block.
    ``slice_align``     — reward for landing in a slice that already
                          carries load (concentrates the workload into
                          fewer slices). Inert without a topology block.

    Serialized into bench records (``WorkloadResult.packing_weights``) so a
    measured frontier is reproducible from its JSON alone.
    """

    score_weight: float = 0.25
    priority_weight: float = 0.1
    alpha_open: float = 1.0
    beta_frag: float = 0.5
    dual_step: float = 0.1
    dual_decay: float = 0.9
    tie_band: float = 0.15
    lam_cap_frac: float = 2.0
    slice_frag: float = 0.5
    slice_align: float = 0.25

    def tensor(self) -> jnp.ndarray:
        """The ``(K,)`` float32 device tensor the solver consumes."""
        return jnp.asarray(
            [
                self.score_weight, self.priority_weight, self.alpha_open,
                self.beta_frag, self.dual_step, self.dual_decay,
                self.tie_band, self.lam_cap_frac,
                self.slice_frag, self.slice_align,
            ],
            dtype=jnp.float32,
        )

    def to_json(self) -> dict:
        return {
            "score_weight": self.score_weight,
            "priority_weight": self.priority_weight,
            "alpha_open": self.alpha_open,
            "beta_frag": self.beta_frag,
            "dual_step": self.dual_step,
            "dual_decay": self.dual_decay,
            "tie_band": self.tie_band,
            "lam_cap_frac": self.lam_cap_frac,
            "slice_frag": self.slice_frag,
            "slice_align": self.slice_align,
        }


def _banded_tie_choice(mask, util, active, band):
    """Per-pod target node: the batched engine's tie-spread argmax with the
    tie predicate widened from ``== best`` to ``>= best − band`` — nodes
    whose utility sits within the band of the max count as one tie class
    and the class's pods fan across it by rank. ``band == 0`` reduces to
    the exact tie-spread. Returns (P,) int32, -1 = no feasible node."""
    p, n = mask.shape
    feasible = mask & active[:, None]
    any_f = jnp.any(feasible, axis=1)
    masked = jnp.where(feasible, util, I64_MIN)
    best = jnp.max(masked, axis=1)                         # (P,)
    ties = feasible & (masked >= best[:, None] - band)     # (P, N)

    # group hash: deterministic projection of the tie row + the max
    # utility (collisions only merge rank counters — suboptimal spreading,
    # never incorrect; acceptance still enforces capacity)
    w = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761) + 1).astype(
        jnp.uint64
    )
    h = jnp.sum(jnp.where(ties, w[None, :], 0), axis=1)
    h = h ^ (best.astype(jnp.uint64) << jnp.uint64(1))
    h = jnp.where(any_f & active, h, jnp.uint64(0))

    # rank of each pod within its hash group, by pod (queue) order
    iota = jnp.arange(p, dtype=jnp.int32)
    sh, si = jax.lax.sort((h, iota), num_keys=2)
    seg_start = jnp.where(
        jnp.concatenate([jnp.array([True]), sh[1:] != sh[:-1]]), iota, 0
    )
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    rank_sorted = iota - seg_start
    rank = jnp.zeros(p, dtype=jnp.int32).at[si].set(rank_sorted)

    cnt = jnp.sum(ties, axis=1).astype(jnp.int32)          # (P,)
    r = jnp.where(cnt > 0, rank % jnp.maximum(cnt, 1), 0)
    # the (r+1)-th True column of the tie row
    csum = jnp.cumsum(ties.astype(jnp.int32), axis=1)      # (P, N)
    choice = jnp.argmax(csum == (r[:, None] + 1), axis=1).astype(jnp.int32)
    return jnp.where(any_f & active, choice, jnp.int32(-1))


def _priority_order(priority, pod_valid):
    """(P,) int32 rank of each pod under (priority desc, queue order asc):
    rank 0 schedules first. Invalid pods sink to the end."""
    p = priority.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    # single sortable key: higher priority first, queue order within a tier
    key = jnp.where(pod_valid, -priority.astype(jnp.int64), 2**40) * p + iota
    _, si = jax.lax.sort((key, iota), num_keys=1)
    return jnp.zeros(p, dtype=jnp.int32).at[si].set(iota)


def _accept_packed(choice, requests, free, count_room, order, coupled,
                   check_capacity=True):
    """Priority-ordered MULTI-admission: every pod whose prefix (by
    admission ``order``, within its target node's chooser set) still fits
    the node's free capacity and pod-count room is admitted this round —
    a whole bin fills in one iteration instead of one pod per round (the
    batched engine's one-per-node rule buys greedy parity; packing buys
    convergence speed instead). Capacity stays the exact projection: the
    prefix-sum check is cumulative, so the admitted set never overcommits
    (assume-between-pods semantics, like the scan). With ``check_capacity``
    off (NodeResourcesFit filter disabled) every chooser is admitted — the
    greedy scan happily overcommits there too.

    ``coupled`` marks pods whose landing changes constraint state other
    pods' round-start masks already read (hostPorts, spread-count
    contributions, affinity-sum updates): co-admitting two of those to one
    node could violate a constraint the mask can't see mid-round (two
    port-80 pods both admitted to the node that had the port free). At
    most ONE coupled pod is admitted per node per round — plain pods keep
    full multi-admission, which is the convergence win; constraint-heavy
    pods degrade to exactly the batched engine's within-node serialism."""
    p = requests.shape[0]
    n = free.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    key = jnp.where(choice >= 0, choice, jnp.int32(n))     # inactive last
    sk, _so, si = jax.lax.sort((key, order, iota), num_keys=2)
    ok = sk < n
    first = jnp.concatenate([jnp.array([True]), sk[1:] != sk[:-1]])
    # segment-start position broadcast forward (the same seg_start trick
    # as the tie-spread rank) — shared by the capacity prefix sums and the
    # one-coupled-per-segment rule
    seg_pos = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, iota, 0)
    )
    if check_capacity:
        node = jnp.minimum(sk, n - 1)
        # segment-relative inclusive prefix sums: cum − base, where base is
        # the exclusive cumsum at the segment start
        s_req = requests[si].astype(jnp.int64)             # (P, R)
        cum = jnp.cumsum(s_req, axis=0)
        excl = cum - s_req
        base = excl[seg_pos]                                # (P, R)
        within = cum - base                                 # inclusive
        cnt = iota - seg_pos + 1                            # 1-based rank
        ok = (
            ok
            & jnp.all(within <= free[node], axis=1)
            & (cnt <= count_room[node])
        )
    # one coupled pod per segment per round (conservative: rejected-for-
    # capacity coupled choosers still count — costs a round, never safety)
    s_c = coupled[si].astype(jnp.int32)
    cum_c = jnp.cumsum(s_c)
    c_within = cum_c - (cum_c - s_c)[seg_pos]               # inclusive
    ok = ok & ((s_c == 0) | (c_within == 1))
    accepted = jnp.zeros(p, dtype=bool).at[si].set(ok)
    return accepted & (choice >= 0)


@partial(jax.jit, static_argnames=("params", "max_iters"),
         donate_argnums=(2,))
def packing_assign_device(
    b: rt.DeviceBatch, params: rt.ScoreParams, lam: jnp.ndarray,
    weights: jnp.ndarray, max_iters: int = 0,
):
    """One packing solve. ``lam`` is the (N,) float32 warm-start dual
    vector (DONATED — callers must rebind it from the result, DS001);
    ``weights`` the (K,) :class:`PackingWeights` tensor.

    Returns ``(assignments, final_state, lam, objective, iters,
    nodes_used)`` — the first two are the engine contract, the rest feed
    the solver-state block, flight recorder and telemetry.
    """
    p = b.requests.shape[0]
    n = b.alloc.shape[0]
    cap = max_iters or p
    prio = (
        b.pod_priority if b.pod_priority is not None
        else jnp.zeros(p, dtype=jnp.int32)
    )
    w_score, w_prio = weights[0], weights[1]
    alpha, beta = weights[2], weights[3]
    step, decay = weights[4], weights[5]
    band_f, cap_frac = weights[6], weights[7]
    w_sfrag, w_salign = weights[8], weights[9]
    lam = lam * decay                  # forget a fraction of stale prices
    lam_cap = alpha * cap_frac
    band = jnp.round(band_f * _UTIL_SCALE).astype(jnp.int64)
    order = _priority_order(prio, b.pod_valid)
    # pods whose landing mutates constraint state (ports taken, spread
    # counts, affinity sums) — _accept_packed serializes these within a
    # node so a round-start mask is never violated mid-round
    coupled = jnp.any(b.pod_ports != 0, axis=1)
    if b.spread is not None:
        coupled = coupled | jnp.any(b.spread.pod_match_sig != 0, axis=1)
    if b.podaffinity is not None:
        coupled = coupled | jnp.any(b.podaffinity.update != 0, axis=1)
    node_iota = jnp.arange(n, dtype=jnp.int32)
    alloc_f = jnp.maximum(b.alloc, 1).astype(jnp.float32)
    has_cap = (b.alloc > 0) & b.node_valid[:, None]
    res_n = jnp.maximum(jnp.sum(has_cap, axis=1), 1).astype(jnp.float32)

    def emptiness(requested):
        """(N,) mean free-fraction over capacity-bearing resources — the
        best-fit pull: fuller nodes read lower."""
        free_frac = jnp.where(
            has_cap, (b.alloc - requested).astype(jnp.float32) / alloc_f, 0.0
        )
        return jnp.sum(free_frac, axis=1) / res_n

    def cond(carry):
        (_, _, _, _, _, _, _, active, _, progress, _, iters) = carry
        return jnp.any(active) & progress & (iters < cap)

    def body(carry):
        (requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
         nom_active, active, assignments, _, lam, iters) = carry
        mask, score = rt.feasible_and_scores(
            b, params,
            requested=requested, nonzero_requested=nonzero,
            pod_count=pod_count, node_ports=node_ports,
            spread_counts=spread_counts, pa_sums=pa_sums,
            nominated_active=nom_active,
        )
        # packing utility: per-pod row-normalized profile score as the
        # tiebreak, node-level packing terms as the decision
        score_f = score.astype(jnp.float32)
        row_max = jnp.max(
            jnp.where(mask, jnp.abs(score_f), 0.0), axis=1, keepdims=True
        )
        norm = score_f / jnp.maximum(row_max, 1.0)          # (P, N) in [-1,1]
        closed = ((pod_count == 0) & b.node_valid).astype(jnp.float32)
        # deterministic low-index bias on CLOSED nodes only, one step per
        # index WIDER than the tie band: still-empty nodes must never form
        # a tie class (fanning pods across empty nodes is exactly
        # anti-packing — bins open one at a time, lowest index first).
        # Open nodes carry no bias, so near-equal open nodes DO tie and
        # the class fills in parallel.
        bias = closed * node_iota.astype(jnp.float32) * (2.0 * band_f)
        node_pen = alpha * closed + beta * emptiness(requested) + lam + bias
        if b.topology is not None:
            # slice terms recompute per round from the CURRENT requested
            # rows, so the first pod admitted into a free slice flips its
            # price for every later round — slices open one at a time
            from ..ops.topology import slice_occupancy

            sid, n_sl = b.topology.slice_id, b.topology.num_slices
            s_active, _ = slice_occupancy(requested, b.node_valid, sid, n_sl)
            labeled_n = sid < n_sl
            in_free = labeled_n & ~s_active[sid]
            in_active = labeled_n & s_active[sid]
            node_pen = node_pen + (
                w_sfrag * in_free.astype(jnp.float32)
                - w_salign * in_active.astype(jnp.float32)
            )
        util_f = w_score * norm - node_pen[None, :]
        util = jnp.where(
            mask, jnp.round(util_f * _UTIL_SCALE).astype(jnp.int64), I64_MIN
        )
        choice = _banded_tie_choice(mask, util, active, band)
        accepted = _accept_packed(
            choice, b.requests,
            free=b.alloc - requested,
            count_room=b.allowed_pods - pod_count,
            order=order, coupled=coupled,
            check_capacity=params.filter_fit,
        )
        # dual ascent on the OVERFLOW (choosers that did not fit this
        # round): λ prices sustained contention so the next round — and,
        # warm-started, the next cycle — spreads straight to where room is
        seg_all = jnp.where(choice >= 0, choice, n)
        choosers = jax.ops.segment_sum(
            (active & (choice >= 0)).astype(jnp.float32),
            seg_all, num_segments=n + 1,
        )[:n]
        admitted_n = jax.ops.segment_sum(
            accepted.astype(jnp.float32), seg_all, num_segments=n + 1,
        )[:n]
        lam = jnp.clip(
            lam + step * jnp.log1p(jnp.maximum(choosers - admitted_n, 0.0)),
            0.0, lam_cap,
        )
        # no commit prefix (that is the batched engine's greedy-parity
        # device; packing has its own order): every admitted pod commits.
        # A pod with no feasible node finalizes only if it precedes every
        # rejection in admission order — a later state update (affinity,
        # spread) could still open a node for it otherwise. The earliest-
        # ordered active pod always commits or finalizes, so every
        # iteration progresses and the loop terminates in ≤ P rounds.
        rejected = active & (choice >= 0) & ~accepted
        first_rej = jnp.min(jnp.where(rejected, order, jnp.int32(p)))
        finalize = active & (choice < 0) & (order < first_rej)
        seg = jnp.where(accepted, choice, n)               # N = drop bucket
        a64 = accepted.astype(jnp.int64)
        requested = requested + jax.ops.segment_sum(
            b.requests * a64[:, None], seg, num_segments=n + 1
        )[:n]
        nonzero = nonzero + jax.ops.segment_sum(
            b.nonzero_requests * a64[:, None], seg, num_segments=n + 1
        )[:n]
        pod_count = pod_count + jax.ops.segment_sum(
            accepted.astype(pod_count.dtype), seg, num_segments=n + 1
        )[:n]
        node_ports = node_ports | (
            jax.ops.segment_sum(
                b.pod_ports.astype(jnp.int64) * a64[:, None],
                seg, num_segments=n + 1,
            )[:n] > 0
        )
        if spread_counts is not None:
            onehot = (choice[:, None] == node_iota[None, :]) & accepted[:, None]
            upd = jnp.einsum(
                "ps,pn->sn", b.spread.pod_match_sig.astype(jnp.int32),
                onehot.astype(jnp.int32),
            ) * b.spread.eligible.astype(jnp.int32)
            spread_counts = spread_counts + upd.astype(spread_counts.dtype)
        if pa_sums is not None:
            pa = b.podaffinity
            r_rows, d = pa_sums.shape
            safe_choice = jnp.maximum(choice, 0)
            dcol = pa.node_domain[:, safe_choice].T           # (P, R)
            valid = (dcol >= 0) & accepted[:, None]
            inc = jnp.where(valid, pa.update, 0)              # (P, R)
            flat_ids = jnp.where(
                valid,
                jnp.arange(r_rows, dtype=jnp.int32)[None, :] * d
                + jnp.maximum(dcol, 0),
                r_rows * d,                                   # drop bucket
            )
            flat = jax.ops.segment_sum(
                inc.reshape(-1), flat_ids.reshape(-1),
                num_segments=r_rows * d + 1,
            )[: r_rows * d]
            pa_sums = pa_sums + flat.reshape(r_rows, d)
        if nom_active is not None:
            idx = b.nominated_pod_idx
            consumed = (idx >= 0) & accepted[jnp.maximum(idx, 0)]
            nom_active = nom_active & ~consumed
        assignments = jnp.where(accepted, choice, assignments)
        active = active & ~accepted & ~finalize
        progress = jnp.any(accepted | finalize)
        return (requested, nonzero, pod_count, node_ports, spread_counts,
                pa_sums, nom_active, active, assignments, progress, lam,
                iters + 1)

    init = (
        b.requested, b.nonzero_requested, b.pod_count, b.node_ports,
        None if b.spread is None else b.spread.node_count,
        None if b.podaffinity is None else b.podaffinity.base_sums,
        None if b.nominated_pod_idx is None
        else jnp.ones(b.nominated_pod_idx.shape[0], dtype=bool),
        b.pod_valid,
        jnp.full(p, -1, dtype=jnp.int32),
        jnp.array(True),
        lam,
        jnp.int32(0),
    )
    out = jax.lax.while_loop(cond, body, init)
    (requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
     nom_active, _active, assignments, _progress, lam, iters) = out
    # warm-start output: the EQUALIZATION price at the fixed point, not the
    # loop's raw ascent residue. At an LP-bin-pack optimum the duals
    # equalize penalized utilities across the active bins; computing that
    # directly — λ_j = relu(v_j − v_marginal) over start-state node
    # utilities v, marginal = the worst node this solve actually used —
    # collapses the whole used set into ONE tie band for the next solve,
    # so an unchanged cluster fans out in round one instead of replaying
    # the band-by-band descent. Unused nodes sit strictly below the band
    # (they priced out this solve too), so warm never opens extra nodes.
    closed0 = ((b.pod_count == 0) & b.node_valid).astype(jnp.float32)
    bias0 = closed0 * node_iota.astype(jnp.float32) * (2.0 * band_f)
    v0 = -(alpha * closed0 + beta * emptiness(b.requested) + bias0)
    used = (pod_count > b.pod_count) & b.node_valid
    v_marg = jnp.min(jnp.where(used, v0, jnp.inf))
    lam_eq = jnp.clip(jnp.maximum(v0 - v_marg, 0.0), 0.0, lam_cap)
    lam = jnp.where(jnp.any(used), lam_eq, lam)
    final_state = (
        requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
        nom_active,
    )
    # cluster-level objective, the recorded "why": priority-weighted
    # admission minus what the placement spent in nodes and fragmentation
    admitted = (assignments >= 0) & b.pod_valid
    admission = jnp.sum(
        jnp.where(admitted, 1.0 + w_prio * prio.astype(jnp.float32), 0.0)
    )
    open_nodes = (pod_count > 0) & b.node_valid
    nodes_used = jnp.sum(open_nodes).astype(jnp.int32)
    frag = jnp.sum(jnp.where(open_nodes, emptiness(requested), 0.0))
    objective = admission - alpha * nodes_used.astype(jnp.float32) - beta * frag
    if b.topology is not None:
        # slice-fragmentation spend: slices this solve opened from fully
        # free (the recorded "why" mirrors the per-round utility terms)
        from ..ops.topology import slice_occupancy

        sid, n_sl = b.topology.slice_id, b.topology.num_slices
        act0, _ = slice_occupancy(b.requested, b.node_valid, sid, n_sl)
        act1, _ = slice_occupancy(requested, b.node_valid, sid, n_sl)
        newly_opened = jnp.sum(
            (act1[:n_sl] & ~act0[:n_sl]).astype(jnp.float32)
        )
        objective = objective - w_sfrag * newly_opened
    return assignments, final_state, lam, objective, iters, nodes_used


class PackingEngine:
    """The registered ``engine="packing"`` callable: the scheduler's
    ``(DeviceBatch, ScoreParams) -> (assignments, final_state)`` contract
    wrapping :func:`packing_assign_device` plus the cross-cycle solver
    state. Holds the ``PackingSolverState`` dual block (warm start), the
    ``PackingWeights`` device tensor, and the last solve's diagnostics
    (``last_objective`` / ``last_iters`` / ``last_nodes_used`` — device
    scalars; the scheduler fetches them at cycle finish alongside the
    assignments so no extra sync point is added)."""

    def __init__(self, weights: PackingWeights | None = None, mesh=None):
        self.weights = weights or PackingWeights()
        self.state = rt.PackingSolverState(mesh=mesh)
        self._w: jnp.ndarray | None = None
        self.last_objective = None
        self.last_iters = None
        self.last_nodes_used = None

    def bind_mesh(self, mesh) -> None:
        """Adopt the scheduler's resolved mesh (the seam constructs the
        engine before mesh resolution); drops any un-sharded duals."""
        self.state.bind_mesh(mesh)

    def __call__(self, b: rt.DeviceBatch, params: rt.ScoreParams):
        if self._w is None:
            self._w = self.weights.tensor()
        n = b.alloc.shape[0]
        lam = self.state.duals(n)
        assignments, final_state, lam_out, objective, iters, nodes_used = (
            packing_assign_device(b, params, lam, self._w)
        )
        self.state.store(n, lam_out)
        self.last_objective = objective
        self.last_iters = iters
        self.last_nodes_used = nodes_used
        return assignments, final_state

    @property
    def _cache_size(self):
        # compile-miss accounting (metrics.tpu.jit_cache_size) delegates
        # to the inner jit so packing cycles classify like the other two
        return getattr(packing_assign_device, "_cache_size", None)
