"""Assignment engines — the replacement for the reference's per-pod argmax
(``selectHost``, pkg/scheduler/schedule_one.go:605) and its one-pod-at-a-time
outer loop (``ScheduleOne``, schedule_one.go:67).

- ``greedy``: device-resident ``lax.scan`` with exact sequential-consistency
  semantics (each assignment updates node usage before the next pod is
  scored) — the ≥99%-parity reference mode.
- ``sinkhorn``: capacity-coupled batched assignment (LP-relaxed bin-pack via
  entropic OT) — the throughput mode; diffed against greedy by the parity
  harness.
- ``packing``: constraint-based packing (penalized LP-relaxation of the
  bin-pack, warm-started duals) — cluster-level objectives (nodes used,
  priority-weighted admission); hard constraints stay exact.
"""

from .greedy import greedy_assign, greedy_assign_device  # noqa: F401
