"""Batched assignment v2 — capacity-coupled rounds instead of a per-pod scan.

The reference schedules strictly one pod at a time; its own opportunistic
batching (pkg/scheduler/framework/runtime/batch.go:33) only reuses scores
for identical-signature pods and hits a capacity-coupling wall
(batch.go:61-64): reused placements may violate capacity, so it re-checks
serially. This module is the TPU answer to that wall: solve the whole batch
as a small number of *rounds*, each a single fixed-shape device program:

1. Score all still-unassigned pods against the CURRENT node state (the same
   ``feasible_and_scores`` composition the greedy scan steps through).
2. **Tie-spread argmax**: pods whose (max score, tie set) coincide — the
   identical-pod case that dominates scheduler_perf workloads — are fanned
   across their tie set by rank instead of all piling onto the first max.
   For a singleton group this reduces to exactly the greedy scan's
   "first max-score node" choice, and for K identical pods over an
   equal-score node set it reproduces the scan's round-robin outcome
   (each assignment drops a node's score below the others).
3. **One-per-node queue-order acceptance**: of the pods that chose a node,
   only the first in queue order is admitted this round (capacity checked);
   the rest are rescored next round against the updated state. Because a
   resource assignment only lowers the assigned node's own score, a
   non-conflicted choice is exactly what the scan would have chosen — so
   resource-monotone profiles get pod-for-pod parity with greedy, and
   capacity/ports are never violated (assume-between-pods semantics,
   schedule_one.go:1102).

Rounds run under ``lax.while_loop`` with fixed shapes (an ``active`` mask
carries the frontier) until no pod makes progress. A batch spread over many
feasible nodes converges in O(P / distinct-target-nodes) rounds — one round
for SchedulingBasic shapes; the adversarial case (every pod feasible on one
node only) degrades to the scan's O(P) — with the same result.

This is the LP-relaxation/Sinkhorn family member that keeps integer
semantics: the tie-spread argmax is the zero-temperature limit of a
Sinkhorn row/column balancing over score-equivalent columns, and the
acceptance step is the exact (not relaxed) capacity projection, so the
parity harness (tests/test_batched.py) can hold it to the greedy scan's
results pod-for-pod on the SchedulingBasic shapes.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..framework import runtime as rt

# plain int — a module-level jnp scalar would init the backend at import
I64_MIN = -(2**62)


def _tie_spread_choice(mask, score, active):
    """Per-pod target node: rank-r pod of each (max score, tie set) group
    takes the (r mod |ties|)-th tie node. Returns (P,) int32, -1 = no
    feasible node."""
    p, n = mask.shape
    feasible = mask & active[:, None]
    any_f = jnp.any(feasible, axis=1)
    masked = jnp.where(feasible, score, I64_MIN)
    best = jnp.max(masked, axis=1)                         # (P,)
    ties = feasible & (masked == best[:, None])            # (P, N)

    # group hash: deterministic projection of the tie row + the max score.
    # A collision only merges two groups' rank counters (suboptimal
    # spreading, never incorrect — acceptance still enforces capacity).
    w = (jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761) + 1).astype(
        jnp.uint64
    )
    h = jnp.sum(jnp.where(ties, w[None, :], 0), axis=1)
    h = h ^ (best.astype(jnp.uint64) << jnp.uint64(1))
    h = jnp.where(any_f & active, h, jnp.uint64(0))

    # rank of each pod within its hash group, by pod (queue) order
    iota = jnp.arange(p, dtype=jnp.int32)
    sh, si = jax.lax.sort((h, iota), num_keys=2)
    seg_start = jnp.where(
        jnp.concatenate([jnp.array([True]), sh[1:] != sh[:-1]]), iota, 0
    )
    seg_start = jax.lax.associative_scan(jnp.maximum, seg_start)
    rank_sorted = iota - seg_start
    rank = jnp.zeros(p, dtype=jnp.int32).at[si].set(rank_sorted)

    cnt = jnp.sum(ties, axis=1).astype(jnp.int32)          # (P,)
    r = jnp.where(cnt > 0, rank % jnp.maximum(cnt, 1), 0)
    # the (r+1)-th True column of the tie row
    csum = jnp.cumsum(ties.astype(jnp.int32), axis=1)      # (P, N)
    choice = jnp.argmax(csum == (r[:, None] + 1), axis=1).astype(jnp.int32)
    return jnp.where(any_f & active, choice, jnp.int32(-1))


def _accept(choice, requests, free, count_room, check_capacity=True):
    """Queue-order admission, at most ONE pod per node per round.

    One-per-node is the sequential-consistency key: with it, a pod's round-k
    choice diverges from the greedy scan only when its target was taken
    earlier in the round — and then it is REJECTED and rescored next round
    against the updated state, which is exactly the scan's view. Since a
    resource assignment only lowers the assigned node's own score
    (LeastAllocated/Balanced are per-node), every non-conflicting choice is
    greedy's choice, so resource-monotone profiles get pod-for-pod parity.
    (Topology-coupled scores — zone anti-affinity — can still shift OTHER
    nodes' ranking mid-round; the harness measures that residual.)

    ``choice`` (P,) target node (-1 = none); ``free`` (N, R) remaining
    resources; ``count_room`` (N,) remaining pod slots. Feasibility vs. the
    node STATE (ports included) was already enforced by the choice mask.
    ``check_capacity`` mirrors the profile's NodeResourcesFit *filter*: when
    that filter is disabled, the greedy scan happily overcommits a node
    (nothing masks it out), so the batched engine must not re-impose the
    capacity projection here or the two engines diverge.
    """
    p = requests.shape[0]
    n = free.shape[0]
    iota = jnp.arange(p, dtype=jnp.int32)
    key = jnp.where(choice >= 0, choice, jnp.int32(n))     # inactive last
    sk, si = jax.lax.sort((key, iota), num_keys=2)
    first = jnp.concatenate([jnp.array([True]), sk[1:] != sk[:-1]])
    node = jnp.minimum(sk, n - 1)
    ok = first & (sk < n)
    if check_capacity:
        s_req = requests[si]
        ok = (
            ok
            & jnp.all(s_req <= free[node], axis=1)
            & (count_room[node] >= 1)
        )
    accepted = jnp.zeros(p, dtype=bool).at[si].set(ok)
    return accepted & (choice >= 0)


@partial(jax.jit, static_argnames=("params", "max_rounds"))
def batched_assign_device(
    b: rt.DeviceBatch, params: rt.ScoreParams, max_rounds: int = 0
):
    """Run the round loop. Same contract as ``greedy_assign_device``:
    returns ``(assignments (P,) int32 node index or -1, final_state)`` with
    the identical 7-slot final-state tuple."""
    p = b.requests.shape[0]
    n = b.alloc.shape[0]
    cap = max_rounds or p
    node_iota = jnp.arange(n, dtype=jnp.int32)

    def cond(carry):
        (_, _, _, _, _, _, _, active, _, progress, rounds) = carry
        return jnp.any(active) & progress & (rounds < cap)

    def body(carry):
        (requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
         nom_active, active, assignments, _, rounds) = carry
        mask, score = rt.feasible_and_scores(
            b, params,
            requested=requested, nonzero_requested=nonzero,
            pod_count=pod_count, node_ports=node_ports,
            spread_counts=spread_counts, pa_sums=pa_sums,
            nominated_active=nom_active,
        )
        choice = _tie_spread_choice(mask, score, active)
        accepted = _accept(
            choice, b.requests,
            free=b.alloc - requested,
            count_room=b.allowed_pods - pod_count,
            check_capacity=params.filter_fit,
        )
        # Commit only the queue-order prefix before the FIRST rejection: a
        # rejected pod re-chooses next round, and anything a later pod
        # grabbed this round might be exactly what it re-chooses — greedy
        # order says the earlier pod gets it. Pods with no feasible node
        # inside the committed prefix finalize as unschedulable (each pod
        # gets exactly one attempt at its turn, like the scan). The earliest
        # active pod always commits or finalizes, so every round progresses.
        iota_p = jnp.arange(p, dtype=jnp.int32)
        rejected = active & (choice >= 0) & ~accepted
        first_rej = jnp.min(jnp.where(rejected, iota_p, jnp.int32(p)))
        commit = accepted & (iota_p < first_rej)
        finalize = active & (choice < 0) & (iota_p < first_rej)
        accepted = commit
        seg = jnp.where(accepted, choice, n)               # N = drop bucket
        a64 = accepted.astype(jnp.int64)
        requested = requested + jax.ops.segment_sum(
            b.requests * a64[:, None], seg, num_segments=n + 1
        )[:n]
        nonzero = nonzero + jax.ops.segment_sum(
            b.nonzero_requests * a64[:, None], seg, num_segments=n + 1
        )[:n]
        pod_count = pod_count + jax.ops.segment_sum(
            accepted.astype(pod_count.dtype), seg, num_segments=n + 1
        )[:n]
        node_ports = node_ports | (
            jax.ops.segment_sum(
                b.pod_ports.astype(jnp.int64) * a64[:, None],
                seg, num_segments=n + 1,
            )[:n] > 0
        )
        if spread_counts is not None:
            onehot = (choice[:, None] == node_iota[None, :]) & accepted[:, None]
            upd = jnp.einsum(
                "ps,pn->sn", b.spread.pod_match_sig.astype(jnp.int32),
                onehot.astype(jnp.int32),
            ) * b.spread.eligible.astype(jnp.int32)
            spread_counts = spread_counts + upd.astype(spread_counts.dtype)
        if pa_sums is not None:
            pa = b.podaffinity
            r_rows, d = pa_sums.shape
            safe_choice = jnp.maximum(choice, 0)
            dcol = pa.node_domain[:, safe_choice].T           # (P, R)
            valid = (dcol >= 0) & accepted[:, None]
            inc = jnp.where(valid, pa.update, 0)              # (P, R)
            flat_ids = jnp.where(
                valid,
                jnp.arange(r_rows, dtype=jnp.int32)[None, :] * d
                + jnp.maximum(dcol, 0),
                r_rows * d,                                   # drop bucket
            )
            flat = jax.ops.segment_sum(
                inc.reshape(-1), flat_ids.reshape(-1),
                num_segments=r_rows * d + 1,
            )[: r_rows * d]
            pa_sums = pa_sums + flat.reshape(r_rows, d)
        if nom_active is not None:
            idx = b.nominated_pod_idx
            consumed = (idx >= 0) & accepted[jnp.maximum(idx, 0)]
            nom_active = nom_active & ~consumed
        assignments = jnp.where(accepted, choice, assignments)
        active = active & ~accepted & ~finalize
        progress = jnp.any(accepted | finalize)
        return (requested, nonzero, pod_count, node_ports, spread_counts,
                pa_sums, nom_active, active, assignments, progress,
                rounds + 1)

    init = (
        b.requested, b.nonzero_requested, b.pod_count, b.node_ports,
        None if b.spread is None else b.spread.node_count,
        None if b.podaffinity is None else b.podaffinity.base_sums,
        None if b.nominated_pod_idx is None
        else jnp.ones(b.nominated_pod_idx.shape[0], dtype=bool),
        b.pod_valid,
        jnp.full(p, -1, dtype=jnp.int32),
        jnp.array(True),
        jnp.int32(0),
    )
    out = jax.lax.while_loop(cond, body, init)
    (requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
     nom_active, _active, assignments, _progress, rounds) = out
    final_state = (
        requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
        nom_active,
    )
    return assignments, final_state
