"""Greedy assignment as one device-resident ``lax.scan``.

The reference schedules pods strictly one at a time: ``scheduleOne`` pops a
pod, filters + scores all nodes against the *current* cache (which includes
all previously assumed pods), picks the best node (``selectHost``,
schedule_one.go:605), and assumes the pod onto it (cache.AssumePod,
backend/cache/cache.go:397) before the next pod starts. That serialization is
what makes greedy results well-defined on saturated clusters.

Here the same semantics run as a single XLA program: ``lax.scan`` over the
pod axis, carrying ``(requested, nonzero_requested, pod_count)`` node-state
tensors; each step re-runs the full Filter+Score composition for one pod
against the running state and updates it with a one-hot scatter. No
host↔device round-trips inside the batch.

Tie-breaking: the reference picks uniformly at random among max-score nodes
(schedule_one.go:1037 reservoir sample). We take the FIRST max-score node in
snapshot order — deterministic, replayable, and within the documented parity
budget (ties are score-equivalent by definition).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..framework import runtime as rt


def _pod_view(b: rt.DeviceBatch, i) -> rt.DeviceBatch:
    """P=1 view of pod ``i`` (traced index) over the same nodes."""

    def row(a):
        return None if a is None else a[i][None]

    return rt.DeviceBatch(
        # the persistent node block passes through whole (the scan threads
        # its own running node state via the feasible_and_scores overrides)
        nodes=b.nodes,
        requests=b.requests[i][None],
        nonzero_requests=b.nonzero_requests[i][None],
        pod_valid=b.pod_valid[i][None],
        # (S, N) signature arrays pass through whole; the view narrows only
        # the per-pod row indices (device gathers the row inside the kernel)
        static_mask=b.static_mask,
        static_sig=row(b.static_sig),
        node_affinity_raw=b.node_affinity_raw,
        taint_prefer_raw=b.taint_prefer_raw,
        score_sig=row(b.score_sig),
        image_sum_scores=b.image_sum_scores,
        image_sig=row(b.image_sig),
        image_count=row(b.image_count),
        extender_mask=row(b.extender_mask),
        extender_score=row(b.extender_score),
        dra_score_raw=b.dra_score_raw,
        dra_score_sig=row(b.dra_score_sig),
        pod_ports=b.pod_ports[i][None],
        node_ports=b.node_ports,
        port_conflict=b.port_conflict,
        nominated_node=b.nominated_node,
        nominated_req=b.nominated_req,
        nominated_gate=row(b.nominated_gate),
        nominated_ports=b.nominated_ports,
        nominated_pod_idx=b.nominated_pod_idx,
        spread=_spread_view(b.spread, i),
        podaffinity=_pa_view(b.podaffinity, i),
    )


def _pa_view(pa, i):
    if pa is None:
        return None
    import dataclasses

    return dataclasses.replace(
        pa,
        update=pa.update[i][None],
        fa_rows=pa.fa_rows[i][None],
        fa_self=pa.fa_self[i][None],
        ra_rows=pa.ra_rows[i][None],
        ea_rows=pa.ea_rows[i][None],
        score_rows=pa.score_rows[i][None],
        score_vals=pa.score_vals[i][None],
    )


def _spread_view(sp, i):
    if sp is None:
        return None
    import dataclasses

    return dataclasses.replace(
        sp,
        sig_idx=sp.sig_idx[i][None],
        action=sp.action[i][None],
        max_skew=sp.max_skew[i][None],
        min_domains=sp.min_domains[i][None],
        self_match=sp.self_match[i][None],
        pod_match_sig=sp.pod_match_sig[i][None],
        ignored=sp.ignored[i][None],
    )


@partial(jax.jit, static_argnames=("params",))
def greedy_assign_device(b: rt.DeviceBatch, params: rt.ScoreParams):
    """Run the greedy scan. Returns ``(assignments (P,) int32 node index or
    -1, final_state)`` where final_state is the post-batch
    ``(requested, nonzero_requested, pod_count)`` — the cache applies it as
    the batch's assume step.

    Buffer-donation note: the scan CARRY is double-buffered by XLA itself
    (loop state aliases in place inside the compiled program), so the hot
    per-step node-state updates never copy. The INPUT node block must NOT
    be donated here: in pipeline mode those buffers are the device-resident
    cluster state (runtime.ResidentNodeState) reused by the next cycle's
    delta scatter, and the post-cycle preemption PostFilter re-reads them
    through the cycle context. Donation of the node-state buffers happens
    at the one seam where they are provably unreferenced — the resident
    scatter (runtime._scatter_node_rows)."""

    n = b.alloc.shape[0]
    node_iota = jnp.arange(n, dtype=jnp.int32)

    def step(state, i):
        (requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
         nom_active) = state
        view = _pod_view(b, i)
        mask, score = rt.feasible_and_scores(
            view, params,
            requested=requested, nonzero_requested=nonzero,
            pod_count=pod_count, node_ports=node_ports,
            spread_counts=spread_counts, pa_sums=pa_sums,
            nominated_active=nom_active,
        )
        mask, score = mask[0], score[0]
        feasible = jnp.any(mask)
        best = jnp.argmax(jnp.where(mask, score, -1)).astype(jnp.int32)
        chosen = jnp.where(feasible, best, jnp.int32(-1))
        onehot = (node_iota == chosen) & feasible           # (N,) bool
        oh64 = onehot.astype(jnp.int64)[:, None]
        requested = requested + oh64 * view.requests[0][None, :]
        nonzero = nonzero + oh64 * view.nonzero_requests[0][None, :]
        pod_count = pod_count + onehot.astype(pod_count.dtype)
        node_ports = node_ports | (onehot[:, None] & view.pod_ports[0][None, :])
        if spread_counts is not None:
            # updateWithPod (podtopologyspread/filtering.go:181): +1 in every
            # signature whose selector+namespace the assigned pod matches, on
            # the chosen node, when that node is eligible for the signature.
            upd = (
                b.spread.pod_match_sig[i][:, None]
                & b.spread.eligible
                & onehot[None, :]
            )
            spread_counts = spread_counts + upd.astype(spread_counts.dtype)
        if pa_sums is not None:
            # interpodaffinity updateWithPod (filtering.go:75): scatter the
            # assigned pod's increments into each row at the chosen node's
            # domain (no-op when the node lacks the row's topology key).
            pa = b.podaffinity
            r = pa_sums.shape[0]
            dcol = jnp.where(
                chosen >= 0, pa.node_domain[:, jnp.maximum(chosen, 0)], -1
            )                                                   # (R,)
            inc = jnp.where(dcol >= 0, pa.update[i], 0)
            pa_sums = pa_sums.at[
                jnp.arange(r), jnp.maximum(dcol, 0)
            ].add(inc)
        if nom_active is not None:
            # assume deletes the nomination (schedule_one.go:307): once the
            # scan assigns a nomination's own pod, stop charging it
            nom_active = nom_active & ~(
                (b.nominated_pod_idx == i) & feasible
            )
        return (
            requested, nonzero, pod_count, node_ports, spread_counts, pa_sums,
            nom_active,
        ), chosen

    p = b.requests.shape[0]
    init = (
        b.requested, b.nonzero_requested, b.pod_count, b.node_ports,
        None if b.spread is None else b.spread.node_count,
        None if b.podaffinity is None else b.podaffinity.base_sums,
        None if b.nominated_pod_idx is None
        else jnp.ones(b.nominated_pod_idx.shape[0], dtype=bool),
    )
    final_state, assignments = jax.lax.scan(
        step, init, jnp.arange(p, dtype=jnp.int32)
    )
    return assignments, final_state


def greedy_assign(
    batch: rt.EncodedBatch, profile=None, params: rt.ScoreParams | None = None
) -> list[str | None]:
    """Host wrapper: run the scan and map node indices back to names.
    Unschedulable (and padded) pods map to ``None``."""
    if params is None:
        from ..framework import config as C
        params = rt.score_params(profile or C.Profile(), batch.resource_names)
    assignments, _ = greedy_assign_device(batch.device, params)
    out: list[str | None] = []
    idx = jax.device_get(assignments)
    for i in range(batch.num_pods):
        j = int(idx[i])
        out.append(batch.node_names[j] if 0 <= j < len(batch.node_names) else None)
    return out
