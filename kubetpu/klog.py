"""Structured, leveled, CONTEXTUAL logging — the klog v2 analog.

Reference: klog v2 with contextual logging (``klog.FromContext(ctx)``
everywhere, e.g. schedule_one.go:68): components log structured key-value
pairs through a logger that carries bound context (pod, node, cycle …),
gated by a verbosity level (v=2 prod default; v=10 score dumps). Here:

- ``get_logger(name)`` → a component logger; ``log.with_values(pod=key)``
  binds context for everything logged through the child (the FromContext/
  WithValues shape — context rides the LOGGER, pump-driven code has no
  ctx parameter to thread).
- ``log.info/warning/error(msg, **kv)`` emit one line:
  ``I kubetpu.sched "msg" pod="ns/p" node="n0"`` — klog's structured
  output format (message quoted, then key=value pairs).
- ``log.v(level)`` gates verbose paths: enabled when ``KUBETPU_V``
  (default 2) is >= level, so ``log.v(4).info(...)`` is the
  ``klog.V(4).InfoS`` idiom.

Sink is stderr by default; ``set_sink`` redirects (tests, json shippers).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Callable

_SEVERITY = {"info": "I", "warning": "W", "error": "E"}
_lock = threading.Lock()
_sink: Callable[[str], None] | None = None


def set_sink(fn: Callable[[str], None] | None) -> None:
    """Redirect every logger's output (None = stderr)."""
    global _sink
    _sink = fn


def verbosity() -> int:
    try:
        return int(os.environ.get("KUBETPU_V", "2"))
    except ValueError:
        return 2


def _fmt_value(v: Any) -> str:
    if isinstance(v, str):
        return f'"{v}"'
    return str(v)


class _Nop:
    """Disabled verbosity gate: swallow everything."""

    def info(self, *a, **k) -> None:
        pass

    warning = error = info


_NOP = _Nop()


class Logger:
    def __init__(self, name: str, values: tuple[tuple[str, Any], ...] = ()):
        self.name = name
        self._values = values

    def with_values(self, **kv: Any) -> "Logger":
        """Bind context carried by every line (klog.LoggerWithValues)."""
        return Logger(self.name, self._values + tuple(kv.items()))

    def v(self, level: int) -> "Logger | _Nop":
        """klog.V(level): a logger when enabled, a no-op otherwise."""
        return self if verbosity() >= level else _NOP

    def _emit(self, sev: str, msg: str, kv: dict[str, Any]) -> None:
        pairs = " ".join(
            f"{k}={_fmt_value(v)}" for k, v in (*self._values, *kv.items())
        )
        line = f'{_SEVERITY[sev]} {self.name} "{msg}"' + (
            f" {pairs}" if pairs else ""
        )
        sink = _sink
        with _lock:
            if sink is not None:
                sink(line)
            else:
                print(line, file=sys.stderr, flush=True)

    def info(self, msg: str, **kv: Any) -> None:
        self._emit("info", msg, kv)

    def warning(self, msg: str, **kv: Any) -> None:
        self._emit("warning", msg, kv)

    def error(self, msg: str, **kv: Any) -> None:
        self._emit("error", msg, kv)


_loggers: dict[str, Logger] = {}


def get_logger(name: str) -> Logger:
    log = _loggers.get(name)
    if log is None:
        log = _loggers[name] = Logger(name)
    return log
