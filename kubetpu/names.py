"""Canonical plugin names (pkg/scheduler/framework/plugins/names/names.go:19-42).

Shared by the config layer (framework.config) and the tensorization layer
(state.encoder) — the encoder gates its static predicates on the enabled
filter set without importing the framework package.
"""

NODE_RESOURCES_FIT = "NodeResourcesFit"
NODE_RESOURCES_BALANCED = "NodeResourcesBalancedAllocation"
NODE_AFFINITY = "NodeAffinity"
TAINT_TOLERATION = "TaintToleration"
NODE_NAME = "NodeName"
NODE_PORTS = "NodePorts"
NODE_UNSCHEDULABLE = "NodeUnschedulable"
POD_TOPOLOGY_SPREAD = "PodTopologySpread"
INTER_POD_AFFINITY = "InterPodAffinity"
IMAGE_LOCALITY = "ImageLocality"
DEFAULT_PREEMPTION = "DefaultPreemption"
DEFAULT_BINDER = "DefaultBinder"
PRIORITY_SORT = "PrioritySort"
SCHEDULING_GATES = "SchedulingGates"
VOLUME_RESTRICTIONS = "VolumeRestrictions"
VOLUME_ZONE = "VolumeZone"
NODE_VOLUME_LIMITS = "NodeVolumeLimits"
VOLUME_BINDING = "VolumeBinding"
DYNAMIC_RESOURCES = "DynamicResources"
GANG_SCHEDULING = "GangScheduling"
NODE_DECLARED_FEATURES = "NodeDeclaredFeatures"
POD_GROUP_PODS_COUNT = "PodGroupPodsCount"

ALL_FILTERS = frozenset({
    NODE_RESOURCES_FIT,
    NODE_AFFINITY,
    TAINT_TOLERATION,
    NODE_NAME,
    NODE_PORTS,
    NODE_UNSCHEDULABLE,
    POD_TOPOLOGY_SPREAD,
    INTER_POD_AFFINITY,
    VOLUME_RESTRICTIONS,
    VOLUME_ZONE,
    NODE_VOLUME_LIMITS,
    VOLUME_BINDING,
    DYNAMIC_RESOURCES,
    NODE_DECLARED_FEATURES,
})
