"""Typed cluster objects — the scheduling-relevant envelope of the reference's
``staging/src/k8s.io/api/core/v1`` types.

These are plain Python dataclasses, deliberately flat (no nested Container
lists on the hot path): a Pod carries its *aggregated* resource request, which
the reference computes in ``computePodResourceRequest``
(pkg/scheduler/framework/plugins/noderesources/fit.go:317) as
``max(sum(containers), max(initContainers)) + overhead``. Use
``kubetpu.api.requests.pod_requests`` to aggregate from containers when
constructing pods from full specs.

Canonical resource units (reference: apimachinery resource.Quantity, reduced
to int64 canonical form exactly as NodeInfo.Resource does):
  - cpu:               millicores (int)
  - memory:            bytes (int)
  - ephemeral-storage: bytes (int)
  - pods:              count (int, node allocatable only)
  - any other name:    extended/scalar resource, opaque int quantity
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Mapping, Sequence

# Canonical resource names (reference: k8s.io/api/core/v1/types.go ResourceName).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

# Defaults the reference applies for scoring when a pod does not specify a
# request (pkg/scheduler/util/pod_resources.go:28-31). Used only by the
# NonZeroRequested view, never by the Fit filter.
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

# Score bounds (staging/src/k8s.io/kube-scheduler/framework: MaxNodeScore=100).
MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0
MAX_TOTAL_SCORE = (1 << 63) - 1

ResourceList = Mapping[str, int]


class Operator(str, enum.Enum):
    """Label/node-selector requirement operator
    (reference: k8s.io/api/core/v1 NodeSelectorOperator + metav1 LabelSelectorOperator)."""

    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"
    GT = "Gt"
    LT = "Lt"


@dataclass(frozen=True)
class Requirement:
    """One match expression: ``key op values``."""

    key: str
    operator: Operator
    values: tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """metav1.LabelSelector: match_labels AND all match_expressions.

    An empty selector matches everything; ``None`` (where allowed) matches
    nothing — callers encode that distinction, as the reference does.
    """

    match_labels: tuple[tuple[str, str], ...] = ()
    match_expressions: tuple[Requirement, ...] = ()

    @staticmethod
    def of(labels: Mapping[str, str] | None = None,
           exprs: Sequence[Requirement] = ()) -> "LabelSelector":
        return LabelSelector(
            match_labels=tuple(sorted((labels or {}).items())),
            match_expressions=tuple(exprs),
        )


@dataclass(frozen=True)
class NodeSelectorTerm:
    """One term of a NodeSelector: AND of its expressions (+ match_fields on
    metadata.name). Terms are ORed."""

    match_expressions: tuple[Requirement, ...] = ()
    match_fields: tuple[Requirement, ...] = ()  # only metadata.name supported


@dataclass(frozen=True)
class NodeSelector:
    """OR of terms (reference: k8s.io/api/core/v1 NodeSelector)."""

    terms: tuple[NodeSelectorTerm, ...] = ()


@dataclass(frozen=True)
class PreferredSchedulingTerm:
    weight: int  # 1..100
    term: NodeSelectorTerm = NodeSelectorTerm()


class TaintEffect(str, enum.Enum):
    NO_SCHEDULE = "NoSchedule"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"
    NO_EXECUTE = "NoExecute"


@dataclass(frozen=True)
class Taint:
    key: str
    value: str = ""
    effect: TaintEffect = TaintEffect.NO_SCHEDULE


class TolerationOperator(str, enum.Enum):
    EXISTS = "Exists"
    EQUAL = "Equal"


@dataclass(frozen=True)
class Toleration:
    """Reference semantics (component-helpers/scheduling/corev1/helpers.go
    Toleration.ToleratesTaint): empty key + Exists tolerates everything;
    empty effect matches all effects."""

    key: str = ""
    operator: TolerationOperator = TolerationOperator.EQUAL
    value: str = ""
    effect: TaintEffect | None = None  # None = all effects
    # v1 TolerationSeconds: how long a NoExecute taint is tolerated before
    # eviction (None = forever; consumed by the tainteviction controller)
    toleration_seconds: float | None = None


@dataclass(frozen=True)
class PodAffinityTerm:
    """Reference: k8s.io/api/core/v1 PodAffinityTerm. The selector matches
    labels of candidate (existing) pods; namespaces + namespace_selector pick
    which namespaces those pods may live in (empty namespaces + None selector
    = the incoming pod's own namespace)."""

    topology_key: str
    selector: LabelSelector | None = None
    namespaces: tuple[str, ...] = ()
    namespace_selector: LabelSelector | None = None  # None = no selector


@dataclass(frozen=True)
class WeightedPodAffinityTerm:
    weight: int  # 1..100
    term: PodAffinityTerm = None  # type: ignore[assignment]


@dataclass(frozen=True)
class PodAffinity:
    required: tuple[PodAffinityTerm, ...] = ()
    preferred: tuple[WeightedPodAffinityTerm, ...] = ()


@dataclass(frozen=True)
class NodeAffinity:
    required: NodeSelector | None = None
    preferred: tuple[PreferredSchedulingTerm, ...] = ()


@dataclass(frozen=True)
class Affinity:
    node_affinity: NodeAffinity | None = None
    pod_affinity: PodAffinity | None = None
    pod_anti_affinity: PodAffinity | None = None


class UnsatisfiableConstraintAction(str, enum.Enum):
    DO_NOT_SCHEDULE = "DoNotSchedule"
    SCHEDULE_ANYWAY = "ScheduleAnyway"


@dataclass(frozen=True)
class TopologySpreadConstraint:
    """Reference: k8s.io/api/core/v1 TopologySpreadConstraint."""

    max_skew: int
    topology_key: str
    when_unsatisfiable: UnsatisfiableConstraintAction
    selector: LabelSelector | None = None
    min_domains: int | None = None
    # Honor|Ignore; reference defaults: nodeAffinityPolicy=Honor, nodeTaintsPolicy=Ignore
    node_affinity_policy: str = "Honor"
    node_taints_policy: str = "Ignore"
    match_label_keys: tuple[str, ...] = ()


@dataclass(frozen=True)
class ContainerPort:
    host_port: int
    protocol: str = "TCP"
    host_ip: str = ""


@dataclass(frozen=True)
class Pod:
    """A pod as the scheduler sees it. ``requests`` is the aggregated resource
    request (fit.go:317 semantics — aggregate with api.requests.pod_requests
    if building from containers)."""

    name: str
    namespace: str = "default"
    uid: str = ""
    labels: tuple[tuple[str, str], ...] = ()
    requests: tuple[tuple[str, int], ...] = ()  # canonical units, sorted
    # NonZeroRequested scoring view (types.go:1035 CalculateResource). The
    # 100mCPU/200MiB defaults are PER CONTAINER, so this must be aggregated
    # from containers (api.requests.pod_nonzero_requests). None = derive from
    # ``requests`` assuming a single container.
    nonzero: tuple[tuple[str, int], ...] | None = None
    node_name: str = ""          # assigned node ("" = pending)
    node_selector: tuple[tuple[str, str], ...] = ()  # spec.nodeSelector (ANDed equality)
    affinity: Affinity | None = None
    tolerations: tuple[Toleration, ...] = ()
    topology_spread_constraints: tuple[TopologySpreadConstraint, ...] = ()
    priority: int = 0
    ports: tuple[ContainerPort, ...] = ()
    scheduling_gates: tuple[str, ...] = ()
    images: tuple[str, ...] = ()          # container images, for ImageLocality
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    creation_index: int = 0  # monotonic stand-in for creationTimestamp
    # spec.schedulingGroup.podGroupName (core/v1 types.go:4641
    # PodSchedulingGroup) — names a PodGroup in the pod's namespace; drives
    # gang / workload-aware scheduling. "" = not a group member.
    scheduling_group: str = ""
    # spec.volumes, PVC references only (the volume plugin family)
    volumes: tuple[PodVolume, ...] = ()
    # spec.resourceClaims with template instances resolved to claim names
    # (the DynamicResources plugin family)
    resource_claims: tuple["PodResourceClaim", ...] = ()
    # spec.schedulerName — selects the profile (profile.go:46 Map); pods
    # naming an unknown profile are not this scheduler's to place
    scheduler_name: str = "default-scheduler"
    # status.phase slice (Pending/Running/Succeeded/Failed) — maintained by
    # the node agent (kubetpu.kubelet), consumed by podgc
    phase: str = "Pending"
    # metadata.ownerReferences slice: the controller that stamped this pod
    # ("kind/namespace/name"), consumed by replicaset adoption
    owner: str = ""
    # the feature set InferForPodScheduling derives from the spec
    # (component-helpers/nodedeclaredfeatures) — explicit here because the
    # envelope carries aggregated specs; NodeDeclaredFeatures Filter
    # requires it to be a subset of the node's declared_features
    required_node_features: tuple[str, ...] = ()
    # restartPolicy: Never + finite workload (the batch/Job shape): the
    # node agent transitions Running -> Succeeded instead of running forever
    terminates: bool = False
    # metadata.finalizers: a DELETE with finalizers present soft-deletes
    # (deletion_timestamp set, object retained) until every finalizer is
    # cleared — registry/store.go's graceful-deletion/finalizer gate; the
    # Job controller's tracking finalizer rides this
    finalizers: tuple[str, ...] = ()
    # metadata.deletionTimestamp (epoch seconds): non-None = terminating;
    # the node agent winds the pod down, and the store removes the object
    # on the first update that sees finalizers empty
    deletion_timestamp: float | None = None
    # attribution-plane stamps, set ONCE by the apiserver at REST create
    # (sched.flightrecorder): a trace id plus the create's perf_counter
    # second — carried through the watch frame so the scheduler can charge
    # api_ingest/e2e latency to the right pod. Zero values = never stamped
    # (direct-mode harnesses feed the informer seam without an apiserver).
    # perf_counter is PROCESS/HOST-monotonic: the stamp is only comparable
    # when apiserver and scheduler share a host (the in-process stack);
    # the recorder sanity-gates it and degrades to delivery-based
    # attribution for a foreign clock domain. Neither field joins the
    # encode signatures (encoder._static_*), so unique stamps cannot
    # break template-keyed row sharing.
    trace_id: str = ""
    ingest_ts: float = 0.0

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)

    def requests_dict(self) -> dict[str, int]:
        return dict(self.requests)

    def nonzero_requests(self) -> dict[str, int]:
        """The NonZeroRequested view used by resource *scoring* only
        (pkg/scheduler/framework/types.go:1035, util/pod_resources.go)."""
        if self.nonzero is not None:
            return dict(self.nonzero)
        out = dict(self.requests)
        if out.get(CPU, 0) == 0:
            out[CPU] = DEFAULT_MILLI_CPU_REQUEST
        if out.get(MEMORY, 0) == 0:
            out[MEMORY] = DEFAULT_MEMORY_REQUEST
        return out

    def with_node(self, node_name: str) -> "Pod":
        return dataclasses.replace(self, node_name=node_name)


@dataclass(frozen=True)
class PodVolume:
    """The scheduling slice of v1.Volume: only PVC references matter to the
    volume plugins (volumezone/volume_zone.go Filter: 'Currently this is
    only supported with PersistentVolumeClaims'); other volume sources are
    node-agnostic."""

    name: str
    pvc_name: str = ""          # persistentVolumeClaim.claimName ("" = other source)
    read_only: bool = False


# v1.PersistentVolumeAccessMode values the restrictions/binding plugins read
READ_WRITE_ONCE_POD = "ReadWriteOncePod"


@dataclass(frozen=True)
class PersistentVolume:
    """The scheduling slice of v1.PersistentVolume: zone/region labels
    (VolumeZone), spec.nodeAffinity.required (VolumeBinding bound-PV check),
    class/capacity/access (the WaitForFirstConsumer binding search), the CSI
    driver (NodeVolumeLimits counting), and the claim binding."""

    name: str
    labels: tuple[tuple[str, str], ...] = ()
    node_affinity: NodeSelector | None = None
    storage_class: str = ""
    capacity: int = 0                           # storage bytes
    access_modes: tuple[str, ...] = ()
    claim_ref: str = ""                         # "ns/name" of bound PVC
    driver: str = ""                            # CSI driver name

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)


@dataclass(frozen=True)
class PersistentVolumeClaim:
    """The scheduling slice of v1.PersistentVolumeClaim."""

    name: str
    namespace: str = "default"
    volume_name: str = ""                       # bound PV ("" = unbound)
    storage_class: str = ""
    access_modes: tuple[str, ...] = ()
    request: int = 0                            # requested storage bytes

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


# storagev1.VolumeBindingMode
BINDING_IMMEDIATE = "Immediate"
BINDING_WAIT_FOR_FIRST_CONSUMER = "WaitForFirstConsumer"

# provisioner value that means "no dynamic provisioning"
NO_PROVISIONER = "kubernetes.io/no-provisioner"


@dataclass(frozen=True)
class StorageClass:
    """The scheduling slice of storagev1.StorageClass."""

    name: str
    binding_mode: str = BINDING_IMMEDIATE
    provisioner: str = NO_PROVISIONER


# --------------------------------------------------------------------------
# Dynamic Resource Allocation (resource.k8s.io/v1 — GA in the 1.37 snapshot;
# staging/src/k8s.io/api/resource/v1/types.go). The scheduling slice only:
# device classes select devices via CEL, ResourceSlices publish per-node
# device inventories, ResourceClaims request devices, and an allocation in
# claim status pins the claim (and its pods) to a node.
# --------------------------------------------------------------------------

@dataclass(frozen=True)
class Device:
    """One device in a ResourceSlice pool (resource/v1 types.go Device):
    a name plus typed attributes (string/int/bool, qualified names) and
    integer capacities."""

    name: str
    attributes: tuple[tuple[str, object], ...] = ()
    capacity: tuple[tuple[str, int], ...] = ()

    def attributes_dict(self) -> dict:
        return dict(self.attributes)


@dataclass(frozen=True)
class CELSelector:
    """DeviceSelector.cel.expression — a CEL expression over ``device``.
    kubetpu evaluates the structured subset the in-tree perf/e2e configs
    use (see state.dra.parse_cel); anything else fails loudly at
    class/claim validation, like a CEL compile error in the reference."""

    expression: str


@dataclass(frozen=True)
class DeviceClass:
    """resource/v1 DeviceClass: named selector bundle
    (dra/templates/deviceclass.yaml shape)."""

    name: str
    selectors: tuple[CELSelector, ...] = ()


@dataclass(frozen=True)
class ResourceSlice:
    """resource/v1 ResourceSlice: one driver's device pool. Node-local
    (``node_name``) is the common case; ``all_nodes`` / ``node_selector``
    publish network-attached devices reachable from many nodes."""

    name: str
    driver: str
    pool: str
    node_name: str = ""
    all_nodes: bool = False
    node_selector: NodeSelector | None = None
    devices: tuple[Device, ...] = ()


@dataclass(frozen=True)
class DeviceSubRequest:
    """One alternative of a prioritized-list request
    (DeviceRequest.firstAvailable, resource/v1 types.go)."""

    name: str
    device_class_name: str
    selectors: tuple[CELSelector, ...] = ()
    count: int = 1


# resourceapi.FirstAvailableDeviceRequestMaxSize — the Score contribution of
# choosing alternative i is (MAX - i) (dynamicresources.go computeScore)
FIRST_AVAILABLE_MAX = 8


@dataclass(frozen=True)
class DeviceRequest:
    """ResourceClaim spec.devices.requests[] — either ``exactly`` (class +
    selectors + count | all) or a ``first_available`` prioritized list."""

    name: str
    device_class_name: str = ""
    selectors: tuple[CELSelector, ...] = ()
    count: int = 1
    all_devices: bool = False          # allocationMode: All
    first_available: tuple[DeviceSubRequest, ...] = ()


@dataclass(frozen=True)
class DeviceConstraint:
    """spec.devices.constraints[]: all devices allocated for ``requests``
    (empty = every request) must share the ``match_attribute`` value."""

    match_attribute: str
    requests: tuple[str, ...] = ()


@dataclass(frozen=True)
class DeviceResult:
    """status.allocation.devices.results[] — one concrete device."""

    request: str
    driver: str
    pool: str
    device: str


@dataclass(frozen=True)
class ClaimAllocation:
    """status.allocation: devices + the node the claim is usable from
    ('' = available everywhere, the network-attached case)."""

    node_name: str
    results: tuple[DeviceResult, ...] = ()


# resourceclaim.ReservedForMaxSize — max pods sharing one claim
RESERVED_FOR_MAX = 256


@dataclass(frozen=True)
class ResourceClaim:
    """resource/v1 ResourceClaim (scheduling slice): device requests +
    constraints, and the allocation/reservedFor status the scheduler both
    reads and (via Reserve/PreBind) writes."""

    name: str
    namespace: str = "default"
    uid: str = ""
    requests: tuple[DeviceRequest, ...] = ()
    constraints: tuple[DeviceConstraint, ...] = ()
    allocation: ClaimAllocation | None = None
    reserved_for: tuple[str, ...] = ()   # pod uids
    # owning pod ("Pod/<ns>/<name>") for template-stamped instances — the
    # resourceclaim controller GCs claims whose pod is gone
    owner: str = ""

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class PodResourceClaim:
    """spec.resourceClaims[]: either a direct ``claim_name`` reference or a
    ``template`` (resourceClaimTemplateName) the resourceclaim controller
    resolves into a per-pod claim instance, recording the resolved name
    here (status.resourceClaimStatuses)."""

    name: str
    claim_name: str = ""
    template: str = ""


@dataclass(frozen=True)
class Service:
    """The scheduling slice of v1.Service: its selector feeds the DEFAULT
    PodTopologySpread constraints (component-helpers DefaultSelector merges
    the selectors of services/controllers owning the pod;
    podtopologyspread/common.go:62 buildDefaultConstraints)."""

    name: str
    namespace: str = "default"
    selector: tuple[tuple[str, str], ...] = ()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class GangPolicy:
    """GangSchedulingPolicy (scheduling/v1alpha3 types.go:237): the group is
    admitted only when ``min_count`` pods can be scheduled together."""

    min_count: int = 1


@dataclass(frozen=True)
class PodGroup:
    """The scheduling slice of scheduling/v1alpha3 PodGroup (types.go:339):
    gang policy + topology constraint keys (SchedulingConstraints.Topology,
    types.go:595 — all pods of the group colocate within one domain of each
    key; currently a single key, like the reference)."""

    name: str
    namespace: str = "default"
    gang: GangPolicy | None = None
    topology_keys: tuple[str, ...] = ()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class ResourceClaimTemplate:
    """resource/v1 ResourceClaimTemplate: the claim spec to stamp per pod
    (dra/templates/resourceclaimtemplate.yaml shape)."""

    name: str
    namespace: str = "default"
    requests: tuple[DeviceRequest, ...] = ()
    constraints: tuple[DeviceConstraint, ...] = ()

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class StatefulSet:
    """The slice of apps/v1 StatefulSet the control loop consumes: stable
    ordinal identities (<name>-0 … <name>-N−1), ordered scale-up (pod i
    waits for pod i−1 Running) and reverse-ordered scale-down
    (pkg/controller/statefulset's OrderedReady management policy)."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: LabelSelector | None = None
    template: "Pod | None" = None
    pod_management_policy: str = "OrderedReady"   # or "Parallel"

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Job:
    """The slice of batch/v1 Job the control loop consumes: desired
    completions under a parallelism bound, a backoff limit on failures,
    and the derived status (pkg/controller/job syncJob's inputs/outputs)."""

    name: str
    namespace: str = "default"
    completions: int = 1
    parallelism: int = 1
    backoff_limit: int = 6
    template: "Pod | None" = None
    # status (written by the controller)
    succeeded: int = 0
    failed: int = 0
    complete: bool = False
    failed_state: bool = False
    # uncountedTerminatedPods (batch/v1 JobStatus): pod keys whose
    # termination is COUNTED in succeeded/failed but whose objects may not
    # be removed yet — the exactly-once bridge across controller restarts
    uncounted: tuple[str, ...] = ()
    # spec.ttlSecondsAfterFinished (ttlafterfinished controller): delete
    # the Job this long after it finishes; None = keep forever
    ttl_seconds_after_finished: float | None = None
    # status.completionTime (epoch seconds), stamped when complete/failed
    completion_time: float | None = None
    # owning controller ("CronJob/<ns>/<name>"), "" = standalone
    owner: str = ""

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class CronJob:
    """The slice of batch/v1 CronJob the control loop consumes: a 5-field
    cron ``schedule`` stamping Job instances (pkg/controller/cronjob
    ``syncCronJob``), a ``suspend`` gate, and concurrency policy (Allow |
    Forbid | Replace)."""

    name: str
    namespace: str = "default"
    schedule: str = "* * * * *"
    suspend: bool = False
    concurrency_policy: str = "Allow"     # Allow | Forbid | Replace
    # the Job prototype (spec.jobTemplate)
    completions: int = 1
    parallelism: int = 1
    backoff_limit: int = 6
    ttl_seconds_after_finished: float | None = None
    template: "Pod | None" = None
    # status
    last_schedule_time: float | None = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class ResourceQuota:
    """core/v1 ResourceQuota slice: per-namespace hard caps on object
    counts and aggregate resource requests (pkg/controller/resourcequota
    recomputes ``used``; the apiserver's quota admission rejects writes
    that would exceed ``hard``)."""

    name: str
    namespace: str = "default"
    hard: tuple[tuple[str, int], ...] = ()   # "pods" | "requests.cpu" | "requests.memory"
    used: tuple[tuple[str, int], ...] = ()

    def hard_dict(self) -> dict[str, int]:
        return dict(self.hard)

    def used_dict(self) -> dict[str, int]:
        return dict(self.used)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Deployment:
    """The scheduling-relevant slice of apps/v1 Deployment: desired
    replicas, selector, pod template, and the rollout strategy knobs
    (pkg/controller/deployment rolling.go consumes maxSurge /
    maxUnavailable)."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: LabelSelector | None = None
    template: "Pod | None" = None
    strategy: str = "RollingUpdate"      # or "Recreate"
    max_surge: int = 1
    max_unavailable: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class NodeHeartbeat:
    """The coordination.k8s.io Lease slice kubelets renew per node
    (pkg/kubelet/nodelease; consumed by the nodelifecycle controller)."""

    node_name: str
    renew_time: float


@dataclass(frozen=True)
class LeaderElectionRecord:
    """The coordination Lease slice leader election CASes
    (client-go tools/leaderelection LeaderElectionRecord)."""

    holder_identity: str
    lease_duration_s: float
    acquire_time: float
    renew_time: float
    leader_transitions: int = 0


@dataclass(frozen=True)
class ReplicaSet:
    """The scheduling-relevant slice of apps/v1 ReplicaSet: desired replica
    count, the selector that claims pods, and the pod template to stamp
    (pkg/controller/replicaset syncReplicaSet's inputs)."""

    name: str
    namespace: str = "default"
    replicas: int = 1
    selector: LabelSelector | None = None
    template: "Pod | None" = None     # prototype; name/uid/owner stamped
    # the owning controller ("Deployment/<ns>/<name>"), "" = standalone
    owner: str = ""

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Event:
    """events.k8s.io/v1 Event (the slice the control plane emits):
    what happened (``reason``/``note``/``type``) to which object
    (``regarding`` — "Kind/<ns>/<name>"), reported by whom, how many times
    (series aggregation — client-go tools/events' EventSeries)."""

    name: str
    namespace: str = "default"
    regarding: str = ""                   # "Kind/<ns>/<name>"
    reason: str = ""                      # e.g. "Scheduled", "FailedScheduling"
    note: str = ""
    type: str = "Normal"                  # Normal | Warning
    reporting_controller: str = ""
    count: int = 1
    first_timestamp: float = 0.0
    last_timestamp: float = 0.0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class DaemonSet:
    """The slice of apps/v1 DaemonSet the control loop consumes: one pod
    per eligible node (pkg/controller/daemon daemon_controller.go
    ``nodeShouldRunDaemonPod``). Daemon pods are scheduled by the default
    scheduler pinned via required node affinity on ``metadata.name`` —
    the reference's post-1.12 shape (util.ReplaceDaemonSetPodNodeName-
    NodeAffinity)."""

    name: str
    namespace: str = "default"
    selector: LabelSelector | None = None
    template: "Pod | None" = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class Namespace:
    """The slice of v1.Namespace affinity needs: its labels, matched by
    PodAffinityTerm.namespace_selector (framework/types.go
    AffinityTerm.Matches takes nsLabels)."""

    name: str
    labels: tuple[tuple[str, str], ...] = ()

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)


@dataclass(frozen=True)
class PodDisruptionBudget:
    """The slice of policy/v1 PodDisruptionBudget preemption consumes
    (framework/plugins/defaultpreemption/default_preemption.go:406
    filterPodsWithPDBViolation): namespace-scoped label selector,
    ``status.disruptionsAllowed``, and ``status.disruptedPods`` (victims
    already processed by the API server don't double-count)."""

    name: str
    namespace: str = "default"
    selector: LabelSelector | None = None
    disruptions_allowed: int = 0
    disrupted_pods: tuple[str, ...] = ()
    # spec (policy/v1): exactly one of the two; the disruption controller
    # derives status.disruptionsAllowed from it
    min_available: int | None = None
    max_unavailable: int | None = None

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class ImageState:
    """Summary of one image on a node (fwk.ImageStateSummary)."""

    size_bytes: int
    num_nodes: int = 1


@dataclass(frozen=True)
class Node:
    name: str
    labels: tuple[tuple[str, str], ...] = ()
    allocatable: tuple[tuple[str, int], ...] = ()  # includes "pods" count
    taints: tuple[Taint, ...] = ()
    unschedulable: bool = False
    images: tuple[tuple[str, ImageState], ...] = ()
    # status.declaredFeatures (core/v1 types.go:6828, +featureGate=
    # NodeDeclaredFeatures): kubelet-declared feature names
    declared_features: tuple[str, ...] = ()

    def labels_dict(self) -> dict[str, str]:
        return dict(self.labels)

    def allocatable_dict(self) -> dict[str, int]:
        return dict(self.allocatable)


def freeze_map(m: Mapping[str, int] | Mapping[str, str] | None):
    return tuple(sorted((m or {}).items()))
