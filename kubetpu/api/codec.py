"""Wire codec seam — one place that turns API objects into bytes.

Two codecs behind one surface, negotiated per request via content type
(the reference's NegotiatedSerializer, apimachinery runtime/serializer):

- ``json`` — the original kind-tagged JSON (``kubetpu.api.scheme``), the
  compatibility + debugging format; and
- ``binary`` — a compact msgpack/CBOR-style binary format ("ktpb"),
  self-describing at the value level (every value carries a type tag) and
  SPARSE at the object level: a registered dataclass is written as a kind
  id plus only its non-default fields, referenced through a schema table
  both sides derive deterministically from the scheme registry. The
  schema's fingerprint rides the negotiated content type
  (``application/x-kubetpu-bin; v=1; schema=<fp>``) so a client and
  server built from different registries can NEVER mis-decode each other:
  the mismatch 415s and the client falls back to JSON (remote.py).

Why sparse matters: the JSON encoding spells every field of every object
— a bench pod is ~30 fields of defaults around ~7 real values — so the
binary form cuts both wire bytes (the ≥60% reduction the fullstack
ladder measures) and encode/decode work (only present fields are walked,
no intermediate dict tree is ever built: encode packs straight off the
dataclass, decode constructs the dataclass straight from the buffer).

Splice-safe by construction: every encoded value is self-contained (no
cross-value state like string interning), so the serialize-once caches —
the apiserver's EventEncodeCache and the native store's per-event body
ring — can concatenate cached event bodies into reply envelopes with the
header helpers here (``events_envelope``/``buckets_envelope``) without
re-encoding a single event.

Value tags (all little-endian):

    0x00-0x7f  posfixint            0xa7/a8/a9  str8/16/32 (len + utf-8)
    0x80-0x9f  fixstr (len 0-31)    0xaa/ab     list8/32 (count + items)
    0xa0/a1/a2 None/False/True      0xac/ad     map8/32 (count + k,v …)
    0xa3/a5/a4 int16/int32/int64    0xae        object (see below)
    0xa6       float64              0xaf        bigint (|i64| overflow)
    0xe0-0xff  negfixint (-32..-1)

    object: 0xae, kind_id u8, n_present u8, then n × (field_id u16 LE,
    value). kind_id indexes the sorted kind-name table; field_id indexes
    the global sorted field-name table — both derived from the scheme
    registry and pinned by the negotiated schema fingerprint.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Any, Callable

from . import scheme

JSON = "json"
BINARY = "binary"

#: negotiated wire format version (part of the content type AND the
#: schema fingerprint — bump on any tag-layout change)
WIRE_VERSION = 1

CT_JSON = "application/json"
CT_BINARY = "application/x-kubetpu-bin"
#: the streaming-watch frame form of the binary codec (u32-length-prefixed
#: frames instead of ndjson lines)
CT_BINARY_STREAM = "application/x-kubetpu-bin-seq"
CT_NDJSON = "application/x-ndjson"


class UnsupportedWireError(ValueError):
    """The peer speaks a binary dialect we do not (missing/mismatched
    schema fingerprint, undecodable body) — the HTTP 415 of the
    negotiation, consumed by the client's fall-back-to-JSON path."""


# --------------------------------------------------------------- schema

class _KindPlan:
    """Per-kind encode/decode plan: ordered fields with their global
    name ids, defaults (MISSING = required, always encoded) and type
    hints (decode-side coercion shares the scheme's strict rules)."""

    __slots__ = ("kind_id", "kind", "cls", "fields", "by_fid")

    def __init__(self, kind_id: int, kind: str, cls: type,
                 name_ids: dict[str, int]) -> None:
        self.kind_id = kind_id
        self.kind = kind
        self.cls = cls
        hints = scheme.type_hints(cls)
        self.fields: list[tuple[int, str, Any]] = []
        self.by_fid: dict[int, tuple[str, Any]] = {}
        for f in dataclasses.fields(cls):
            if f.default is not dataclasses.MISSING:
                default = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                default = f.default_factory()  # type: ignore[misc]
            else:
                default = dataclasses.MISSING
            fid = name_ids[f.name]
            self.fields.append((fid, f.name, default))
            self.by_fid[fid] = (f.name, hints[f.name])


class _Tables:
    """The negotiated schema: kind table, field-name table, per-kind
    plans, and the fingerprint that pins all of it."""

    def __init__(self) -> None:
        kinds = scheme.kind_registry()
        self.kind_names: list[str] = sorted(kinds)
        if len(self.kind_names) > 255:
            raise scheme.SchemeError("binary codec: >255 registered kinds")
        names: set[str] = set()
        for kind in self.kind_names:
            for f in dataclasses.fields(kinds[kind]):
                names.add(f.name)
        self.field_names: list[str] = sorted(names)
        self.name_ids: dict[str, int] = {
            n: i for i, n in enumerate(self.field_names)
        }
        self.plans_by_kind: dict[str, _KindPlan] = {}
        self.plans_by_cls: dict[type, _KindPlan] = {}
        self.plans_by_id: list[_KindPlan] = []
        for kid, kind in enumerate(self.kind_names):
            plan = _KindPlan(kid, kind, kinds[kind], self.name_ids)
            self.plans_by_kind[kind] = plan
            self.plans_by_cls[kinds[kind]] = plan
            self.plans_by_id.append(plan)
        # the fingerprint covers everything decode depends on: the wire
        # version, the kind table, and each kind's (field, default) set —
        # a default change alters what an ABSENT field decodes to, so it
        # is a schema change. MISSING (required field) gets a FIXED token:
        # repr(MISSING) embeds a memory address, which would make the
        # fingerprint process-specific — two identical builds could never
        # negotiate binary across a process boundary, and a WAL written
        # by one process would refuse to decode in any other
        spec = [WIRE_VERSION, self.field_names]
        for kind in self.kind_names:
            plan = self.plans_by_kind[kind]
            spec.append([
                kind,
                [
                    (name, "<required>" if default is dataclasses.MISSING
                     else repr(default))
                    for _fid, name, default in plan.fields
                ],
            ])
        self.fingerprint = hashlib.sha1(
            repr(spec).encode()
        ).hexdigest()[:12]


_TABLES: _Tables | None = None
_TABLES_GEN = -1


def tables() -> _Tables:
    """The schema tables for the CURRENT scheme registry (rebuilt when a
    kind registration lands after import)."""
    global _TABLES, _TABLES_GEN
    gen = scheme.registry_generation()
    if _TABLES is None or _TABLES_GEN != gen:
        _TABLES = _Tables()
        _TABLES_GEN = gen
    return _TABLES


def schema_fingerprint() -> str:
    return tables().fingerprint


def binary_content_type() -> str:
    return f"{CT_BINARY}; v={WIRE_VERSION}; schema={schema_fingerprint()}"


def binary_stream_content_type() -> str:
    return f"{CT_BINARY_STREAM}; v={WIRE_VERSION}; schema={schema_fingerprint()}"


#: the W3C trace-context header (JSON wire) and its binary-envelope twin:
#: on the binary content type the traceparent rides as a media-type
#: parameter (``tp=00-…``) next to the schema fingerprint — one envelope,
#: negotiated and parsed by the same seam, so a 415/JSON fallback simply
#: moves the SAME value back to the header. Both are ABSENT when telemetry
#: is off (byte-identical wire).
TRACEPARENT_HEADER = "traceparent"
TRACEPARENT_PARAM = "tp"


def content_type_for(codec: str, traceparent: str | None = None) -> str:
    """The request/reply Content-Type for ``codec``. ``traceparent``
    attaches the trace context to a BINARY envelope (the ``tp`` media-type
    parameter); the JSON wire carries it in the ``traceparent`` header
    instead (see ``traceparent_from_headers``)."""
    if codec == BINARY:
        ct = binary_content_type()
        if traceparent:
            ct += f"; {TRACEPARENT_PARAM}={traceparent}"
        return ct
    return CT_JSON


def traceparent_from_headers(headers) -> str | None:
    """Extract a propagated traceparent from one request's headers,
    whichever envelope carried it: the binary Content-Type's ``tp``
    parameter wins (the binary envelope field), else the W3C
    ``traceparent`` header (the JSON wire). Returns the RAW value —
    validation (malformed → ignored, never fatal) is the parser's job
    (kubetpu.telemetry.context.parse_traceparent)."""
    _media, params = parse_content_type(headers.get("Content-Type"))
    tp = params.get(TRACEPARENT_PARAM)
    if tp:
        return tp
    return headers.get(TRACEPARENT_HEADER)


def parse_content_type(value: str | None) -> tuple[str, dict[str, str]]:
    """``type/subtype; k=v; …`` → (media type, params). Tolerant: an
    absent header reads as JSON (the pre-binary wire)."""
    if not value:
        return CT_JSON, {}
    parts = [p.strip() for p in value.split(";")]
    params: dict[str, str] = {}
    for p in parts[1:]:
        k, sep, v = p.partition("=")
        if sep:
            params[k.strip().lower()] = v.strip().strip('"')
    return parts[0].lower(), params


def codec_for_content_type(value: str | None) -> str:
    """The codec a BODY with this content type is encoded in. Raises
    UnsupportedWireError for a binary type whose schema fingerprint does
    not match ours (the 415 path — decoding would be garbage)."""
    media, params = parse_content_type(value)
    if media in (CT_BINARY, CT_BINARY_STREAM):
        if params.get("schema") != schema_fingerprint():
            raise UnsupportedWireError(
                f"binary schema {params.get('schema')!r} != local "
                f"{schema_fingerprint()!r} (negotiate JSON)"
            )
        return BINARY
    return JSON


def accepts_binary(accept_header: str | None) -> bool:
    """True when the Accept header names OUR binary dialect (media type
    + matching schema fingerprint). Anything else — absent header, JSON,
    a foreign fingerprint — negotiates JSON: replying a dialect the
    client cannot decode is never an option, so mismatch degrades
    instead of erroring."""
    if not accept_header or CT_BINARY not in accept_header:
        return False
    for part in accept_header.split(","):
        media, params = parse_content_type(part)
        if (
            media in (CT_BINARY, CT_BINARY_STREAM)
            and params.get("schema") == schema_fingerprint()
        ):
            return True
    return False


# --------------------------------------------------------------- encode

_pack_h = struct.Struct("<h").pack
_pack_i = struct.Struct("<i").pack
_pack_q = struct.Struct("<q").pack
_pack_d = struct.Struct("<d").pack
_pack_H = struct.Struct("<H").pack
_unpack_h = struct.Struct("<h").unpack_from
_unpack_i = struct.Struct("<i").unpack_from
_unpack_q = struct.Struct("<q").unpack_from
_unpack_d = struct.Struct("<d").unpack_from
_unpack_H = struct.Struct("<H").unpack_from

_I16 = 1 << 15
_I32 = 1 << 31
_I64 = 1 << 63


def _pack_int(out: bytearray, v: int) -> None:
    if 0 <= v < 0x80:
        out.append(v)
    elif -32 <= v < 0:
        out.append(0x100 + v)
    elif -_I16 <= v < _I16:
        out.append(0xA3)
        out += _pack_h(v)
    elif -_I32 <= v < _I32:
        out.append(0xA5)
        out += _pack_i(v)
    elif -_I64 <= v < _I64:
        out.append(0xA4)
        out += _pack_q(v)
    else:
        raw = repr(v).encode()
        if len(raw) > 255:
            raise scheme.SchemeError("int too large for the wire")
        out.append(0xAF)
        out.append(len(raw))
        out += raw


def _pack_str(out: bytearray, v: str) -> None:
    raw = v.encode()
    n = len(raw)
    if n < 32:
        out.append(0x80 | n)
    elif n < 256:
        out.append(0xA7)
        out.append(n)
    elif n < 65536:
        out.append(0xA8)
        out += _pack_H(n)
    else:
        out.append(0xA9)
        out += _pack_i(n)
    out += raw


def list_header(n: int) -> bytes:
    """The envelope splicers build lists around pre-encoded bodies."""
    if n < 256:
        return bytes((0xAA, n))
    return bytes((0xAB,)) + _pack_i(n)


def map_header(n: int) -> bytes:
    if n < 256:
        return bytes((0xAC, n))
    return bytes((0xAD,)) + _pack_i(n)


def _pack(out: bytearray, v: Any, t: _Tables) -> None:
    if v is None:
        out.append(0xA0)
    elif v is True:
        out.append(0xA2)
    elif v is False:
        out.append(0xA1)
    elif isinstance(v, str):        # str-enums land here (their value)
        _pack_str(out, v)
    elif isinstance(v, int):
        _pack_int(out, v)
    elif isinstance(v, float):
        out.append(0xA6)
        out += _pack_d(v)
    elif isinstance(v, (list, tuple)):
        out += list_header(len(v))
        for x in v:
            _pack(out, x, t)
    elif isinstance(v, dict):
        out += map_header(len(v))
        for k, x in v.items():
            _pack(out, k, t)
            _pack(out, x, t)
    else:
        plan = t.plans_by_cls.get(type(v))
        if plan is None:
            raise scheme.SchemeError(
                f"cannot binary-encode {type(v).__name__} "
                "(not a registered kind)"
            )
        present: list[tuple[int, Any]] = []
        for fid, name, default in plan.fields:
            val = getattr(v, name)
            if val is default or val == default:
                continue
            present.append((fid, val))
        if len(present) > 255:
            raise scheme.SchemeError(f"{plan.kind}: >255 present fields")
        out.append(0xAE)
        out.append(plan.kind_id)
        out.append(len(present))
        for fid, val in present:
            out += _pack_H(fid)
            _pack(out, val, t)


def pack_value(v: Any) -> bytes:
    """One self-contained binary value (objects may appear anywhere in
    the tree) — the unit the serialize-once caches store and the
    envelope helpers splice."""
    out = bytearray()
    _pack(out, v, tables())
    return bytes(out)


# --------------------------------------------------------------- decode

def _unpack(buf: bytes, pos: int, t: _Tables) -> tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag < 0x80:
        return tag, pos
    if tag >= 0xE0:
        return tag - 0x100, pos
    if tag < 0xA0:                      # fixstr
        n = tag & 0x1F
        return buf[pos:pos + n].decode(), pos + n
    if tag == 0xA0:
        return None, pos
    if tag == 0xA1:
        return False, pos
    if tag == 0xA2:
        return True, pos
    if tag == 0xA3:
        return _unpack_h(buf, pos)[0], pos + 2
    if tag == 0xA5:
        return _unpack_i(buf, pos)[0], pos + 4
    if tag == 0xA4:
        return _unpack_q(buf, pos)[0], pos + 8
    if tag == 0xA6:
        return _unpack_d(buf, pos)[0], pos + 8
    if tag in (0xA7, 0xA8, 0xA9):       # str8/16/32
        if tag == 0xA7:
            n = buf[pos]
            pos += 1
        elif tag == 0xA8:
            n = _unpack_H(buf, pos)[0]
            pos += 2
        else:
            n = _unpack_i(buf, pos)[0]
            pos += 4
        return buf[pos:pos + n].decode(), pos + n
    if tag in (0xAA, 0xAB):             # list
        if tag == 0xAA:
            n = buf[pos]
            pos += 1
        else:
            n = _unpack_i(buf, pos)[0]
            pos += 4
        out = []
        for _ in range(n):
            v, pos = _unpack(buf, pos, t)
            out.append(v)
        return out, pos
    if tag in (0xAC, 0xAD):             # map
        if tag == 0xAC:
            n = buf[pos]
            pos += 1
        else:
            n = _unpack_i(buf, pos)[0]
            pos += 4
        m = {}
        for _ in range(n):
            k, pos = _unpack(buf, pos, t)
            v, pos = _unpack(buf, pos, t)
            m[k] = v
        return m, pos
    if tag == 0xAE:                     # object
        kid = buf[pos]
        nf = buf[pos + 1]
        pos += 2
        if kid >= len(t.plans_by_id):
            raise UnsupportedWireError(f"unknown kind id {kid}")
        plan = t.plans_by_id[kid]
        kwargs: dict[str, Any] = {}
        for _ in range(nf):
            fid = _unpack_H(buf, pos)[0]
            pos += 2
            raw, pos = _unpack(buf, pos, t)
            got = plan.by_fid.get(fid)
            if got is None:
                raise scheme.SchemeError(
                    f"{plan.kind}: unknown field id {fid} "
                    "(strict decoding)"
                )
            name, hint = got
            kwargs[name] = scheme.coerce_value(raw, hint)
        return scheme.apply_defaults(plan.cls(**kwargs)), pos
    if tag == 0xAF:                     # bigint
        n = buf[pos]
        pos += 1
        return int(buf[pos:pos + n]), pos + n
    raise UnsupportedWireError(f"bad wire tag 0x{tag:02x}")


def unpack_value(data: bytes) -> Any:
    try:
        v, pos = _unpack(data, 0, tables())
    except (IndexError, struct.error, UnicodeDecodeError) as e:
        raise UnsupportedWireError(f"truncated/garbled binary body: {e}") \
            from None
    if pos != len(data):
        raise UnsupportedWireError(
            f"{len(data) - pos} trailing bytes after binary value"
        )
    return v


# ----------------------------------------------------------- the seam

def jsonify(tree: Any) -> Any:
    """Registered objects anywhere in ``tree`` → their kind-tagged JSON
    form (``scheme.encode`` recursion; plain values pass through)."""
    return scheme.encode(tree)


def dumps(tree: Any, codec: str = JSON) -> bytes:
    """One wire body. ``tree`` may contain live registered dataclasses —
    both codecs encode them in place, so no handler pre-serializes."""
    if codec == BINARY:
        return pack_value(tree)
    return json.dumps(jsonify(tree), separators=(",", ":")).encode()


def loads(data: bytes, codec: str = JSON) -> Any:
    """The inverse. Binary bodies come back with registered objects
    MATERIALIZED (dataclasses, defaults applied); JSON bodies come back
    as the plain tree — normalize nested objects with ``as_object``."""
    if codec == BINARY:
        return unpack_value(data)
    try:
        return json.loads(data or b"{}")
    except ValueError as e:
        raise UnsupportedWireError(f"bad JSON body: {e}") from None


def as_object(value: Any) -> Any:
    """One decoded "object" slot → the typed object, whichever codec
    carried it: binary already materialized it; JSON left the kind-tagged
    dict. None passes through (tombstones)."""
    if value is None or not isinstance(value, (dict, list)):
        return value
    return scheme.decode(value)


def event_wire_bytes(
    ev_type: str, key: str, obj: Any, resource_version: int,
    codec: str = JSON,
) -> bytes:
    """One watch event's wire body — the unit the serialize-once caches
    hold. ``obj`` None is the scoped DELETED tombstone (no body)."""
    if codec == BINARY:
        return pack_value({
            "type": ev_type, "key": key, "object": obj,
            "resourceVersion": resource_version,
        })
    return json.dumps({
        "type": ev_type, "key": key,
        "object": None if obj is None else scheme.encode(obj),
        "resourceVersion": resource_version,
    }, separators=(",", ":")).encode()


def events_envelope(parts: list[bytes], cursor: int, codec: str = JSON) -> bytes:
    """The watch-poll reply ``{"events": […], "resourceVersion": N}``
    assembled by SPLICING pre-encoded event bodies — no event is ever
    re-encoded on the fan-out path."""
    if codec == BINARY:
        out = bytearray(map_header(2))
        _pack_str(out, "events")
        out += list_header(len(parts))
        for p in parts:
            out += p
        _pack_str(out, "resourceVersion")
        _pack_int(out, cursor)
        return bytes(out)
    return (
        b'{"events":[' + b",".join(parts)
        + b'],"resourceVersion":' + str(cursor).encode() + b"}"
    )


def list_item_wire_bytes(key: str, obj: Any, codec: str = JSON) -> bytes:
    """One LIST item's wire body ``{"key": …, "object": …}`` — the unit
    the apiserver's list-item encode cache holds and ``items_envelope``
    splices. Byte-identical to the item's slice of the pre-pagination
    monolithic reply, so a spliced page decodes through the same client
    path."""
    if codec == BINARY:
        return pack_value({"key": key, "object": obj})
    return json.dumps(
        {"key": key, "object": scheme.encode(obj)}, separators=(",", ":")
    ).encode()


def items_envelope(
    parts: list[bytes], resource_version: int, codec: str = JSON,
    cont: str | None = None,
) -> bytes:
    """The (paged) LIST reply ``{"items": […], "resourceVersion": N
    [, "continue": tok]}`` assembled by SPLICING pre-encoded item bodies
    — a 50k-node page re-encodes nothing that the list-item cache
    already holds. ``cont`` (the opaque continue token) is present only
    when the walk has more pages."""
    if codec == BINARY:
        out = bytearray(map_header(3 if cont else 2))
        _pack_str(out, "items")
        out += list_header(len(parts))
        for p in parts:
            out += p
        _pack_str(out, "resourceVersion")
        _pack_int(out, resource_version)
        if cont:
            _pack_str(out, "continue")
            _pack_str(out, cont)
        return bytes(out)
    tail = b'],"resourceVersion":' + str(resource_version).encode()
    if cont:
        tail += b',"continue":' + json.dumps(cont).encode()
    return b'{"items":[' + b",".join(parts) + tail + b"}"


def encode_continue(snapshot_rv: int, after_seq: int,
                    generation: int = 0, through_seq: int = 0) -> str:
    """The LIST continue token: opaque to clients (they hand it back
    verbatim), pinned to the resourceVersion snapshot the walk started
    at plus the seq cursor the next page resumes after and the seq BOUND
    the walk may not cross (objects created after the first page have
    higher seqs — the bound is what keeps them out of later pages),
    stamped with the store's list generation (seqs renumber on snapshot
    loads — crash recovery, replica resync — so a cursor is only
    meaningful within one generation). URL-safe — it rides a query
    parameter."""
    import base64

    raw = f"v1:{snapshot_rv}:{after_seq}:{generation}:{through_seq}".encode()
    return base64.urlsafe_b64encode(raw).decode().rstrip("=")


def decode_continue(token: str) -> tuple[int, int, int, int]:
    """(snapshot_rv, after_seq, generation, through_seq) from a continue
    token; raises ValueError on garbage (the server 400s — distinct from
    the 410 an EXPIRED but well-formed token earns)."""
    import base64

    try:
        raw = base64.urlsafe_b64decode(
            (token + "=" * (-len(token) % 4)).encode()
        ).decode()
        version, rv, seq, gen, bound = raw.split(":")
        if version != "v1":
            raise ValueError(version)
        return int(rv), int(seq), int(gen), int(bound)
    except (ValueError, UnicodeDecodeError) as e:
        raise ValueError(f"malformed continue token: {e}") from None


def buckets_envelope(parts: list[tuple[str, bytes]], codec: str = JSON) -> bytes:
    """The batched-poll reply ``{"buckets": {kind: body, …}}`` spliced
    from per-kind pre-assembled bodies (an events envelope or a 410
    error body per kind)."""
    if codec == BINARY:
        out = bytearray(map_header(1))
        _pack_str(out, "buckets")
        out += map_header(len(parts))
        for kind, body in parts:
            _pack_str(out, kind)
            out += body
        return bytes(out)
    return (
        b'{"buckets":{'
        + b",".join(
            json.dumps(kind).encode() + b":" + body for kind, body in parts
        )
        + b"}}"
    )


def stream_frame(body: bytes, codec: str = JSON) -> bytes:
    """One streaming-watch frame: ndjson line (json) or u32-length-
    prefixed binary body (the negotiated frame stream)."""
    if codec == BINARY:
        return len(body).to_bytes(4, "little") + body
    return body + b"\n"


#: wire-body slots in the native store's per-event ring (must stay dense
#: small ints — they index a fixed array in memstore_core.cpp)
WIRE_CODEC_IDS: dict[str, int] = {JSON: 0, BINARY: 1}

#: ring event-type ids → wire names (the store cores carry the int)
EVENT_TYPE_NAMES = ("ADDED", "MODIFIED", "DELETED")


def event_body_encoder(codec: str) -> Callable[[int, str, Any, int], bytes]:
    """The body ring's miss-path encoder: ``(type id, key, obj, rv) →
    wire bytes``. Called by the store core under its lock — it must (and
    does) never re-enter the store."""
    def _enc(ev_type: int, key: str, obj: Any, rv: int) -> bytes:
        return event_wire_bytes(EVENT_TYPE_NAMES[ev_type], key, obj, rv,
                                codec)
    return _enc
