"""Object builders for tests and workloads — the analog of
``pkg/scheduler/testing/wrappers.go``."""

from __future__ import annotations

from typing import Mapping, Sequence

from . import types as t
from .requests import pod_nonzero_requests, pod_requests


def make_node(
    name: str,
    cpu_milli: int = 4000,
    memory: int = 16 * 1024**3,
    pods: int = 110,
    ephemeral: int = 0,
    labels: Mapping[str, str] | None = None,
    taints: Sequence[t.Taint] = (),
    extended: Mapping[str, int] | None = None,
    unschedulable: bool = False,
    images: Mapping[str, t.ImageState] | None = None,
    declared_features: Sequence[str] = (),
) -> t.Node:
    alloc: dict[str, int] = {t.CPU: cpu_milli, t.MEMORY: memory, t.PODS: pods}
    if ephemeral:
        alloc[t.EPHEMERAL_STORAGE] = ephemeral
    for k, v in (extended or {}).items():
        alloc[k] = v
    return t.Node(
        name=name,
        labels=t.freeze_map(labels),
        allocatable=t.freeze_map(alloc),
        declared_features=tuple(sorted(declared_features)),
        taints=tuple(taints),
        unschedulable=unschedulable,
        images=tuple(sorted((images or {}).items())),
    )


def make_pod(
    name: str,
    namespace: str = "default",
    cpu_milli: int = 0,
    memory: int = 0,
    labels: Mapping[str, str] | None = None,
    requests: Mapping[str, int] | None = None,
    containers: Sequence[Mapping[str, int]] | None = None,
    init_containers: Sequence[Mapping[str, int]] = (),
    init_restartable: Sequence[bool] | None = None,
    overhead: Mapping[str, int] | None = None,
    node_name: str = "",
    node_selector: Mapping[str, str] | None = None,
    affinity: t.Affinity | None = None,
    tolerations: Sequence[t.Toleration] = (),
    spread: Sequence[t.TopologySpreadConstraint] = (),
    priority: int = 0,
    host_ports: Sequence[int] = (),
    protocols: Sequence[str] = (),
    gates: Sequence[str] = (),
    images: Sequence[str] = (),
    creation_index: int = 0,
    preemption_policy: str = "PreemptLowerPriority",
    scheduling_group: str = "",
    pvcs: Sequence[str] = (),
    claims: Sequence[str] = (),
    required_features: Sequence[str] = (),
    scheduler_name: str = "default-scheduler",
) -> t.Pod:
    nonzero = None
    if containers is not None:
        req = pod_requests(
            containers, init_containers, overhead,
            init_restartable=init_restartable,
        )
        nonzero = t.freeze_map(
            pod_nonzero_requests(
                containers, init_containers, overhead,
                init_restartable=init_restartable,
            )
        )
    else:
        req = dict(requests or {})
        if cpu_milli:
            req[t.CPU] = cpu_milli
        if memory:
            req[t.MEMORY] = memory
    ports = tuple(
        t.ContainerPort(host_port=p, protocol=(protocols[i] if i < len(protocols) else "TCP"))
        for i, p in enumerate(host_ports)
    )
    return t.Pod(
        name=name,
        namespace=namespace,
        uid=f"{namespace}/{name}",
        labels=t.freeze_map(labels),
        requests=t.freeze_map(req),
        nonzero=nonzero,
        node_name=node_name,
        node_selector=t.freeze_map(node_selector),
        affinity=affinity,
        tolerations=tuple(tolerations),
        topology_spread_constraints=tuple(spread),
        priority=priority,
        ports=ports,
        scheduling_gates=tuple(gates),
        images=tuple(images),
        creation_index=creation_index,
        preemption_policy=preemption_policy,
        scheduling_group=scheduling_group,
        volumes=tuple(
            t.PodVolume(name=f"vol-{i}", pvc_name=c)
            for i, c in enumerate(pvcs)
        ),
        resource_claims=tuple(
            t.PodResourceClaim(name=f"claim-{i}", claim_name=c)
            for i, c in enumerate(claims)
        ),
        required_node_features=tuple(sorted(required_features)),
        scheduler_name=scheduler_name,
    )


def make_pod_group(
    name: str,
    namespace: str = "default",
    min_count: int | None = None,
    topology_keys: Sequence[str] = (),
) -> t.PodGroup:
    """A PodGroup with an optional gang policy (min_count) and topology
    constraint keys (scheduling/v1alpha3 PodGroupSpec)."""
    return t.PodGroup(
        name=name,
        namespace=namespace,
        gang=t.GangPolicy(min_count=min_count) if min_count else None,
        topology_keys=tuple(topology_keys),
    )


def req_in(key: str, *values: str) -> t.Requirement:
    return t.Requirement(key, t.Operator.IN, tuple(values))


def req_exists(key: str) -> t.Requirement:
    return t.Requirement(key, t.Operator.EXISTS)


def node_affinity_required(*terms: t.NodeSelectorTerm) -> t.Affinity:
    return t.Affinity(node_affinity=t.NodeAffinity(required=t.NodeSelector(tuple(terms))))


def pod_affinity_term(
    topology_key: str,
    match_labels: Mapping[str, str] | None = None,
    exprs: Sequence[t.Requirement] = (),
    namespaces: Sequence[str] = (),
    namespace_selector: t.LabelSelector | None = None,
) -> t.PodAffinityTerm:
    return t.PodAffinityTerm(
        topology_key=topology_key,
        selector=t.LabelSelector.of(match_labels, exprs),
        namespaces=tuple(namespaces),
        namespace_selector=namespace_selector,
    )


def spread_constraint(
    max_skew: int,
    topology_key: str,
    when: t.UnsatisfiableConstraintAction = t.UnsatisfiableConstraintAction.DO_NOT_SCHEDULE,
    match_labels: Mapping[str, str] | None = None,
    min_domains: int | None = None,
) -> t.TopologySpreadConstraint:
    return t.TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=topology_key,
        when_unsatisfiable=when,
        selector=t.LabelSelector.of(match_labels),
        min_domains=min_domains,
    )
