"""Scheme + serializers — the apimachinery runtime.Scheme analog.

Reference: ``staging/src/k8s.io/apimachinery`` — ``runtime.Scheme`` maps
GroupVersionKinds to Go types and back; serializers encode objects with a
``kind``/``apiVersion`` tag so any component can round-trip any registered
object. Here the registry maps **kind names to dataclasses** and the codec
round-trips the typed scheduling envelope (dataclasses, enums, tuples,
nested objects) through plain JSON with a ``"kind"`` tag — the wire format
of the apiserver layer (kubetpu.apiserver) and anything else that ships
typed objects across a process boundary.

Unknown kinds and unknown fields fail loudly (strict decoding — the
reference's strict serializer mode); None round-trips as null; tuples of
nested dataclasses are reconstructed from the field's type annotation.

GVK VERSIONING (apimachinery runtime.Scheme's group/version surface):
objects may carry an ``apiVersion`` tag. The registered dataclasses are
the HUB (internal) types; per-(kind, apiVersion) CONVERTERS decode other
versions into the hub — and the load-bearing registration is the real
Kubernetes ``v1`` wire format: a genuine upstream Pod/Node manifest
(``apiVersion: v1``) decodes through the bridge codecs
(kubetpu.bridge.convert), so ``kubetpu apply -f`` accepts reference
manifests verbatim. ``encode_versioned`` is the reverse conversion.
Unknown apiVersions fail loudly. Per-kind DEFAULTING hooks
(``register_defaults`` — the reference's zz_generated.defaults funcs)
run after construction on every decode path.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any

from . import types as t

# kind name -> dataclass. The registered surface is every wire-visible
# object of the framework (the "API types" layer).
_KINDS: dict[str, type] = {}

# bumped on every registration: the binary codec (kubetpu.api.codec)
# derives its schema tables from this registry and caches them per
# generation, so a late registration rebuilds the tables (and changes
# the negotiated schema fingerprint) instead of silently missing a kind
_GENERATION = 0


def register(cls: type, kind: str | None = None) -> type:
    global _GENERATION
    _KINDS[kind or cls.__name__] = cls
    _GENERATION += 1
    return cls


def kind_registry() -> dict[str, type]:
    """The live kind → dataclass map (read-only view for the codec's
    schema-table derivation)."""
    return _KINDS


def registry_generation() -> int:
    return _GENERATION


for _cls in (
    t.Node, t.Pod, t.Taint, t.Toleration, t.Affinity, t.NodeAffinity,
    t.PodAffinity, t.PodAffinityTerm, t.WeightedPodAffinityTerm,
    t.PreferredSchedulingTerm, t.NodeSelector, t.NodeSelectorTerm,
    t.Requirement, t.LabelSelector, t.TopologySpreadConstraint,
    t.ContainerPort, t.PodVolume, t.PersistentVolume,
    t.PersistentVolumeClaim, t.StorageClass, t.Service, t.Namespace,
    t.PodDisruptionBudget, t.PodGroup, t.GangPolicy, t.ImageState,
    t.ReplicaSet, t.DeviceClass, t.CELSelector, t.ResourceSlice, t.Device,
    t.DeviceRequest, t.DeviceSubRequest, t.DeviceConstraint,
    t.ResourceClaim, t.ClaimAllocation, t.DeviceResult, t.PodResourceClaim,
    t.NodeHeartbeat, t.LeaderElectionRecord, t.Deployment, t.Job,
    t.StatefulSet, t.ResourceClaimTemplate, t.DaemonSet, t.Event,
    t.CronJob, t.ResourceQuota,
):
    register(_cls)


class SchemeError(ValueError):
    pass


# the hub version every plain "kind"-tagged object implicitly carries
HUB_VERSION = "kubetpu/v1"

# (kind, apiVersion) -> converter(raw dict) -> hub object
_CONVERTERS: dict[tuple[str, str], Any] = {}
# hub class -> defaulting fn(obj) -> obj (zz_generated.defaults analog)
_DEFAULTERS: dict[type, Any] = {}


def register_conversion(kind: str, api_version: str, fn) -> None:
    """Decode ``apiVersion``-tagged wire objects of ``kind`` into the hub
    type (runtime.Scheme.AddConversionFunc's role)."""
    _CONVERTERS[(kind, api_version)] = fn


def register_defaults(cls: type, fn) -> None:
    """Run ``fn(obj) -> obj`` after every decode of ``cls``."""
    _DEFAULTERS[cls] = fn


def _apply_defaults(obj: Any) -> Any:
    fn = _DEFAULTERS.get(type(obj))
    return fn(obj) if fn is not None else obj


def encode_versioned(obj: Any, api_version: str = HUB_VERSION) -> Any:
    """Encode into a SPECIFIC version's wire format (the reverse
    conversion). The hub version is the plain kind-tagged envelope;
    ``v1`` Pods/Nodes emit the real Kubernetes JSON."""
    if api_version == HUB_VERSION:
        out = encode(obj)
        if isinstance(out, dict):
            out["apiVersion"] = HUB_VERSION
        return out
    kind = type(obj).__name__
    if api_version == "v1" and kind == "Pod":
        from ..bridge.convert import pod_to_v1

        wire = pod_to_v1(obj)
        wire.setdefault("apiVersion", "v1")
        wire.setdefault("kind", "Pod")
        return wire
    raise SchemeError(
        f"no conversion from {kind} to apiVersion {api_version!r}"
    )


def encode(obj: Any) -> Any:
    """Object → JSON-safe value. Dataclasses carry a "kind" tag."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        if isinstance(obj, enum.Enum):   # str-enums are str instances
            return obj.value
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        kind = type(obj).__name__
        if kind not in _KINDS:
            raise SchemeError(f"kind {kind!r} is not registered")
        out: dict[str, Any] = {"kind": kind}
        for f in dataclasses.fields(obj):
            out[f.name] = encode(getattr(obj, f.name))
        return out
    if isinstance(obj, (list, tuple)):
        return [encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    raise SchemeError(f"cannot encode {type(obj).__name__}")


def _resolve_hints(cls: type) -> dict[str, Any]:
    # evaluated lazily + cached on the class (postponed annotations)
    cached = cls.__dict__.get("__kubetpu_hints__")
    if cached is None:
        cached = typing.get_type_hints(cls, vars(t))
        setattr(cls, "__kubetpu_hints__", cached)
    return cached


def _coerce(value: Any, hint: Any) -> Any:
    """Rebuild tuples/enums/nested dataclasses from the field annotation."""
    if value is None:
        return None
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        # already typed: the binary codec materializes nested objects
        # before coercion (its object tag carries the kind), so a typed
        # value passes straight through the same strict path
        return value
    if isinstance(value, dict) and "kind" in value:
        return decode(value)
    origin = typing.get_origin(hint)
    if origin in (typing.Union, getattr(__import__("types"), "UnionType", ())):
        for arm in typing.get_args(hint):
            if arm is type(None):
                continue
            try:
                return _coerce(value, arm)
            except (SchemeError, TypeError, ValueError):
                continue
        raise SchemeError(f"no union arm of {hint} accepts {value!r}")
    if origin is tuple:
        if not isinstance(value, list):
            raise SchemeError(f"expected array for {hint}, got {value!r}")
        args = typing.get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_coerce(v, args[0]) for v in value)
        if args:
            return tuple(
                _coerce(v, args[i % len(args)]) for i, v in enumerate(value)
            )
        return tuple(value)
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return hint(value)
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        if isinstance(value, dict):
            return _decode_into(hint, value)
        raise SchemeError(f"expected object for {hint.__name__}, got {value!r}")
    if origin is dict:
        if not isinstance(value, dict):
            raise SchemeError(f"expected object for {hint}, got {value!r}")
        args = typing.get_args(hint)
        if args:
            return {str(k): _coerce(v, args[1]) for k, v in value.items()}
        return value
    # Primitive leaves are type-checked against the annotation — strict
    # decoding covers field types, not just unknown kinds/fields. bool is
    # checked before int (bool is an int subclass); int is accepted where
    # float is annotated (JSON has one number type).
    if hint is bool:
        if not isinstance(value, bool):
            raise SchemeError(f"expected bool, got {value!r}")
        return value
    if hint is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise SchemeError(f"expected int, got {value!r}")
        return value
    if hint is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SchemeError(f"expected float, got {value!r}")
        return value
    if hint is str:
        if not isinstance(value, str):
            raise SchemeError(f"expected str, got {value!r}")
        return value
    return value


def coerce_value(value: Any, hint: Any) -> Any:
    """Public face of the field-coercion rules (tuple rebuild, enum
    reconstruction, strict primitive checks) — the binary codec decodes
    through the SAME rules as the JSON path, so the two codecs cannot
    drift on what a field accepts."""
    return _coerce(value, hint)


def apply_defaults(obj: Any) -> Any:
    """Run the kind's registered defaulting hook (every decode path —
    JSON and binary — must apply the same defaults)."""
    return _apply_defaults(obj)


def type_hints(cls: type) -> dict[str, Any]:
    """Resolved field annotations for a registered class (cached)."""
    return _resolve_hints(cls)


def _decode_into(cls: type, data: dict) -> Any:
    hints = _resolve_hints(cls)
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for key, raw in data.items():
        if key in ("kind", "apiVersion"):
            continue
        if key not in field_names:
            raise SchemeError(
                f"{cls.__name__}: unknown field {key!r} (strict decoding)"
            )
        kwargs[key] = _coerce(raw, hints[key])
    return cls(**kwargs)


def decode(data: Any) -> Any:
    """JSON value → typed object (requires the "kind" tag on objects).
    An ``apiVersion`` other than the hub's routes through the registered
    conversion (e.g. real Kubernetes ``v1`` Pod/Node manifests)."""
    if isinstance(data, dict):
        kind = data.get("kind")
        if kind is None:
            raise SchemeError("object has no 'kind' tag")
        version = data.get("apiVersion", HUB_VERSION)
        if version != HUB_VERSION:
            converter = _CONVERTERS.get((kind, version))
            if converter is None:
                raise SchemeError(
                    f"no conversion registered for {kind!r} "
                    f"apiVersion {version!r}"
                )
            return _apply_defaults(converter(data))
        cls = _KINDS.get(kind)
        if cls is None:
            raise SchemeError(
                f"kind {kind!r} is not registered "
                f"(known: {sorted(_KINDS)})"
            )
        return _apply_defaults(_decode_into(cls, data))
    if isinstance(data, list):
        return [decode(x) for x in data]
    return data


def _register_v1_conversions() -> None:
    """The real Kubernetes v1 wire format as a scheme version: upstream
    Pod/Node manifests decode via the bridge codecs."""

    def _pod_v1(raw: dict) -> Any:
        from ..bridge.convert import pod_from_v1

        return pod_from_v1(raw)

    def _node_v1(raw: dict) -> Any:
        from ..bridge.convert import node_from_v1

        return node_from_v1(raw)

    register_conversion("Pod", "v1", _pod_v1)
    register_conversion("Node", "v1", _node_v1)


_register_v1_conversions()


def _default_pod(pod: Any) -> Any:
    """pkg/apis/core/v1 defaulting slice: an empty schedulerName becomes
    "default-scheduler" (SetDefaults_PodSpec)."""
    if not pod.scheduler_name:
        import dataclasses

        return dataclasses.replace(pod, scheduler_name="default-scheduler")
    return pod


register_defaults(t.Pod, _default_pod)
