"""Scheme + serializers — the apimachinery runtime.Scheme analog.

Reference: ``staging/src/k8s.io/apimachinery`` — ``runtime.Scheme`` maps
GroupVersionKinds to Go types and back; serializers encode objects with a
``kind``/``apiVersion`` tag so any component can round-trip any registered
object. Here the registry maps **kind names to dataclasses** and the codec
round-trips the typed scheduling envelope (dataclasses, enums, tuples,
nested objects) through plain JSON with a ``"kind"`` tag — the wire format
of the apiserver layer (kubetpu.apiserver) and anything else that ships
typed objects across a process boundary.

Unknown kinds and unknown fields fail loudly (strict decoding — the
reference's strict serializer mode); None round-trips as null; tuples of
nested dataclasses are reconstructed from the field's type annotation.
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any

from . import types as t

# kind name -> dataclass. The registered surface is every wire-visible
# object of the framework (the "API types" layer).
_KINDS: dict[str, type] = {}


def register(cls: type, kind: str | None = None) -> type:
    _KINDS[kind or cls.__name__] = cls
    return cls


for _cls in (
    t.Node, t.Pod, t.Taint, t.Toleration, t.Affinity, t.NodeAffinity,
    t.PodAffinity, t.PodAffinityTerm, t.WeightedPodAffinityTerm,
    t.PreferredSchedulingTerm, t.NodeSelector, t.NodeSelectorTerm,
    t.Requirement, t.LabelSelector, t.TopologySpreadConstraint,
    t.ContainerPort, t.PodVolume, t.PersistentVolume,
    t.PersistentVolumeClaim, t.StorageClass, t.Service, t.Namespace,
    t.PodDisruptionBudget, t.PodGroup, t.GangPolicy, t.ImageState,
    t.ReplicaSet, t.DeviceClass, t.CELSelector, t.ResourceSlice, t.Device,
    t.DeviceRequest, t.DeviceSubRequest, t.DeviceConstraint,
    t.ResourceClaim, t.ClaimAllocation, t.DeviceResult, t.PodResourceClaim,
    t.NodeHeartbeat, t.LeaderElectionRecord, t.Deployment, t.Job,
    t.StatefulSet, t.ResourceClaimTemplate, t.DaemonSet,
):
    register(_cls)


class SchemeError(ValueError):
    pass


def encode(obj: Any) -> Any:
    """Object → JSON-safe value. Dataclasses carry a "kind" tag."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        if isinstance(obj, enum.Enum):   # str-enums are str instances
            return obj.value
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        kind = type(obj).__name__
        if kind not in _KINDS:
            raise SchemeError(f"kind {kind!r} is not registered")
        out: dict[str, Any] = {"kind": kind}
        for f in dataclasses.fields(obj):
            out[f.name] = encode(getattr(obj, f.name))
        return out
    if isinstance(obj, (list, tuple)):
        return [encode(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): encode(v) for k, v in obj.items()}
    raise SchemeError(f"cannot encode {type(obj).__name__}")


def _resolve_hints(cls: type) -> dict[str, Any]:
    # evaluated lazily + cached on the class (postponed annotations)
    cached = cls.__dict__.get("__kubetpu_hints__")
    if cached is None:
        cached = typing.get_type_hints(cls, vars(t))
        setattr(cls, "__kubetpu_hints__", cached)
    return cached


def _coerce(value: Any, hint: Any) -> Any:
    """Rebuild tuples/enums/nested dataclasses from the field annotation."""
    if value is None:
        return None
    if isinstance(value, dict) and "kind" in value:
        return decode(value)
    origin = typing.get_origin(hint)
    if origin in (typing.Union, getattr(__import__("types"), "UnionType", ())):
        for arm in typing.get_args(hint):
            if arm is type(None):
                continue
            try:
                return _coerce(value, arm)
            except (SchemeError, TypeError, ValueError):
                continue
        raise SchemeError(f"no union arm of {hint} accepts {value!r}")
    if origin is tuple:
        if not isinstance(value, list):
            raise SchemeError(f"expected array for {hint}, got {value!r}")
        args = typing.get_args(hint)
        if len(args) == 2 and args[1] is Ellipsis:
            return tuple(_coerce(v, args[0]) for v in value)
        if args:
            return tuple(
                _coerce(v, args[i % len(args)]) for i, v in enumerate(value)
            )
        return tuple(value)
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return hint(value)
    if isinstance(hint, type) and dataclasses.is_dataclass(hint):
        if isinstance(value, dict):
            return _decode_into(hint, value)
        raise SchemeError(f"expected object for {hint.__name__}, got {value!r}")
    if origin is dict:
        if not isinstance(value, dict):
            raise SchemeError(f"expected object for {hint}, got {value!r}")
        args = typing.get_args(hint)
        if args:
            return {str(k): _coerce(v, args[1]) for k, v in value.items()}
        return value
    # Primitive leaves are type-checked against the annotation — strict
    # decoding covers field types, not just unknown kinds/fields. bool is
    # checked before int (bool is an int subclass); int is accepted where
    # float is annotated (JSON has one number type).
    if hint is bool:
        if not isinstance(value, bool):
            raise SchemeError(f"expected bool, got {value!r}")
        return value
    if hint is int:
        if isinstance(value, bool) or not isinstance(value, int):
            raise SchemeError(f"expected int, got {value!r}")
        return value
    if hint is float:
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise SchemeError(f"expected float, got {value!r}")
        return value
    if hint is str:
        if not isinstance(value, str):
            raise SchemeError(f"expected str, got {value!r}")
        return value
    return value


def _decode_into(cls: type, data: dict) -> Any:
    hints = _resolve_hints(cls)
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs: dict[str, Any] = {}
    for key, raw in data.items():
        if key == "kind":
            continue
        if key not in field_names:
            raise SchemeError(
                f"{cls.__name__}: unknown field {key!r} (strict decoding)"
            )
        kwargs[key] = _coerce(raw, hints[key])
    return cls(**kwargs)


def decode(data: Any) -> Any:
    """JSON value → typed object (requires the "kind" tag on objects)."""
    if isinstance(data, dict):
        kind = data.get("kind")
        if kind is None:
            raise SchemeError("object has no 'kind' tag")
        cls = _KINDS.get(kind)
        if cls is None:
            raise SchemeError(
                f"kind {kind!r} is not registered "
                f"(known: {sorted(_KINDS)})"
            )
        return _decode_into(cls, data)
    if isinstance(data, list):
        return [decode(x) for x in data]
    return data
