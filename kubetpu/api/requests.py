"""Pod resource-request aggregation.

Reference semantics: ``resource.PodRequests`` (k8s.io/component-helpers
resource helpers, helpers.go:243 podRequests / :438 aggregation), as used by
``computePodResourceRequest`` (pkg/scheduler/framework/plugins/noderesources/
fit.go:317-327):

    total  = sum over app containers of per-resource requests
    sidecar init containers (restartPolicy: Always) run for the pod's whole
    lifetime: their requests ADD to the running total, and accumulate into a
    sidecar sum that also rides along with every later (non-sidecar) init
    container's peak:
        for each init container, in order:
            if sidecar: total += req; sidecar_sum += req; candidate = sidecar_sum
            else:       candidate = req + sidecar_sum
            init_peak = max(init_peak, candidate)     (element-wise)
    total  = max(total, init_peak)                    (element-wise)
    total += pod overhead

Pod-level resources (PodLevelResources feature) take precedence when set.
"""

from __future__ import annotations

from typing import Mapping, Sequence


def _add(a: dict[str, int], b: Mapping[str, int]) -> None:
    for k, v in b.items():
        a[k] = a.get(k, 0) + v


def _max_merge(a: dict[str, int], b: Mapping[str, int]) -> None:
    for k, v in b.items():
        if v > a.get(k, 0):
            a[k] = v


def pod_requests(
    containers: Sequence[Mapping[str, int]] = (),
    init_containers: Sequence[Mapping[str, int]] = (),
    overhead: Mapping[str, int] | None = None,
    pod_level: Mapping[str, int] | None = None,
    init_restartable: Sequence[bool] | None = None,
) -> dict[str, int]:
    """Aggregate container requests into the pod's effective request.

    ``init_restartable[i]`` marks init container *i* as a sidecar
    (``restartPolicy: Always``) — its requests persist for the pod's
    lifetime instead of participating only in the init-phase peak
    (helpers.go:243 podRequests restartable branch).
    """
    total: dict[str, int] = {}
    for c in containers:
        _add(total, c)
    sidecar_sum: dict[str, int] = {}
    init_peak: dict[str, int] = {}
    for i, ic in enumerate(init_containers):
        if init_restartable is not None and i < len(init_restartable) and init_restartable[i]:
            _add(total, ic)
            _add(sidecar_sum, ic)
            candidate: Mapping[str, int] = dict(sidecar_sum)
        else:
            cand = dict(ic)
            _add(cand, sidecar_sum)
            candidate = cand
        _max_merge(init_peak, candidate)
    _max_merge(total, init_peak)
    if pod_level:
        # Pod-level resources override the aggregate for the resources they name.
        for k, v in pod_level.items():
            total[k] = v
    if overhead:
        _add(total, overhead)
    return {k: v for k, v in total.items() if v != 0}


def pod_nonzero_requests(
    containers: Sequence[Mapping[str, int]] = (),
    init_containers: Sequence[Mapping[str, int]] = (),
    overhead: Mapping[str, int] | None = None,
    pod_level: Mapping[str, int] | None = None,
    init_restartable: Sequence[bool] | None = None,
) -> dict[str, int]:
    """The NonZeroRequested (scoring) view of the pod's cpu/memory request.

    Reference: PodInfo.CalculateResource (pkg/scheduler/framework/types.go:1035)
    — every *container* missing a cpu/memory request is treated as requesting
    100 mCPU / 200 MiB (getNonMissingContainerRequests, :1387), then the same
    max(sum(containers), max(init)) + overhead aggregation runs. The defaults
    are per-container, so a pod with containers [{cpu:500m}, {memory:1GiB}]
    has Non0CPU = 600m, not 500m. A request EXPLICITLY set to zero is NOT
    defaulted ("Override if un-set, but not if explicitly set to zero" —
    schedutil GetRequestForResource): a present-but-zero key stays zero.

    When pod-level resources are set for a resource, that resource's default
    is not filled (the pod-level value wins).
    """
    from .types import CPU, DEFAULT_MEMORY_REQUEST, DEFAULT_MILLI_CPU_REQUEST, MEMORY

    def fill(c: Mapping[str, int]) -> dict[str, int]:
        out = dict(c)
        if CPU not in out and not (pod_level and pod_level.get(CPU, 0) > 0):
            out[CPU] = DEFAULT_MILLI_CPU_REQUEST
        if MEMORY not in out and not (pod_level and pod_level.get(MEMORY, 0) > 0):
            out[MEMORY] = DEFAULT_MEMORY_REQUEST
        return out

    return pod_requests(
        [fill(c) for c in containers],
        [fill(ic) for ic in init_containers],
        overhead,
        pod_level,
        init_restartable,
    )
