"""Host-side selector evaluation.

Pure-Python (numpy-free) predicate evaluation used by the tensorization layer
to precompute boolean match matrices; the device kernels only ever see the
resulting masks. Semantics mirror the reference helpers:

- metav1 LabelSelector matching: apimachinery ``labels.Requirement.Matches``
  (NotIn/DoesNotExist match when the key is absent).
- NodeSelector matching: ``component-helpers/scheduling/corev1/nodeaffinity``
  (terms are ORed; expressions within a term are ANDed; a term with no
  expressions and no fields matches nothing; Gt/Lt parse integers).
- Taint toleration: ``component-helpers/scheduling/corev1``
  ``Toleration.ToleratesTaint``.
"""

from __future__ import annotations

from typing import Mapping

from .types import (
    LabelSelector,
    NodeSelector,
    NodeSelectorTerm,
    Operator,
    Requirement,
    Taint,
    TaintEffect,
    Toleration,
)


def requirement_matches(req: Requirement, labels: Mapping[str, str]) -> bool:
    has = req.key in labels
    val = labels.get(req.key)
    op = req.operator
    if op == Operator.IN:
        return has and val in req.values
    if op == Operator.NOT_IN:
        return (not has) or val not in req.values
    if op == Operator.EXISTS:
        return has
    if op == Operator.DOES_NOT_EXIST:
        return not has
    if op in (Operator.GT, Operator.LT):
        if not has or len(req.values) != 1:
            return False
        try:
            lhs = int(val)  # type: ignore[arg-type]
            rhs = int(req.values[0])
        except ValueError:
            return False
        return lhs > rhs if op == Operator.GT else lhs < rhs
    raise ValueError(f"unknown operator {op}")


def label_selector_matches(sel: LabelSelector, labels: Mapping[str, str]) -> bool:
    """Empty selector matches everything (metav1 semantics)."""
    for k, v in sel.match_labels:
        if labels.get(k) != v:
            return False
    for req in sel.match_expressions:
        if req.operator in (Operator.GT, Operator.LT):
            # metav1 LabelSelector does not allow Gt/Lt; treat as no match.
            return False
        if not requirement_matches(req, labels):
            return False
    return True


def node_selector_term_matches(
    term: NodeSelectorTerm, labels: Mapping[str, str], node_name: str
) -> bool:
    if not term.match_expressions and not term.match_fields:
        return False  # nil/empty term selects no objects
    for req in term.match_expressions:
        if not requirement_matches(req, labels):
            return False
    for req in term.match_fields:
        if req.key != "metadata.name":
            return False
        if not requirement_matches(req, {"metadata.name": node_name}):
            return False
    return True


def node_selector_matches(
    sel: NodeSelector, labels: Mapping[str, str], node_name: str
) -> bool:
    """OR over terms. An empty term list matches nothing."""
    return any(
        node_selector_term_matches(t, labels, node_name) for t in sel.terms
    )


def tolerates(tol: Toleration, taint: Taint) -> bool:
    """staging/src/k8s.io/api/core/v1/toleration.go ToleratesTaint: the key
    check is skipped entirely for an empty key (so empty-key+Equal compares
    values, and empty-key+Exists tolerates everything)."""
    if tol.effect is not None and tol.effect != taint.effect:
        return False
    if tol.key != "" and tol.key != taint.key:
        return False
    if tol.operator.value == "Exists":
        return True
    return tol.value == taint.value


def find_untolerated_taint(
    taints: tuple[Taint, ...],
    tolerations: tuple[Toleration, ...],
    effects: tuple[TaintEffect, ...] = (TaintEffect.NO_SCHEDULE, TaintEffect.NO_EXECUTE),
) -> Taint | None:
    """First taint with one of ``effects`` that no toleration tolerates
    (v1helper.FindMatchingUntoleratedTaint, as the TaintToleration filter uses)."""
    for taint in taints:
        if taint.effect not in effects:
            continue
        if not any(tolerates(t, taint) for t in tolerations):
            return taint
    return None


def parse_simple_selector(s: str) -> tuple[tuple[str, bool, str], ...]:
    """Parse the ``k=v,k2!=v2`` list/watch selector string (the subset of
    labels.Parse / fields.ParseSelector the reference's list options use:
    ``=``, ``==``, ``!=``) into ``(key, equals, value)`` terms. An empty
    string selects everything. Malformed terms raise ValueError (the
    apiserver's 400 on a bad selector)."""
    terms: list[tuple[str, bool, str]] = []
    for part in s.split(","):
        part = part.strip()
        if not part:
            continue
        if "!=" in part:
            k, _, v = part.partition("!=")
            eq = False
        elif "==" in part:
            k, _, v = part.partition("==")
            eq = True
        elif "=" in part:
            k, _, v = part.partition("=")
            eq = True
        else:
            raise ValueError(f"malformed selector term {part!r}")
        k = k.strip()
        if not k:
            raise ValueError(f"malformed selector term {part!r}")
        terms.append((k, eq, v.strip()))
    return tuple(terms)


# fieldSelector paths the server understands (the reference's supported
# fields per resource — registry strategies' GetAttrs; spec.nodeName is the
# kubelet's pod watch, pkg/registry/core/pod/strategy.go NodeNameTriggerFunc)
def object_field(obj, path: str) -> str | None:
    if path == "metadata.name":
        return getattr(obj, "name", None)
    if path == "metadata.namespace":
        return getattr(obj, "namespace", None)
    if path == "spec.nodeName":
        return getattr(obj, "node_name", None)
    if path == "status.phase":
        return getattr(obj, "phase", None)
    if path == "spec.schedulerName":
        return getattr(obj, "scheduler_name", None)
    return None


def simple_selector_matches(
    terms: tuple[tuple[str, bool, str], ...], get
) -> bool:
    """``get(key) -> str | None``; a None field only matches ``!=``."""
    for key, eq, value in terms:
        got = get(key)
        if eq:
            if got != value:
                return False
        elif got == value:
            return False
    return True


def object_matches_selectors(
    obj,
    label_terms: tuple[tuple[str, bool, str], ...] = (),
    field_terms: tuple[tuple[str, bool, str], ...] = (),
) -> bool:
    if label_terms:
        labels = getattr(obj, "labels_dict", dict)()
        if not simple_selector_matches(label_terms, labels.get):
            return False
    if field_terms:
        if not simple_selector_matches(
            field_terms, lambda p: object_field(obj, p)
        ):
            return False
    return True


def count_intolerable_prefer_no_schedule(
    taints: tuple[Taint, ...], tolerations: tuple[Toleration, ...]
) -> int:
    """TaintToleration Score raw value
    (tainttoleration/taint_toleration.go:163): count PreferNoSchedule taints
    not tolerated by the pod's PreferNoSchedule-or-effectless tolerations."""
    prefer_tols = tuple(
        t for t in tolerations
        if t.effect is None or t.effect == TaintEffect.PREFER_NO_SCHEDULE
    )
    n = 0
    for taint in taints:
        if taint.effect != TaintEffect.PREFER_NO_SCHEDULE:
            continue
        if not any(tolerates(t, taint) for t in prefer_tols):
            n += 1
    return n
