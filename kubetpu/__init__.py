"""kubetpu — a TPU-native batch scheduling framework.

A from-scratch re-design of the Kubernetes scheduling stack (reference:
kube-scheduler, /root/reference/pkg/scheduler) for TPU hardware: the in-tree
Filter plugins become boolean-mask kernels and the Score plugins become
vectorized JAX/XLA kernels over a device-resident ``(pods, nodes)`` tensor;
the per-pod greedy ``scheduleOne`` loop becomes a single device-resident
``lax.scan`` (greedy-parity mode) or a capacity-coupled batched assignment
(Sinkhorn mode), sharded over a TPU mesh with ``shard_map``/``pjit``.

Subpackages
-----------
- ``api``:       typed cluster objects (Pod, Node, selectors, taints, affinity)
                 — the scheduling-relevant envelope of ``staging/src/k8s.io/api``.
- ``state``:     host snapshot store + string interning + device tensorization
                 — the analog of ``pkg/scheduler/backend/cache``.
- ``ops``:       filter/score kernels — the analog of
                 ``pkg/scheduler/framework/plugins``.
- ``assign``:    assignment engines (greedy scan, Sinkhorn bin-pack) — replaces
                 ``pkg/scheduler/schedule_one.go``'s argmax-per-pod.
- ``parallel``:  mesh construction + sharding rules (node/pod axis over ICI).
- ``framework``: plugin registry, profiles, KubeSchedulerConfiguration subset —
                 the analog of ``pkg/scheduler/framework/runtime``.
- ``sched``:     scheduling queue + batch scheduling/binding cycles.
- ``bridge``:    extender-webhook wire protocol server (the integration seam
                 with a real kube-scheduler, ``pkg/scheduler/extender.go``).
- ``perf``:      scheduler_perf-style workload harness.
- ``utils``:     metrics, feature gates, logging.

Integer-exact score parity with the reference requires 64-bit resource
arithmetic (quantities are int64 in the reference, and memory-bytes overflow
int32), so importing this package enables jax x64 mode. kubetpu is an
application framework — the process is expected to be a scheduler. If you are
embedding the host-side API types into a process whose JAX numerics must stay
32-bit, set ``KUBETPU_NO_X64=1`` before import and avoid the device kernels.
"""

import os

import jax

if not os.environ.get("KUBETPU_NO_X64"):
    jax.config.update("jax_enable_x64", True)

# Honor JAX_PLATFORMS even when a site hook clobbered it: this image's axon
# sitecustomize unconditionally does jax.config.update("jax_platforms",
# "axon,cpu") at interpreter startup, so a child process launched with
# JAX_PLATFORMS=cpu still initializes the axon backend on its first device
# op — and hangs forever when the TPU relay is down. Re-assert the env ONLY
# over that exact site-hook signature and only when the env's preferred
# platform isn't axon anyway — an explicit jax.config.update made by the
# embedding process before importing kubetpu always wins (the config no
# longer reads "axon,cpu"), and ambient axon environments are untouched.
_env_platforms = os.environ.get("JAX_PLATFORMS", "")
if (
    _env_platforms
    and jax.config.jax_platforms == "axon,cpu"
    and _env_platforms.split(",")[0] not in ("axon", "")
):
    jax.config.update("jax_platforms", _env_platforms)
del _env_platforms

__version__ = "0.4.0"
