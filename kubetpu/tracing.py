"""Latency tracing — the utiltrace + component-base/tracing analog.

Reference surfaces:
- ``k8s.io/utils/trace`` (utiltrace): ``schedulePod`` opens a trace and
  logs its step breakdown when the cycle exceeds 100 ms
  (schedule_one.go:566-567). Mirrored by ``Tracer.span`` + the
  over-threshold log hook.
- ``component-base/tracing`` (OTel, utils.go:79-85): ratio-sampled spans
  with attributes exported off-process. Mirrored structurally: spans carry
  ids/parents/attributes and land in a bounded in-memory buffer an exporter
  can drain (``Tracer.drain``); the scheduler joins device + host work by
  cycle id, the OTel-span-per-cycle design SURVEY §5 prescribes.
- JAX profiler: ``device_profile`` wraps ``jax.profiler.trace`` so a
  perf investigation captures XLA device traces alongside the host spans.

Single-owner like the scheduler loop: span entry/exit runs on the loop
thread, so the parent stack is a plain list (no contextvars in the hot
path). Recording one span costs two ``perf_counter`` calls and an append.
"""

from __future__ import annotations

import collections
import itertools
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable


@dataclass
class Span:
    name: str
    span_id: int
    parent_id: int | None
    start: float
    end: float = 0.0
    attrs: dict[str, Any] = field(default_factory=dict)
    # recorded out-of-stack (Tracer.record): overlaps loop spans and other
    # off-stack spans, so the Chrome-trace exporter lays it out on its own
    # non-overlapping lane (tid >= 2)
    off_stack: bool = False
    # a zero-duration marker (Tracer.instant) — exported as a Chrome-trace
    # instant ("i") event instead of a complete span
    instant: bool = False

    @property
    def duration_s(self) -> float:
        return max(self.end - self.start, 0.0)


class Tracer:
    """Bounded in-memory span recorder with utiltrace threshold logging."""

    def __init__(
        self,
        enabled: bool = True,
        max_spans: int = 4096,
        threshold_s: float = 0.1,
        log: Callable[[str], None] | None = None,
        clock: Callable[[], float] = time.perf_counter,
    ) -> None:
        self.enabled = enabled
        self.threshold_s = threshold_s
        self._clock = clock
        self._log = log
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=max_spans
        )
        self._stack: list[Span] = []
        self._ids = itertools.count(1)

    @contextmanager
    def span(self, name: str, **attrs):
        """Open a span; yields it so steps can attach attributes. A
        TOP-LEVEL span exceeding ``threshold_s`` logs its child breakdown
        (utiltrace's LogIfLong)."""
        if not self.enabled:
            yield None
            return
        parent = self._stack[-1] if self._stack else None
        sp = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=parent.span_id if parent else None,
            start=self._clock(),
            attrs=dict(attrs),
        )
        self._stack.append(sp)
        try:
            yield sp
        finally:
            sp.end = self._clock()
            self._stack.pop()
            self._spans.append(sp)
            if parent is None and sp.duration_s >= self.threshold_s:
                self._log_long(sp)

    def record(
        self,
        name: str,
        start: float,
        end: float,
        parent_id: int | None = None,
        off_stack: bool = True,
        **attrs,
    ) -> Span | None:
        """Record a span whose timing happened OFF the loop thread's span
        stack (an async bind measured dispatch→completion): the caller
        supplies start/end on this tracer's clock; the span lands in the
        buffer like any other but never touches the parent stack.
        ``off_stack=False`` places it on the loop lane (tid 1) in the
        Chrome-trace export — for loop-owned phases whose start/end bracket
        other calls (the pipelined scheduling cycle spans dispatch→sync
        across two loop iterations), provided the caller guarantees proper
        nesting with the lane's other spans."""
        if not self.enabled:
            return None
        sp = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=parent_id,
            start=start,
            end=end,
            attrs=dict(attrs),
            off_stack=off_stack,
        )
        self._spans.append(sp)
        return sp

    def instant(self, name: str, **attrs) -> Span | None:
        """Record a zero-duration marker at 'now' (an event, not a phase —
        e.g. an encode-cache invalidation). Lands in the buffer like any
        span; the Chrome-trace export renders it as an instant event."""
        if not self.enabled:
            return None
        now = self._clock()
        sp = Span(
            name=name,
            span_id=next(self._ids),
            parent_id=None,
            start=now,
            end=now,
            attrs=dict(attrs),
            off_stack=True,
            instant=True,
        )
        self._spans.append(sp)
        return sp

    # ---- inspection ------------------------------------------------------
    def _snapshot_spans(self) -> list[Span]:
        """Copy the buffer tolerating concurrent appends: a diagnostics
        HTTP thread snapshots while the loop thread records (deque appends
        are atomic, but iterating during an append raises RuntimeError —
        retry instead of locking the hot path)."""
        while True:
            try:
                return list(self._spans)
            except RuntimeError:
                continue

    def recent(self, n: int = 100) -> list[Span]:
        return self._snapshot_spans()[-n:]

    def drain(self) -> list[Span]:
        """Hand the buffered spans to an exporter and remove EXACTLY those
        spans from the buffer. A bare ``clear()`` here would erase spans
        recorded between the snapshot and the clear (the loop thread
        records while an exporter drains) — those must survive for the
        next drain and for concurrent readers (``/trace``, the flight
        recorder), so only the snapshotted prefix is popped."""
        out = self._snapshot_spans()
        drained = {id(s) for s in out}
        while True:
            try:
                head = self._spans[0]
            except IndexError:
                break
            if id(head) not in drained:
                break            # a newer span reached the head: stop
            self._spans.popleft()
        return out

    # ---- export ----------------------------------------------------------
    def chrome_trace(self, spans: list[Span] | None = None) -> dict:
        """The buffered spans as Chrome-trace-format JSON (Perfetto /
        chrome://tracing loadable): one complete ("X") event per span,
        µs timestamps on the tracer's monotonic clock, span/parent ids and
        attributes (incl. the cycle id the device-side counter records
        join on) under ``args``. Non-destructive — ``drain`` separately to
        clear the buffer."""
        src = self._snapshot_spans() if spans is None else spans
        events = []
        # off-stack spans (async binds) overlap the loop's spans AND each
        # other; complete events on one tid must nest properly or Perfetto
        # misnests/drops them, so each off-stack span takes the first free
        # LANE (tid >= 2) whose previous span already ended
        lane_ends: list[float] = []
        for sp in sorted(src, key=lambda s: s.start):
            if sp.instant:
                # marker events take no lane — process-scoped instants
                events.append({
                    "name": sp.name,
                    "cat": "kubetpu",
                    "ph": "i",
                    "s": "p",
                    "ts": sp.start * 1e6,
                    "pid": 1,
                    "tid": 1,
                    "args": {"span_id": sp.span_id, **sp.attrs},
                })
                continue
            if sp.off_stack:
                for lane, end in enumerate(lane_ends):
                    if end <= sp.start:
                        lane_ends[lane] = sp.end
                        break
                else:
                    lane = len(lane_ends)
                    lane_ends.append(sp.end)
                tid = 2 + lane
            else:
                tid = 1
            events.append({
                "name": sp.name,
                "cat": "kubetpu",
                "ph": "X",
                "ts": sp.start * 1e6,
                "dur": sp.duration_s * 1e6,
                "pid": 1,
                "tid": tid,
                "args": {
                    "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    **sp.attrs,
                },
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_chrome_trace(
        self, path: str, spans: list[Span] | None = None
    ) -> str:
        """Write ``chrome_trace`` to ``path``; returns the path."""
        import json

        with open(path, "w") as f:
            json.dump(self.chrome_trace(spans), f)
        return path

    def children_of(self, span: Span) -> list[Span]:
        return [
            s for s in self._snapshot_spans()
            if s.parent_id == span.span_id
        ]

    # ---- threshold logging ----------------------------------------------
    def _log_long(self, sp: Span) -> None:
        steps = "; ".join(
            f"{c.name} {c.duration_s * 1000:.1f}ms"
            for c in self.children_of(sp)
        )
        attrs = ",".join(f"{k}={v}" for k, v in sp.attrs.items())
        msg = (
            f"Trace[{sp.name}] ({attrs}): {sp.duration_s * 1000:.1f}ms"
            + (f" — steps: {steps}" if steps else "")
        )
        if self._log is not None:
            self._log(msg)
        else:  # pragma: no cover - default sink
            import logging

            logging.getLogger("kubetpu.trace").warning(msg)


@contextmanager
def device_profile(log_dir: str):
    """Capture an XLA device trace for the enclosed block (JAX profiler —
    the TPU side of a latency investigation; view with tensorboard/xprof)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
