"""Native runtime components — compile-on-first-use C++ extensions.

The reference's runtime is compiled code end to end (Go binaries + native
etcd); kubetpu's device path is XLA-compiled, and THIS package supplies the
native host-runtime pieces: currently the store core
(``memstore_core.cpp`` — the versioned object map + watch ring behind
``kubetpu.store.MemStore``).

Build model: ``g++ -O2 -shared -fPIC`` against the running CPython's
headers, cached under ``.native_cache/`` next to this package (keyed by
source mtime + python version). No pip, no pybind11 — the CPython C API
only (environment contract). A missing compiler or ``KUBETPU_NO_NATIVE=1``
falls back to the pure-Python implementation with identical semantics; the
store test suite exercises the same contract against both backends.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys
import sysconfig

_CACHE: dict[str, object] = {}


def _build_dir() -> str:
    d = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".native_cache")
    os.makedirs(d, exist_ok=True)
    return d


def _so_path(name: str, src: str) -> str:
    tag = f"{sys.version_info.major}{sys.version_info.minor}"
    mtime = int(os.stat(src).st_mtime)
    return os.path.join(_build_dir(), f"{name}.py{tag}.{mtime}.so")


def load_extension(name: str, source_file: str):
    """Compile (if needed) and import the named CPython extension; returns
    the module or None when native is disabled/unbuildable. EVERY failure
    mode (read-only package dir, missing compiler, concurrent build, torn
    artifact) degrades to the Python fallback — never a startup crash."""
    if os.environ.get("KUBETPU_NO_NATIVE"):
        return None
    if name in _CACHE:
        return _CACHE[name]
    try:
        mod = _load_extension(name, source_file)
    except Exception as e:
        print(f"kubetpu.native: {name} unavailable "
              f"({type(e).__name__}: {e}); using the Python fallback",
              file=sys.stderr)
        mod = None
    _CACHE[name] = mod
    return mod


def _load_extension(name: str, source_file: str):
    src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       source_file)
    so = _so_path(name, src)
    if not os.path.exists(so):
        include = sysconfig.get_paths()["include"]
        # build to a per-process temp name, then atomically rename: two
        # processes racing the first build can never leave (or load) a
        # torn .so under the cached name
        tmp = f"{so}.tmp.{os.getpid()}"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            f"-I{include}", src, "-o", tmp,
        ]
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=120,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            # loud once (a broken toolchain should be visible), then fall back
            print(f"kubetpu.native: build of {name} failed:\n"
                  f"{proc.stderr[-2000:]}", file=sys.stderr)
            return None
        os.replace(tmp, so)
    spec = importlib.util.spec_from_file_location(name, so)
    if spec is None or spec.loader is None:
        return None
    mod = importlib.util.module_from_spec(spec)
    try:
        spec.loader.exec_module(mod)
    except ImportError:
        return None
    return mod


def store_core():
    """The native StoreCore class, or None (fallback to pure Python)."""
    mod = load_extension("_kubetpu_store", "memstore_core.cpp")
    return getattr(mod, "StoreCore", None) if mod is not None else None
