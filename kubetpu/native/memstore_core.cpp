// Native store core — the versioned object map + watch event ring behind
// kubetpu.store.MemStore (layer 0). The reference's storage layer is native
// code (etcd, compiled Go, spoken over gRPC: apiserver/pkg/storage/etcd3);
// this is the framework's equivalent: the hot create/update/get/list/
// events-since paths in C++, exposed through the CPython C API, holding
// opaque PyObject* values (no serialization on the in-process path).
//
// Concurrency contract: the Python wrapper (kubetpu.store.memstore.MemStore)
// serializes every call under its Condition lock — and CPython extension
// calls hold the GIL — so this core is single-writer by construction and
// keeps no locks of its own.
//
// Build: kubetpu/native/__init__.py compiles this with g++ on first use and
// caches the .so; KUBETPU_NO_NATIVE=1 (or a missing compiler) falls back to
// the pure-Python dict implementation with identical semantics (the test
// suite runs the same contract against both).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Event {
  int type;  // 0 ADDED, 1 MODIFIED, 2 DELETED
  std::string kind;
  std::string key;
  PyObject* obj;  // owned reference
  long long rv;
};

// seq is the insertion order (stable across updates) so list() returns the
// same ordering as the pure-Python dict core — informer replace/replay
// order, and therefore cache insertion order and score tie-breaking, must
// not depend on which store backend is active.
struct Entry {
  PyObject* obj;  // owned reference
  long long rv;
  long long seq;
};

struct StoreObject {
  PyObject_HEAD
  long long rv;
  long long compacted_through;
  long long seq_counter;
  size_t history;
  std::unordered_map<std::string, Entry>* objects;
  std::deque<Event>* events;
};

std::string map_key(const char* kind, const char* key) {
  std::string k(kind);
  k.push_back('\x1f');  // unit separator — never in identifiers
  k.append(key);
  return k;
}

void push_event(StoreObject* self, int type, const char* kind,
                const char* key, PyObject* obj) {
  if (self->events->size() >= self->history) {
    Event& old = self->events->front();
    self->compacted_through = old.rv;
    Py_DECREF(old.obj);
    self->events->pop_front();
  }
  Py_INCREF(obj);
  self->events->push_back(Event{type, kind, key, obj, self->rv});
}

// ---------------------------------------------------------------- methods

PyObject* store_create(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "ssO", &kind, &key, &obj)) return nullptr;
  auto mk = map_key(kind, key);
  if (self->objects->count(mk)) {
    PyErr_Format(PyExc_KeyError, "%s/%s already exists", kind, key);
    return nullptr;
  }
  self->rv += 1;
  Py_INCREF(obj);
  (*self->objects)[mk] = {obj, self->rv, ++self->seq_counter};
  push_event(self, 0, kind, key, obj);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_update(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  PyObject* obj;
  long long expect = -1;
  if (!PyArg_ParseTuple(args, "ssO|L", &kind, &key, &obj, &expect))
    return nullptr;
  auto mk = map_key(kind, key);
  auto it = self->objects->find(mk);
  bool existed = it != self->objects->end();
  if (expect >= 0) {
    long long have = existed ? it->second.rv : -1;
    if (!existed || have != expect) {
      PyErr_Format(PyExc_ValueError, "%s/%s: expected rv %lld, have %lld",
                   kind, key, expect, have);
      return nullptr;
    }
  }
  self->rv += 1;
  Py_INCREF(obj);
  if (existed) {
    Py_DECREF(it->second.obj);
    it->second.obj = obj;
    it->second.rv = self->rv;  // seq unchanged: updates do not reorder
  } else {
    (*self->objects)[mk] = {obj, self->rv, ++self->seq_counter};
  }
  push_event(self, existed ? 1 : 0, kind, key, obj);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_delete(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  if (!PyArg_ParseTuple(args, "ss", &kind, &key)) return nullptr;
  auto mk = map_key(kind, key);
  auto it = self->objects->find(mk);
  if (it == self->objects->end()) {
    PyErr_Format(PyExc_KeyError, "%s/%s not found", kind, key);
    return nullptr;
  }
  PyObject* old = it->second.obj;
  self->objects->erase(it);
  self->rv += 1;
  push_event(self, 2, kind, key, old);
  Py_DECREF(old);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_get(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  if (!PyArg_ParseTuple(args, "ss", &kind, &key)) return nullptr;
  auto it = self->objects->find(map_key(kind, key));
  if (it == self->objects->end()) {
    return Py_BuildValue("(OL)", Py_None, 0LL);
  }
  return Py_BuildValue("(OL)", it->second.obj, it->second.rv);
}

PyObject* store_list(StoreObject* self, PyObject* args) {
  const char* kind;
  if (!PyArg_ParseTuple(args, "s", &kind)) return nullptr;
  std::string prefix(kind);
  prefix.push_back('\x1f');
  struct Hit {
    long long seq;
    const std::string* key;
    const Entry* entry;
    bool operator<(const Hit& o) const { return seq < o.seq; }
  };
  std::vector<Hit> hits;
  for (auto& kv : *self->objects) {
    if (kv.first.compare(0, prefix.size(), prefix) != 0) continue;
    hits.push_back(Hit{kv.second.seq, &kv.first, &kv.second});
  }
  std::sort(hits.begin(), hits.end());  // insertion order, like dict
  PyObject* items = PyList_New(0);
  if (!items) return nullptr;
  for (auto& h : hits) {
    PyObject* entry = Py_BuildValue(
        "(sO)", h.key->c_str() + prefix.size(), h.entry->obj);
    if (!entry || PyList_Append(items, entry) < 0) {
      Py_XDECREF(entry);
      Py_DECREF(items);
      return nullptr;
    }
    Py_DECREF(entry);
  }
  PyObject* out = Py_BuildValue("(NL)", items, self->rv);
  return out;
}

// events_since(kind_or_None, rv) -> (list[(type, kind, key, obj, rv)], cursor)
// raises LookupError when rv predates the ring buffer (compacted).
PyObject* store_events_since(StoreObject* self, PyObject* args) {
  PyObject* kind_obj;
  long long rv;
  if (!PyArg_ParseTuple(args, "OL", &kind_obj, &rv)) return nullptr;
  const char* kind =
      kind_obj == Py_None ? nullptr : PyUnicode_AsUTF8(kind_obj);
  if (kind_obj != Py_None && !kind) return nullptr;
  if (rv < self->compacted_through) {
    PyErr_Format(PyExc_LookupError, "rv %lld compacted (through %lld)", rv,
                 self->compacted_through);
    return nullptr;
  }
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  long long cursor = rv;
  if (!self->events->empty() && self->events->back().rv > rv) {
    cursor = self->events->back().rv;
    // scan only events NEWER than rv (rv-ordered deque, from the back)
    std::vector<const Event*> hits;
    for (auto it = self->events->rbegin(); it != self->events->rend(); ++it) {
      if (it->rv <= rv) break;
      if (!kind || it->kind == kind) hits.push_back(&*it);
    }
    for (auto rit = hits.rbegin(); rit != hits.rend(); ++rit) {
      const Event* e = *rit;
      PyObject* entry =
          Py_BuildValue("(issOL)", e->type, e->kind.c_str(), e->key.c_str(),
                        e->obj, e->rv);
      if (!entry || PyList_Append(out, entry) < 0) {
        Py_XDECREF(entry);
        Py_DECREF(out);
        return nullptr;
      }
      Py_DECREF(entry);
    }
  }
  return Py_BuildValue("(NL)", out, cursor);
}

PyObject* store_resource_version(StoreObject* self, PyObject*) {
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_compacted_through(StoreObject* self, PyObject*) {
  return PyLong_FromLongLong(self->compacted_through);
}

// ----------------------------------------------------------------- type

PyObject* store_new(PyTypeObject* type, PyObject* args, PyObject*) {
  long long history = 8192;
  if (!PyArg_ParseTuple(args, "|L", &history)) return nullptr;
  StoreObject* self = (StoreObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->rv = 0;
  self->compacted_through = 0;
  self->seq_counter = 0;
  self->history = (size_t)(history > 0 ? history : 1);
  self->objects = new std::unordered_map<std::string, Entry>();
  self->events = new std::deque<Event>();
  return (PyObject*)self;
}

void store_dealloc(StoreObject* self) {
  for (auto& kv : *self->objects) Py_DECREF(kv.second.obj);
  for (auto& e : *self->events) Py_DECREF(e.obj);
  delete self->objects;
  delete self->events;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyMethodDef store_methods[] = {
    {"create", (PyCFunction)store_create, METH_VARARGS, nullptr},
    {"update", (PyCFunction)store_update, METH_VARARGS, nullptr},
    {"delete", (PyCFunction)store_delete, METH_VARARGS, nullptr},
    {"get", (PyCFunction)store_get, METH_VARARGS, nullptr},
    {"list", (PyCFunction)store_list, METH_VARARGS, nullptr},
    {"events_since", (PyCFunction)store_events_since, METH_VARARGS, nullptr},
    {"resource_version", (PyCFunction)store_resource_version, METH_NOARGS,
     nullptr},
    {"compacted_through", (PyCFunction)store_compacted_through, METH_NOARGS,
     nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject StoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_kubetpu_store",
    "native versioned object store core", -1, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__kubetpu_store(void) {
  StoreType.tp_name = "_kubetpu_store.StoreCore";
  StoreType.tp_basicsize = sizeof(StoreObject);
  StoreType.tp_flags = Py_TPFLAGS_DEFAULT;
  StoreType.tp_new = store_new;
  StoreType.tp_dealloc = (destructor)store_dealloc;
  StoreType.tp_methods = store_methods;
  if (PyType_Ready(&StoreType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&module_def);
  if (!m) return nullptr;
  Py_INCREF(&StoreType);
  if (PyModule_AddObject(m, "StoreCore", (PyObject*)&StoreType) < 0) {
    Py_DECREF(&StoreType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
