// Native store core — the versioned object map + watch event ring behind
// kubetpu.store.MemStore (layer 0). The reference's storage layer is native
// code (etcd, compiled Go, spoken over gRPC: apiserver/pkg/storage/etcd3);
// this is the framework's equivalent: the hot create/update/get/list/
// events-since paths in C++, exposed through the CPython C API, holding
// opaque PyObject* values (no serialization on the in-process path).
//
// Concurrency contract: the Python wrapper (kubetpu.store.memstore.MemStore)
// serializes every call under its Condition lock — and CPython extension
// calls hold the GIL — so this core is single-writer by construction and
// keeps no locks of its own.
//
// Build: kubetpu/native/__init__.py compiles this with g++ on first use and
// caches the .so; KUBETPU_NO_NATIVE=1 (or a missing compiler) falls back to
// the pure-Python dict implementation with identical semantics (the test
// suite runs the same contract against both).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <algorithm>
#include <cstring>
#include <deque>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

// codec slots in the per-event wire-body cache (must stay aligned with
// kubetpu.api.codec.WIRE_CODEC_IDS and memstore._WIRE_IDS)
constexpr int kNumCodecs = 2;  // 0 json, 1 binary

struct Event {
  int type;  // 0 ADDED, 1 MODIFIED, 2 DELETED
  std::string kind;
  std::string key;
  PyObject* obj;  // owned reference
  long long rv;
  // serialize-once body ring: the event's wire bytes per codec, encoded
  // at most once (events are immutable — writes replace objects — so a
  // cached body can never go stale; it dies with the ring entry)
  PyObject* bodies[kNumCodecs];  // owned references or nullptr
};

// seq is the insertion order (stable across updates) so list() returns the
// same ordering as the pure-Python dict core — informer replace/replay
// order, and therefore cache insertion order and score tie-breaking, must
// not depend on which store backend is active.
struct Entry {
  PyObject* obj;  // owned reference
  long long rv;
  long long seq;
};

struct StoreObject {
  PyObject_HEAD
  long long rv;
  long long compacted_through;
  long long seq_counter;
  size_t history;
  long long body_hits[kNumCodecs];
  long long body_misses[kNumCodecs];
  std::unordered_map<std::string, Entry>* objects;
  std::deque<Event>* events;
};

std::string map_key(const char* kind, const char* key) {
  std::string k(kind);
  k.push_back('\x1f');  // unit separator — never in identifiers
  k.append(key);
  return k;
}

void push_event(StoreObject* self, int type, const char* kind,
                const char* key, PyObject* obj) {
  if (self->events->size() >= self->history) {
    Event& old = self->events->front();
    self->compacted_through = old.rv;
    Py_DECREF(old.obj);
    for (int c = 0; c < kNumCodecs; ++c) Py_XDECREF(old.bodies[c]);
    self->events->pop_front();
  }
  Py_INCREF(obj);
  self->events->push_back(Event{type, kind, key, obj, self->rv, {}});
}

// ------------------------------------------------- watch-ring walkers

// Ring entries newer than rv for `kind` (nullptr = every kind), oldest
// first, + the new cursor. Pointers stay valid while the caller holds
// the wrapper's store lock (no concurrent push/pop).
long long collect_since(StoreObject* self, const char* kind, long long rv,
                        std::vector<Event*>* out) {
  if (self->events->empty() || self->events->back().rv <= rv) return rv;
  long long cursor = self->events->back().rv;
  for (auto it = self->events->rbegin(); it != self->events->rend(); ++it) {
    if (it->rv <= rv) break;
    if (!kind || it->kind == kind) out->push_back(&*it);
  }
  std::reverse(out->begin(), out->end());
  return cursor;
}

PyObject* event_tuple(const Event* e) {
  return Py_BuildValue("(issOL)", e->type, e->kind.c_str(), e->key.c_str(),
                       e->obj, e->rv);
}

// One event's cached wire body (new reference), encoding through the
// Python callback on first sight. The callback runs under the wrapper's
// store lock and must never re-enter the store (kubetpu.api.codec's
// encoders are pure).
PyObject* event_body(StoreObject* self, Event* e, int cid,
                     PyObject* encoder) {
  if (e->bodies[cid]) {
    self->body_hits[cid] += 1;
    Py_INCREF(e->bodies[cid]);
    return e->bodies[cid];
  }
  PyObject* body = PyObject_CallFunction(encoder, "isOL", e->type,
                                         e->key.c_str(), e->obj, e->rv);
  if (!body) return nullptr;
  if (!PyBytes_Check(body)) {
    Py_DECREF(body);
    PyErr_SetString(PyExc_TypeError,
                    "event body encoder must return bytes");
    return nullptr;
  }
  self->body_misses[cid] += 1;
  Py_INCREF(body);
  e->bodies[cid] = body;
  return body;
}

// ---------------------------------------------------- selector matching
// The list/watch simple-selector subset (kubetpu.api.selectors
// parse_simple_selector terms: (key, equals, value)) evaluated in C —
// the native half of MemStore.list's server-side filtering.

// obj.labels_dict() (absent method = empty labels) — new reference.
PyObject* get_labels(PyObject* obj) {
  PyObject* meth = PyObject_GetAttrString(obj, "labels_dict");
  if (!meth) {
    PyErr_Clear();
    return PyDict_New();
  }
  PyObject* d = PyObject_CallObject(meth, nullptr);
  Py_DECREF(meth);
  return d;  // nullptr propagates the call's error
}

// fieldSelector path → attribute value (api.selectors.object_field's
// exact map) — new reference; Py_None for unknown paths/absent attrs.
PyObject* field_value(PyObject* obj, const char* path) {
  const char* attr = nullptr;
  if (!std::strcmp(path, "metadata.name")) attr = "name";
  else if (!std::strcmp(path, "metadata.namespace")) attr = "namespace";
  else if (!std::strcmp(path, "spec.nodeName")) attr = "node_name";
  else if (!std::strcmp(path, "status.phase")) attr = "phase";
  else if (!std::strcmp(path, "spec.schedulerName")) attr = "scheduler_name";
  if (!attr) Py_RETURN_NONE;
  PyObject* v = PyObject_GetAttrString(obj, attr);
  if (!v) {
    PyErr_Clear();
    Py_RETURN_NONE;
  }
  return v;
}

// one term against the looked-up value: 1 match, 0 no, -1 error
int term_ok(PyObject* got, int eq, PyObject* value) {
  int equal = PyObject_RichCompareBool(got, value, Py_EQ);
  if (equal < 0) return -1;
  return eq ? equal : !equal;
}

// terms are tuples of (key: str, equals: bool, value: str); empty/None
// means unconstrained. 1 match, 0 no match, -1 error.
int matches_selectors(PyObject* obj, PyObject* lterms, PyObject* fterms) {
  if (lterms && lterms != Py_None && PyTuple_GET_SIZE(lterms) > 0) {
    PyObject* labels = get_labels(obj);
    if (!labels) return -1;
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(lterms); ++i) {
      PyObject* term = PyTuple_GET_ITEM(lterms, i);
      PyObject* key = PyTuple_GET_ITEM(term, 0);
      int eq = PyObject_IsTrue(PyTuple_GET_ITEM(term, 1));
      PyObject* value = PyTuple_GET_ITEM(term, 2);
      PyObject* got = PyDict_GetItemWithError(labels, key);  // borrowed
      if (!got) {
        if (PyErr_Occurred()) {
          Py_DECREF(labels);
          return -1;
        }
        got = Py_None;
      }
      int ok = term_ok(got, eq, value);
      if (ok != 1) {
        Py_DECREF(labels);
        return ok;
      }
    }
    Py_DECREF(labels);
  }
  if (fterms && fterms != Py_None && PyTuple_GET_SIZE(fterms) > 0) {
    for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(fterms); ++i) {
      PyObject* term = PyTuple_GET_ITEM(fterms, i);
      const char* path = PyUnicode_AsUTF8(PyTuple_GET_ITEM(term, 0));
      if (!path) return -1;
      int eq = PyObject_IsTrue(PyTuple_GET_ITEM(term, 1));
      PyObject* value = PyTuple_GET_ITEM(term, 2);
      PyObject* got = field_value(obj, path);
      if (!got) return -1;
      int ok = term_ok(got, eq, value);
      Py_DECREF(got);
      if (ok != 1) return ok;
    }
  }
  return 1;
}

// ---------------------------------------------------------------- methods

PyObject* store_create(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  PyObject* obj;
  if (!PyArg_ParseTuple(args, "ssO", &kind, &key, &obj)) return nullptr;
  auto mk = map_key(kind, key);
  if (self->objects->count(mk)) {
    PyErr_Format(PyExc_KeyError, "%s/%s already exists", kind, key);
    return nullptr;
  }
  self->rv += 1;
  Py_INCREF(obj);
  (*self->objects)[mk] = {obj, self->rv, ++self->seq_counter};
  push_event(self, 0, kind, key, obj);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_update(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  PyObject* obj;
  long long expect = -1;
  if (!PyArg_ParseTuple(args, "ssO|L", &kind, &key, &obj, &expect))
    return nullptr;
  auto mk = map_key(kind, key);
  auto it = self->objects->find(mk);
  bool existed = it != self->objects->end();
  if (expect >= 0) {
    long long have = existed ? it->second.rv : -1;
    if (!existed || have != expect) {
      PyErr_Format(PyExc_ValueError, "%s/%s: expected rv %lld, have %lld",
                   kind, key, expect, have);
      return nullptr;
    }
  }
  self->rv += 1;
  Py_INCREF(obj);
  if (existed) {
    Py_DECREF(it->second.obj);
    it->second.obj = obj;
    it->second.rv = self->rv;  // seq unchanged: updates do not reorder
  } else {
    (*self->objects)[mk] = {obj, self->rv, ++self->seq_counter};
  }
  push_event(self, existed ? 1 : 0, kind, key, obj);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_delete(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  if (!PyArg_ParseTuple(args, "ss", &kind, &key)) return nullptr;
  auto mk = map_key(kind, key);
  auto it = self->objects->find(mk);
  if (it == self->objects->end()) {
    PyErr_Format(PyExc_KeyError, "%s/%s not found", kind, key);
    return nullptr;
  }
  PyObject* old = it->second.obj;
  self->objects->erase(it);
  self->rv += 1;
  push_event(self, 2, kind, key, old);
  Py_DECREF(old);
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_get(StoreObject* self, PyObject* args) {
  const char* kind;
  const char* key;
  if (!PyArg_ParseTuple(args, "ss", &kind, &key)) return nullptr;
  auto it = self->objects->find(map_key(kind, key));
  if (it == self->objects->end()) {
    return Py_BuildValue("(OL)", Py_None, 0LL);
  }
  return Py_BuildValue("(OL)", it->second.obj, it->second.rv);
}

// list(kind[, label_terms, field_terms]) — selector terms are evaluated
// HERE (the native list filter): per object, no Python bytecode runs.
PyObject* store_list(StoreObject* self, PyObject* args) {
  const char* kind;
  PyObject* lterms = nullptr;
  PyObject* fterms = nullptr;
  if (!PyArg_ParseTuple(args, "s|OO", &kind, &lterms, &fterms))
    return nullptr;
  std::string prefix(kind);
  prefix.push_back('\x1f');
  struct Hit {
    long long seq;
    const std::string* key;
    const Entry* entry;
    bool operator<(const Hit& o) const { return seq < o.seq; }
  };
  std::vector<Hit> hits;
  for (auto& kv : *self->objects) {
    if (kv.first.compare(0, prefix.size(), prefix) != 0) continue;
    hits.push_back(Hit{kv.second.seq, &kv.first, &kv.second});
  }
  std::sort(hits.begin(), hits.end());  // insertion order, like dict
  PyObject* items = PyList_New(0);
  if (!items) return nullptr;
  for (auto& h : hits) {
    int ok = matches_selectors(h.entry->obj, lterms, fterms);
    if (ok < 0) {
      Py_DECREF(items);
      return nullptr;
    }
    if (!ok) continue;
    PyObject* entry = Py_BuildValue(
        "(sO)", h.key->c_str() + prefix.size(), h.entry->obj);
    if (!entry || PyList_Append(items, entry) < 0) {
      Py_XDECREF(entry);
      Py_DECREF(items);
      return nullptr;
    }
    Py_DECREF(entry);
  }
  PyObject* out = Py_BuildValue("(NL)", items, self->rv);
  return out;
}

// list_page(kind[, label_terms, field_terms, limit, after_seq,
// through_seq]) -> (items [(key, obj, rv)], store_rv, next_seq,
// has_more, through_seq) — one bounded page of the seq-ordered list
// walk (the pagination primitive behind MemStore._list_page_locked).
// Seq order is insertion order and updates never reorder, so a page
// walk resumed at next_seq can neither duplicate nor skip an object
// that existed across the whole walk; through_seq caps the walk at a
// seq bound so objects CREATED mid-walk never splice into later pages
// (through_seq <= 0 captures the current max seq and echoes it back
// for the caller's continue token); limit <= 0 means unbounded (the
// full-list form). Selector-filtered candidates still advance
// next_seq, so a filtered walk always makes progress; has_more reports
// whether any in-bound candidate of the kind remains past this page.
PyObject* store_list_page(StoreObject* self, PyObject* args) {
  const char* kind;
  PyObject* lterms = nullptr;
  PyObject* fterms = nullptr;
  long long limit = 0;
  long long after_seq = 0;
  long long through_seq = 0;
  if (!PyArg_ParseTuple(args, "s|OOLLL", &kind, &lterms, &fterms, &limit,
                        &after_seq, &through_seq))
    return nullptr;
  long long bound = through_seq > 0 ? through_seq : self->seq_counter;
  std::string prefix(kind);
  prefix.push_back('\x1f');
  struct Hit {
    long long seq;
    const std::string* key;
    const Entry* entry;
    bool operator<(const Hit& o) const { return seq < o.seq; }
  };
  std::vector<Hit> hits;
  for (auto& kv : *self->objects) {
    if (kv.first.compare(0, prefix.size(), prefix) != 0) continue;
    if (kv.second.seq <= after_seq || kv.second.seq > bound) continue;
    hits.push_back(Hit{kv.second.seq, &kv.first, &kv.second});
  }
  std::sort(hits.begin(), hits.end());
  PyObject* items = PyList_New(0);
  if (!items) return nullptr;
  long long next_seq = after_seq;
  int has_more = 0;
  for (auto& h : hits) {
    if (limit > 0 && PyList_GET_SIZE(items) >= limit) {
      has_more = 1;
      break;
    }
    int ok = matches_selectors(h.entry->obj, lterms, fterms);
    if (ok < 0) {
      Py_DECREF(items);
      return nullptr;
    }
    if (ok) {
      PyObject* entry = Py_BuildValue(
          "(sOL)", h.key->c_str() + prefix.size(), h.entry->obj,
          h.entry->rv);
      if (!entry || PyList_Append(items, entry) < 0) {
        Py_XDECREF(entry);
        Py_DECREF(items);
        return nullptr;
      }
      Py_DECREF(entry);
    }
    next_seq = h.seq;
  }
  return Py_BuildValue("(NLLOL)", items, self->rv, next_seq,
                       has_more ? Py_True : Py_False, bound);
}

// events_since(kind_or_None, rv) -> (list[(type, kind, key, obj, rv)], cursor)
// raises LookupError when rv predates the ring buffer (compacted).
PyObject* store_events_since(StoreObject* self, PyObject* args) {
  PyObject* kind_obj;
  long long rv;
  if (!PyArg_ParseTuple(args, "OL", &kind_obj, &rv)) return nullptr;
  const char* kind =
      kind_obj == Py_None ? nullptr : PyUnicode_AsUTF8(kind_obj);
  if (kind_obj != Py_None && !kind) return nullptr;
  if (rv < self->compacted_through) {
    PyErr_Format(PyExc_LookupError, "rv %lld compacted (through %lld)", rv,
                 self->compacted_through);
    return nullptr;
  }
  std::vector<Event*> hits;
  long long cursor = collect_since(self, kind, rv, &hits);
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  for (Event* e : hits) {
    PyObject* entry = event_tuple(e);
    if (!entry || PyList_Append(out, entry) < 0) {
      Py_XDECREF(entry);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(entry);
  }
  return Py_BuildValue("(NL)", out, cursor);
}

// events_since_bulk({kind: rv, …}) -> ({kind: (events, cursor) | None},
// drain_rv) — every cursor drained in ONE call (None marks a compacted
// kind; the wrapper turns it into a CompactedError VALUE).
PyObject* store_events_since_bulk(StoreObject* self, PyObject* args) {
  PyObject* cursors;
  if (!PyArg_ParseTuple(args, "O!", &PyDict_Type, &cursors)) return nullptr;
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  PyObject* k;
  PyObject* v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(cursors, &pos, &k, &v)) {
    const char* kind = PyUnicode_AsUTF8(k);
    long long rv = PyLong_AsLongLong(v);
    if (!kind || (rv == -1 && PyErr_Occurred())) {
      Py_DECREF(out);
      return nullptr;
    }
    if (rv < self->compacted_through) {
      if (PyDict_SetItem(out, k, Py_None) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
      continue;
    }
    std::vector<Event*> hits;
    long long cursor = collect_since(self, kind, rv, &hits);
    PyObject* evs = PyList_New(0);
    if (!evs) {
      Py_DECREF(out);
      return nullptr;
    }
    for (Event* e : hits) {
      PyObject* entry = event_tuple(e);
      if (!entry || PyList_Append(evs, entry) < 0) {
        Py_XDECREF(entry);
        Py_DECREF(evs);
        Py_DECREF(out);
        return nullptr;
      }
      Py_DECREF(entry);
    }
    PyObject* pair = Py_BuildValue("(NL)", evs, cursor);
    if (!pair || PyDict_SetItem(out, k, pair) < 0) {
      Py_XDECREF(pair);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(pair);
  }
  return Py_BuildValue("(NL)", out, self->rv);
}

// the body-list builder shared by event_bodies_since(+_bulk)
PyObject* bodies_list(StoreObject* self, std::vector<Event*>& hits, int cid,
                      PyObject* encoder) {
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  for (Event* e : hits) {
    PyObject* body = event_body(self, e, cid, encoder);
    if (!body || PyList_Append(out, body) < 0) {
      Py_XDECREF(body);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(body);
  }
  return out;
}

// event_bodies_since(kind_or_None, rv, codec_id, encoder) ->
// (list[bytes], cursor): the serialize-once fan-out path — cached wire
// bodies, no Python-side event materialization.
PyObject* store_event_bodies_since(StoreObject* self, PyObject* args) {
  PyObject* kind_obj;
  long long rv;
  int cid;
  PyObject* encoder;
  if (!PyArg_ParseTuple(args, "OLiO", &kind_obj, &rv, &cid, &encoder))
    return nullptr;
  if (cid < 0 || cid >= kNumCodecs) {
    PyErr_Format(PyExc_ValueError, "codec id %d out of range", cid);
    return nullptr;
  }
  const char* kind =
      kind_obj == Py_None ? nullptr : PyUnicode_AsUTF8(kind_obj);
  if (kind_obj != Py_None && !kind) return nullptr;
  if (rv < self->compacted_through) {
    PyErr_Format(PyExc_LookupError, "rv %lld compacted (through %lld)", rv,
                 self->compacted_through);
    return nullptr;
  }
  std::vector<Event*> hits;
  long long cursor = collect_since(self, kind, rv, &hits);
  PyObject* out = bodies_list(self, hits, cid, encoder);
  if (!out) return nullptr;
  return Py_BuildValue("(NL)", out, cursor);
}

// event_bodies_since_bulk({kind: rv}, codec_id, encoder) ->
// ({kind: (list[bytes], cursor) | None}, drain_rv)
PyObject* store_event_bodies_since_bulk(StoreObject* self, PyObject* args) {
  PyObject* cursors;
  int cid;
  PyObject* encoder;
  if (!PyArg_ParseTuple(args, "O!iO", &PyDict_Type, &cursors, &cid,
                        &encoder))
    return nullptr;
  if (cid < 0 || cid >= kNumCodecs) {
    PyErr_Format(PyExc_ValueError, "codec id %d out of range", cid);
    return nullptr;
  }
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  PyObject* k;
  PyObject* v;
  Py_ssize_t pos = 0;
  while (PyDict_Next(cursors, &pos, &k, &v)) {
    const char* kind = PyUnicode_AsUTF8(k);
    long long rv = PyLong_AsLongLong(v);
    if (!kind || (rv == -1 && PyErr_Occurred())) {
      Py_DECREF(out);
      return nullptr;
    }
    if (rv < self->compacted_through) {
      if (PyDict_SetItem(out, k, Py_None) < 0) {
        Py_DECREF(out);
        return nullptr;
      }
      continue;
    }
    std::vector<Event*> hits;
    long long cursor = collect_since(self, kind, rv, &hits);
    PyObject* bodies = bodies_list(self, hits, cid, encoder);
    if (!bodies) {
      Py_DECREF(out);
      return nullptr;
    }
    PyObject* pair = Py_BuildValue("(NL)", bodies, cursor);
    if (!pair || PyDict_SetItem(out, k, pair) < 0) {
      Py_XDECREF(pair);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(pair);
  }
  return Py_BuildValue("(NL)", out, self->rv);
}

// body_cache_stats() -> {codec_id: (hits, misses)}
// clear_event_bodies() -> None: drop every cached wire body (the ring
// events themselves stay). Binary bodies embed schema-table ids — a
// scheme registration after bodies were cached shifts those ids, so the
// wrapper flushes the ring when the registry generation moves.
PyObject* store_clear_event_bodies(StoreObject* self, PyObject*) {
  for (auto& e : *self->events) {
    for (int c = 0; c < kNumCodecs; ++c) {
      Py_CLEAR(e.bodies[c]);
    }
  }
  Py_RETURN_NONE;
}

PyObject* store_body_cache_stats(StoreObject* self, PyObject*) {
  PyObject* out = PyDict_New();
  if (!out) return nullptr;
  for (int c = 0; c < kNumCodecs; ++c) {
    PyObject* key = PyLong_FromLong(c);
    PyObject* pair =
        Py_BuildValue("(LL)", self->body_hits[c], self->body_misses[c]);
    if (!key || !pair || PyDict_SetItem(out, key, pair) < 0) {
      Py_XDECREF(key);
      Py_XDECREF(pair);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(key);
    Py_DECREF(pair);
  }
  return out;
}

// ------------------------------------------------- durability surface
// dump() -> [(kind, key, obj, rv), ...] in insertion (seq) order, and
// load_snapshot(items, rv): reset to a recovery snapshot — objects with
// their per-object rvs (CAS survives recovery), store revision rv, event
// ring EMPTY with the compaction horizon at rv. Both mirror the Python
// twin exactly (kubetpu.store.memstore._PyCore) — the WAL recovery path
// replays into either core through this same surface.

PyObject* store_dump(StoreObject* self, PyObject*) {
  struct Hit {
    long long seq;
    const std::string* key;
    const Entry* entry;
    bool operator<(const Hit& o) const { return seq < o.seq; }
  };
  std::vector<Hit> hits;
  hits.reserve(self->objects->size());
  for (auto& kv : *self->objects)
    hits.push_back(Hit{kv.second.seq, &kv.first, &kv.second});
  std::sort(hits.begin(), hits.end());
  PyObject* out = PyList_New(0);
  if (!out) return nullptr;
  for (auto& h : hits) {
    size_t sep = h.key->find('\x1f');
    PyObject* entry = Py_BuildValue(
        "(s#s#OL)", h.key->c_str(), (Py_ssize_t)sep,
        h.key->c_str() + sep + 1, (Py_ssize_t)(h.key->size() - sep - 1),
        h.entry->obj, h.entry->rv);
    if (!entry || PyList_Append(out, entry) < 0) {
      Py_XDECREF(entry);
      Py_DECREF(out);
      return nullptr;
    }
    Py_DECREF(entry);
  }
  return out;
}

PyObject* store_load_snapshot(StoreObject* self, PyObject* args) {
  PyObject* items;
  long long rv;
  if (!PyArg_ParseTuple(args, "OL", &items, &rv)) return nullptr;
  PyObject* seq = PySequence_Fast(items, "load_snapshot wants a sequence");
  if (!seq) return nullptr;
  for (auto& kv : *self->objects) Py_DECREF(kv.second.obj);
  self->objects->clear();
  for (auto& e : *self->events) {
    Py_DECREF(e.obj);
    for (int c = 0; c < kNumCodecs; ++c) Py_XDECREF(e.bodies[c]);
  }
  self->events->clear();
  self->seq_counter = 0;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);  // borrowed
    const char* kind;
    const char* key;
    PyObject* obj;
    long long obj_rv;
    if (!PyArg_ParseTuple(item, "ssOL", &kind, &key, &obj, &obj_rv)) {
      Py_DECREF(seq);
      return nullptr;
    }
    Py_INCREF(obj);
    (*self->objects)[map_key(kind, key)] = {obj, obj_rv,
                                            ++self->seq_counter};
  }
  Py_DECREF(seq);
  self->rv = rv;
  self->compacted_through = rv;
  Py_RETURN_NONE;
}

PyObject* store_resource_version(StoreObject* self, PyObject*) {
  return PyLong_FromLongLong(self->rv);
}

PyObject* store_compacted_through(StoreObject* self, PyObject*) {
  return PyLong_FromLongLong(self->compacted_through);
}

// ----------------------------------------------------------------- type

PyObject* store_new(PyTypeObject* type, PyObject* args, PyObject*) {
  long long history = 8192;
  if (!PyArg_ParseTuple(args, "|L", &history)) return nullptr;
  StoreObject* self = (StoreObject*)type->tp_alloc(type, 0);
  if (!self) return nullptr;
  self->rv = 0;
  self->compacted_through = 0;
  self->seq_counter = 0;
  self->history = (size_t)(history > 0 ? history : 1);
  for (int c = 0; c < kNumCodecs; ++c) {
    self->body_hits[c] = 0;
    self->body_misses[c] = 0;
  }
  self->objects = new std::unordered_map<std::string, Entry>();
  self->events = new std::deque<Event>();
  return (PyObject*)self;
}

void store_dealloc(StoreObject* self) {
  for (auto& kv : *self->objects) Py_DECREF(kv.second.obj);
  for (auto& e : *self->events) {
    Py_DECREF(e.obj);
    for (int c = 0; c < kNumCodecs; ++c) Py_XDECREF(e.bodies[c]);
  }
  delete self->objects;
  delete self->events;
  Py_TYPE(self)->tp_free((PyObject*)self);
}

PyMethodDef store_methods[] = {
    {"create", (PyCFunction)store_create, METH_VARARGS, nullptr},
    {"update", (PyCFunction)store_update, METH_VARARGS, nullptr},
    {"delete", (PyCFunction)store_delete, METH_VARARGS, nullptr},
    {"get", (PyCFunction)store_get, METH_VARARGS, nullptr},
    {"list", (PyCFunction)store_list, METH_VARARGS, nullptr},
    {"list_page", (PyCFunction)store_list_page, METH_VARARGS, nullptr},
    {"events_since", (PyCFunction)store_events_since, METH_VARARGS, nullptr},
    {"events_since_bulk", (PyCFunction)store_events_since_bulk, METH_VARARGS,
     nullptr},
    {"event_bodies_since", (PyCFunction)store_event_bodies_since,
     METH_VARARGS, nullptr},
    {"event_bodies_since_bulk", (PyCFunction)store_event_bodies_since_bulk,
     METH_VARARGS, nullptr},
    {"clear_event_bodies", (PyCFunction)store_clear_event_bodies,
     METH_NOARGS, nullptr},
    {"body_cache_stats", (PyCFunction)store_body_cache_stats, METH_NOARGS,
     nullptr},
    {"dump", (PyCFunction)store_dump, METH_NOARGS, nullptr},
    {"load_snapshot", (PyCFunction)store_load_snapshot, METH_VARARGS,
     nullptr},
    {"resource_version", (PyCFunction)store_resource_version, METH_NOARGS,
     nullptr},
    {"compacted_through", (PyCFunction)store_compacted_through, METH_NOARGS,
     nullptr},
    {nullptr, nullptr, 0, nullptr},
};

PyTypeObject StoreType = {
    PyVarObject_HEAD_INIT(nullptr, 0)
};

PyModuleDef module_def = {
    PyModuleDef_HEAD_INIT, "_kubetpu_store",
    "native versioned object store core", -1, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit__kubetpu_store(void) {
  StoreType.tp_name = "_kubetpu_store.StoreCore";
  StoreType.tp_basicsize = sizeof(StoreObject);
  StoreType.tp_flags = Py_TPFLAGS_DEFAULT;
  StoreType.tp_new = store_new;
  StoreType.tp_dealloc = (destructor)store_dealloc;
  StoreType.tp_methods = store_methods;
  if (PyType_Ready(&StoreType) < 0) return nullptr;
  PyObject* m = PyModule_Create(&module_def);
  if (!m) return nullptr;
  Py_INCREF(&StoreType);
  if (PyModule_AddObject(m, "StoreCore", (PyObject*)&StoreType) < 0) {
    Py_DECREF(&StoreType);
    Py_DECREF(m);
    return nullptr;
  }
  return m;
}
