"""Feature gates — the component-base featuregate analog.

Reference: staging/src/k8s.io/component-base/featuregate/feature_gate.go
(:947 ``Enabled``) with the scheduler-relevant registry entries from
pkg/features/kube_features.go (stages as of the 1.37 snapshot):

- GenericWorkload          alpha, default false (kube_features.go:1419)
- GangScheduling           alpha, default false, requires GenericWorkload
  (:1415; dependency map :2348)
- TopologyAwareWorkloadScheduling  alpha, default false, requires
  GenericWorkload (:1966, :2568)
- OpportunisticBatching    beta, default true (:1674)
- SchedulerQueueingHints   GA-ish default true

Unknown names and unmet dependencies fail LOUDLY at construction — the
reference's --feature-gates parsing errors the binary out the same way.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


@dataclass(frozen=True)
class FeatureSpec:
    default: bool
    stage: str = ALPHA
    requires: tuple[str, ...] = ()


KNOWN_FEATURES: dict[str, FeatureSpec] = {
    "GenericWorkload": FeatureSpec(False, ALPHA),
    "GangScheduling": FeatureSpec(False, ALPHA, requires=("GenericWorkload",)),
    "TopologyAwareWorkloadScheduling": FeatureSpec(
        False, ALPHA, requires=("GenericWorkload",)
    ),
    "OpportunisticBatching": FeatureSpec(True, BETA),
    "SchedulerQueueingHints": FeatureSpec(True, BETA),
    # DRA core is GA (resource.k8s.io/v1, kube_features.go DynamicResource-
    # Allocation); the prioritized-list extension is beta default-on
    "DynamicResourceAllocation": FeatureSpec(True, GA),
    "NodeDeclaredFeatures": FeatureSpec(False, ALPHA),
    "DRAPrioritizedList": FeatureSpec(True, BETA),
}


class FeatureGate:
    """Immutable-after-construction gate set (the reference mutates only at
    flag-parse time too)."""

    def __init__(self, overrides: Mapping[str, bool] | None = None) -> None:
        self._enabled = {name: spec.default for name, spec in KNOWN_FEATURES.items()}
        for name, value in (overrides or {}).items():
            if name not in KNOWN_FEATURES:
                raise ValueError(
                    f"unknown feature gate {name!r} "
                    f"(known: {sorted(KNOWN_FEATURES)})"
                )
            self._enabled[name] = bool(value)
        for name, spec in KNOWN_FEATURES.items():
            if self._enabled[name]:
                for dep in spec.requires:
                    if not self._enabled[dep]:
                        raise ValueError(
                            f"feature {name} requires {dep} to be enabled"
                        )

    def enabled(self, name: str) -> bool:
        try:
            return self._enabled[name]
        except KeyError:
            raise ValueError(f"unknown feature gate {name!r}") from None


def default_feature_gates() -> FeatureGate:
    return FeatureGate()
