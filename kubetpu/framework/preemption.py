"""Preemption evaluator — the host orchestration around the victim-search
kernel.

Analog of ``pkg/scheduler/framework/preemption/preemption.go`` Evaluator
(:65, Preempt :103) + the DefaultPreemption plugin's policy pieces
(defaultpreemption/default_preemption.go): eligibility (:364
PodEligibleToPreemptOthers), candidate discovery, victim selection, node
choice, and the sequencing of several preemptors in one batch.

Differences from the reference, by design:
- the dry run is exhaustive over ALL resolvable-failure nodes in one device
  program (the reference samples ``calculateNumCandidates`` nodes from a
  random offset, default_preemption.go:219 — a CPU-cost concession the
  vmapped kernel doesn't need);
- several preemptors in one batch run back-to-back against a host-updated
  victim state (the reference reaches the same serialization through one
  scheduling cycle per pod), so two preemptors never claim the same victim.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..ops import preemption as OP
from ..state.preemption import VictimTensors, encode_victims
from . import runtime as rt


@dataclass
class PreemptionResult:
    """Mirror of PostFilterResult + Status (preemption.go:87 contract)."""

    status: str                       # "success" | "unschedulable" | "not_eligible"
    node_name: str | None = None      # nominatedNodeName on success
    victim_uids: list[str] = field(default_factory=list)
    victim_pods: list[t.Pod] = field(default_factory=list)
    num_pdb_violations: int = 0
    message: str = ""


class PreemptionEvaluator:
    """Per-batch evaluator. Build once after a failed assignment pass; call
    ``preempt(pod_index)`` for each unschedulable pod, in queue order."""

    def __init__(
        self,
        batch: rt.EncodedBatch,
        params: rt.ScoreParams,
        pdbs: tuple[t.PodDisruptionBudget, ...] = (),
        requested: np.ndarray | None = None,
        pod_count: np.ndarray | None = None,
        node_ports_counts: np.ndarray | None = None,
        spread_counts=None,
        pa_sums=None,
        nominated_active: np.ndarray | None = None,
    ):
        if batch.node_tensors is None:
            raise ValueError("batch was encoded without node_tensors")
        self.batch = batch
        self.params = params
        nt = batch.node_tensors
        kp = int(batch.device.port_conflict.shape[0])
        self.victims: VictimTensors = encode_victims(
            nt, kp, batch.port_vocab, pdbs=pdbs
        )
        # Mutable node usage state (post-assignment view if provided). The
        # victim tensors describe only pods present in the SNAPSHOT; pods the
        # current batch just assumed are part of `requested` but are not
        # preemptable this cycle (their bind is in flight) — same window the
        # reference has between assume and the next informer update.
        self.requested = np.array(
            requested if requested is not None else np.asarray(batch.device.requested)
        )
        self.pod_count = np.array(
            pod_count if pod_count is not None else np.asarray(batch.device.pod_count)
        )
        self.port_counts = np.array(
            node_ports_counts
            if node_ports_counts is not None
            else self.victims.port_counts
        )
        self.pdb_allowed = self.victims.pdb_allowed.copy()
        # Post-batch spread/affinity state (the greedy scan's final carry):
        # the potential mask must see the batch's OWN assignments, or a node
        # the batch just tipped past max_skew could be nominated.
        self.spread_counts = spread_counts
        self.pa_sums = pa_sums
        # Nomination charging state. ``nominated_active`` (G,) marks
        # nominations NOT consumed by this batch's own greedy pass (a nominee
        # the scan just assigned is already in `requested` — charging its
        # nomination again would double-count). The _nom_node/_nom_req/
        # _nom_gate/_nom_pod_idx/_nom_ports host copies are hoisted once and
        # never change; _nom_active IS mutated by each preempt() call (stale
        # nominations drop as their pods re-preempt).
        b = batch.device
        self._pod_requests = np.asarray(jax.device_get(b.requests))
        self._pod_ports = np.asarray(jax.device_get(b.pod_ports))
        if b.nominated_node is not None:
            self._nom_node = np.asarray(jax.device_get(b.nominated_node))
            self._nom_req = np.asarray(jax.device_get(b.nominated_req))
            self._nom_gate = np.asarray(jax.device_get(b.nominated_gate))
            self._nom_pod_idx = (
                np.asarray(jax.device_get(b.nominated_pod_idx))
                if b.nominated_pod_idx is not None
                else np.full(self._nom_node.shape[0], -1, dtype=np.int32)
            )
            self._nom_ports = (
                np.asarray(jax.device_get(b.nominated_ports))
                if b.nominated_ports is not None else None
            )
            self._nom_active = (
                np.asarray(jax.device_get(nominated_active))
                if nominated_active is not None
                else np.ones(self._nom_node.shape[0], dtype=bool)
            )
        else:
            self._nom_node = None

    def _potential_mask(self, i: int) -> jnp.ndarray:
        """(N,) — nodes whose failure is the resolvable kind: all
        victim-independent filters pass, fit/ports fail (preemption.go:180
        NodesForStatusCode(Unschedulable))."""
        b = self.batch.device
        view = _one_pod_view(b, i)
        static, fit, ports_ok, spread_ok, pa_ok, _, _ = rt.filter_components(
            view, self.params,
            requested=jnp.asarray(self.requested),
            pod_count=jnp.asarray(self.pod_count),
            node_ports=jnp.asarray(self.port_counts > 0),
            spread_counts=self.spread_counts,
            pa_sums=self.pa_sums,
            nominated_active=(
                jnp.asarray(self._nom_active)
                if self._nom_node is not None else None
            ),
        )
        ok_independent = static[0]
        for part in (spread_ok, pa_ok):
            if part is not None:
                ok_independent = ok_independent & part[0]
        failed_dep = jnp.zeros_like(ok_independent)
        for part in (fit, ports_ok):
            if part is not None:
                failed_dep = failed_dep | ~part[0]
        return ok_independent & failed_dep

    def preempt(self, i: int, extender_hook=None) -> PreemptionResult:
        """Run preemption for pending pod ``i`` of the batch.

        ``extender_hook`` (optional) is the ProcessPreemption seam
        (preemption.go callExtenders): called with
        ``(pod, {node_name: (victim_pods, n_pdb_violations)})`` over the FULL
        candidate set, it returns the trimmed
        ``{node_name: (victim_uids, n_pdb_violations)}`` map — nodes it drops
        become ineligible, victim lists may shrink — and the best-candidate
        pick then runs host-side over the survivors. Raising ExtenderError
        fails the preemption attempt (non-ignorable extender failure)."""
        pod = self.batch.pods[i]
        # PodEligibleToPreemptOthers (default_preemption.go:364): policy gate.
        # (Terminating-victims-on-nominated-node check needs pod deletion
        # timestamps — not modeled yet; informer-level requeue covers it.)
        if pod.preemption_policy == "Never":
            return PreemptionResult(
                "not_eligible", message="not eligible due to preemptionPolicy=Never."
            )

        b = self.batch.device
        v = self.victims
        # This preempt() replaces any prior nomination of pod i (on success a
        # new node is charged via _apply; on failure the caller removes the
        # nomination) — stop charging the stale one for the rest of the
        # batch, or pod i would be double-charged on two nodes.
        if self._nom_node is not None:
            self._nom_active = self._nom_active & (self._nom_pod_idx != i)
        wants_conf = (
            jnp.einsum(
                "k,kl->l",
                b.pod_ports[i].astype(jnp.int32),
                b.port_conflict.astype(jnp.int32),
            ) > 0
        )
        # Charge equal/higher-priority nominated pods (resources, count AND
        # host ports) to their nominated nodes before the victim search,
        # mirroring the reference's RunFilterPluginsWithNominatedPods inside
        # SelectVictimsOnNode (default_preemption.go:303,:323): a preemptor
        # must not claim room another nominee has already reserved. The
        # encoded gate row is exactly the >=-priority-and-not-self rule;
        # nominations consumed by this batch's own assignments are inactive.
        req, cnt, ports = self.requested, self.pod_count, self.port_counts
        if self._nom_node is not None:
            sel = self._nom_gate[i] & self._nom_active & (self._nom_node >= 0)
            if sel.any():
                req = req.copy()
                cnt = cnt.copy()
                np.add.at(req, self._nom_node[sel], self._nom_req[sel])
                np.add.at(cnt, self._nom_node[sel], 1)
                if self._nom_ports is not None and self._nom_ports[sel].any():
                    ports = ports.copy()
                    np.add.at(
                        ports, self._nom_node[sel],
                        self._nom_ports[sel].astype(ports.dtype),
                    )
        node_idx, victims, ok_mask, n_pdb = OP.dry_run_preemption(
            b.requests[i],
            jnp.asarray(np.int64(pod.priority)),
            wants_conf,
            self._potential_mask(i),
            b.alloc,
            jnp.asarray(req),
            jnp.asarray(cnt),
            b.allowed_pods,
            jnp.asarray(ports),
            jnp.asarray(v.valid),
            jnp.asarray(v.priority),
            jnp.asarray(v.start),
            jnp.asarray(v.requests),
            jnp.asarray(v.victim_ports),
            jnp.asarray(v.pdb),
            jnp.asarray(self.pdb_allowed),
        )
        if extender_hook is not None:
            picked = self._pick_with_extenders(
                pod, victims, ok_mask, n_pdb, extender_hook
            )
            if picked is None:
                return PreemptionResult(
                    "unschedulable",
                    message="preemption: no candidate survived extenders",
                )
            n, vrow = picked
        else:
            n = int(jax.device_get(node_idx))
            if n < 0:
                return PreemptionResult(
                    "unschedulable",
                    message="preemption: 0/%d nodes are available"
                    % self.batch.num_nodes,
                )
            vrow = np.asarray(jax.device_get(victims[n]))
        uids = [
            v.uids[n][k] for k in np.flatnonzero(vrow) if v.uids[n][k] is not None
        ]
        info = self.batch.node_tensors.infos[n]
        pods = [info.pods[u] for u in uids if u in info.pods]
        self._apply(n, vrow, preemptor_index=i)
        return PreemptionResult(
            "success",
            node_name=self.batch.node_names[n],
            victim_uids=uids,
            victim_pods=pods,
        )

    def _pick_with_extenders(
        self, pod: t.Pod, victims, ok_mask, n_pdb, extender_hook
    ) -> tuple[int, np.ndarray] | None:
        """callExtenders + SelectCandidate on the host: present every dry-run
        candidate to the extender chain, drop vetoed nodes, adopt trimmed
        victim lists, then re-run pickOneNodeForPreemption's lexicographic
        refinement over the survivors (preemption.go:311 — stats recomputed
        from the FINAL victim sets, NumPDBViolations taken from the extender
        response as the reference's MetaVictims carry it)."""
        v = self.victims
        okh = np.asarray(jax.device_get(ok_mask))
        if not okh.any():
            return None
        vall = np.asarray(jax.device_get(victims))
        pdbh = np.asarray(jax.device_get(n_pdb))
        infos = self.batch.node_tensors.infos
        cand: dict[str, tuple[list[t.Pod], int]] = {}
        slots: dict[str, tuple[int, list[int]]] = {}
        for n in np.flatnonzero(okh):
            name = self.batch.node_names[n]
            ks = [
                int(k) for k in np.flatnonzero(vall[n])
                if v.uids[n][k] is not None
            ]
            pods = [
                infos[n].pods[v.uids[n][k]]
                for k in ks if v.uids[n][k] in infos[n].pods
            ]
            cand[name] = (pods, int(pdbh[n]))
            slots[name] = (int(n), ks)
        trimmed = extender_hook(pod, cand)
        best: tuple | None = None
        for name in cand:                     # ascending node index order
            if name not in trimmed:
                continue                       # extender vetoed the node
            uids, npdb = trimmed[name]
            n, ks = slots[name]
            keep = set(uids)
            uid_slot = {v.uids[n][k]: k for k in ks}
            final = [uid_slot[u] for u in keep if u in uid_slot]
            if not final:
                # victim list trimmed to nothing (or to unknown uids): the
                # node is no longer a preemption candidate — the reference
                # drops empty-victims nodes after callExtenders; keeping it
                # would nominate onto a still-full node with zero deletions
                continue
            prios = v.priority[n, final]
            max_p = int(prios.max())
            sum_p = int((prios + OP.PRIO_OFFSET).sum())
            highest = [k for k in final if v.priority[n, k] == max_p]
            early = int(v.start[n, highest].min())
            key = (-int(npdb), -max_p, -sum_p, -len(final), early)
            if best is None or key > best[0]:
                vrow = np.zeros(vall.shape[1], dtype=bool)
                vrow[final] = True
                best = (key, n, vrow)
        if best is None:
            return None
        return best[1], best[2]

    def _apply(
        self, n: int, victim_row: np.ndarray, preemptor_index: int | None = None
    ) -> None:
        """Commit one preemption to the host state so the NEXT preemptor in
        this batch sees the victims gone (and the PDB budget spent) — AND the
        just-nominated preemptor's reservation charged (preemptors run in
        priority order, so every later pod in this cycle has priority <= this
        one and the >=-priority charging rule applies)."""
        v = self.victims
        ks = np.flatnonzero(victim_row)
        for k in ks:
            self.requested[n] -= v.requests[n, k]
            self.pod_count[n] -= 1
            self.port_counts[n] -= v.victim_ports[n, k]
            self.pdb_allowed -= v.pdb[n, k].astype(np.int64)
            v.valid[n, k] = False
        if preemptor_index is not None:
            self.requested[n] += self._pod_requests[preemptor_index]
            self.pod_count[n] += 1
            # ports too: a later same-batch preemptor with a conflicting
            # hostPort must not also be nominated here
            self.port_counts[n] += self._pod_ports[preemptor_index].astype(
                self.port_counts.dtype
            )


def extender_chain_hook(extenders):
    """Build the ProcessPreemption hook for ``PreemptionEvaluator.preempt``
    from the scheduler's configured extenders, or None when no extender has
    a preempt verb. Extenders run in order, each further trimming the
    candidate map (preemption.go callExtenders); an uninterested extender is
    skipped, an ignorable failing one too, and a non-ignorable failure
    propagates (the attempt fails)."""
    active = [e for e in extenders if e.supports_preemption()]
    if not active:
        return None

    def hook(
        pod: t.Pod, cand: dict[str, tuple[list[t.Pod], int]]
    ) -> dict[str, tuple[list[str], int]]:
        current = cand
        for e in active:
            if not e.is_interested(pod):
                continue
            try:
                res = e.process_preemption(pod, current)
            except Exception:
                if e.cfg.ignorable:
                    continue
                raise
            # re-materialize pods for the next extender in the chain
            nxt: dict[str, tuple[list[t.Pod], int]] = {}
            for node, (uids, npdb) in res.items():
                pods_prev = {p.uid: p for p in current.get(node, ([], 0))[0]}
                nxt[node] = (
                    [pods_prev[u] for u in uids if u in pods_prev], npdb
                )
            current = nxt
        return {
            node: ([p.uid for p in pods], npdb)
            for node, (pods, npdb) in current.items()
        }

    return hook


def _one_pod_view(b: rt.DeviceBatch, i: int) -> rt.DeviceBatch:
    """P=1 view of pod ``i`` (concrete index) — like assign.greedy._pod_view
    but for a host-chosen pod, so filter_components sees (1, N) shapes."""
    from ..assign.greedy import _pod_view

    return _pod_view(b, i)
