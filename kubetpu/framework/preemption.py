"""Preemption evaluator — the host orchestration around the victim-search
kernel.

Analog of ``pkg/scheduler/framework/preemption/preemption.go`` Evaluator
(:65, Preempt :103) + the DefaultPreemption plugin's policy pieces
(defaultpreemption/default_preemption.go): eligibility (:364
PodEligibleToPreemptOthers), candidate discovery, victim selection, node
choice, and the sequencing of several preemptors in one batch.

Differences from the reference, by design:
- the dry run is exhaustive over ALL resolvable-failure nodes in one device
  program (the reference samples ``calculateNumCandidates`` nodes from a
  random offset, default_preemption.go:219 — a CPU-cost concession the
  vmapped kernel doesn't need);
- several preemptors in one batch run back-to-back against a host-updated
  victim state (the reference reaches the same serialization through one
  scheduling cycle per pod), so two preemptors never claim the same victim.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..ops import preemption as OP
from ..state.preemption import VictimTensors, encode_victims
from . import runtime as rt


@dataclass
class PreemptionResult:
    """Mirror of PostFilterResult + Status (preemption.go:87 contract)."""

    status: str                       # "success" | "unschedulable" | "not_eligible"
    node_name: str | None = None      # nominatedNodeName on success
    victim_uids: list[str] = field(default_factory=list)
    victim_pods: list[t.Pod] = field(default_factory=list)
    num_pdb_violations: int = 0
    message: str = ""


class PreemptionEvaluator:
    """Per-batch evaluator. Build once after a failed assignment pass; call
    ``preempt(pod_index)`` for each unschedulable pod, in queue order."""

    def __init__(
        self,
        batch: rt.EncodedBatch,
        params: rt.ScoreParams,
        pdbs: tuple[t.PodDisruptionBudget, ...] = (),
        requested: np.ndarray | None = None,
        pod_count: np.ndarray | None = None,
        node_ports_counts: np.ndarray | None = None,
        spread_counts=None,
        pa_sums=None,
    ):
        if batch.node_tensors is None:
            raise ValueError("batch was encoded without node_tensors")
        self.batch = batch
        self.params = params
        nt = batch.node_tensors
        kp = int(batch.device.port_conflict.shape[0])
        self.victims: VictimTensors = encode_victims(
            nt, kp, batch.port_vocab, pdbs=pdbs
        )
        # Mutable node usage state (post-assignment view if provided). The
        # victim tensors describe only pods present in the SNAPSHOT; pods the
        # current batch just assumed are part of `requested` but are not
        # preemptable this cycle (their bind is in flight) — same window the
        # reference has between assume and the next informer update.
        self.requested = np.array(
            requested if requested is not None else np.asarray(batch.device.requested)
        )
        self.pod_count = np.array(
            pod_count if pod_count is not None else np.asarray(batch.device.pod_count)
        )
        self.port_counts = np.array(
            node_ports_counts
            if node_ports_counts is not None
            else self.victims.port_counts
        )
        self.pdb_allowed = self.victims.pdb_allowed.copy()
        # Post-batch spread/affinity state (the greedy scan's final carry):
        # the potential mask must see the batch's OWN assignments, or a node
        # the batch just tipped past max_skew could be nominated.
        self.spread_counts = spread_counts
        self.pa_sums = pa_sums

    def _potential_mask(self, i: int) -> jnp.ndarray:
        """(N,) — nodes whose failure is the resolvable kind: all
        victim-independent filters pass, fit/ports fail (preemption.go:180
        NodesForStatusCode(Unschedulable))."""
        b = self.batch.device
        view = _one_pod_view(b, i)
        static, fit, ports_ok, spread_ok, pa_ok, _, _ = rt.filter_components(
            view, self.params,
            requested=jnp.asarray(self.requested),
            pod_count=jnp.asarray(self.pod_count),
            node_ports=jnp.asarray(self.port_counts > 0),
            spread_counts=self.spread_counts,
            pa_sums=self.pa_sums,
        )
        ok_independent = static[0]
        for part in (spread_ok, pa_ok):
            if part is not None:
                ok_independent = ok_independent & part[0]
        failed_dep = jnp.zeros_like(ok_independent)
        for part in (fit, ports_ok):
            if part is not None:
                failed_dep = failed_dep | ~part[0]
        return ok_independent & failed_dep

    def preempt(self, i: int) -> PreemptionResult:
        """Run preemption for pending pod ``i`` of the batch."""
        pod = self.batch.pods[i]
        # PodEligibleToPreemptOthers (default_preemption.go:364): policy gate.
        # (Terminating-victims-on-nominated-node check needs pod deletion
        # timestamps — not modeled yet; informer-level requeue covers it.)
        if pod.preemption_policy == "Never":
            return PreemptionResult(
                "not_eligible", message="not eligible due to preemptionPolicy=Never."
            )

        b = self.batch.device
        v = self.victims
        wants_conf = (
            jnp.einsum(
                "k,kl->l",
                b.pod_ports[i].astype(jnp.int32),
                b.port_conflict.astype(jnp.int32),
            ) > 0
        )
        node_idx, victims = OP.dry_run_preemption(
            b.requests[i],
            jnp.asarray(np.int64(pod.priority)),
            wants_conf,
            self._potential_mask(i),
            b.alloc,
            jnp.asarray(self.requested),
            jnp.asarray(self.pod_count),
            b.allowed_pods,
            jnp.asarray(self.port_counts),
            jnp.asarray(v.valid),
            jnp.asarray(v.priority),
            jnp.asarray(v.start),
            jnp.asarray(v.requests),
            jnp.asarray(v.victim_ports),
            jnp.asarray(v.pdb),
            jnp.asarray(self.pdb_allowed),
        )
        n = int(jax.device_get(node_idx))
        if n < 0:
            return PreemptionResult(
                "unschedulable",
                message="preemption: 0/%d nodes are available" % self.batch.num_nodes,
            )
        vrow = np.asarray(jax.device_get(victims[n]))
        uids = [
            v.uids[n][k] for k in np.flatnonzero(vrow) if v.uids[n][k] is not None
        ]
        info = self.batch.node_tensors.infos[n]
        pods = [info.pods[u] for u in uids if u in info.pods]
        self._apply(n, vrow)
        return PreemptionResult(
            "success",
            node_name=self.batch.node_names[n],
            victim_uids=uids,
            victim_pods=pods,
        )

    def _apply(self, n: int, victim_row: np.ndarray) -> None:
        """Commit one preemption to the host state so the NEXT preemptor in
        this batch sees the victims gone (and the PDB budget spent)."""
        v = self.victims
        ks = np.flatnonzero(victim_row)
        for k in ks:
            self.requested[n] -= v.requests[n, k]
            self.pod_count[n] -= 1
            self.port_counts[n] -= v.victim_ports[n, k]
            self.pdb_allowed -= v.pdb[n, k].astype(np.int64)
            v.valid[n, k] = False


def _one_pod_view(b: rt.DeviceBatch, i: int) -> rt.DeviceBatch:
    """P=1 view of pod ``i`` (concrete index) — like assign.greedy._pod_view
    but for a host-chosen pod, so filter_components sees (1, N) shapes."""
    from ..assign.greedy import _pod_view

    return _pod_view(b, i)
