"""Versioned component-config decoding — KubeSchedulerConfiguration v1.

Reference: staging/src/k8s.io/kube-scheduler/config/v1/types.go:44
(`KubeSchedulerConfiguration`), defaults `pkg/scheduler/apis/config/v1/
defaults.go`, plugin-set merge semantics `pkg/scheduler/apis/config/v1/
default_plugins.go:79 (mergePlugins)`: a profile STARTS from the default
plugin set; its ``disabled`` list removes (name or "*" for all), then its
``enabled`` list appends in order with per-plugin weight. Per-plugin args
arrive through ``pluginConfig`` (types_pluginargs.go).

The decoder is loud (apis/config/validation philosophy): wrong apiVersion/
kind, unknown extension points, malformed args, or an invalid resulting
profile raise ``ConfigError`` with field paths — a malformed file must
never reach the scheduler loop.
"""

from __future__ import annotations

import json
import re
from typing import Any, Mapping

from ..api import types as t
from .. import names as N
from . import config as C

ACCEPTED_API_VERSIONS = (
    "kubescheduler.config.k8s.io/v1",
)
KIND = "KubeSchedulerConfiguration"


class ConfigError(ValueError):
    pass


def _err(msg: str):
    raise ConfigError(msg)


def load_config(path: str):
    """Read + decode a config file (YAML or JSON by content)."""
    with open(path) as f:
        raw = f.read()
    try:
        try:
            import yaml

            obj = yaml.safe_load(raw)
        except ImportError:  # pragma: no cover - yaml is baked into the image
            obj = json.loads(raw)
    except Exception as e:
        # parser errors join the loud-ConfigError contract (the CLI shows
        # "invalid: …", never a traceback)
        raise ConfigError(f"{path}: {type(e).__name__}: {e}") from None
    if not isinstance(obj, Mapping):
        raise ConfigError(f"{path}: not a config object")
    return decode_config(obj)


# extension point name (v1 JSON) -> which Profile set it lands in
_POINT_TO_SET = {
    "preFilter": "filters",
    "filter": "filters",
    "postFilter": None,          # fixed in-tree DefaultPreemption wiring
    "preScore": "scores",
    "score": "scores",
    "reserve": "lifecycle",
    "permit": "lifecycle",
    "preBind": "lifecycle",
    "postBind": "lifecycle",
    # queueSort/bind/preEnqueue have fixed in-tree implementations here;
    # accepted and checked for known names, but not independently pluggable
    "queueSort": None,
    "bind": None,
    "preEnqueue": None,
    "multiPoint": "multi",
}

# which default sets a multiPoint-enabled plugin joins (the reference
# expands multiPoint across every interface the plugin implements)
_MULTIPOINT_SETS = {
    N.NODE_RESOURCES_FIT: ("filters", "scores"),
    N.NODE_RESOURCES_BALANCED: ("scores",),
    N.NODE_AFFINITY: ("filters", "scores"),
    N.TAINT_TOLERATION: ("filters", "scores"),
    N.NODE_NAME: ("filters",),
    N.NODE_PORTS: ("filters",),
    N.NODE_UNSCHEDULABLE: ("filters",),
    N.POD_TOPOLOGY_SPREAD: ("filters", "scores"),
    N.INTER_POD_AFFINITY: ("filters", "scores"),
    N.IMAGE_LOCALITY: ("scores",),
    N.VOLUME_BINDING: ("filters", "lifecycle"),
    N.VOLUME_RESTRICTIONS: ("filters",),
    N.VOLUME_ZONE: ("filters",),
    N.NODE_VOLUME_LIMITS: ("filters",),
    N.DYNAMIC_RESOURCES: ("filters", "scores", "lifecycle"),
}

_DEFAULT_LIFECYCLE = C.Profile().lifecycle

_ACCEPTED_NOOP_ARGS = frozenset({
    N.DEFAULT_PREEMPTION,   # minCandidateNodes* — this engine is exhaustive
    N.NODE_AFFINITY,        # addedAffinity — not modeled
    N.VOLUME_BINDING,       # bindTimeoutSeconds — dispatcher owns timeouts
})


def _merge_set(
    base: C.PluginSet, spec: Mapping | None, path: str
) -> C.PluginSet:
    """mergePlugins semantics for one extension point."""
    if not spec:
        return base
    disabled = spec.get("disabled") or ()
    enabled = spec.get("enabled") or ()
    items = list(base.enabled)
    for d in disabled:
        name = (d or {}).get("name", "")
        if name == "*":
            items = []
        else:
            items = [(n, w) for n, w in items if n != name]
    for e in enabled:
        name = (e or {}).get("name", "")
        if not name:
            raise ConfigError(f"{path}.enabled[]: plugin name required")
        weight = int(e.get("weight", 1) or 1)
        items = [(n, w) for n, w in items if n != name]
        items.append((name, weight))
    return C.PluginSet(enabled=tuple(items))


def _decode_spread_constraint(obj: Mapping, path: str) -> t.TopologySpreadConstraint:
    try:
        return t.TopologySpreadConstraint(
            max_skew=int(obj["maxSkew"]),
            topology_key=obj["topologyKey"],
            when_unsatisfiable=obj.get("whenUnsatisfiable", "DoNotSchedule"),
        )
    except KeyError as e:
        raise ConfigError(f"{path}: missing {e.args[0]}") from None


def _apply_plugin_args(
    kwargs: dict, name: str, args: Mapping, path: str
) -> None:
    """types_pluginargs.go subset: NodeResourcesFitArgs,
    InterPodAffinityArgs, PodTopologySpreadArgs."""
    if name == N.NODE_RESOURCES_FIT:
        ss = args.get("scoringStrategy") or {}
        resources = tuple(
            (r["name"], int(r.get("weight", 1)))
            for r in ss.get("resources") or ()
        )
        shape = tuple(
            (int(p["utilization"]), int(p["score"]))
            for p in ((ss.get("requestedToCapacityRatio") or {}).get("shape")
                      or ())
        )
        kwargs["scoring_strategy"] = C.ScoringStrategy(
            type=ss.get("type", C.LEAST_ALLOCATED),
            resources=resources or C.ScoringStrategy().resources,
            shape=shape,
        )
    elif name == N.INTER_POD_AFFINITY:
        kwargs["hard_pod_affinity_weight"] = int(
            args.get("hardPodAffinityWeight", 1)
        )
    elif name == N.POD_TOPOLOGY_SPREAD:
        if args.get("defaultingType", "System") == "List":
            kwargs["default_spread_constraints"] = tuple(
                _decode_spread_constraint(c, f"{path}.defaultConstraints")
                for c in args.get("defaultConstraints") or ()
            )
    elif name in _ACCEPTED_NOOP_ARGS:
        # args the reference defines but whose knobs don't change this
        # engine's behavior (e.g. preemption candidate subsampling — we are
        # exhaustive); accepted so stock config files load unmodified
        pass
    else:
        raise ConfigError(f"{path}: no args decoder for plugin {name!r}")


def _decode_profile(obj: Mapping, idx: int) -> C.Profile:
    path = f"profiles[{idx}]"
    name = obj.get("schedulerName", "default-scheduler")
    sets = {
        "filters": C.DEFAULT_FILTERS,
        "scores": C.DEFAULT_SCORES,
        "lifecycle": _DEFAULT_LIFECYCLE,
    }
    plugins = obj.get("plugins") or {}
    for point in plugins:
        if point not in _POINT_TO_SET:
            raise ConfigError(f"{path}.plugins.{point}: unknown extension point")
    # multiPoint applies FIRST, specific extension points override it —
    # regardless of key order in the file (default_plugins.go: specific
    # point config always wins over multiPoint expansion)
    ordered = sorted(
        plugins.items(), key=lambda kv: 0 if kv[0] == "multiPoint" else 1
    )
    for point, spec in ordered:
        target = _POINT_TO_SET[point]
        if target == "multi":
            # expand per plugin across the sets it implements
            for e in (spec or {}).get("disabled") or ():
                nm = (e or {}).get("name", "")
                for key in sets:
                    sets[key] = _merge_set(
                        sets[key], {"disabled": [{"name": nm}]},
                        f"{path}.plugins.multiPoint",
                    )
            for e in (spec or {}).get("enabled") or ():
                nm = (e or {}).get("name", "")
                targets = _MULTIPOINT_SETS.get(nm)
                if targets is None:
                    raise ConfigError(
                        f"{path}.plugins.multiPoint: unknown plugin {nm!r}"
                    )
                for key in targets:
                    sets[key] = _merge_set(
                        sets[key], {"enabled": [e]},
                        f"{path}.plugins.multiPoint",
                    )
        elif target is not None:
            sets[target] = _merge_set(
                sets[target], spec, f"{path}.plugins.{point}"
            )
    kwargs: dict = {}
    for i, pc in enumerate(obj.get("pluginConfig") or ()):
        if not isinstance(pc, Mapping) or not pc.get("name"):
            raise ConfigError(
                f"{path}.pluginConfig[{i}]: plugin name required"
            )
        pname = pc["name"]
        _apply_plugin_args(
            kwargs, pname, pc.get("args") or {},
            f"{path}.pluginConfig[{pname!r}]",
        )
    return C.Profile(
        name=name,
        filters=sets["filters"],
        scores=sets["scores"],
        lifecycle=sets["lifecycle"],
        **kwargs,
    )


_DURATION_SEG = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|us|µs|ns)")
_DURATION_UNIT = {
    "ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 0.001,
    "s": 1.0, "m": 60.0, "h": 3600.0,
}


def _duration_s(v, path: str) -> float:
    """metav1.Duration: bare seconds, or Go duration strings INCLUDING the
    compound forms time.Duration.String() emits ("1m0s", "1h30m5s") — a
    config round-tripped through kubectl/configz must load unmodified."""
    if isinstance(v, (int, float)):
        return float(v)
    text = str(v).strip()
    pos = 0
    total = 0.0
    for m in _DURATION_SEG.finditer(text):
        if m.start() != pos:
            raise ConfigError(f"{path}: bad duration {v!r}")
        total += float(m.group(1)) * _DURATION_UNIT[m.group(2)]
        pos = m.end()
    if pos != len(text) or pos == 0:
        raise ConfigError(f"{path}: bad duration {v!r}")
    return total


def _decode_extender(obj: Mapping, idx: int) -> C.ExtenderConfig:
    path = f"extenders[{idx}]"
    url = obj.get("urlPrefix", "")
    if not url:
        raise ConfigError(f"{path}.urlPrefix: required")
    return C.ExtenderConfig(
        url_prefix=url,
        filter_verb=obj.get("filterVerb", ""),
        prioritize_verb=obj.get("prioritizeVerb", ""),
        bind_verb=obj.get("bindVerb", ""),
        preempt_verb=obj.get("preemptVerb", ""),
        weight=int(obj.get("weight", 1)),
        node_cache_capable=bool(obj.get("nodeCacheCapable", False)),
        ignorable=bool(obj.get("ignorable", False)),
        http_timeout_s=_duration_s(
            obj.get("httpTimeout", 30), f"{path}.httpTimeout"
        ),
        managed_resources=tuple(
            (r or {}).get("name") or _err(f"{path}.managedResources[]: name required")
            for r in obj.get("managedResources") or ()
        ),
    )


def decode_config(obj: Mapping) -> C.SchedulerConfiguration:
    """Decode + validate; EVERY failure surfaces as ConfigError (structural
    surprises — wrong types where mappings/ints were expected — are
    rewrapped so the CLI never shows a traceback)."""
    try:
        return _decode_config(obj)
    except ConfigError:
        raise
    except (AttributeError, TypeError, ValueError, KeyError) as e:
        raise ConfigError(
            f"malformed configuration: {type(e).__name__}: {e}"
        ) from None


def _decode_config(obj: Mapping) -> C.SchedulerConfiguration:
    api = obj.get("apiVersion", "")
    if api not in ACCEPTED_API_VERSIONS:
        raise ConfigError(
            f"apiVersion: {api!r} not in {list(ACCEPTED_API_VERSIONS)}"
        )
    kind = obj.get("kind", "")
    if kind != KIND:
        raise ConfigError(f"kind: {kind!r} != {KIND!r}")
    profile_objs = obj.get("profiles")
    profiles = (
        tuple(_decode_profile(p, i) for i, p in enumerate(profile_objs))
        if profile_objs else (C.Profile(),)
    )
    seen = set()
    for p in profiles:
        if p.name in seen:
            raise ConfigError(f"profiles: duplicate schedulerName {p.name!r}")
        seen.add(p.name)
    cfg = C.SchedulerConfiguration(
        profiles=profiles,
        parallelism=int(obj.get("parallelism", 16)),
        percentage_of_nodes_to_score=int(
            obj.get("percentageOfNodesToScore", 0) or 0
        ),
        pod_initial_backoff_seconds=_duration_s(
            obj.get("podInitialBackoffSeconds", 1), "podInitialBackoffSeconds"
        ),
        pod_max_backoff_seconds=_duration_s(
            obj.get("podMaxBackoffSeconds", 10), "podMaxBackoffSeconds"
        ),
        extenders=tuple(
            _decode_extender(e, i)
            for i, e in enumerate(obj.get("extenders") or ())
        ),
    )
    # the same loud validation the scheduler runs at construction — fail at
    # decode time with the file's field paths instead
    from .lifecycle import default_registry
    from .validation import validate_profile

    errs: list[str] = []
    reg = default_registry()
    for p in cfg.profiles:
        errs.extend(validate_profile(p, reg))
    if errs:
        raise ConfigError("; ".join(errs))
    return cfg
