"""Framework runtime — compose filter/score kernels per profile.

The analog of ``pkg/scheduler/framework/runtime/framework.go``: the reference
runs, per pod, PreFilter → parallel per-node Filter → PreScore → parallel
per-node Score → NormalizeScore → weight multiply → sum
(``RunScorePlugins``, framework.go:1351). Here the whole batch is one tensor
program: every enabled plugin contributes a ``(P, N)`` raw score tensor, the
runtime applies each plugin's NormalizeScore rule (masked to feasible nodes —
the reference only ever scores nodes that passed Filter), multiplies by the
profile weight, and sums into the total ``(P, N)`` score used for selection.

The encoded, padded device batch is a pytree (``DeviceBatch``) so it can flow
through jit/scan/shard_map unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..api import types as t
from ..ops import filters as F
from ..ops import scores as S
from ..ops import podaffinity as PA
from ..ops import spread as SP
from ..state import podaffinity as enc_podaffinity
from ..state import spread as enc_spread
from ..state import encoder as enc
from ..state.snapshot import Snapshot
from . import config as C


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class DeviceNodeState:
    """The persistent node-state block of a scheduling problem: everything
    on the node axis that survives from cycle to cycle. In pipeline mode
    these arrays LIVE on device across cycles (``ResidentNodeState``) and
    only dirty rows are re-uploaded; a ``DeviceBatch`` composes this block
    with the per-batch pod block."""

    alloc: jnp.ndarray              # (N, R) int64
    requested: jnp.ndarray          # (N, R) int64 exact
    nonzero_requested: jnp.ndarray  # (N, R) int64 scoring view
    pod_count: jnp.ndarray          # (N,) int32
    allowed_pods: jnp.ndarray       # (N,) int32
    node_valid: jnp.ndarray         # (N,) bool


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class DeviceBatch:
    """Padded device-resident scheduling problem: P pods × N nodes × R
    resources. Padding rows/cols are masked out (``node_valid``/``pod_valid``
    False, ``static_mask`` False on pads) so kernels need no special cases.

    Split into the persistent ``nodes`` block (device-resident across cycles
    in pipeline mode) and the per-batch pod block; the node-field properties
    keep every kernel reading ``b.alloc`` etc. unchanged."""

    # persistent node-state block
    nodes: DeviceNodeState
    # pods
    requests: jnp.ndarray           # (P, R) int64 exact
    nonzero_requests: jnp.ndarray   # (P, R) int64
    pod_valid: jnp.ndarray          # (P,) bool
    # static per-(pod,node) facts from the encoder, SIGNATURE-compressed:
    # (S, N) rows for S distinct pod signatures plus a per-pod (P,) row
    # index; kernels gather rows on device (the host→device transfer and
    # host encode are O(S·N), not O(P·N) — S=1 for replicated workloads).
    # None (an empty pytree leaf) when the profile does not score that
    # plugin / no pod has a static constraint.
    static_mask: jnp.ndarray | None        # (S, N) bool
    node_affinity_raw: jnp.ndarray | None  # (S2, N) int64
    taint_prefer_raw: jnp.ndarray | None   # (S2, N) int64
    image_sum_scores: jnp.ndarray | None   # (S3, N) int64
    image_count: jnp.ndarray | None        # (P,) int32
    # NodePorts dynamic filter (interned triples, see encoder._encode_ports)
    pod_ports: jnp.ndarray          # (P, K) bool
    node_ports: jnp.ndarray         # (N, K) bool
    port_conflict: jnp.ndarray      # (K, K) bool
    # Nominator reservations (queue/nominator.py) — None when no nominations
    nominated_node: jnp.ndarray | None = None  # (G,) int32 node idx (-1 none)
    nominated_req: jnp.ndarray | None = None   # (G, R) int64
    nominated_gate: jnp.ndarray | None = None  # (P, G) bool
    nominated_ports: jnp.ndarray | None = None  # (G, K) bool port triples
    # batch index of each nomination's own pod (-1 if not in this batch):
    # once the scan assigns that pod, its nomination stops being charged
    # (the reference deletes nominations at assume, schedule_one.go:307)
    nominated_pod_idx: jnp.ndarray | None = None  # (G,) int32
    # PodTopologySpread (None when no pod has constraints)
    spread: "SpreadDevice | None" = None
    # InterPodAffinity (None when no pod carries (anti)affinity)
    podaffinity: "PodAffinityDevice | None" = None
    # per-pod signature row indices for the (S, N) arrays above (None when
    # the matching array is None)
    static_sig: jnp.ndarray | None = None  # (P,) int32 row into static_mask
    score_sig: jnp.ndarray | None = None   # (P,) int32 row into na/tt raws
    image_sig: jnp.ndarray | None = None   # (P,) int32 row into image sums
    # extender webhook verdicts for this cycle (sched/extender.py):
    # candidates may only SHRINK; scores arrive pre-weighted/scaled
    extender_mask: jnp.ndarray | None = None   # (P, N) bool
    extender_score: jnp.ndarray | None = None  # (P, N) int64
    # DynamicResources prioritized-list raw score (dynamicresources.go:1059
    # computeScore), signature-compressed like the other static raws
    dra_score_raw: jnp.ndarray | None = None   # (S5, N) int64
    dra_score_sig: jnp.ndarray | None = None   # (P,) int32
    # per-pod priority column (assign.packing admission order + objective;
    # None only for hand-built batches — finalize_batch always sets it)
    pod_priority: jnp.ndarray | None = None     # (P,) int32
    # dense node-topology coordinates (state.topology) — present only when
    # topology scoring is ACTIVE (--topology on, or auto with labeled
    # nodes). None keeps the pytree — and therefore every compiled kernel
    # and its outputs — bit-identical to a build without the feature.
    topology: "TopologyDevice | None" = None

    # node-block accessors (kernels read b.alloc etc. — the split into a
    # persistent node block is invisible to them)
    @property
    def alloc(self) -> jnp.ndarray:
        return self.nodes.alloc

    @property
    def requested(self) -> jnp.ndarray:
        return self.nodes.requested

    @property
    def nonzero_requested(self) -> jnp.ndarray:
        return self.nodes.nonzero_requested

    @property
    def pod_count(self) -> jnp.ndarray:
        return self.nodes.pod_count

    @property
    def allowed_pods(self) -> jnp.ndarray:
        return self.nodes.allowed_pods

    @property
    def node_valid(self) -> jnp.ndarray:
        return self.nodes.node_valid


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class PodAffinityDevice:
    """Device-side InterPodAffinity rows (see state.podaffinity)."""

    node_domain: jnp.ndarray  # (R, N) int32
    has_key: jnp.ndarray      # (R, N) bool
    base_sums: jnp.ndarray    # (R, D) int64 — scan state init
    update: jnp.ndarray       # (P, R) int64
    fa_rows: jnp.ndarray      # (P, CA) int32
    fa_self: jnp.ndarray      # (P,) bool
    ra_rows: jnp.ndarray      # (P, CR) int32
    ea_rows: jnp.ndarray      # (P, CE) int32
    score_rows: jnp.ndarray   # (P, CS) int32
    score_vals: jnp.ndarray   # (P, CS) int64
    has_filter_work: bool = field(metadata=dict(static=True), default=False)
    has_score_work: bool = field(metadata=dict(static=True), default=False)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class SpreadDevice:
    """Device-side spread tensors (see state.spread.SpreadTensors)."""

    eligible: jnp.ndarray        # (S, N) bool
    node_domain: jnp.ndarray     # (S, N) int32
    node_count: jnp.ndarray      # (S, N) int32 — base counts (scan state init)
    has_key: jnp.ndarray         # (S, N) bool
    domain_present: jnp.ndarray  # (S, D) bool
    num_domains: jnp.ndarray     # (S,) int32
    is_hostname: jnp.ndarray     # (S,) bool
    sig_idx: jnp.ndarray         # (P, C) int32
    action: jnp.ndarray          # (P, C) int8
    max_skew: jnp.ndarray        # (P, C) int32
    min_domains: jnp.ndarray     # (P, C) int32
    self_match: jnp.ndarray      # (P, C) int32
    pod_match_sig: jnp.ndarray   # (P, S) bool
    ignored: jnp.ndarray         # (P, N) bool
    has_hard: bool = field(metadata=dict(static=True), default=False)
    has_soft: bool = field(metadata=dict(static=True), default=False)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class TopologyDevice:
    """Device-side dense topology coordinates (see state.topology).

    Domain counts are STATIC so alignment/fragmentation segment-sums get
    a fixed ``num_segments`` — a new slice label retraces, exactly like a
    spread constraint growing a domain axis."""

    slice_id: jnp.ndarray  # (N,) int32; value == num_slices ⇒ unlabeled
    rack_id: jnp.ndarray   # (N,) int32; value == num_racks ⇒ unlabeled
    num_slices: int = field(metadata=dict(static=True), default=0)
    num_racks: int = field(metadata=dict(static=True), default=0)


@dataclass
class EncodedBatch:
    """Host-side handle pairing the device pytree with name lookups."""

    device: DeviceBatch
    node_names: list[str]
    pods: list[t.Pod]
    resource_names: list[str]
    num_nodes: int                  # real (unpadded) N
    num_pods: int                   # real (unpadded) P
    # host-side references preemption/extender paths reuse (not device data)
    node_tensors: "enc.NodeTensors | None" = None
    port_vocab: object | None = None
    # actual host→device bytes this encode shipped (pod block + node delta;
    # equals the full pytree bytes when no resident node state was used)
    upload_bytes: int = 0
    # bytes of the device-resident node block backing this batch (0 when the
    # node block was a one-shot upload, i.e. no residency)
    resident_bytes: int = 0


class StaleStaticEncode(Exception):
    """A pre-encoded StaticBatch can no longer be finalized against the
    current cluster state (e.g. an assumed pod introduced a host-port triple
    outside the batch's interned vocabulary, or the nomination set changed).
    Callers fall back to a full re-encode."""


def _node_block_nbytes(nodes: DeviceNodeState) -> int:
    return sum(
        int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(nodes)
    )


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _scatter_node_rows(
    alloc, requested, nonzero, pod_count, allowed, valid,
    idx, u_alloc, u_req, u_nz, u_pc, u_al, u_vd,
):
    """Write the dirty node rows into the device-resident block. The six
    state buffers are DONATED: each output aliases its input (same
    shape/dtype), so the update is in-place on device and the old buffers
    are invalidated — the ResidentNodeState owner is the only holder by
    contract. ``idx`` is padded to a compile bucket with out-of-range
    indices; mode="drop" discards those writes. ``valid`` rides along so an
    incremental reshard (node add/delete within the same padded capacity)
    can flip validity rows without a full re-upload."""
    return (
        alloc.at[idx].set(u_alloc, mode="drop"),
        requested.at[idx].set(u_req, mode="drop"),
        nonzero.at[idx].set(u_nz, mode="drop"),
        pod_count.at[idx].set(u_pc, mode="drop"),
        allowed.at[idx].set(u_al, mode="drop"),
        valid.at[idx].set(u_vd, mode="drop"),
    )


def _make_routed_scatter(mesh, axis: str):
    """Build the per-shard routed twin of ``_scatter_node_rows`` for a
    sharded resident block: every input is sharded on its leading (shard)
    axis, so each device receives ONLY its own update block — the
    host→device routing happened at ``device_put`` — and the scatter body
    runs shard-local (indices are shard-local; no collectives). Donation
    aliases each state buffer in place, like the single-device scatter."""
    from jax.experimental.shard_map import shard_map

    spec = jax.sharding.PartitionSpec(axis)

    @partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
    @partial(
        shard_map, mesh=mesh,
        in_specs=(spec,) * 13, out_specs=(spec,) * 6,
    )
    def scatter(alloc, requested, nonzero, pod_count, allowed, valid,
                idx, u_alloc, u_req, u_nz, u_pc, u_al, u_vd):
        i = idx[0]
        return (
            alloc.at[i].set(u_alloc[0], mode="drop"),
            requested.at[i].set(u_req[0], mode="drop"),
            nonzero.at[i].set(u_nz[0], mode="drop"),
            pod_count.at[i].set(u_pc[0], mode="drop"),
            allowed.at[i].set(u_al[0], mode="drop"),
            valid.at[i].set(u_vd[0], mode="drop"),
        )

    return scatter


class ResidentNodeState:
    """Owner of the persistent device-resident node block (pipeline mode).

    ``refresh(nt, num_nodes)`` brings the device block up to date with the
    host ``NodeTensors``: a full upload when the block doesn't exist yet or
    is not comparable (resource axis / padded capacity change), a dirty-row
    scatter consuming ``nt.pending_device_rows`` in steady state — host→
    device traffic O(Δ rows · R), not O(N · R) — and, when the encode was
    REBUILT but kept the same shape (node add/delete within a padding
    bucket), an *incremental reshard*: the old and new NodeTensors are
    diffed row-wise and only the rows that actually changed (plus the
    validity boundary) are scattered. The scatter donates the old buffers
    (see ``_scatter_node_rows``), so after a refresh any previously
    returned DeviceNodeState is dead; callers must not hold device batches
    across a refresh (the scheduler refreshes only between completed
    cycles).

    ``mesh``: a 1-D node-axis ``jax.sharding.Mesh`` — the block then lives
    SHARDED across the mesh (each device owns ``NC / n_shards`` contiguous
    node rows), full uploads place each shard's rows on its owner only, and
    delta uploads are ROUTED: dirty rows are grouped by owning shard on the
    host, shipped as a shard-axis-sharded update block (each device
    receives only its own rows), and scattered shard-locally via shard_map
    — no collectives on the upload path."""

    def __init__(self, mesh=None, axis=None) -> None:
        self.device: DeviceNodeState | None = None
        self._nt_token: object | None = None
        self._num_nodes = -1
        self.last_upload_bytes = 0
        self.mesh = mesh
        self.axis = axis if axis is not None else "nodes"
        self._n_shards = 1
        self._shardings = None
        self._routed_scatter = None
        self._block_sharded = False
        if mesh is not None:
            from ..parallel.mesh import (
                _axis_size,
                node_axes_of,
                node_state_shardings,
            )

            if axis is None:
                self.axis, _ = node_axes_of(mesh)
            self._n_shards = _axis_size(mesh, self.axis)
            self._shardings = node_state_shardings(mesh, self.axis)
            self._routed_scatter = _make_routed_scatter(mesh, self.axis)
        # per-shard view of the LAST refresh (length n_shards): bytes each
        # shard received and how many real dirty rows were routed to it —
        # the feed for the shard-labeled transfer metrics / trace instants
        self.last_upload_bytes_per_shard: list[int] = [0] * self._n_shards
        self.last_rows_per_shard: list[int] = [0] * self._n_shards

    @property
    def nbytes(self) -> int:
        return _node_block_nbytes(self.device) if self.device is not None else 0

    @property
    def nbytes_per_shard(self) -> list[int]:
        """Per-shard resident bytes, honest about placement: an even split
        when the block really is sharded, everything on shard 0 when the
        single-device fallback placed it there."""
        total = self.nbytes
        if total and self._block_sharded and self._n_shards > 1:
            return [total // self._n_shards] * self._n_shards
        return [total] + [0] * (self._n_shards - 1)

    def _full_upload(self, nt: "enc.NodeTensors", num_nodes: int) -> DeviceNodeState:
        NC = nt.alloc.shape[0]
        node_valid = np.zeros(NC, dtype=bool)
        node_valid[:num_nodes] = True
        dev = DeviceNodeState(
            alloc=nt.alloc,
            requested=nt.requested,
            nonzero_requested=nt.nonzero_requested,
            pod_count=nt.pod_count,
            allowed_pods=nt.allowed_pods,
            node_valid=node_valid,
        )
        sharded = self._shardings is not None and NC % self._n_shards == 0
        if sharded:
            dev = jax.device_put(dev, self._shardings)
        else:
            dev = jax.device_put(dev)
        self._block_sharded = sharded
        self.device = dev
        self._nt_token = nt
        self._num_nodes = num_nodes
        nt.pending_device_rows = set()   # start delta accumulation
        self.last_upload_bytes = _node_block_nbytes(dev)
        if sharded:
            per = self.last_upload_bytes // self._n_shards
            self.last_upload_bytes_per_shard = [per] * self._n_shards
            self.last_rows_per_shard = [NC // self._n_shards] * self._n_shards
        else:
            # single-device fallback (shard count does not divide NC):
            # everything landed on one device — attribute it there, like
            # _scatter_single, so per-chip metrics never claim an even
            # split that didn't happen
            self.last_upload_bytes_per_shard = (
                [self.last_upload_bytes] + [0] * (self._n_shards - 1)
            )
            self.last_rows_per_shard = [NC] + [0] * (self._n_shards - 1)
        return dev

    def _reshard_rows(
        self, nt: "enc.NodeTensors", num_nodes: int
    ) -> "list[int] | None":
        """Dirty rows for an incremental reshard: the encode was rebuilt
        (new NodeTensors object — node add/delete/reorder) but padded
        capacity and resource axis still match the resident block. Diff the
        old tensors (what the device holds, modulo their un-flushed pending
        rows) against the new ones and return the union of value-changed
        rows, the old pending set, and the validity boundary. None = not
        comparable (full upload)."""
        old = self._nt_token
        if old is None or getattr(old, "alloc", None) is None:
            return None
        diff = nt.diff_rows(old)
        if diff is None:
            return None
        rows = set(diff)
        if old.pending_device_rows:
            # rows dirty on the OLD tensors but never shipped: the device
            # copy differs from old AND possibly from new — re-send them
            rows.update(old.pending_device_rows)
        lo, hi = sorted((self._num_nodes, num_nodes))
        rows.update(range(lo, hi))   # validity flips on the boundary
        return sorted(rows)

    def refresh(self, nt: "enc.NodeTensors", num_nodes: int) -> DeviceNodeState:
        pending = nt.pending_device_rows
        if self.device is None or self._nt_token is None:
            return self._full_upload(nt, num_nodes)
        if self._nt_token is not nt:
            # the encode was REBUILT (node add/delete/reorder): incremental
            # reshard when the block is still comparable, else full upload
            rows = self._reshard_rows(nt, num_nodes)
            if rows is None:
                return self._full_upload(nt, num_nodes)
        elif pending is None:
            # same tensors object but no delta bookkeeping: be safe
            return self._full_upload(nt, num_nodes)
        else:
            rows_set = set(pending)
            if self._num_nodes != num_nodes:
                # the append-incremental encode grew the node count IN
                # PLACE (same tensors object): the boundary rows flip
                # validity and ride the same delta scatter as any dirty
                # row — an add-wave must not force a full re-upload
                lo, hi = sorted((self._num_nodes, num_nodes))
                rows_set.update(range(lo, hi))
            if not rows_set:
                self.last_upload_bytes = 0
                self.last_upload_bytes_per_shard = [0] * self._n_shards
                self.last_rows_per_shard = [0] * self._n_shards
                return self.device
            rows = sorted(rows_set)
        nt.pending_device_rows = set()
        self._nt_token = nt
        if not rows:
            # reshard diff found nothing to ship (values identical)
            self.last_upload_bytes = 0
            self.last_upload_bytes_per_shard = [0] * self._n_shards
            self.last_rows_per_shard = [0] * self._n_shards
            self._num_nodes = num_nodes
            return self.device
        if 2 * len(rows) >= num_nodes:
            # dense update: a full contiguous upload beats a scatter
            return self._full_upload(nt, num_nodes)
        NC = nt.alloc.shape[0]
        valid_of = np.asarray(rows, dtype=np.int64) < num_nodes
        self._num_nodes = num_nodes
        if self._shardings is not None and NC % self._n_shards == 0:
            dev = self._scatter_routed(nt, rows, valid_of, NC)
            if dev is None:
                # routing would ship >= the full block (dirty rows
                # clustered in few shards → every shard bucket-padded to
                # the max): a contiguous full upload is strictly smaller
                return self._full_upload(nt, num_nodes)
        else:
            dev = self._scatter_single(nt, rows, valid_of, NC)
        self.device = dev
        return dev

    def _scatter_single(
        self, nt: "enc.NodeTensors", rows: list, valid_of: np.ndarray, NC: int
    ) -> DeviceNodeState:
        pad = enc.round_up(len(rows))
        idx = np.full(pad, NC, dtype=np.int32)   # pad rows → dropped writes
        idx[: len(rows)] = rows

        def deltas(a: np.ndarray) -> np.ndarray:
            u = np.zeros((pad,) + a.shape[1:], dtype=a.dtype)
            u[: len(rows)] = a[rows]
            return u

        u_alloc = deltas(nt.alloc)
        u_req = deltas(nt.requested)
        u_nz = deltas(nt.nonzero_requested)
        u_pc = deltas(nt.pod_count)
        u_al = deltas(nt.allowed_pods)
        u_vd = np.zeros(pad, dtype=bool)
        u_vd[: len(rows)] = valid_of
        dev = self.device
        alloc, req, nz, pc, al, vd = _scatter_node_rows(
            dev.alloc, dev.requested, dev.nonzero_requested,
            dev.pod_count, dev.allowed_pods, dev.node_valid,
            jnp.asarray(idx), jnp.asarray(u_alloc), jnp.asarray(u_req),
            jnp.asarray(u_nz), jnp.asarray(u_pc), jnp.asarray(u_al),
            jnp.asarray(u_vd),
        )
        self.last_upload_bytes = int(
            idx.nbytes + u_alloc.nbytes + u_req.nbytes + u_nz.nbytes
            + u_pc.nbytes + u_al.nbytes + u_vd.nbytes
        )
        # keep the per-shard arrays n_shards long even on the (shouldn't-
        # happen: encode pads NC to a shard multiple) unsharded fallback,
        # so shard-labeled metrics never disagree with mesh_shape
        self.last_upload_bytes_per_shard = (
            [self.last_upload_bytes] + [0] * (self._n_shards - 1)
        )
        self.last_rows_per_shard = [len(rows)] + [0] * (self._n_shards - 1)
        return DeviceNodeState(
            alloc=alloc, requested=req, nonzero_requested=nz,
            pod_count=pc, allowed_pods=al, node_valid=vd,
        )

    def _scatter_routed(
        self, nt: "enc.NodeTensors", rows: list, valid_of: np.ndarray, NC: int
    ) -> "DeviceNodeState | None":
        """Per-shard routed delta upload (see class docstring): group dirty
        rows by owning shard, pad each shard's group to a common bucket,
        ship the blocks shard-axis-sharded (each device receives only its
        rows) and scatter shard-locally with LOCAL indices. Returns None
        when the bucket-padded slot count reaches the full row count (the
        caller full-uploads instead — routing would not ship less)."""
        n_sh = self._n_shards
        rows_per_shard = NC // n_sh
        rows_arr = np.asarray(rows, dtype=np.int64)   # sorted ascending
        shard_of = rows_arr // rows_per_shard
        counts = np.bincount(shard_of, minlength=n_sh)
        bucket = enc.round_up(int(counts.max()), minimum=1)
        if n_sh * bucket >= NC:
            return None
        # rows are sorted, so each shard's rows are contiguous: the flat
        # slot of row j inside the (n_sh, bucket) block is
        # shard * bucket + (j - first index of its shard)
        starts = np.zeros(n_sh + 1, dtype=np.int64)
        starts[1:] = np.cumsum(counts)
        flat = shard_of * bucket + (np.arange(len(rows_arr)) - starts[shard_of])
        # local out-of-range sentinel → shard-local mode="drop"
        idx = np.full(n_sh * bucket, rows_per_shard, dtype=np.int32)
        idx[flat] = rows_arr - shard_of * rows_per_shard

        def blocks(a: np.ndarray) -> np.ndarray:
            u = np.zeros((n_sh * bucket,) + a.shape[1:], dtype=a.dtype)
            u[flat] = a[rows_arr]
            return u.reshape((n_sh, bucket) + a.shape[1:])

        u_alloc = blocks(nt.alloc)
        u_req = blocks(nt.requested)
        u_nz = blocks(nt.nonzero_requested)
        u_pc = blocks(nt.pod_count)
        u_al = blocks(nt.allowed_pods)
        u_vd = np.zeros(n_sh * bucket, dtype=bool)
        u_vd[flat] = valid_of
        u_vd = u_vd.reshape(n_sh, bucket)
        idx = idx.reshape(n_sh, bucket)
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_sh = NamedSharding(self.mesh, P(self.axis))
        put = partial(jax.device_put, device=row_sh)
        dev = self.device
        alloc, req, nz, pc, al, vd = self._routed_scatter(
            dev.alloc, dev.requested, dev.nonzero_requested,
            dev.pod_count, dev.allowed_pods, dev.node_valid,
            put(idx), put(u_alloc), put(u_req), put(u_nz), put(u_pc),
            put(u_al), put(u_vd),
        )
        per_row_bytes = (
            u_alloc.nbytes + u_req.nbytes + u_nz.nbytes + u_pc.nbytes
            + u_al.nbytes + u_vd.nbytes + idx.nbytes
        ) // (n_sh * bucket)
        self.last_upload_bytes = per_row_bytes * n_sh * bucket
        self.last_upload_bytes_per_shard = [per_row_bytes * bucket] * n_sh
        self.last_rows_per_shard = counts.tolist()
        return DeviceNodeState(
            alloc=alloc, requested=req, nonzero_requested=nz,
            pod_count=pc, allowed_pods=al, node_valid=vd,
        )


class PackingSolverState:
    """Device-resident dual-variable block for the packing engine — the
    warm-start twin of :class:`ResidentNodeState`.

    Holds one ``(NC,)`` float32 dual-price vector λ per padded node
    capacity (the scheduler's warmup ladder touches several bucket sizes;
    each keeps its own prices). ``duals(n)`` hands the current vector to
    the solver — zeros on first sight of a capacity (a cold start,
    counted in ``resets``) — and the solver DONATES it
    (``packing_assign_device`` donate_argnums), so the caller must
    ``store(n, …)`` the returned vector back; this class is the only
    holder by contract, mirroring the resident node block's donation
    discipline. ``carries`` counts warm handoffs — the warm-start
    evidence rides ``solver_iters_per_cycle``, these counters attribute
    it.

    ``mesh``: when the scheduler runs node-axis sharded, λ is placed
    sharded along the same node axis so the solver's per-node penalty
    row stays shard-local (``bind_mesh`` — the engine is constructed
    before the scheduler resolves its mesh, so binding is late)."""

    def __init__(self, mesh=None, axis=None) -> None:
        self._lam: dict[int, jnp.ndarray] = {}
        self.resets = 0
        self.carries = 0
        self.mesh = None
        self._sharding = None
        self.bind_mesh(mesh, axis)

    def bind_mesh(self, mesh, axis=None) -> None:
        if mesh is self.mesh:
            return
        self.mesh = mesh
        self._sharding = None
        if mesh is not None:
            from ..parallel.mesh import node_axes_of

            if axis is None:
                axis, _ = node_axes_of(mesh)
            self._sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(axis)
            )
        # duals placed under the old layout are stale; drop them
        self._lam.clear()

    def duals(self, n: int) -> jnp.ndarray:
        lam = self._lam.pop(n, None)
        if lam is None:
            self.resets += 1
            lam = jnp.zeros(n, dtype=jnp.float32)
            if self._sharding is not None:
                lam = jax.device_put(lam, self._sharding)
        else:
            self.carries += 1
        return lam

    def store(self, n: int, lam: jnp.ndarray) -> None:
        self._lam[n] = lam

    def reset(self) -> None:
        """Drop every price vector (cold-start escape hatch)."""
        self._lam.clear()

    @property
    def nbytes(self) -> int:
        return sum(int(v.nbytes) for v in self._lam.values())


def _resource_weights(
    resource_names: Sequence[str], spec: Sequence[tuple[str, int]]
) -> np.ndarray:
    w = np.zeros(len(resource_names), dtype=np.int64)
    idx = {r: i for i, r in enumerate(resource_names)}
    for name, weight in spec:
        j = idx.get(name)
        if j is not None:
            w[j] = weight
    return w


def _is_scalar(resource_names: Sequence[str]) -> np.ndarray:
    return np.array(
        [r not in enc.BASE_RESOURCES for r in resource_names], dtype=bool
    )


def _image_tensors(
    nt: enc.NodeTensors, pods: Sequence[t.Pod], pad_pods: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """ImageLocality host encoding (imagelocality/image_locality.go:60
    sumImageScores + :118 scaledImageScore): per (pod, node) the sum over the
    pod's container images present on the node of
    ``size * numNodesWithImage // totalNumNodes``. Signature-compressed: one
    (N,) row per distinct image set, pods carry the row index."""
    N = nt.num_nodes
    NC = nt.alloc.shape[0]
    P = len(pods)
    PP = max(pad_pods or P, P)
    total = max(N, 1)
    if not any(p.images for p in pods):
        # no image anywhere → the raw score is identically zero; skip the
        # three device leaves entirely (feasible_and_scores None-guards)
        return None, None, None
    counts = np.zeros(PP, dtype=np.int32)
    sig = np.zeros(PP, dtype=np.int32)
    node_images: list[dict[str, t.ImageState]] = [
        dict(info.node.images) for info in nt.infos
    ]
    ids: dict[tuple[str, ...], int] = {(): 0}
    rows: list[np.ndarray] = [np.zeros(N, dtype=np.int64)]
    for i, p in enumerate(pods):
        counts[i] = len(p.images)
        key = p.images
        sid = ids.get(key)
        if sid is None:
            v = np.zeros(N, dtype=np.int64)
            for n_i, imgs in enumerate(node_images):
                s = 0
                for name in key:
                    st = imgs.get(name)
                    if st is not None:
                        s += st.size_bytes * st.num_nodes // total
                v[n_i] = s
            sid = len(rows)
            ids[key] = sid
            rows.append(v)
        sig[i] = sid
    sums = np.zeros((len(rows), NC), dtype=np.int64)
    for s, v in enumerate(rows):
        sums[s, :N] = v
    return sums, sig, counts


@dataclass
class StaticBatch:
    """The assume-independent half of an encoded batch (pipeline stage 1).

    Everything here is a function of the node set's static facts (labels,
    taints, images, ports vocabulary) and the pending pods — NOT of which
    pods are assigned where. The pipelined scheduler builds this while the
    previous cycle's device program runs, then ``finalize_batch`` patches in
    the assume-dependent slice (node resource rows via delta upload, spread
    counts, affinity sums, nominations, in-use ports) after that cycle's
    assumes land."""

    pods: list
    profile: "C.Profile | None"
    nt: "enc.NodeTensors"
    pb: "enc.PodBatch"
    resource_names: list[str]
    num_nodes: int
    num_pods: int
    pad_nodes: int
    pad_pods: int
    folded: frozenset
    want_na: bool
    want_tt: bool
    want_img: bool
    want_spread: bool
    want_interpod: bool
    dra_score_raw: "np.ndarray | None"
    dra_score_sig: "np.ndarray | None"
    img_sums: "np.ndarray | None"
    img_sig: "np.ndarray | None"
    img_counts: "np.ndarray | None"
    node_valid: np.ndarray
    pod_valid: np.ndarray
    nominated_key: tuple
    # True when the static encode itself already depends on assignment state
    # (folded singleton scalars, volumes, DRA) — a pre-encoded StaticBatch
    # with this set must not be reused across an assume boundary
    assume_coupled: bool = False
    # set by refresh_static when node rows moved since stage 1: the in-use
    # port rows baked into ``pb`` are then stale and finalize re-derives
    # them from the current NodeInfos (the one-shot encode path keeps
    # pb.node_ports as-is — nothing ran in between)
    ports_stale: bool = False
    # the EncodeCache (state.encode_cache) stage 1 encoded against; stage 2
    # reuses its persistent affinity/spread term caches
    cache: object | None = None
    # topology mode ("off"|"auto"|"on") — finalize_batch attaches the dense
    # coordinate block when the mode is active AND any node carries a
    # topology label; coordinates are read fresh from the NodeTensors memo
    # at stage 2 so a label change between stages is never baked stale
    topology: str = "off"


def encode_batch(
    snapshot: Snapshot,
    pods: Sequence[t.Pod],
    profile: C.Profile | None = None,
    pad: bool = True,
    resource_names: Sequence[str] | None = None,
    nominated: Sequence = (),
    prev_nt: "enc.NodeTensors | None" = None,
    resident: "ResidentNodeState | None" = None,
    cache=None,
    track_changes: bool = True,
    mesh=None,
    topology: str = "off",
) -> EncodedBatch:
    """Snapshot + pending pods → padded device batch.

    Padding buckets P and N to powers of two so churning clusters reuse the
    XLA compile cache (SURVEY §7 'dynamic shapes'): padded nodes have zero
    allocatable and ``allowed_pods``=0 (infeasible for every pod), padded pods
    have an all-False static mask.

    ``prev_nt``: the previous cycle's ``EncodedBatch.node_tensors`` — lets
    ``encode_snapshot`` refresh only the node rows whose generation moved
    (the loop's per-cycle host encode becomes O(Δ + batch)).

    ``resident``: a ResidentNodeState — the node block is delta-uploaded
    into the device-resident buffers instead of shipped whole.

    ``cache``: an ``encode_cache.EncodeCache`` — static pod rows become
    gathers over template-keyed rows shared across pods and cycles (the
    host-side O(Δ) twin of ``prev_nt``/``resident``).

    ``mesh``: a node-axis ``jax.sharding.Mesh`` — the device pytree is
    placed with the parallel.mesh sharding rules (node-axis leaves sharded,
    pod leaves replicated) in the same single ``device_put``, so the
    assignment engines run SPMD with XLA-inserted collectives.
    """
    if mesh is None and resident is not None:
        mesh = resident.mesh
    pad_multiple = 1
    if mesh is not None:
        from ..parallel.mesh import node_pad_multiple

        pad_multiple = node_pad_multiple(mesh)
    sb = encode_batch_static(
        snapshot, pods, profile, pad=pad, resource_names=resource_names,
        nominated=nominated, prev_nt=prev_nt, cache=cache,
        track_changes=track_changes, pad_multiple=pad_multiple,
        topology=topology,
    )
    return finalize_batch(
        sb, snapshot, nominated=nominated, resident=resident, mesh=mesh
    )


def encode_batch_static(
    snapshot: Snapshot,
    pods: Sequence[t.Pod],
    profile: C.Profile | None = None,
    pad: bool = True,
    resource_names: Sequence[str] | None = None,
    nominated: Sequence = (),
    prev_nt: "enc.NodeTensors | None" = None,
    cache=None,
    track_changes: bool = True,
    pad_multiple: int = 1,
    topology: str = "off",
) -> StaticBatch:
    """Stage 1: the assume-independent host encode (see StaticBatch).
    ``track_changes=False`` (serial loop) skips the pipeline-only
    staleness diff in the incremental snapshot encode. ``pad_multiple``:
    round the padded NODE capacity up to this multiple — a mesh of
    n_shards devices needs NC % n_shards == 0 or the sharded resident
    block degrades to per-cycle replication (round_up's buckets are
    multiples of 8, so this only bites past 8 shards on tiny clusters)."""
    N, P = snapshot.num_nodes(), len(pods)
    NP = enc.round_up(N) if pad else N
    if pad:
        NP = enc.shard_aligned(NP, pad_multiple)
    PP = enc.round_up(P) if pad else P
    folded: frozenset = frozenset()
    if resource_names is None:
        resource_names, folded = enc.batch_resource_axis(snapshot, pods)
    # DRA (state.dra): pre-analyze the batch's claims so dense pool columns
    # join the resource axis BEFORE the node tensors are built; pool ids are
    # interned on the cache's index, keeping the axis cycle-stable for the
    # incremental encode
    dra_state = None
    want_dra_plugin = profile is None or (
        profile.has_filter(C.DYNAMIC_RESOURCES)
    )
    if (
        want_dra_plugin
        and getattr(snapshot, "dra", None) is not None
        and any(p_.resource_claims for p_ in pods)
    ):
        from ..state.dra import DraState

        dra_state = DraState(snapshot)
        for p_ in pods:
            dra_state.analyze(p_)
        pool_names = dra_state.pool_resource_names()
        if pool_names:
            resource_names = list(resource_names) + pool_names
    nt = enc.encode_snapshot(
        snapshot, resource_names=resource_names, pods=pods, pad_nodes=NP,
        prev=prev_nt, track_changes=track_changes,
    )
    if dra_state is not None and dra_state.used_pools:
        dra_state.fill_node_columns(
            nt, len(nt.resource_names) - len(dra_state.used_pools)
        )
    enabled = (
        frozenset(profile.filters.names()) if profile is not None else None
    )
    enabled_sc = (
        frozenset(profile.scores.names()) if profile is not None else None
    )
    nominated_triples: list[tuple[int, str, str]] = []
    for e in nominated:
        nominated_triples.extend(getattr(e, "ports", ()))
    vol_state = None
    if any(v.pvc_name for p_ in pods for v in p_.volumes):
        # a pod referencing a PVC engages the volume plugins even when the
        # listers are empty (a MISSING claim is what rejects it)
        from ..state.volumes import VolumeState

        vol_state = VolumeState(snapshot)
    # a nomination whose own pod sits in THIS batch is excluded: the folded
    # resource is a batch singleton, so the nominee is its only requester —
    # charging would block the nominee from its own nominated node (the
    # dense path's self-exclusion is the per-pod gate, e.uid != p.uid)
    batch_uids = {p_.uid for p_ in pods}
    folded_nominated = (
        [
            (e.node_name, tuple(e.requests))
            for e in nominated
            if getattr(e, "node_name", "") and e.uid not in batch_uids
        ]
        if folded else ()
    )
    pb = enc.encode_pod_batch(
        nt, pods, enabled_filters=enabled, pad_pods=PP,
        enabled_scores=enabled_sc, extra_port_triples=nominated_triples,
        volume_state=vol_state,
        folded_resources=folded,
        folded_nominated=folded_nominated,
        dra_state=dra_state,
        cache=cache,
    )
    # DRA prioritized-list score rows (per distinct host-spec set)
    dra_score_raw = dra_score_sig = None
    want_dra_score = profile is None or profile.has_score(C.DYNAMIC_RESOURCES)
    if dra_state is not None and want_dra_score:
        NC = nt.alloc.shape[0]
        row_ids: dict[tuple, int] = {}
        rows: list[np.ndarray] = []
        sig_arr = np.zeros(PP, dtype=np.int32)
        any_score = False
        for i, p_ in enumerate(pods):
            d = dra_state.analyze(p_)
            specs = tuple(
                s for s in d.host_specs
                if dra_state.spec_score(s, nt) is not None
            )
            sid = row_ids.get(specs)
            if sid is None:
                v = np.zeros(N, dtype=np.int64)
                for s in specs:
                    v = v + dra_state.spec_score(s, nt)
                sid = len(rows)
                row_ids[specs] = sid
                rows.append(v)
            sig_arr[i] = sid
            if specs:
                any_score = True
        if any_score:
            dra_score_raw = np.zeros((len(rows), NC), dtype=np.int64)
            for s_i, v in enumerate(rows):
                dra_score_raw[s_i, :N] = v
            dra_score_sig = sig_arr
    want_na = profile is None or profile.has_score(C.NODE_AFFINITY)
    want_tt = profile is None or profile.has_score(C.TAINT_TOLERATION)
    want_img = profile is None or profile.has_score(C.IMAGE_LOCALITY)
    want_spread = profile is None or (
        profile.has_filter(C.POD_TOPOLOGY_SPREAD)
        or profile.has_score(C.POD_TOPOLOGY_SPREAD)
    )
    want_interpod = profile is None or (
        profile.has_filter(C.INTER_POD_AFFINITY)
        or profile.has_score(C.INTER_POD_AFFINITY)
    )
    img_sums, img_sig, img_counts = (
        _image_tensors(nt, pods, pad_pods=PP)
        if want_img else (None, None, None)
    )
    node_valid = np.zeros(nt.alloc.shape[0], dtype=bool)
    node_valid[:N] = True
    pod_valid = np.zeros(PP, dtype=bool)
    pod_valid[:P] = True
    return StaticBatch(
        pods=list(pods),
        profile=profile,
        nt=nt,
        pb=pb,
        resource_names=nt.resource_names,
        num_nodes=N,
        num_pods=P,
        pad_nodes=nt.alloc.shape[0],
        pad_pods=PP,
        folded=folded,
        want_na=want_na,
        want_tt=want_tt,
        want_img=want_img,
        want_spread=want_spread,
        want_interpod=want_interpod,
        dra_score_raw=dra_score_raw,
        dra_score_sig=dra_score_sig,
        img_sums=img_sums,
        img_sig=img_sig,
        img_counts=img_counts,
        node_valid=node_valid,
        pod_valid=pod_valid,
        nominated_key=tuple(id(e) for e in nominated),
        assume_coupled=bool(folded) or dra_state is not None
        or vol_state is not None,
        cache=cache,
        topology=topology,
    )


def refresh_static(sb: StaticBatch, snapshot: Snapshot) -> bool:
    """Re-encode the node resource rows of a pre-encoded StaticBatch on its
    own axis (stage-2 entry: fold in the assumes that landed since stage 1).
    Returns False when the node SET changed since stage 1 — the StaticBatch
    is then unusable (its num_nodes/node_valid/static_mask are pinned at
    the stage-1 node count) and the caller must re-encode from scratch.
    Object identity alone no longer detects that: the append-incremental
    encoder extends the SAME NodeTensors in place on a pure node add, so
    the node count is checked explicitly."""
    nt = enc.encode_snapshot(
        snapshot, resource_names=sb.resource_names, pods=(),
        pad_nodes=sb.pad_nodes, prev=sb.nt,
    )
    if nt is not sb.nt or nt.num_nodes != sb.num_nodes:
        return False
    if nt.last_dirty_rows:
        # node accounting moved (the assumes this refresh folds in) — the
        # stage-1 port rows no longer reflect in-use triples
        sb.ports_stale = True
    return True


def _node_port_rows(
    nt: "enc.NodeTensors", vocab, NC: int, K: int
) -> np.ndarray:
    """(NC, K) in-use port-triple rows from the CURRENT NodeInfo state —
    the assume-dependent half of the NodePorts tensors. Raises
    StaleStaticEncode when a node holds a triple outside the batch's
    interned vocabulary (an assume introduced a new triple; the conflict
    matrix can't express it)."""
    rows = np.zeros((NC, K), dtype=bool)
    for i, info in enumerate(nt.infos):
        for tr in info.port_triples:
            tid = vocab.get(tr)
            if tid < 0:
                raise StaleStaticEncode(f"port triple {tr} not in batch vocab")
            rows[i, tid] = True
    return rows


def finalize_batch(
    sb: StaticBatch,
    snapshot: Snapshot,
    nominated: Sequence = (),
    resident: "ResidentNodeState | None" = None,
    mesh=None,
) -> EncodedBatch:
    """Stage 2: patch the assume-dependent slice onto a StaticBatch and
    build the device pytree — spread counts and affinity sums re-derived
    from the CURRENT NodeInfo state, nominations re-encoded, in-use ports
    recomputed, and the node block delta-uploaded when ``resident`` is
    given. Raises StaleStaticEncode when the StaticBatch can't be patched
    (nomination set changed since stage 1, or an unknown port triple)."""
    if tuple(id(e) for e in nominated) != sb.nominated_key:
        raise StaleStaticEncode("nomination set changed since static encode")
    profile, pods, nt, pb = sb.profile, sb.pods, sb.nt, sb.pb
    N, P, PP = sb.num_nodes, sb.num_pods, sb.pad_pods
    NC = sb.pad_nodes
    cache = sb.cache
    if cache is not None:
        # namespace labels feed affinity namespaceSelectors: a moved
        # generation clears the cache's persistent match verdicts
        cache.sync_namespaces(snapshot.namespaces_generation)
    # template groups of the existing pods, shared by the spread and
    # affinity encoders (one O(pods) pass, built only if either needs it)
    _groups_memo: list = []

    def groups_of():
        if not _groups_memo:
            from ..state.encode_cache import groups_for

            _groups_memo.append(groups_for(nt, cache))
        return _groups_memo[0]

    pa_dev = None
    # affinity-free cluster fast path: the cache maintains a count of
    # assigned pods carrying any (anti)affinity, so a SchedulingBasic-shaped
    # steady state skips the template-group pass AND the affinity encoder
    # in O(pending) attribute checks
    want_pa = sb.want_interpod and not (
        snapshot.pods_with_affinity == 0
        and not any(enc_podaffinity.has_any_affinity(p) for p in pods)
    )
    if want_pa:
        pa = enc_podaffinity.encode_pod_affinity(
            nt, pods,
            hard_pod_affinity_weight=(
                profile.hard_pod_affinity_weight if profile is not None else 1
            ),
            pad_pods=PP,
            namespaces=snapshot.namespaces,
            cache=cache,
            groups=groups_of(),
        )
        if pa is not None:
            # host numpy leaves — the single batched device_put below ships
            # the whole pytree in one dispatch instead of ~30
            pa_dev = PodAffinityDevice(
                node_domain=pa.node_domain,
                has_key=pa.has_key,
                base_sums=pa.base_sums,
                update=pa.update,
                fa_rows=pa.fa_rows,
                fa_self=pa.fa_self,
                ra_rows=pa.ra_rows,
                ea_rows=pa.ea_rows,
                score_rows=pa.score_rows,
                score_vals=pa.score_vals,
                has_filter_work=pa.has_filter_work,
                has_score_work=pa.has_score_work,
            )
    spread_dev = None
    if sb.want_spread:
        defaults = (
            profile.default_spread_constraints if profile is not None else ()
        )
        sp = enc_spread.encode_spread(
            nt, pods, pad_pods=PP,
            default_constraints=defaults,
            default_selector_of=(
                enc_spread.default_selector_from_services(snapshot)
                if defaults and snapshot.services else None
            ),
            cache=cache,
            # reuse the affinity encoder's group pass when it ran; spread
            # builds its own only past its cheap no-constraints early-out
            groups=_groups_memo[0] if _groups_memo else None,
        )
        if sp is not None:
            spread_dev = SpreadDevice(
                eligible=sp.eligible,
                node_domain=sp.node_domain,
                node_count=sp.node_count,
                has_key=sp.has_key,
                domain_present=sp.domain_present,
                num_domains=sp.num_domains,
                is_hostname=sp.is_hostname,
                sig_idx=sp.sig_idx,
                action=sp.action,
                max_skew=sp.max_skew,
                min_domains=sp.min_domains,
                self_match=sp.self_match,
                pod_match_sig=sp.pod_match_sig,
                ignored=sp.ignored,
                has_hard=sp.has_hard,
                has_soft=sp.has_soft,
            )
    img_sums, img_sig, img_counts = sb.img_sums, sb.img_sig, sb.img_counts
    node_valid, pod_valid = sb.node_valid, sb.pod_valid

    # in-use ports: the stage-1 rows are reused verbatim unless node state
    # moved since (refresh_static flags it) — then they are re-derived from
    # the current NodeInfos (assumes occupy ports)
    K = pb.port_conflict.shape[0]
    node_ports = (
        _node_port_rows(nt, pb.port_vocab, NC, K)
        if sb.ports_stale else pb.node_ports
    )

    # Nominator reservations (queue/nominator.py): the gate row for pod p
    # enables nomination g iff g's priority >= p's and g is not p itself
    # (framework/runtime's RunFilterPluginsWithNominatedPods rule).
    nom_node = nom_req = nom_gate = nom_ports = nom_pod_idx = None
    if nominated:
        name_to_idx = {n: j for j, n in enumerate(nt.node_names)}
        uid_to_idx = {p_.uid: i for i, p_ in enumerate(pods)}
        G = len(nominated)
        nom_node = np.full(G, -1, dtype=np.int32)
        nom_req = np.zeros((G, len(nt.resource_names)), dtype=np.int64)
        nom_gate = np.zeros((PP, G), dtype=bool)
        nom_ports = np.zeros((G, K), dtype=bool)
        nom_pod_idx = np.full(G, -1, dtype=np.int32)
        ridx = {r: j for j, r in enumerate(nt.resource_names)}
        for g, e in enumerate(nominated):
            nom_node[g] = name_to_idx.get(e.node_name, -1)
            nom_pod_idx[g] = uid_to_idx.get(e.uid, -1)
            for k, val in e.requests:
                j = ridx.get(k)
                if j is not None:
                    nom_req[g, j] = val
            for tr in getattr(e, "ports", ()):
                tid = pb.port_vocab.get(tr)
                if tid >= 0:
                    nom_ports[g, tid] = True
            for i, p_ in enumerate(pods):
                nom_gate[i, g] = e.priority >= p_.priority and e.uid != p_.uid

    # topology coordinates: attached ONLY when the mode is active and some
    # node actually carries a slice/rack label ("auto" on an unlabeled
    # cluster leaves the leaf absent → the pytree, the compiled kernels and
    # their outputs are bit-identical to topology-off)
    topo_dev = None
    if sb.topology != "off":
        from ..state.topology import topology_tensors

        tt = topology_tensors(nt)
        if tt.labeled:
            topo_dev = TopologyDevice(
                slice_id=tt.slice_id,
                rack_id=tt.rack_id,
                num_slices=tt.num_slices,
                num_racks=tt.num_racks,
            )

    if resident is not None:
        nodes_block = resident.refresh(nt, N)
        node_upload = resident.last_upload_bytes
        resident_bytes = resident.nbytes
    else:
        nodes_block = DeviceNodeState(
            alloc=nt.alloc,
            requested=nt.requested,
            nonzero_requested=nt.nonzero_requested,
            pod_count=nt.pod_count,
            allowed_pods=nt.allowed_pods,
            node_valid=node_valid,
        )
        node_upload = _node_block_nbytes(nodes_block)
        resident_bytes = 0

    if mesh is None and resident is not None:
        mesh = resident.mesh
    # host numpy leaves throughout; ONE batched device_put ships the whole
    # pytree (leaf-by-leaf jnp.asarray was ~30 separate dispatches per
    # cycle). Resident-path node buffers are already on device — and, under
    # a mesh, already sharded with the same rules — device_put passes them
    # through untouched.
    dev = DeviceBatch(
        nodes=nodes_block,
        requests=pb.requests,
        nonzero_requests=pb.nonzero_requests,
        pod_valid=pod_valid,
        static_mask=pb.static_mask,
        static_sig=(
            pb.static_sig if pb.static_mask is not None else None
        ),
        node_affinity_raw=(
            pb.node_affinity_raw
            if sb.want_na and pb.node_affinity_raw is not None else None
        ),
        taint_prefer_raw=(
            pb.taint_prefer_raw
            if sb.want_tt and pb.taint_prefer_raw is not None else None
        ),
        score_sig=(
            pb.score_sig
            if pb.score_sig is not None
            and ((sb.want_na and pb.node_affinity_raw is not None)
                 or (sb.want_tt and pb.taint_prefer_raw is not None))
            else None
        ),
        image_sum_scores=img_sums if sb.want_img else None,
        image_sig=img_sig if sb.want_img else None,
        image_count=img_counts if sb.want_img else None,
        pod_ports=pb.pod_ports,
        node_ports=node_ports,
        port_conflict=pb.port_conflict,
        nominated_node=nom_node,
        nominated_req=nom_req,
        nominated_gate=nom_gate,
        nominated_ports=nom_ports,
        nominated_pod_idx=nom_pod_idx,
        spread=spread_dev,
        podaffinity=pa_dev,
        dra_score_raw=sb.dra_score_raw,
        dra_score_sig=(
            sb.dra_score_sig if sb.dra_score_raw is not None else None
        ),
        pod_priority=pb.priority,
        topology=topo_dev,
    )
    if mesh is not None:
        from ..parallel.mesh import batch_shardings, node_axes_of

        axis, pod_axis = node_axes_of(mesh)
        dev = jax.device_put(
            dev, batch_shardings(dev, mesh, axis, pod_axis, guard=True)
        )
    else:
        dev = jax.device_put(dev)
    from ..metrics.tpu import batch_nbytes

    total_bytes = batch_nbytes(dev)
    pod_block_bytes = total_bytes - _node_block_nbytes(nodes_block)
    return EncodedBatch(
        device=dev,
        node_names=nt.node_names,
        pods=list(pods),
        resource_names=nt.resource_names,
        num_nodes=N,
        num_pods=P,
        node_tensors=nt,
        port_vocab=pb.port_vocab,
        upload_bytes=pod_block_bytes + node_upload,
        resident_bytes=resident_bytes,
    )


@dataclass(frozen=True)
class ScoreParams:
    """Static numeric config handed to the jitted program (weights aligned to
    the batch's resource axis)."""

    fit_weights: tuple[int, ...]
    balanced_weights: tuple[int, ...]
    is_scalar: tuple[bool, ...]
    strategy: str
    shape_x: tuple[int, ...]
    shape_y: tuple[int, ...]          # pre-scaled ×10 (MaxNodeScore/MaxCustomPriorityScore)
    w_fit: int
    w_balanced: int
    w_node_affinity: int
    w_taint: int
    w_image: int
    w_spread: int
    w_interpod: int
    w_dra: int
    filter_fit: bool
    filter_ports: bool
    filter_spread: bool
    filter_interpod: bool


def score_params(profile: C.Profile, resource_names: Sequence[str]) -> ScoreParams:
    ss = profile.scoring_strategy
    shape = ss.shape or ((0, 0), (100, 10))
    return ScoreParams(
        fit_weights=tuple(_resource_weights(resource_names, ss.resources).tolist()),
        balanced_weights=tuple(
            _resource_weights(resource_names, profile.balanced_resources).tolist()
        ),
        is_scalar=tuple(_is_scalar(resource_names).tolist()),
        strategy=ss.type,
        shape_x=tuple(x for x, _ in shape),
        shape_y=tuple(y * 10 for _, y in shape),
        w_fit=profile.score_weight(C.NODE_RESOURCES_FIT),
        w_balanced=profile.score_weight(C.NODE_RESOURCES_BALANCED),
        w_node_affinity=profile.score_weight(C.NODE_AFFINITY),
        w_taint=profile.score_weight(C.TAINT_TOLERATION),
        w_image=profile.score_weight(C.IMAGE_LOCALITY),
        w_spread=profile.score_weight(C.POD_TOPOLOGY_SPREAD),
        w_interpod=profile.score_weight(C.INTER_POD_AFFINITY),
        w_dra=profile.score_weight(C.DYNAMIC_RESOURCES),
        filter_fit=profile.has_filter(C.NODE_RESOURCES_FIT),
        filter_ports=profile.has_filter(C.NODE_PORTS),
        filter_spread=profile.has_filter(C.POD_TOPOLOGY_SPREAD),
        filter_interpod=profile.has_filter(C.INTER_POD_AFFINITY),
    )


def masked_normalize(raw: jnp.ndarray, mask: jnp.ndarray, reverse: bool = False) -> jnp.ndarray:
    """DefaultNormalizeScore over feasible nodes only (the reference's
    nodeScoreList contains only nodes that passed Filter)."""
    masked = jnp.where(mask, raw, 0)
    return S.default_normalize(masked, reverse=reverse)


def filter_components(
    b: DeviceBatch,
    p: ScoreParams,
    requested: jnp.ndarray | None = None,
    pod_count: jnp.ndarray | None = None,
    node_ports: jnp.ndarray | None = None,
    spread_counts: jnp.ndarray | None = None,
    pa_sums: jnp.ndarray | None = None,
    nominated_active: jnp.ndarray | None = None,
):
    """Per-plugin Filter masks, un-ANDed — the split preemption needs:
    failures of ``static`` / ``spread_ok`` / ``pa_ok`` are
    UnschedulableAndUnresolvable for the victim-search (removing pods can't
    fix node labels; spread/affinity removal effects are conservatively out
    of kernel scope, ops/preemption.py docstring), while ``fit``/``ports_ok``
    failures are the resolvable kind (preemption.go:180 NodesForStatusCode).

    Returns ``(static, fit, ports_ok, spread_ok, pa_ok, sp_counts,
    pa_state)``; mask entries are None when the plugin is disabled or has no
    work.
    """
    req = b.requested if requested is None else requested
    pc = b.pod_count if pod_count is None else pod_count
    ports = b.node_ports if node_ports is None else node_ports

    static = b.node_valid[None, :] & b.pod_valid[:, None]
    if b.static_mask is not None:
        # (S, N) rows gathered per pod on device (fused into consumers)
        sm = (
            b.static_mask[b.static_sig]
            if b.static_sig is not None else b.static_mask
        )
        static = static & sm
    fit = None
    if p.filter_fit:
        if b.nominated_node is not None:
            gate = b.nominated_gate
            if nominated_active is not None:
                # a nomination stops charging once its own pod was assigned
                # earlier in this batch (assume deletes the nomination)
                gate = gate & nominated_active[None, :]
            fit = F.resource_fit_mask_nominated(
                b.requests, b.alloc, req, pc, b.allowed_pods,
                gate, b.nominated_node, b.nominated_req,
            )
        else:
            fit = F.resource_fit_mask(
                b.requests, b.alloc, req, pc, b.allowed_pods
            )
    ports_ok = None
    if p.filter_ports:
        # conflict[p, n] = any pod triple k conflicting with in-use triple l
        wants_conf = jnp.einsum(
            "pk,kl->pl", b.pod_ports.astype(jnp.int32),
            b.port_conflict.astype(jnp.int32),
        )                                                     # (P, K)
        conflict = jnp.einsum(
            "pl,nl->pn", wants_conf, ports.astype(jnp.int32)
        ) > 0                                                 # (P, N)
        if b.nominated_ports is not None and b.nominated_node is not None:
            # nominated pods' host ports are reserved on their nominated
            # node for >=-priority-gated pods, like their resources
            # (RunFilterPluginsWithNominatedPods adds the whole pod)
            gate = b.nominated_gate
            if nominated_active is not None:
                gate = gate & nominated_active[None, :]
            nom_conf = jnp.einsum(
                "pl,gl->pg", wants_conf,
                b.nominated_ports.astype(jnp.int32),
            )                                                 # (P, G)
            n_nodes = ports.shape[0]
            at_node = (
                b.nominated_node[:, None]
                == jnp.arange(n_nodes, dtype=b.nominated_node.dtype)[None, :]
            )                                                 # (G, N)
            conflict = conflict | (
                jnp.einsum(
                    "pg,gn->pn",
                    (gate & (nom_conf > 0)).astype(jnp.int32),
                    at_node.astype(jnp.int32),
                ) > 0
            )
        ports_ok = ~conflict
    sp = b.spread
    sp_counts = None
    spread_ok = None
    if sp is not None:
        sp_counts = sp.node_count if spread_counts is None else spread_counts
        if p.filter_spread and sp.has_hard:
            spread_ok = jax.vmap(
                lambda si, ac, ms, md, sm: SP.spread_filter_pod(
                    sp, sp_counts, si, ac, ms, md, sm
                )
            )(sp.sig_idx, sp.action, sp.max_skew, sp.min_domains, sp.self_match)
    pa = b.podaffinity
    pa_state = None
    pa_ok = None
    if pa is not None:
        pa_state = pa.base_sums if pa_sums is None else pa_sums
        if p.filter_interpod and pa.has_filter_work:
            pa_ok = jax.vmap(
                lambda fr, fs, rr, er: PA.affinity_filter_pod(
                    pa, pa_state, fr, fs, rr, er
                )
            )(pa.fa_rows, pa.fa_self, pa.ra_rows, pa.ea_rows)
    return static, fit, ports_ok, spread_ok, pa_ok, sp_counts, pa_state


def feasible_and_scores(
    b: DeviceBatch,
    p: ScoreParams,
    requested: jnp.ndarray | None = None,
    nonzero_requested: jnp.ndarray | None = None,
    pod_count: jnp.ndarray | None = None,
    node_ports: jnp.ndarray | None = None,
    spread_counts: jnp.ndarray | None = None,
    pa_sums: jnp.ndarray | None = None,
    nominated_active: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The full Filter + Score composition for a batch against ONE snapshot
    state (no inter-pod capacity coupling — that is the assignment engine's
    job). Returns ``(mask (P,N) bool, total (P,N) int64)``.

    Optional ``requested``/``nonzero_requested``/``pod_count`` override the
    batch's node usage — the greedy scan threads its running state through
    here so this one function is both the one-shot and the stepped semantics.
    """
    req = b.requested if requested is None else requested
    nz = b.nonzero_requested if nonzero_requested is None else nonzero_requested

    w_fit = jnp.asarray(p.fit_weights, dtype=jnp.int64)
    w_bal = jnp.asarray(p.balanced_weights, dtype=jnp.int64)
    scal = jnp.asarray(p.is_scalar, dtype=bool)

    # --- Filter ----------------------------------------------------------
    static, fit, ports_ok, spread_ok, pa_ok, sp_counts, pa_state = (
        filter_components(
            b, p, requested=requested, pod_count=pod_count,
            node_ports=node_ports, spread_counts=spread_counts,
            pa_sums=pa_sums, nominated_active=nominated_active,
        )
    )
    mask = static
    for part in (fit, ports_ok, spread_ok, pa_ok):
        if part is not None:
            mask = mask & part
    if b.extender_mask is not None:
        # findNodesThatPassExtenders (schedule_one.go:886): extenders only
        # shrink the feasible set
        mask = mask & b.extender_mask
    sp = b.spread
    pa = b.podaffinity

    # --- Score -----------------------------------------------------------
    total = jnp.zeros(mask.shape, dtype=jnp.int64)
    if p.w_fit:
        if p.strategy == C.LEAST_ALLOCATED:
            raw = S.least_allocated_score(b.nonzero_requests, nz, b.alloc, w_fit, scal)
        elif p.strategy == C.MOST_ALLOCATED:
            raw = S.most_allocated_score(b.nonzero_requests, nz, b.alloc, w_fit, scal)
        else:
            raw = S.requested_to_capacity_ratio_score(
                b.nonzero_requests, nz, b.alloc, w_fit, scal,
                jnp.asarray(p.shape_x, dtype=jnp.int64),
                jnp.asarray(p.shape_y, dtype=jnp.int64),
            )
        total = total + p.w_fit * raw          # no NormalizeScore (already 0..100)
    if p.w_balanced:
        raw = S.balanced_allocation_score(b.requests, req, b.alloc, w_bal, scal)
        total = total + p.w_balanced * raw
    if p.w_node_affinity and b.node_affinity_raw is not None:
        na_raw = (
            b.node_affinity_raw[b.score_sig]
            if b.score_sig is not None else b.node_affinity_raw
        )
        total = total + p.w_node_affinity * masked_normalize(na_raw, mask)
    if p.w_taint and b.taint_prefer_raw is not None:
        tt_raw = (
            b.taint_prefer_raw[b.score_sig]
            if b.score_sig is not None else b.taint_prefer_raw
        )
        total = total + p.w_taint * masked_normalize(tt_raw, mask, reverse=True)
    if p.w_image and b.image_sum_scores is not None:
        img = (
            b.image_sum_scores[b.image_sig]
            if b.image_sig is not None else b.image_sum_scores
        )
        total = total + p.w_image * S.image_locality_score(img, b.image_count)
    if sp is not None and p.w_spread and sp.has_soft:
        spread_sc = jax.vmap(
            lambda si, ac, ms, ig, m: SP.spread_score_pod(
                sp, sp_counts, si, ac, ms, ig, m
            )
        )(sp.sig_idx, sp.action, sp.max_skew, sp.ignored, mask)
        total = total + p.w_spread * spread_sc
    if pa is not None and p.w_interpod and pa.has_score_work:
        pa_sc = jax.vmap(
            lambda sr, sv, m: PA.affinity_score_pod(pa, pa_state, sr, sv, m)
        )(pa.score_rows, pa.score_vals, mask)
        total = total + p.w_interpod * pa_sc
    if p.w_dra and b.dra_score_raw is not None:
        # DynamicResources prioritized-list score + DefaultNormalizeScore
        # (dynamicresources.go:1059 Score, :1138 NormalizeScore)
        dra_raw = (
            b.dra_score_raw[b.dra_score_sig]
            if b.dra_score_sig is not None else b.dra_score_raw
        )
        total = total + p.w_dra * masked_normalize(dra_raw, mask)
    if b.extender_score is not None:
        # extender Prioritize, pre-scaled weight*MaxNodeScore/MaxExtenderPriority
        # (schedule_one.go:1015) — added after plugin normalization
        total = total + b.extender_score
    return mask, total


@partial(jax.jit, static_argnames=("params",))
def filter_score_batch(b: DeviceBatch, params: ScoreParams):
    """One-shot batch Filter+Score (all pods vs. the same snapshot) — the
    extender Prioritize path and the first half of batched assignment."""
    return feasible_and_scores(b, params)
