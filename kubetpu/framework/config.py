"""Scheduler configuration — the envelope of ``KubeSchedulerConfiguration``.

The reference's config surface (pkg/scheduler/apis/config/types.go:37,
versioned staging/src/k8s.io/kube-scheduler/config/v1/types.go:44) is a list
of *profiles*, each enabling plugins per extension point with weights and
per-plugin args (types_pluginargs.go). This module models the subset that
drives the tensor kernels:

- which Filter predicates are enabled,
- which Score plugins are enabled with what weights,
- per-plugin args (scoring strategy + resource weights for NodeResourcesFit,
  RequestedToCapacityRatio shape, default topology-spread constraints).

Defaults mirror ``getDefaultPlugins``
(pkg/scheduler/apis/config/v1/default_plugins.go:30) and the defaulted plugin
args (apis/config/v1/defaults.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ..api import types as t

from ..names import (  # noqa: F401  (canonical plugin names, re-exported)
    DEFAULT_BINDER,
    DEFAULT_PREEMPTION,
    DYNAMIC_RESOURCES,
    IMAGE_LOCALITY,
    INTER_POD_AFFINITY,
    NODE_AFFINITY,
    NODE_NAME,
    NODE_PORTS,
    NODE_RESOURCES_BALANCED,
    NODE_RESOURCES_FIT,
    NODE_UNSCHEDULABLE,
    NODE_VOLUME_LIMITS,
    POD_TOPOLOGY_SPREAD,
    PRIORITY_SORT,
    SCHEDULING_GATES,
    TAINT_TOLERATION,
    VOLUME_BINDING,
    VOLUME_RESTRICTIONS,
    VOLUME_ZONE,
)

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"


@dataclass(frozen=True)
class ScoringStrategy:
    """NodeResourcesFitArgs.ScoringStrategy (types_pluginargs.go). ``resources``
    is the scored resource set with weights (default cpu:1, memory:1 —
    apis/config/v1/defaults.go defaultResourceSpec). ``shape`` is the
    RequestedToCapacityRatio bracket, y values in 0..10 (MaxCustomPriorityScore)
    exactly as configured; the runtime scales them ×10."""

    type: str = LEAST_ALLOCATED
    resources: tuple[tuple[str, int], ...] = ((t.CPU, 1), (t.MEMORY, 1))
    shape: tuple[tuple[int, int], ...] = ()  # (utilization 0..100, score 0..10)


@dataclass(frozen=True)
class PluginSet:
    """Enabled plugins for one extension point: (name, weight) pairs.
    Weight is meaningful only for Score."""

    enabled: tuple[tuple[str, int], ...] = ()

    def names(self) -> list[str]:
        return [n for n, _ in self.enabled]

    def weight(self, name: str) -> int:
        for n, w in self.enabled:
            if n == name:
                return w
        return 0


# Default plugin sets (default_plugins.go:30). Weights: TaintToleration 3,
# NodeAffinity 2, PodTopologySpread 2, InterPodAffinity 2, the rest 1.
DEFAULT_FILTERS = PluginSet(enabled=(
    (NODE_UNSCHEDULABLE, 1),
    (NODE_NAME, 1),
    (TAINT_TOLERATION, 1),
    (NODE_AFFINITY, 1),
    (NODE_PORTS, 1),
    (NODE_RESOURCES_FIT, 1),
    (VOLUME_RESTRICTIONS, 1),
    (NODE_VOLUME_LIMITS, 1),
    (VOLUME_BINDING, 1),
    (VOLUME_ZONE, 1),
    (POD_TOPOLOGY_SPREAD, 1),
    (INTER_POD_AFFINITY, 1),
    # DynamicResources joins the default set with DRA GA (resource.k8s.io/v1
    # in the 1.37 snapshot; default_plugins.go:60-73 feature-gated add)
    (DYNAMIC_RESOURCES, 1),
))
DEFAULT_SCORES = PluginSet(enabled=(
    (TAINT_TOLERATION, 3),
    (NODE_AFFINITY, 2),
    (NODE_RESOURCES_FIT, 1),
    (POD_TOPOLOGY_SPREAD, 2),
    (INTER_POD_AFFINITY, 2),
    (NODE_RESOURCES_BALANCED, 1),
    (IMAGE_LOCALITY, 1),
    (DYNAMIC_RESOURCES, 1),
))


@dataclass(frozen=True)
class Profile:
    """One scheduler profile (pkg/scheduler/profile/profile.go:46)."""

    name: str = "default-scheduler"
    filters: PluginSet = DEFAULT_FILTERS
    scores: PluginSet = DEFAULT_SCORES
    # Host-side lifecycle plugins (Reserve/Permit/PreBind/PostBind —
    # interface.go:636-680), resolved by name against the scheduler's
    # lifecycle Registry; one name may serve several extension points, like
    # reference plugins implementing multiple interfaces. VolumeBinding's
    # Reserve/PreBind half is in the default set (default_plugins.go:30).
    lifecycle: PluginSet = PluginSet(
        enabled=((VOLUME_BINDING, 1), (DYNAMIC_RESOURCES, 1))
    )
    scoring_strategy: ScoringStrategy = ScoringStrategy()
    balanced_resources: tuple[tuple[str, int], ...] = ((t.CPU, 1), (t.MEMORY, 1))
    # InterPodAffinityArgs.HardPodAffinityWeight (types_pluginargs.go, default 1)
    hard_pod_affinity_weight: int = 1
    # Cluster-level default spread constraints applied to pods without their
    # own (pkg/scheduler/framework/plugins/podtopologyspread defaults:
    # zone maxSkew 3 ScheduleAnyway + hostname maxSkew 5 ScheduleAnyway,
    # systemDefaulted, plugin.go buildDefaultConstraints).
    default_spread_constraints: tuple[t.TopologySpreadConstraint, ...] = (
        t.TopologySpreadConstraint(
            max_skew=3,
            topology_key="topology.kubernetes.io/zone",
            when_unsatisfiable=t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY,
            selector=None,
        ),
        t.TopologySpreadConstraint(
            max_skew=5,
            topology_key="kubernetes.io/hostname",
            when_unsatisfiable=t.UnsatisfiableConstraintAction.SCHEDULE_ANYWAY,
            selector=None,
        ),
    )

    def score_weight(self, name: str) -> int:
        return self.scores.weight(name)

    def has_filter(self, name: str) -> bool:
        return name in self.filters.names()

    def has_score(self, name: str) -> bool:
        return name in self.scores.names()


def minimal_profile(
    strategy: str = LEAST_ALLOCATED,
    resources: Sequence[tuple[str, int]] = ((t.CPU, 1), (t.MEMORY, 1)),
    shape: Sequence[tuple[int, int]] = (),
) -> Profile:
    """The BASELINE config #1 profile: NodeResourcesFit only (Filter + Score)."""
    return Profile(
        name="minimal",
        filters=PluginSet(enabled=((NODE_RESOURCES_FIT, 1),)),
        scores=PluginSet(enabled=((NODE_RESOURCES_FIT, 1),)),
        scoring_strategy=ScoringStrategy(
            type=strategy, resources=tuple(resources), shape=tuple(shape)
        ),
        default_spread_constraints=(),
    )


@dataclass(frozen=True)
class ExtenderConfig:
    """apis/config/types.go:267 Extender — the ``extenders:`` block of
    KubeSchedulerConfiguration, consumed by the HTTP extender client
    (sched/extender.py)."""

    url_prefix: str
    filter_verb: str = ""
    prioritize_verb: str = ""
    bind_verb: str = ""
    preempt_verb: str = ""
    weight: int = 1
    node_cache_capable: bool = False
    ignorable: bool = False
    http_timeout_s: float = 30.0
    managed_resources: tuple[str, ...] = ()


@dataclass(frozen=True)
class SchedulerConfiguration:
    """Subset of KubeSchedulerConfiguration (apis/config/types.go:37)."""

    profiles: tuple[Profile, ...] = (Profile(),)
    parallelism: int = 16                 # reference default (scheduler.go:193)
    percentage_of_nodes_to_score: int = 0  # 0 = exhaustive (we never subsample)
    pod_initial_backoff_seconds: float = 1.0
    pod_max_backoff_seconds: float = 10.0
    extenders: tuple[ExtenderConfig, ...] = ()

    def profile(self, name: str | None = None) -> Profile:
        if name is None:
            return self.profiles[0]
        for p in self.profiles:
            if p.name == name:
                return p
        raise KeyError(f"no profile named {name!r}")
