"""Configuration validation — the apis/config/validation analog.

Reference: pkg/scheduler/apis/config/validation/validation.go
(ValidateKubeSchedulerConfiguration) + validation_pluginargs.go: malformed
profiles fail LOUDLY at scheduler construction instead of silently
mis-scheduling. Every error found is reported at once (field-path style
messages, like field.ErrorList aggregation).
"""

from __future__ import annotations

from ..api import types as t
from .. import names as N
from . import config as C

FILTER_PLUGINS = frozenset(N.ALL_FILTERS)
SCORE_PLUGINS = frozenset({
    N.NODE_RESOURCES_FIT,
    N.NODE_RESOURCES_BALANCED,
    N.NODE_AFFINITY,
    N.TAINT_TOLERATION,
    N.IMAGE_LOCALITY,
    N.POD_TOPOLOGY_SPREAD,
    N.INTER_POD_AFFINITY,
    N.DYNAMIC_RESOURCES,
})
STRATEGIES = frozenset({
    C.LEAST_ALLOCATED, C.MOST_ALLOCATED, C.REQUESTED_TO_CAPACITY_RATIO,
})
MAX_CUSTOM_PRIORITY_SCORE = 10   # validation_pluginargs.go maxCustomPriorityScore
MAX_WEIGHT = 100                 # validation.go MaxWeight (MaxTotalScore bound)


def validate_profile(profile: C.Profile, lifecycle_registry=None) -> list[str]:
    """Returns every problem found (empty = valid)."""
    errs: list[str] = []
    path = f"profiles[{profile.name!r}]"
    if not profile.name:
        errs.append(f"{path}.name: must not be empty")

    def check_set(field: str, ps: C.PluginSet, known: frozenset, scored: bool):
        seen = set()
        for name, weight in ps.enabled:
            p = f"{path}.{field}[{name!r}]"
            if name in seen:
                errs.append(f"{p}: duplicate plugin")
            seen.add(name)
            if name not in known:
                errs.append(
                    f"{p}: unknown plugin (known: {sorted(known)})"
                )
            if scored and not (1 <= weight <= MAX_WEIGHT):
                errs.append(
                    f"{p}: weight {weight} must be in 1..{MAX_WEIGHT}"
                )

    check_set("filters", profile.filters, FILTER_PLUGINS, scored=False)
    check_set("scores", profile.scores, SCORE_PLUGINS, scored=True)
    if lifecycle_registry is not None:
        known_lc = frozenset(lifecycle_registry.names())
        for name, _ in profile.lifecycle.enabled:
            if name not in known_lc:
                errs.append(
                    f"{path}.lifecycle[{name!r}]: not registered "
                    f"(known: {sorted(known_lc)})"
                )

    ss = profile.scoring_strategy
    if ss.type not in STRATEGIES:
        errs.append(
            f"{path}.scoringStrategy.type: {ss.type!r} not in {sorted(STRATEGIES)}"
        )
    for rname, weight in ss.resources:
        if not (1 <= weight <= MAX_WEIGHT):
            errs.append(
                f"{path}.scoringStrategy.resources[{rname!r}]: weight "
                f"{weight} must be in 1..{MAX_WEIGHT}"
            )
    if ss.type == C.REQUESTED_TO_CAPACITY_RATIO:
        # validation_pluginargs.go validateFunctionShape: non-empty, strictly
        # increasing utilization in 0..100, scores in 0..maxCustomPriorityScore
        if not ss.shape:
            errs.append(f"{path}.scoringStrategy.shape: required for "
                        f"RequestedToCapacityRatio")
        last_x = -1
        for x, y in ss.shape:
            if not (0 <= x <= 100):
                errs.append(f"{path}.scoringStrategy.shape: utilization {x} "
                            f"must be in 0..100")
            if x <= last_x:
                errs.append(f"{path}.scoringStrategy.shape: utilization must "
                            f"be strictly increasing (got {x} after {last_x})")
            last_x = x
            if not (0 <= y <= MAX_CUSTOM_PRIORITY_SCORE):
                errs.append(f"{path}.scoringStrategy.shape: score {y} must "
                            f"be in 0..{MAX_CUSTOM_PRIORITY_SCORE}")
    if not (0 <= profile.hard_pod_affinity_weight <= MAX_WEIGHT):
        errs.append(
            f"{path}.hardPodAffinityWeight: "
            f"{profile.hard_pod_affinity_weight} must be in 0..{MAX_WEIGHT}"
        )
    for i, sc in enumerate(profile.default_spread_constraints):
        p = f"{path}.defaultConstraints[{i}]"
        if sc.max_skew < 1:
            errs.append(f"{p}.maxSkew: {sc.max_skew} must be >= 1")
        if not sc.topology_key:
            errs.append(f"{p}.topologyKey: must not be empty")
        if sc.min_domains is not None and sc.min_domains < 1:
            errs.append(f"{p}.minDomains: {sc.min_domains} must be >= 1")
    return errs


def validate_configuration(cfg: C.SchedulerConfiguration) -> list[str]:
    errs: list[str] = []
    if not cfg.profiles:
        errs.append("profiles: at least one profile is required")
    seen = set()
    for p in cfg.profiles:
        if p.name in seen:
            errs.append(f"profiles[{p.name!r}]: duplicate profile name")
        seen.add(p.name)
        errs.extend(validate_profile(p))
    if not (0 <= cfg.percentage_of_nodes_to_score <= 100):
        errs.append(
            f"percentageOfNodesToScore: {cfg.percentage_of_nodes_to_score} "
            f"must be in 0..100"
        )
    if cfg.parallelism <= 0:
        errs.append(f"parallelism: {cfg.parallelism} must be > 0")
    if cfg.pod_initial_backoff_seconds < 0:
        errs.append("podInitialBackoffSeconds: must be >= 0")
    if cfg.pod_max_backoff_seconds < cfg.pod_initial_backoff_seconds:
        errs.append(
            "podMaxBackoffSeconds: must be >= podInitialBackoffSeconds"
        )
    return errs


def must_validate(obj, lifecycle_registry=None) -> None:
    """Raise ValueError listing EVERY problem (the reference's
    utilerrors.Aggregate → fatal at startup)."""
    if isinstance(obj, C.SchedulerConfiguration):
        errs = validate_configuration(obj)
    else:
        errs = validate_profile(obj, lifecycle_registry)
    if errs:
        raise ValueError(
            "invalid scheduler configuration:\n  " + "\n  ".join(errs)
        )
