"""Host-side lifecycle extension points: Reserve / Permit / PreBind /
PostBind, waiting pods, and the pluggable registry.

Reference surfaces:
- ReservePlugin (staging/src/k8s.io/kube-scheduler/framework/interface.go:636):
  ``Reserve`` runs after assume, in order; on any failure every Reserve
  plugin's ``Unreserve`` runs in REVERSE order and the pod is rejected.
- PermitPlugin (interface.go:680): approve / reject / wait-with-timeout;
  waiting pods are held before binding (WaitingPod, Allow/Reject per
  plugin; frameworkImpl.WaitOnPermit). Timeout ⇒ rejection.
- PreBindPlugin (interface.go:652): runs in the binding cycle just before
  the bind API call (VolumeBinding does its PV/PVC API writes here); a
  failure fails the binding cycle → Unreserve + requeue.
- PostBindPlugin (interface.go:669): informational, after a successful bind.
- Registry (pkg/scheduler/framework/plugins/registry.go:50): name → factory;
  profiles enable plugins by name, out-of-tree plugins register the same
  way (the reference's app.WithPlugin / frameworkplugins.NewInTreeRegistry
  merge).

One plugin object may implement any subset of the four points (reference
plugins implement multiple interfaces); the runner inspects which methods
are overridden.

These points are HOST-side by design: the tensor path (Filter/Score) stays
on device, while Reserve/Permit/PreBind are control-flow around binding —
exactly the reference's split between the scheduling cycle's compute and
the binding cycle's I/O.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..api import types as t

# Status codes (fwk.Status)
SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
WAIT = "Wait"
ERROR = "Error"


@dataclass(frozen=True)
class Status:
    code: str = SUCCESS
    reason: str = ""
    plugin: str = ""

    @property
    def ok(self) -> bool:
        return self.code == SUCCESS


class LifecyclePlugin:
    """Base for host-side lifecycle plugins. Override any subset of the
    four extension-point methods; un-overridden points are skipped (the
    runner checks method identity, so a subclass pays only for what it
    implements)."""

    name = "LifecyclePlugin"

    # Reserve (interface.go:636). Return a non-ok Status to reject.
    def reserve(self, handle: Any, pod: t.Pod, node_name: str) -> Status:
        return Status()

    def unreserve(self, handle: Any, pod: t.Pod, node_name: str) -> None:
        pass

    # Permit (interface.go:680). Return (Status, timeout_seconds); a WAIT
    # status parks the pod as a waiting pod until every waiting plugin
    # allows it, rejects it, or the smallest timeout fires.
    def permit(
        self, handle: Any, pod: t.Pod, node_name: str
    ) -> tuple[Status, float]:
        return Status(), 0.0

    # PreBind (interface.go:652) — runs in the (async) binding cycle.
    def pre_bind(self, handle: Any, pod: t.Pod, node_name: str) -> Status:
        return Status()

    # PostBind (interface.go:669) — informational.
    def post_bind(self, handle: Any, pod: t.Pod, node_name: str) -> None:
        pass


def _overrides(plugin: LifecyclePlugin, method: str) -> bool:
    return getattr(type(plugin), method) is not getattr(LifecyclePlugin, method)


@dataclass
class WaitingPod:
    """fwk.WaitingPod: a permitted-with-Wait pod parked before binding.
    ``pending`` holds the plugins still waiting; ``Allow``/``Reject`` are
    the per-plugin verdicts (frameworkImpl.waitingPodsMap semantics)."""

    pod: t.Pod
    node_name: str
    info: Any                     # QueuedPodInfo riding through binding
    pending: set[str] = field(default_factory=set)
    deadline: float = 0.0
    rejected: Status | None = None

    def allow(self, plugin: str) -> None:
        self.pending.discard(plugin)

    def reject(self, plugin: str, reason: str = "") -> None:
        self.rejected = Status(UNSCHEDULABLE, reason or "rejected", plugin)

    @property
    def decided(self) -> bool:
        return self.rejected is not None or not self.pending


class LifecycleRunner:
    """Orders and runs the four extension points for one profile.

    ``metrics`` (a ``SchedulerMetricsRegistry``) turns on the reference's
    per-plugin instrumentation: every plugin call observes
    ``scheduler_plugin_execution_duration_seconds{plugin, extension_point,
    status}`` and every ``run_*`` observes
    ``scheduler_framework_extension_point_duration_seconds`` (metrics.go's
    PluginExecutionDuration / FrameworkExtensionPointDuration) — the
    host-side half of the plane; the fused device Filter+Score program is
    timed by the scheduler cycle instead."""

    def __init__(
        self,
        plugins: list[LifecyclePlugin],
        metrics=None,
        profile: str = "",
    ) -> None:
        self.reserve_plugins = [p for p in plugins if _overrides(p, "reserve")
                                or _overrides(p, "unreserve")]
        self.permit_plugins = [p for p in plugins if _overrides(p, "permit")]
        self.pre_bind_plugins = [p for p in plugins if _overrides(p, "pre_bind")]
        self.post_bind_plugins = [p for p in plugins if _overrides(p, "post_bind")]
        self.metrics = metrics
        self.profile = profile

    def __bool__(self) -> bool:
        return bool(
            self.reserve_plugins or self.permit_plugins
            or self.pre_bind_plugins or self.post_bind_plugins
        )

    # ------------------------------------------------------ instrumentation
    def _observe_plugin(
        self, plugin: LifecyclePlugin, point: str, status: str, t0: float
    ) -> None:
        if self.metrics is not None:
            self.metrics.plugin_execution_duration.labels(
                plugin.name, point, status
            ).observe(time.perf_counter() - t0)

    def _observe_point(self, point: str, status: str, t0: float) -> None:
        if self.metrics is not None:
            self.metrics.framework_extension_point_duration.labels(
                point, status, self.profile
            ).observe(time.perf_counter() - t0)

    def run_reserve(self, handle, pod, node_name) -> Status:
        """RunReservePluginsReserve (framework.go): first failure wins; the
        CALLER must then run_unreserve (the reference unreserves all
        plugins, including ones never reserved — Unreserve must be
        idempotent)."""
        point_t0 = time.perf_counter()
        for p in self.reserve_plugins:
            t0 = time.perf_counter()
            try:
                st = p.reserve(handle, pod, node_name)
            except Exception as e:  # plugin bug → Error status
                self._observe_plugin(p, "Reserve", ERROR, t0)
                self._observe_point("Reserve", ERROR, point_t0)
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name)
            code = SUCCESS if st is None or st.ok else st.code
            self._observe_plugin(p, "Reserve", code, t0)
            if st is not None and not st.ok:
                self._observe_point("Reserve", st.code, point_t0)
                return Status(st.code, st.reason, st.plugin or p.name)
        self._observe_point("Reserve", SUCCESS, point_t0)
        return Status()

    def run_unreserve(self, handle, pod, node_name) -> None:
        """RunReservePluginsUnreserve: reverse order, best-effort."""
        point_t0 = time.perf_counter()
        for p in reversed(self.reserve_plugins):
            t0 = time.perf_counter()
            try:
                p.unreserve(handle, pod, node_name)
                self._observe_plugin(p, "Unreserve", SUCCESS, t0)
            except Exception:
                self._observe_plugin(p, "Unreserve", ERROR, t0)
        self._observe_point("Unreserve", SUCCESS, point_t0)

    def run_permit(
        self, handle, pod, node_name, now: float
    ) -> tuple[Status, set[str], float]:
        """RunPermitPlugins: returns (status, waiting plugin names,
        deadline). A WAIT from any plugin wins over successes; any
        rejection wins over everything."""
        point_t0 = time.perf_counter()
        waiting: set[str] = set()
        deadline = 0.0
        for p in self.permit_plugins:
            t0 = time.perf_counter()
            try:
                st, timeout = p.permit(handle, pod, node_name)
            except Exception as e:
                self._observe_plugin(p, "Permit", ERROR, t0)
                self._observe_point("Permit", ERROR, point_t0)
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name), set(), 0.0
            code = SUCCESS if st is None or st.ok else st.code
            self._observe_plugin(p, "Permit", code, t0)
            if st is None or st.ok:
                continue
            if st.code == WAIT:
                waiting.add(p.name)
                dl = now + max(timeout, 0.0)
                deadline = dl if deadline == 0.0 else min(deadline, dl)
            else:
                self._observe_point("Permit", st.code, point_t0)
                return Status(st.code, st.reason, st.plugin or p.name), set(), 0.0
        if waiting:
            self._observe_point("Permit", WAIT, point_t0)
            return Status(WAIT, "waiting on permit"), waiting, deadline
        self._observe_point("Permit", SUCCESS, point_t0)
        return Status(), set(), 0.0

    def run_pre_bind(self, handle, pod, node_name) -> Status:
        point_t0 = time.perf_counter()
        for p in self.pre_bind_plugins:
            t0 = time.perf_counter()
            try:
                st = p.pre_bind(handle, pod, node_name)
            except Exception as e:
                self._observe_plugin(p, "PreBind", ERROR, t0)
                self._observe_point("PreBind", ERROR, point_t0)
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name)
            code = SUCCESS if st is None or st.ok else st.code
            self._observe_plugin(p, "PreBind", code, t0)
            if st is not None and not st.ok:
                self._observe_point("PreBind", st.code, point_t0)
                return Status(st.code, st.reason, st.plugin or p.name)
        self._observe_point("PreBind", SUCCESS, point_t0)
        return Status()

    def run_post_bind(self, handle, pod, node_name) -> None:
        point_t0 = time.perf_counter()
        for p in self.post_bind_plugins:
            t0 = time.perf_counter()
            try:
                p.post_bind(handle, pod, node_name)
                self._observe_plugin(p, "PostBind", SUCCESS, t0)
            except Exception:
                self._observe_plugin(p, "PostBind", ERROR, t0)
        self._observe_point("PostBind", SUCCESS, point_t0)


PluginFactory = Callable[..., LifecyclePlugin]


class Registry:
    """Name-keyed plugin factory registry (plugins/registry.go:50 +
    app.WithPlugin out-of-tree merge). Factories take the profile as their
    single argument."""

    def __init__(self) -> None:
        self._factories: dict[str, PluginFactory] = {}

    def register(self, name: str, factory: PluginFactory) -> None:
        if name in self._factories:
            raise ValueError(f"a plugin named {name!r} already exists")
        self._factories[name] = factory

    def merge(self, other: "Registry") -> None:
        for name, factory in other._factories.items():
            self.register(name, factory)

    def names(self) -> list[str]:
        return sorted(self._factories)

    def build(
        self, names: list[str], profile, metrics=None
    ) -> LifecycleRunner:
        plugins: list[LifecyclePlugin] = []
        for name in names:
            factory = self._factories.get(name)
            if factory is None:
                raise KeyError(
                    f"lifecycle plugin {name!r} is not registered "
                    f"(known: {self.names()})"
                )
            plugin = factory(profile)
            plugin.name = name
            plugins.append(plugin)
        return LifecycleRunner(
            plugins, metrics=metrics,
            profile=getattr(profile, "name", ""),
        )


def default_registry() -> Registry:
    """In-tree lifecycle plugins (NewInTreeRegistry analog)."""
    from .dynamicresources import DynamicResourcesPlugin
    from .volumebinding import VolumeBindingPlugin

    reg = Registry()
    reg.register("VolumeBinding", VolumeBindingPlugin)
    reg.register("DynamicResources", DynamicResourcesPlugin)
    return reg
