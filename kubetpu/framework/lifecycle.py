"""Host-side lifecycle extension points: Reserve / Permit / PreBind /
PostBind, waiting pods, and the pluggable registry.

Reference surfaces:
- ReservePlugin (staging/src/k8s.io/kube-scheduler/framework/interface.go:636):
  ``Reserve`` runs after assume, in order; on any failure every Reserve
  plugin's ``Unreserve`` runs in REVERSE order and the pod is rejected.
- PermitPlugin (interface.go:680): approve / reject / wait-with-timeout;
  waiting pods are held before binding (WaitingPod, Allow/Reject per
  plugin; frameworkImpl.WaitOnPermit). Timeout ⇒ rejection.
- PreBindPlugin (interface.go:652): runs in the binding cycle just before
  the bind API call (VolumeBinding does its PV/PVC API writes here); a
  failure fails the binding cycle → Unreserve + requeue.
- PostBindPlugin (interface.go:669): informational, after a successful bind.
- Registry (pkg/scheduler/framework/plugins/registry.go:50): name → factory;
  profiles enable plugins by name, out-of-tree plugins register the same
  way (the reference's app.WithPlugin / frameworkplugins.NewInTreeRegistry
  merge).

One plugin object may implement any subset of the four points (reference
plugins implement multiple interfaces); the runner inspects which methods
are overridden.

These points are HOST-side by design: the tensor path (Filter/Score) stays
on device, while Reserve/Permit/PreBind are control-flow around binding —
exactly the reference's split between the scheduling cycle's compute and
the binding cycle's I/O.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ..api import types as t

# Status codes (fwk.Status)
SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
WAIT = "Wait"
ERROR = "Error"


@dataclass(frozen=True)
class Status:
    code: str = SUCCESS
    reason: str = ""
    plugin: str = ""

    @property
    def ok(self) -> bool:
        return self.code == SUCCESS


class LifecyclePlugin:
    """Base for host-side lifecycle plugins. Override any subset of the
    four extension-point methods; un-overridden points are skipped (the
    runner checks method identity, so a subclass pays only for what it
    implements)."""

    name = "LifecyclePlugin"

    # Reserve (interface.go:636). Return a non-ok Status to reject.
    def reserve(self, handle: Any, pod: t.Pod, node_name: str) -> Status:
        return Status()

    def unreserve(self, handle: Any, pod: t.Pod, node_name: str) -> None:
        pass

    # Permit (interface.go:680). Return (Status, timeout_seconds); a WAIT
    # status parks the pod as a waiting pod until every waiting plugin
    # allows it, rejects it, or the smallest timeout fires.
    def permit(
        self, handle: Any, pod: t.Pod, node_name: str
    ) -> tuple[Status, float]:
        return Status(), 0.0

    # PreBind (interface.go:652) — runs in the (async) binding cycle.
    def pre_bind(self, handle: Any, pod: t.Pod, node_name: str) -> Status:
        return Status()

    # PostBind (interface.go:669) — informational.
    def post_bind(self, handle: Any, pod: t.Pod, node_name: str) -> None:
        pass


def _overrides(plugin: LifecyclePlugin, method: str) -> bool:
    return getattr(type(plugin), method) is not getattr(LifecyclePlugin, method)


@dataclass
class WaitingPod:
    """fwk.WaitingPod: a permitted-with-Wait pod parked before binding.
    ``pending`` holds the plugins still waiting; ``Allow``/``Reject`` are
    the per-plugin verdicts (frameworkImpl.waitingPodsMap semantics)."""

    pod: t.Pod
    node_name: str
    info: Any                     # QueuedPodInfo riding through binding
    pending: set[str] = field(default_factory=set)
    deadline: float = 0.0
    rejected: Status | None = None

    def allow(self, plugin: str) -> None:
        self.pending.discard(plugin)

    def reject(self, plugin: str, reason: str = "") -> None:
        self.rejected = Status(UNSCHEDULABLE, reason or "rejected", plugin)

    @property
    def decided(self) -> bool:
        return self.rejected is not None or not self.pending


class LifecycleRunner:
    """Orders and runs the four extension points for one profile."""

    def __init__(self, plugins: list[LifecyclePlugin]) -> None:
        self.reserve_plugins = [p for p in plugins if _overrides(p, "reserve")
                                or _overrides(p, "unreserve")]
        self.permit_plugins = [p for p in plugins if _overrides(p, "permit")]
        self.pre_bind_plugins = [p for p in plugins if _overrides(p, "pre_bind")]
        self.post_bind_plugins = [p for p in plugins if _overrides(p, "post_bind")]

    def __bool__(self) -> bool:
        return bool(
            self.reserve_plugins or self.permit_plugins
            or self.pre_bind_plugins or self.post_bind_plugins
        )

    def run_reserve(self, handle, pod, node_name) -> Status:
        """RunReservePluginsReserve (framework.go): first failure wins; the
        CALLER must then run_unreserve (the reference unreserves all
        plugins, including ones never reserved — Unreserve must be
        idempotent)."""
        for p in self.reserve_plugins:
            try:
                st = p.reserve(handle, pod, node_name)
            except Exception as e:  # plugin bug → Error status
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name)
            if st is not None and not st.ok:
                return Status(st.code, st.reason, st.plugin or p.name)
        return Status()

    def run_unreserve(self, handle, pod, node_name) -> None:
        """RunReservePluginsUnreserve: reverse order, best-effort."""
        for p in reversed(self.reserve_plugins):
            try:
                p.unreserve(handle, pod, node_name)
            except Exception:
                pass

    def run_permit(
        self, handle, pod, node_name, now: float
    ) -> tuple[Status, set[str], float]:
        """RunPermitPlugins: returns (status, waiting plugin names,
        deadline). A WAIT from any plugin wins over successes; any
        rejection wins over everything."""
        waiting: set[str] = set()
        deadline = 0.0
        for p in self.permit_plugins:
            try:
                st, timeout = p.permit(handle, pod, node_name)
            except Exception as e:
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name), set(), 0.0
            if st is None or st.ok:
                continue
            if st.code == WAIT:
                waiting.add(p.name)
                dl = now + max(timeout, 0.0)
                deadline = dl if deadline == 0.0 else min(deadline, dl)
            else:
                return Status(st.code, st.reason, st.plugin or p.name), set(), 0.0
        if waiting:
            return Status(WAIT, "waiting on permit"), waiting, deadline
        return Status(), set(), 0.0

    def run_pre_bind(self, handle, pod, node_name) -> Status:
        for p in self.pre_bind_plugins:
            try:
                st = p.pre_bind(handle, pod, node_name)
            except Exception as e:
                return Status(ERROR, f"{type(e).__name__}: {e}", p.name)
            if st is not None and not st.ok:
                return Status(st.code, st.reason, st.plugin or p.name)
        return Status()

    def run_post_bind(self, handle, pod, node_name) -> None:
        for p in self.post_bind_plugins:
            try:
                p.post_bind(handle, pod, node_name)
            except Exception:
                pass


PluginFactory = Callable[..., LifecyclePlugin]


class Registry:
    """Name-keyed plugin factory registry (plugins/registry.go:50 +
    app.WithPlugin out-of-tree merge). Factories take the profile as their
    single argument."""

    def __init__(self) -> None:
        self._factories: dict[str, PluginFactory] = {}

    def register(self, name: str, factory: PluginFactory) -> None:
        if name in self._factories:
            raise ValueError(f"a plugin named {name!r} already exists")
        self._factories[name] = factory

    def merge(self, other: "Registry") -> None:
        for name, factory in other._factories.items():
            self.register(name, factory)

    def names(self) -> list[str]:
        return sorted(self._factories)

    def build(self, names: list[str], profile) -> LifecycleRunner:
        plugins: list[LifecyclePlugin] = []
        for name in names:
            factory = self._factories.get(name)
            if factory is None:
                raise KeyError(
                    f"lifecycle plugin {name!r} is not registered "
                    f"(known: {self.names()})"
                )
            plugin = factory(profile)
            plugin.name = name
            plugins.append(plugin)
        return LifecycleRunner(plugins)


def default_registry() -> Registry:
    """In-tree lifecycle plugins (NewInTreeRegistry analog)."""
    from .dynamicresources import DynamicResourcesPlugin
    from .volumebinding import VolumeBindingPlugin

    reg = Registry()
    reg.register("VolumeBinding", VolumeBindingPlugin)
    reg.register("DynamicResources", DynamicResourcesPlugin)
    return reg
