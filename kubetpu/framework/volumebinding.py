"""VolumeBinding's Reserve / PreBind half as a lifecycle plugin.

Reference: pkg/scheduler/framework/plugins/volumebinding/volume_binding.go —
``Reserve`` (:521) runs AssumePodVolumes: pick concrete PVs for the pod's
unbound WaitForFirstConsumer claims on the chosen node (the binder's
findMatchingVolumes smallest-fit) and assume the binding in cache;
``Unreserve`` (:594) reverts the assumption; ``PreBind`` (:567) issues the
API writes that actually bind the claims (BindPodVolumes) before the pod
binds. The Filter half lives in the encoder's static volume masks
(state/volumes.py).

The assumed PVC→PV bindings are written into the scheduler's CACHE volume
listers (the reference assumes into its PV cache the same way), so later
cycles' Filter masks see claimed PVs as taken; the informer's eventual
PVC/PV updates confirm them.
"""

from __future__ import annotations

from ..api import types as t
from ..state.volumes import VolumeState, node_affinity_matches
from . import lifecycle as lc


class VolumeBindingPlugin(lc.LifecyclePlugin):
    """Reserve/Unreserve/PreBind for WaitForFirstConsumer claims."""

    name = "VolumeBinding"

    def __init__(self, profile=None) -> None:
        # pod key -> [(pvc, pv_name)] assumed at Reserve
        self._assumed: dict[str, list[tuple[t.PersistentVolumeClaim, str]]] = {}

    # -- Reserve (volume_binding.go:521 AssumePodVolumes) -----------------
    def reserve(self, handle, pod: t.Pod, node_name: str) -> lc.Status:
        # FAST PATH: Reserve runs for EVERY scheduled pod — a pod without
        # PVC volumes must cost O(1) here, not a snapshot refresh (that
        # regression turned every cycle into O(batch × nodes))
        if not any(v.pvc_name for v in pod.volumes):
            return lc.Status()
        import dataclasses

        # the live cache IS the lister view (single-owner loop); no
        # snapshot refresh needed for per-pod reserve decisions
        cache = handle.cache
        vs = VolumeState(cache)
        node_info = cache.get_node_info(node_name)
        labels = node_info.node.labels_dict() if node_info else {}
        picks: list[tuple[t.PersistentVolumeClaim, str]] = []
        taken: set[str] = set()   # PVs chosen for EARLIER claims of this pod

        def fail(reason: str) -> lc.Status:
            # revert the picks already applied (AssumePodVolumes reverts on
            # failure — a half-reserved pod must leak nothing)
            for pvc_, pv_name in picks:
                pv_ = cache.pvs.get(pv_name)
                if pv_ is not None:
                    cache.update_pv(dataclasses.replace(pv_, claim_ref=""))
                cache.update_pvc(pvc_)   # original unbound object
            return lc.Status(lc.UNSCHEDULABLE, reason, self.name)

        for vol in pod.volumes:
            if not vol.pvc_name:
                continue
            pvc = cache.pvcs.get(f"{pod.namespace}/{vol.pvc_name}")
            if pvc is None:
                return fail("claim disappeared")
            if pvc.volume_name:
                continue   # already bound
            sc = cache.storage_classes.get(pvc.storage_class)
            if sc is None or sc.binding_mode != t.BINDING_WAIT_FOR_FIRST_CONSUMER:
                return fail("claim not bindable here")
            chosen = ""
            for pv in vs.available_pvs_for(pvc):
                if pv.name in taken:
                    continue   # chosen for an earlier claim of this pod
                if node_affinity_matches(pv.node_affinity, labels, node_name):
                    chosen = pv.name
                    break
            if not chosen:
                if sc.provisioner and sc.provisioner != t.NO_PROVISIONER:
                    continue   # dynamic provisioning handles it at PreBind
                return fail("no matching PersistentVolume on node")
            picks.append((pvc, chosen))
            taken.add(chosen)
            # assume: mark the PV claimed and the PVC bound in the cache's
            # lister view so this cycle's later pods (and later cycles)
            # don't double-book it
            pv = cache.pvs[chosen]
            cache.update_pv(dataclasses.replace(pv, claim_ref=pvc.key))
            cache.update_pvc(dataclasses.replace(pvc, volume_name=chosen))
        if picks:
            self._assumed[f"{pod.namespace}/{pod.name}"] = picks
        return lc.Status()

    def unreserve(self, handle, pod: t.Pod, node_name: str) -> None:
        """RevertAssumedPodVolumes (:594)."""
        import dataclasses

        picks = self._assumed.pop(f"{pod.namespace}/{pod.name}", None)
        if not picks:
            return
        cache = handle.cache
        for pvc, pv_name in picks:
            pv = cache.pvs.get(pv_name)
            if pv is not None and pv.claim_ref == pvc.key:
                cache.update_pv(dataclasses.replace(pv, claim_ref=""))
            cur = cache.pvcs.get(pvc.key)
            if cur is not None and cur.volume_name == pv_name:
                cache.update_pvc(dataclasses.replace(cur, volume_name=""))

    # -- PreBind (volume_binding.go:567 BindPodVolumes) --------------------
    def pre_bind(self, handle, pod: t.Pod, node_name: str) -> lc.Status:
        picks = self._assumed.pop(f"{pod.namespace}/{pod.name}", None)
        if not picks:
            return lc.Status()
        client = handle.dispatcher.client
        bind_pvc = getattr(client, "bind_pvc", None)
        for pvc, pv_name in picks:
            if bind_pvc is not None:
                # the API write (PATCH pvc.spec.volumeName + pv.claimRef)
                bind_pvc(pvc, pv_name)
            # the cache already holds the assumed binding from Reserve; the
            # informer's PVC/PV updates will re-deliver the bound objects
        return lc.Status()


def register(registry: lc.Registry) -> None:
    registry.register("VolumeBinding", VolumeBindingPlugin)
