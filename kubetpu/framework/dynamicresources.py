"""DynamicResources lifecycle half: Reserve / Unreserve / PreBind.

Reference: pkg/scheduler/framework/plugins/dynamicresources/
dynamicresources.go — Reserve allocates devices in-memory (:1146),
Unreserve rolls the in-memory allocation back and drops the pod's
reservation (:1255), PreBind writes claim status through the API (:1334
bindClaim: allocation + reservedFor entry).

The device-side Filter already enforced feasibility (dense pool columns are
capacity-coupled by the assignment engine; host-path specs carried an exact
feasibility mask), so Reserve's exact re-allocation against the live cache
is the *authoritative* check: a pod that lost an in-batch race on a
host-path claim fails here, is forgotten, and requeues — the reference's
assume-then-fail convergence.
"""

from __future__ import annotations

from ..api import types as t
from . import lifecycle as lc


class DynamicResourcesPlugin(lc.LifecyclePlugin):
    name = "DynamicResources"

    def __init__(self, profile=None) -> None:
        # "ns/name" of pod -> (claim keys WE allocated, all claim keys)
        self._assumed: dict[str, tuple[list[str], list[str]]] = {}

    # ------------------------------------------------------------- Reserve
    def reserve(self, handle, pod: t.Pod, node_name: str) -> lc.Status:
        # FAST PATH: Reserve runs for every scheduled pod — claimless pods
        # must cost O(1) here
        if not pod.resource_claims:
            return lc.Status()
        index = handle.cache.dra
        keys = [
            f"{pod.namespace}/{rc.claim_name}"
            for rc in pod.resource_claims if rc.claim_name
        ]
        to_allocate: list[t.ResourceClaim] = []
        shared: list[str] = []
        for key in keys:
            claim = index.claims.get(key)
            if claim is None:
                return lc.Status(
                    lc.UNSCHEDULABLE, f"resourceclaim {key} not found",
                    self.name,
                )
            if claim.allocation is not None:
                pinned = claim.allocation.node_name
                if pinned and pinned != node_name:
                    return lc.Status(
                        lc.UNSCHEDULABLE,
                        f"resourceclaim {key} allocated for node {pinned}",
                        self.name,
                    )
                if (
                    pod.uid not in claim.reserved_for
                    and len(claim.reserved_for) >= t.RESERVED_FOR_MAX
                ):
                    return lc.Status(
                        lc.UNSCHEDULABLE,
                        f"resourceclaim {key} reservedFor is full",
                        self.name,
                    )
                shared.append(key)
            else:
                to_allocate.append(claim)
        allocated: list[str] = []
        if to_allocate:
            labels = self._node_labels(handle, node_name)
            allocs = index.allocate_on_node(to_allocate, node_name, labels)
            if allocs is None:
                # lost an in-batch race (or the world moved): forget + requeue
                return lc.Status(
                    lc.UNSCHEDULABLE,
                    f"cannot allocate devices on node {node_name}",
                    self.name,
                )
            for claim, alloc in zip(to_allocate, allocs):
                index.set_allocation(claim.key, alloc, pod.uid)
                allocated.append(claim.key)
        for key in shared:
            index.add_reserved(key, pod.uid)
        self._assumed[f"{pod.namespace}/{pod.name}"] = (allocated, keys)
        if allocated:
            # the in-memory allocation is what the claim informer will echo
            # after PreBind's status write; pods rejected THIS cycle (e.g. a
            # co-batched sharer of the same claim) must see the transition,
            # so fire the claim event now — the queue's in-flight replay
            # delivers it to pods requeued later in the cycle
            self._fire_claim_events(handle, allocated)
        return lc.Status()

    @staticmethod
    def _fire_claim_events(handle, keys) -> None:
        from ..queue.events import ActionType, ClusterEvent, EventResource

        index = handle.cache.dra
        for key in keys:
            handle.queue.on_event(
                ClusterEvent(EventResource.RESOURCE_CLAIM, ActionType.UPDATE),
                None, index.claims.get(key),
            )

    @staticmethod
    def _node_labels(handle, node_name: str) -> dict:
        info = handle.cache.get_node_info(node_name)
        if info is None:
            return {}
        return info.node.labels_dict()

    # ----------------------------------------------------------- Unreserve
    def unreserve(self, handle, pod: t.Pod, node_name: str) -> None:
        entry = self._assumed.pop(f"{pod.namespace}/{pod.name}", None)
        if entry is None:
            return
        allocated, keys = entry
        index = handle.cache.dra
        released = []
        for key in allocated:
            # deallocate ONLY when no co-batched sharer still reserves the
            # claim (release_claim keeps the allocation alive for them)
            if index.release_claim(key, pod.uid):
                released.append(key)
        for key in keys:
            if key not in allocated:
                index.remove_reserved(key, pod.uid)
        if released:
            # deallocation freed devices — wake parked claimants
            self._fire_claim_events(handle, released)

    # ------------------------------------------------------------- PreBind
    def pre_bind(self, handle, pod: t.Pod, node_name: str) -> lc.Status:
        # the entry stays until PostBind: a bind failure AFTER PreBind must
        # still find it so Unreserve can roll the allocation back
        # (bindingCycle's deferred unreserve, schedule_one.go:391)
        entry = self._assumed.get(f"{pod.namespace}/{pod.name}")
        if entry is None:
            return lc.Status()
        _allocated, keys = entry
        index = handle.cache.dra
        client = handle.dispatcher.client
        update = getattr(client, "update_claim_status", None)
        if update is not None:
            for key in keys:
                claim = index.claims.get(key)
                if claim is not None:
                    # the claim-status API write (bindClaim :1478): the
                    # allocation + the pod's reservedFor entry land together
                    update(claim)
        return lc.Status()

    # ------------------------------------------------------------ PostBind
    def post_bind(self, handle, pod: t.Pod, node_name: str) -> None:
        # the bind landed: the allocation is permanent, drop the rollback
        # record (Unreserve after this point must not deallocate)
        self._assumed.pop(f"{pod.namespace}/{pod.name}", None)
