"""Framework — plugin composition, profiles, configuration.

The analog of ``pkg/scheduler/framework/runtime`` + ``pkg/scheduler/apis/config``.
"""

from . import config  # noqa: F401
from .config import Profile, SchedulerConfiguration, minimal_profile  # noqa: F401
from .lifecycle import (  # noqa: F401
    LifecyclePlugin,
    LifecycleRunner,
    Registry,
    Status,
    WaitingPod,
    default_registry,
)
from .runtime import (  # noqa: F401
    DeviceBatch,
    DeviceNodeState,
    EncodedBatch,
    ResidentNodeState,
    ScoreParams,
    encode_batch,
    encode_batch_static,
    filter_score_batch,
    finalize_batch,
    score_params,
)
