"""Framework — plugin composition, profiles, configuration.

The analog of ``pkg/scheduler/framework/runtime`` + ``pkg/scheduler/apis/config``.
"""

from . import config  # noqa: F401
from .config import Profile, SchedulerConfiguration, minimal_profile  # noqa: F401
from .lifecycle import (  # noqa: F401
    LifecyclePlugin,
    LifecycleRunner,
    Registry,
    Status,
    WaitingPod,
    default_registry,
)
from .runtime import (  # noqa: F401
    DeviceBatch,
    EncodedBatch,
    ScoreParams,
    encode_batch,
    filter_score_batch,
    score_params,
)
