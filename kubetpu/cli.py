"""The kubetpu command line — the cmd/kube-scheduler analog (layer 9).

Reference: cmd/kube-scheduler/app/server.go:93 (``NewSchedulerCommand`` →
``runCommand`` → ``Setup``/``Run``): parse a versioned
KubeSchedulerConfiguration file, build the scheduler, serve healthz +
metrics + configz, optionally leader-elect. Here the serving surface is the
extender webhook bridge (``kubetpu.bridge.server``) — the integration seam
a real kube-scheduler offloads Filter/Prioritize/Bind through — with the
same side endpoints (/healthz, /metrics, /configz).

Commands (the control-plane binaries + tooling):
- ``apiserver``           REST+watch object API over the in-memory store
- ``scheduler``           the scheduler against a remote API server
- ``controller-manager``  the controller family against a remote API server
- ``kubelet``             a hollow node agent (kubemark tier)
- ``serve``               the extender webhook bridge from a config file
- ``get`` / ``apply`` / ``delete``   kubectl-style object access
- ``check-config``        decode + validate a config file, loudly
- ``perf``                the scheduler_perf harness (kubetpu.perf)
- ``explain``             render a pod's scheduling flight-recorder record
                          (timeline + why-node-won / why-filtered) from a
                          scheduler's /debug/flightrecorder or a JSON dump
- ``benchdiff``           compare two bench records with noise-aware
                          thresholds; non-zero exit on regression
- ``store fsck|compact``  durable-store tooling: offline integrity report /
                          WAL-into-snapshot compaction for a persistence dir
- ``version``             print the framework version
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Any, Sequence


def _config_to_dict(obj: Any) -> Any:
    """Dataclass → plain JSON for /configz (live-config introspection)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _config_to_dict(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [_config_to_dict(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _config_to_dict(v) for k, v in obj.items()}
    return obj


def cmd_check_config(args) -> int:
    from .framework.configload import ConfigError, load_config

    try:
        cfg = load_config(args.config)
    except (ConfigError, OSError) as e:
        print(f"invalid: {e}", file=sys.stderr)
        return 1
    names = ", ".join(p.name for p in cfg.profiles)
    print(
        f"ok: {len(cfg.profiles)} profile(s) [{names}], "
        f"{len(cfg.extenders)} extender(s)"
    )
    return 0


def cmd_serve(args) -> int:
    from .bridge.server import ExtenderBackend, ExtenderServer
    from .framework import config as C
    from .framework.configload import ConfigError, load_config

    if args.config:
        try:
            cfg = load_config(args.config)
        except (ConfigError, OSError) as e:
            print(f"invalid config: {e}", file=sys.stderr)
            return 1
    else:
        cfg = C.SchedulerConfiguration()
    try:
        profile = cfg.profile(args.profile)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 1
    backend = ExtenderBackend(profile=profile)
    backend.configz_source = lambda: _config_to_dict(cfg)
    server = ExtenderServer(backend, host=args.host, port=args.port).start()
    print(f"kubetpu extender bridge serving on {server.url} "
          f"(profile {profile.name!r}; verbs: /filter /prioritize /bind "
          f"/preempt; /cache/nodes /cache/pods; /healthz /metrics /configz)",
          flush=True)
    try:
        import threading

        threading.Event().wait()   # serve until interrupted
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


def _make_exporter(telemetry: str, process: str, component: str,
                   replica: str = "", tracer=None, metrics_fn=None,
                   flight_fn=None, alerts_fn=None, bundles_fn=None,
                   embedded_collector=None):
    """One component's telemetry exporter from its ``--telemetry`` flag:
    "off" → None (byte-identical wire, zero export work), "embed" → the
    in-process collector transport, a URL → HTTP export to a remote
    collector. Started on its cadence thread."""
    if not telemetry or telemetry == "off":
        return None
    from .telemetry.exporter import EmbeddedCollectorClient, TelemetryExporter

    client = None
    url = telemetry
    if telemetry == "embed":
        if embedded_collector is None:
            raise ValueError("--telemetry embed needs an embedded collector")
        client = EmbeddedCollectorClient(embedded_collector)
        url = ""
    return TelemetryExporter(
        url, process=process, component=component, replica=replica,
        tracer=tracer, metrics_fn=metrics_fn, flight_fn=flight_fn,
        alerts_fn=alerts_fn, bundles_fn=bundles_fn,
        client=client,
    ).start()


def _install_stop_event():
    """SIGTERM/SIGINT → a threading.Event. SIGTERM matters — the launch
    supervisor's shutdown cascade is TERM-based, and a default-action TERM
    would skip the ``finally`` blocks that close exporters and (for the
    apiserver) flush+close the WAL through the PR-11 graceful path.
    Falls back to an unarmed event when handlers cannot be installed
    (non-main thread — in-process tests; ^C still raises there)."""
    import signal
    import threading

    stop = threading.Event()

    def _stop(_signum, _frame) -> None:
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _stop)
        signal.signal(signal.SIGINT, _stop)
    except ValueError:
        pass
    return stop


def _serve_until_signal(stop=None) -> None:
    """Serve-loop park for the no-work commands (apiserver, collector,
    watch-driver): block until SIGTERM/SIGINT. Pass a pre-installed
    ``stop`` event (``_install_stop_event()`` called BEFORE the serving
    work began) so a TERM arriving during startup is never lost to the
    default disposition."""
    try:
        (stop if stop is not None else _install_stop_event()).wait()
    except KeyboardInterrupt:
        pass


def _attach_alert_sink(sentinel, args) -> str:
    """Bind ``--alert-sink`` to a live sentinel. Returns an error string
    (caller prints + exits non-zero) instead of raising — a bad sink
    spec is an operator typo, not a traceback."""
    spec = getattr(args, "alert_sink", "") or ""
    if not spec:
        return ""
    if sentinel is None:
        return "--alert-sink requires --sentinel on"
    from .telemetry.sentinel import AlertSink

    try:
        sentinel.sink = AlertSink(spec)
    except ValueError as e:
        return str(e)
    return ""


def cmd_apiserver(args) -> int:
    import os

    from .apiserver import APIServer, Registry
    from .store import MemStore
    from .store.wal import WALError
    from .controllers import install_quota_admission

    # handlers BEFORE any serving work: a supervisor TERM that lands
    # mid-startup must still run the graceful close, not the default kill
    stop = _install_stop_event()
    persistence = getattr(args, "persistence", "off")
    follow = getattr(args, "follow", "")
    replicated = bool(getattr(args, "replicated", False))
    if getattr(args, "replicate_from", "") and not follow:
        print("apiserver: --replicate-from requires --follow "
              "(the chain carries a follower's feed)", file=sys.stderr)
        return 2
    if follow and persistence != "off":
        # a follower's WAL is the leader's — local persistence on a
        # replica would fork the durability story, so refuse it early
        print("apiserver: --follow ignores --persistence "
              "(the leader owns the WAL)", file=sys.stderr)
        persistence = "off"
    try:
        store = MemStore(
            persistence=None if persistence == "off" else persistence,
            follower=bool(follow),
        )
    except WALError as e:
        # a corrupt persistence dir must fail LOUDLY at boot, never boot
        # an empty cluster over a recoverable one — `kubetpu store fsck`
        # diagnoses, deleting the dir is the explicit full-resync choice
        print(f"persistence dir unrecoverable: {e}", file=sys.stderr)
        return 1
    registry = Registry()
    # quota enforcement is admission-time (the reference's resourcequota
    # admission plugin): pod creates past a namespace's hard caps get 403;
    # the install also takes the per-namespace write lock so concurrent
    # creates cannot race past hard
    install_quota_admission(registry, store)
    telemetry = getattr(args, "telemetry", "off")
    server = APIServer(
        store, host=args.host, port=args.port, registry=registry,
        wire=getattr(args, "wire", "binary"),
        collector=(telemetry == "embed"),
        sentinel=(getattr(args, "sentinel", "off") == "on"),
    )
    sink_err = _attach_alert_sink(server.sentinel, args)
    if sink_err:
        server.close()
        store.close()
        print(sink_err, file=sys.stderr)
        return 2
    # replication binds AFTER the listener exists (the lease identity /
    # advertised self URL is this server's own address) but BEFORE
    # start() — the first request served must already know its role
    peers = tuple(
        p.strip().rstrip("/")
        for p in (getattr(args, "peers", "") or "").split(",") if p.strip()
    )
    lease_s = float(getattr(args, "lease_duration", 5.0) or 5.0)
    if follow:
        from .store.replication import FollowerReplicator

        server.attach_replication(FollowerReplicator(
            store, follow, wire=getattr(args, "wire", "binary"),
            self_url=server.url, peers=peers,
            replica_index=int(getattr(args, "replica_index", 0) or 0),
            lease_duration_s=lease_s,
            # the election grace scales with the lease so a short-lease
            # plane fails over proportionally fast (at the 5s default
            # this is exactly the replicator's own 6s default)
            grace_s=1.2 * lease_s,
            upstream_url=getattr(args, "replicate_from", "") or "",
        ))
    elif replicated:
        from .store.replication import LeaderLease

        server.attach_replication(
            LeaderLease(store, server.url, lease_duration_s=lease_s)
        )
    server.start()
    exporter = _make_exporter(
        telemetry, process=f"apiserver-{os.getpid()}",
        component="apiserver", tracer=server.tracer,
        metrics_fn=server.metrics_text,
        alerts_fn=(
            server.sentinel.alerts_json if server.sentinel is not None
            else None
        ),
        bundles_fn=(
            server.sentinel.bundles_payload if server.sentinel is not None
            else None
        ),
        embedded_collector=server.collector,
    )
    recovered = ""
    if store.recovery_info is not None:
        ri = store.recovery_info
        recovered = (
            f"; recovered rv {ri.resource_version} "
            f"(snapshot {ri.snapshot_objects} objects @ rv "
            f"{ri.snapshot_rv} + {ri.replayed} replayed"
            + (f", torn tail truncated {ri.truncated_bytes}B"
               if ri.truncated_bytes else "")
            + ")"
        )
    # the machine-readable readiness banner FIRST (one line, the launch
    # supervisor's contract — --port 0 publishes the real address here),
    # then the human serving line
    from .launch.banner import emit_banner

    banner_fields = dict(
        url=server.url, readyz=server.url + "/readyz",
        wire=getattr(args, "wire", "binary"),
        persistence=("" if persistence == "off" else persistence),
        telemetry=telemetry,
    )
    if server.replication is not None:
        banner_fields["role"] = server.replication.role
        if follow:
            banner_fields["leader"] = follow
    emit_banner("apiserver", **banner_fields)
    print(f"kubetpu apiserver serving on {server.url} "
          f"(REST: /apis/<kind>[/<key>], watch: ?watch=1&resourceVersion=N; "
          f"diagnostics: /metrics /healthz /readyz /livez /trace"
          + ("; telemetry collector embedded at /telemetry/"
             if telemetry == "embed" else "")
          + (f"; replication: {server.replication.role}"
             + (f" following {follow}" if follow else "")
             if server.replication is not None else "")
          + f"{recovered})",
          flush=True)
    try:
        _serve_until_signal(stop)
    finally:
        if exporter is not None:
            exporter.close()
        server.close()
        # the store is OURS (passed in, so server.close leaves it alone):
        # flush + close the WAL after the listener stops — a graceful
        # stop never leaves a torn tail
        store.close()
    return 0


def cmd_collector(args) -> int:
    """``kubetpu collector``: the standalone telemetry sink — span/
    metrics/flight-record ingest at /telemetry/export, the merged chrome
    trace at /telemetry/trace, the federated /metrics view, and the
    ``kubetpu top`` summary at /telemetry/top."""
    from .telemetry.collector import CollectorServer

    from .launch.banner import emit_banner

    stop = _install_stop_event()
    server = CollectorServer(host=args.host, port=args.port).start()
    emit_banner(
        "collector", url=server.url, readyz=server.url + "/readyz",
    )
    print(f"kubetpu collector serving on {server.url} "
          f"(ingest: POST /telemetry/export /telemetry/clock; views: "
          f"/telemetry/trace /telemetry/metrics /telemetry/flightrecorder "
          f"/telemetry/top /telemetry/alerts /telemetry/bundle; "
          f"/healthz /readyz)",
          flush=True)
    try:
        _serve_until_signal(stop)
    finally:
        server.close()
    return 0


def cmd_watch_driver(args) -> int:
    """``kubetpu watch-driver``: N concurrent pod watchers against an
    apiserver, as ONE dedicated process — the unit the mp wire ladder
    spreads its 200-watcher fan-out load over (M driver processes instead
    of 200 threads sharing the measuring process's GIL)."""
    from .launch.banner import emit_banner
    from .perf.runner import _WatchFanout

    stop = _install_stop_event()
    fanout = _WatchFanout(args.server, args.wire, args.watchers)
    emit_banner(
        "watch-driver", server=args.server, watchers=args.watchers,
        wire=args.wire,
    )
    print(f"kubetpu watch-driver: {args.watchers} watcher(s) against "
          f"{args.server} (wire {args.wire})", flush=True)
    try:
        _serve_until_signal(stop)
    finally:
        fanout.stop()
    return 0


def cmd_up(args) -> int:
    """``kubetpu up``: the whole control plane as real OS processes — one
    apiserver + N scheduler replicas (+ optional collector / watch-fanout
    drivers) under the launch supervisor: ephemeral ports published via
    readiness banners, /readyz-polled starts, declarative restart policy,
    SIGTERM-cascade shutdown riding every component's graceful-close
    path. ^C (or a TERM from the caller) tears the whole topology down."""
    from .launch import Cluster, SupervisorError
    from .launch.banner import emit_banner

    # handlers BEFORE the children exist: a TERM landing mid-startup must
    # still cascade — an orphaned control plane is the one unforgivable
    # supervisor failure
    stop = _install_stop_event()
    persistence = args.persistence if args.persistence != "off" else None
    cluster = Cluster(
        replicas=args.replicas,
        apiservers=getattr(args, "apiservers", 1),
        replication_chain=bool(getattr(args, "replication_chain", False)),
        partition=args.partition,
        wire=args.wire,
        engine=args.engine,
        topology=getattr(args, "topology", "off"),
        max_batch=args.max_batch,
        persistence=persistence,
        telemetry=args.telemetry,
        fanout_procs=args.fanout_procs,
        fanout_watchers=args.watch_fanout,
        restart=args.restart,
        prewarm=args.prewarm,
    )
    try:
        cluster.start()
    except (SupervisorError, ValueError) as e:
        print(f"kubetpu up failed: {e}", file=sys.stderr)
        cluster.shutdown()
        return 1
    try:
        fields = dict(apiserver=cluster.api_url, replicas=args.replicas,
                      partition=args.partition, wire=args.wire)
        if len(cluster.api_urls) > 1:
            fields["apiservers"] = len(cluster.api_urls)
            fields["followers"] = ",".join(cluster.api_urls[1:])
        if cluster.collector_url:
            fields["collector"] = cluster.collector_url
        emit_banner("cluster", **fields)
        for child in cluster.supervisor.children:
            url = child.url()
            print(f"  {child.name:<16} pid {child.pid}"
                  + (f"  {url}" if url else ""), flush=True)
        print(f"kubetpu up: {cluster.n_processes()} process(es) ready — "
              f"apiserver {cluster.api_url} "
              f"({args.replicas} replica(s), {args.partition}, "
              f"restart {args.restart}); ^C to stop", flush=True)
        _serve_until_signal(stop)
    finally:
        cluster.shutdown()
    return 0


def _fmt_top_row(name: str, p: dict) -> list[str]:
    def num(key, suffix="", scale=1.0, digits=1):
        v = p.get(key)
        if v is None:
            return "-"
        return f"{v * scale:.{digits}f}{suffix}"

    e2e = (p.get("e2e_stages_ms") or {}).get("e2e") or {}
    return [
        name,
        p.get("component") or "-",
        p.get("replica") or "-",
        num("pods_per_s"),
        str(int(p["queue_depth"])) if "queue_depth" in p else "-",
        num("conflict_rate", "%", scale=100.0, digits=2),
        num("wal_fsync_p99_ms", "ms", digits=2),
        (f"{e2e['p99_ms']:.1f}ms" if e2e.get("p99_ms") is not None else "-"),
        (f"{p['alerts_firing']}!" if p.get("alerts_firing") else "-"),
        num("age_s", "s"),
    ]


def render_top(summary: dict) -> str:
    """The ``kubetpu top`` console body: one row per exporting process
    (pods/s, queue depth, conflict rate, WAL fsync p99, e2e p99, firing
    sentinel alerts) plus the collector's span-drop footer — firing
    alert names print inline under the table."""
    headers = ("PROCESS", "COMPONENT", "REPLICA", "PODS/S", "QUEUE",
               "CONFLICT", "FSYNC-P99", "E2E-P99", "ALERTS", "AGE")
    procs = summary.get("processes") or {}
    rows = [
        _fmt_top_row(name, p) for name, p in sorted(procs.items())
    ]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(c.ljust(w) for c, w in zip(cols, widths)).rstrip()
        for cols in [list(headers), *rows]
    ]
    stages: dict = {}
    for name, p in sorted(procs.items()):
        for stage, v in (p.get("e2e_stages_ms") or {}).items():
            if stage != "e2e":
                stages.setdefault(stage, []).append(v.get("p99_ms") or 0.0)
    if stages:
        from .metrics.scheduler_metrics import E2E_STAGES

        parts = [
            f"{st} {max(stages[st]):.1f}" for st in E2E_STAGES
            if st in stages
        ]
        lines.append("staged p99 (ms, worst process): " + " → ".join(parts))
    for name, p in sorted(procs.items()):
        if p.get("firing_alerts"):
            lines.append(
                f"ALERTS FIRING [{name}]: " + ", ".join(p["firing_alerts"])
            )
    lines.append(
        f"collector: {len(procs)} process(es), "
        f"{summary.get('spans_dropped', 0)} span(s) dropped, "
        f"{summary.get('alerts_firing', 0)} alert(s) firing"
    )
    return "\n".join(lines)


def cmd_top(args) -> int:
    """``kubetpu top``: the live control-plane console — per-process
    pods/s, queue depth, conflict rate, WAL fsync p99 and staged e2e
    percentiles from a collector's /telemetry/top (``-o json`` for
    scripts, ``--watch`` to refresh)."""
    import time as _time
    import urllib.request

    url = args.collector.rstrip("/") + "/telemetry/top"
    while True:
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                summary = json.load(resp)
        except OSError as e:
            print(f"cannot reach {url}: {e}", file=sys.stderr)
            return 2
        if args.output == "json":
            print(json.dumps(summary, indent=2), flush=True)
        else:
            print(render_top(summary), flush=True)
        if not args.watch:
            return 0
        try:
            _time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0
        if args.output != "json":
            print("", flush=True)


def _http_json(url: str):
    """GET one JSON body, or (None, message) on transport failure."""
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10) as resp:
            return json.load(resp), ""
    except OSError as e:
        return None, f"cannot reach {url}: {e}"


def render_alerts(body: dict) -> str:
    """The ``kubetpu alerts`` console body — one row per alert, the
    per-process /debug/alerts shape and the collector's merged
    /telemetry/alerts shape both render (the merged rows carry a
    ``processes`` breakdown, the per-process ones a fingerprint)."""
    rows = body.get("alerts") or []
    if not rows:
        return "no alerts (every watched series within budget)"
    headers = ("STATE", "SEVERITY", "RULE", "VALUE", "FIRES", "WHERE")
    table = []
    for a in rows:
        procs = a.get("processes")
        if isinstance(procs, list):
            where = ",".join(
                str(p.get("process") or "?") for p in procs
            )
        else:
            where = str(body.get("process") or "-")
        value = a.get("value")
        table.append([
            str(a.get("state") or "-"),
            str(a.get("severity") or "-"),
            str(a.get("rule") or "-"),
            f"{value:.2f}" if isinstance(value, (int, float)) else "-",
            str(a.get("fires") or 0),
            where,
        ])
    widths = [
        max(len(h), *(len(r[i]) for r in table))
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(c.ljust(w) for c, w in zip(cols, widths)).rstrip()
        for cols in [list(headers), *table]
    ]
    for a in rows:
        if a.get("reason") and a.get("state") != "resolved":
            lines.append(f"  {a.get('rule')}: {a.get('reason')}")
    lines.append(
        f"{body.get('firing', 0)} firing, {body.get('pending', 0)} "
        f"pending, {body.get('resolved', 0)} resolved"
    )
    return "\n".join(lines)


def cmd_alerts(args) -> int:
    """``kubetpu alerts``: the anomaly sentinel's live alert table —
    one process's /debug/alerts (--server, the diagnostics URL) or the
    cluster-wide merge from a collector's /telemetry/alerts."""
    if getattr(args, "collector", ""):
        url = args.collector.rstrip("/") + "/telemetry/alerts"
    else:
        url = args.server.rstrip("/") + "/debug/alerts"
    body, err = _http_json(url)
    if body is None:
        print(err, file=sys.stderr)
        return 2
    if not body.get("enabled", True):
        print("anomaly sentinel is disabled on this process "
              "(--sentinel off)", file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(body, indent=2))
    else:
        print(render_alerts(body))
    return 0


def cmd_bundle(args) -> int:
    """``kubetpu bundle``: triggered diagnostic bundles — summaries
    without --id, the full capture (py stacks, queue snapshot, WAL/cache
    stats, trace slice) with it; --out writes the capture to a file for
    attaching to an incident."""
    import urllib.parse

    if getattr(args, "collector", ""):
        base = args.collector.rstrip("/") + "/telemetry/bundle"
    else:
        base = args.server.rstrip("/") + "/debug/bundle"
    q = {}
    if args.id:
        q["id"] = args.id
    if getattr(args, "process", "") and getattr(args, "collector", ""):
        q["process"] = args.process
    url = base + ("?" + urllib.parse.urlencode(q) if q else "")
    body, err = _http_json(url)
    if body is None:
        print(err, file=sys.stderr)
        return 2
    if not body.get("enabled", True):
        print("anomaly sentinel is disabled on this process "
              "(--sentinel off)", file=sys.stderr)
        return 1
    if args.id:
        bundle = body.get("bundle")
        if bundle is None:
            print(body.get("error") or f"no bundle id {args.id}",
                  file=sys.stderr)
            return 1
        text = json.dumps(bundle, indent=2, default=str)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
            trig = bundle.get("trigger") or {}
            print(f"bundle {bundle.get('id')} "
                  f"({trig.get('rule') or 'manual'}, "
                  f"{len(bundle.get('sections') or {})} section(s), "
                  f"{len((bundle.get('trace') or {}).get('traceEvents') or ())}"
                  f" trace event(s)) -> {args.out}")
        else:
            print(text)
        return 0
    bundles = body.get("bundles") or []
    if args.output == "json":
        print(json.dumps(body, indent=2))
        return 0
    if not bundles:
        print("no diagnostic bundles captured (no alert has fired)")
        return 0
    for b in bundles:
        proc = b.get("process")
        print(f"bundle {b.get('id')}"
              + (f" [{proc}]" if proc else "")
              + f": rule={b.get('rule') or 'manual'} "
              f"severity={b.get('severity') or '-'} "
              f"sections={','.join(b.get('sections') or ())} "
              f"trace_events={b.get('trace_events', 0)}")
    print(f"{len(bundles)} bundle(s); "
          f"--id N for the full capture, --out FILE to save it")
    return 0


def _object_key(obj: Any) -> str:
    """Store key for a typed object: namespace/name when namespaced."""
    key = getattr(obj, "key", None)
    if isinstance(key, str):
        return key
    ns = getattr(obj, "namespace", None)
    name = getattr(obj, "name", None) or getattr(obj, "node_name", None)
    if name is None:
        raise ValueError(f"cannot derive a key for {type(obj).__name__}")
    return f"{ns}/{name}" if ns else str(name)


def _kind_buckets() -> dict:
    """Typed object -> store bucket, built from the SHARED bucket constants
    (one source of truth with the informers/controllers — a literal copy
    here could silently drift into a bucket nothing watches)."""
    from .client import informers as I
    from .controllers.daemonset import DAEMON_SETS
    from .controllers.deployment import DEPLOYMENTS
    from .controllers.job import JOBS
    from .controllers.replicaset import REPLICA_SETS
    from .controllers.resourceclaim import RESOURCE_CLAIM_TEMPLATES
    from .controllers.statefulset import STATEFUL_SETS

    return {
        "ResourceClaimTemplate": RESOURCE_CLAIM_TEMPLATES,
        "Node": I.NODES, "Pod": I.PODS, "ReplicaSet": REPLICA_SETS,
        "Deployment": DEPLOYMENTS, "Job": JOBS,
        "StatefulSet": STATEFUL_SETS, "DaemonSet": DAEMON_SETS,
        "Service": I.SERVICES, "Namespace": I.NAMESPACES,
        "PersistentVolume": I.PERSISTENT_VOLUMES,
        "PersistentVolumeClaim": I.PERSISTENT_VOLUME_CLAIMS,
        "StorageClass": I.STORAGE_CLASSES,
        "PodDisruptionBudget": I.PDBS,
        "PodGroup": I.POD_GROUPS, "DeviceClass": I.DEVICE_CLASSES,
        "ResourceSlice": I.RESOURCE_SLICES,
        "ResourceClaim": I.RESOURCE_CLAIMS,
        "Event": "events", "CronJob": "cronjobs",
        "ResourceQuota": "resourcequotas",
    }


def _retry_start(fn, what: str) -> None:
    """Component startup against a possibly-still-booting apiserver: retry
    transient transport failures forever (the reference components block on
    WaitForCacheSync the same way)."""
    import time

    while True:
        try:
            fn()
            return
        except ConnectionError as e:
            print(f"{what}: apiserver unavailable at startup, retrying: {e}",
                  file=sys.stderr, flush=True)
            time.sleep(2.0)


def _make_loop(run_once, period_s: float = 0.05, stop=None):
    """Component work loop; ``stop`` (an Event from
    ``_install_stop_event``) makes SIGTERM a graceful exit through the
    caller's ``finally`` instead of a mid-cycle kill."""
    import time

    def loop() -> int:
        try:
            while stop is None or not stop.is_set():
                try:
                    run_once()
                except ConnectionError as e:
                    # apiserver unreachable: back off and retry — one
                    # restart must not kill the component
                    print(f"apiserver unavailable, retrying: {e}",
                          file=sys.stderr, flush=True)
                    time.sleep(2.0)
                    continue
                time.sleep(period_s)
        except KeyboardInterrupt:
            pass
        return 0
    return loop


def _maybe_elect(args, store, component: str):
    """Optional --leader-elect wrapper: returns a tick() gate."""
    if not getattr(args, "leader_elect", False):
        return lambda: True
    import os
    import socket
    import uuid

    from .sched.leaderelection import LeaderElector, StoreLeaseClient

    elector = LeaderElector(
        client=StoreLeaseClient(store),
        # hostname + random suffix (client-go's id = hostname + "_" + uuid):
        # a bare PID collides across containers (every replica is PID 1)
        # and two same-identity electors would BOTH take the renew path
        identity=(
            f"{component}-{socket.gethostname()}-{os.getpid()}-"
            f"{uuid.uuid4().hex[:8]}"
        ),
        name=component,
    )
    return elector.tick


def cmd_scheduler(args) -> int:
    """The kube-scheduler binary: informers + batch loop against a remote
    API server (cmd/kube-scheduler/app/server.go Run shape)."""
    from .apiserver import RemoteStore
    from .client import SchedulerInformers, StoreClient
    from .client.events import EventRecorder
    from .framework import config as C
    from .framework.configload import ConfigError, load_config
    from .sched import Scheduler

    try:
        cfg = load_config(args.config) if args.config else C.SchedulerConfiguration()
    except (ConfigError, OSError) as e:
        print(f"invalid config: {e}", file=sys.stderr)
        return 1
    from .parallel.mesh import resolve_mesh

    try:
        mesh = resolve_mesh(args.mesh)
    except ValueError as e:
        # --mesh on with a single visible device is a config error, not a
        # silent single-chip run misreported as multichip
        print(f"invalid --mesh: {e}", file=sys.stderr)
        return 1
    # flag validation BEFORE any real work: --diagnostics-port lost
    # argparse's type=int when it grew the ephemeral/off keywords, so a
    # typo must still die here with a usage error, not mid-startup
    diag_raw = str(getattr(args, "diagnostics_port", "off")).strip()
    if diag_raw not in ("off", "0", "ephemeral", "auto"):
        try:
            int(diag_raw)
        except ValueError:
            print(f"invalid --diagnostics-port {diag_raw!r} "
                  f"(a port number, 'ephemeral', or 'off')",
                  file=sys.stderr)
            return 1
    # handlers BEFORE the (possibly retrying) startup: a supervisor TERM
    # mid-boot must run the graceful teardown, not the default kill
    stop = _install_stop_event()
    telemetry = getattr(args, "telemetry", "off")
    store = RemoteStore(
        args.server, wire=getattr(args, "wire", "binary"),
        # trace-context propagation rides the telemetry switch: off =
        # byte-identical wire (no traceparent header / tp parameter)
        traceparent=(telemetry != "off"),
    )
    # cross-process federation: --partition declares this process one of
    # --replica-count replicas (hash rank / lease fair share / race); the
    # bare --replica-id backcompat stays race mode
    partition = getattr(args, "partition", "")
    membership = None
    if partition:
        from .sched.federation import ReplicaMembership

        try:
            membership = ReplicaMembership(
                store,
                replica_id=args.replica_id or "r0",
                partition=partition,
                replica_count=max(getattr(args, "replica_count", 0) or 1, 1),
                partitions=getattr(args, "partitions", 0) or None,
            )
        except ValueError as e:
            print(f"invalid federation flags: {e}", file=sys.stderr)
            return 1
    client = StoreClient(store)
    if membership is not None:
        client = membership.wrap_client(client)
    sched = Scheduler(
        client, cfg=cfg, engine=args.engine,
        max_batch=getattr(args, "max_batch", 1024),
        pipeline=(args.pipeline == "on"),
        encode_cache=(args.encode_cache == "on"),
        bulk=(args.bulk == "on"),
        mesh=mesh,
        topology=getattr(args, "topology", "off"),
        flight_recorder=(args.flight_recorder == "on"),
        replica_id=args.replica_id,
        federation_mode=(
            partition or ("race" if args.replica_id else "")
        ),
        recorder=EventRecorder(store, "kubetpu-scheduler"),
        sentinel=(getattr(args, "sentinel", "off") == "on"),
    )
    sched.enable_preemption()
    sink_err = _attach_alert_sink(sched.sentinel, args)
    if sink_err:
        print(sink_err, file=sys.stderr)
        return 2
    exporter = None
    if telemetry != "off":
        import os

        store.set_tracer(sched.tracer)  # client rpc spans join server spans
        fr = sched.flight_recorder
        exporter = _make_exporter(
            telemetry,
            process=(
                f"scheduler-{args.replica_id}" if args.replica_id
                else f"scheduler-{os.getpid()}"
            ),
            component="scheduler", replica=args.replica_id,
            tracer=sched.tracer, metrics_fn=sched.metrics_text,
            flight_fn=(
                (lambda: fr.records_json(limit=512))
                if fr is not None else None
            ),
            alerts_fn=(
                sched.sentinel.alerts_json if sched.sentinel is not None
                else None
            ),
            bundles_fn=(
                sched.sentinel.bundles_payload if sched.sentinel is not None
                else None
            ),
        )
    informers = SchedulerInformers(
        store, sched, bulk=(args.bulk == "on"),
        pod_filter=(
            membership.pod_filter() if membership is not None else None
        ),
    )
    _retry_start(informers.start, "scheduler informers")
    if args.prewarm:
        # pay the XLA bucket ladder up front so the first real cycles never
        # stall on compilation (the informers have already synced the node
        # set, so the warmed shapes match the live cluster)
        informers.pump()
        sched.prewarm()
    is_leader = _maybe_elect(args, store, "kube-scheduler")
    # --diagnostics-port: a number, 'off' (no listener), or 'ephemeral'
    # (bind port 0 — the launch supervisor's no-collision default; the
    # real address is published in the readiness banner; validated above)
    diag = None
    if diag_raw not in ("off", "0"):
        from .sched.diagnostics import DiagnosticsServer

        diag_port = 0 if diag_raw in ("ephemeral", "auto") else int(diag_raw)
        try:
            diag = DiagnosticsServer(
                sched, port=diag_port,
                # restart visibility: the client's watch-path reconnect
                # counter rides the scheduler's /metrics page
                metrics_sources=(store.reconnect_metrics_text,),
            )
        except OSError as e:
            # a second scheduler on the host (HA standby) must not die on
            # the diagnostics side port; it just runs unobserved
            print(
                f"diagnostics port {diag_raw} unavailable "
                f"({e}); continuing without the diagnostics listener",
                file=sys.stderr, flush=True,
            )
        else:
            diag.add_informers(informers)
            diag.start()
    # the machine-readable readiness banner (launch supervisor contract):
    # printed only once the informers synced, so "banner seen" already
    # means "connected to the apiserver and caches listed"
    from .launch.banner import emit_banner

    banner_fields = dict(
        server=args.server, engine=args.engine,
        replica=args.replica_id, partition=partition,
    )
    if diag is not None:
        banner_fields["url"] = diag.url
        banner_fields["readyz"] = diag.url + "/readyz"
    emit_banner("scheduler", **banner_fields)
    print(f"kubetpu scheduler running against {args.server} "
          f"(engine {args.engine}"
          + (f"; diagnostics on {diag.url}" if diag is not None else "")
          + (
              "; sentinel on (/debug/alerts /debug/bundle /debug/queue)"
              if sched.sentinel is not None else ""
          )
          + ")", flush=True)

    def once():
        if not is_leader():
            return
        if membership is not None:
            membership.tick(sched)
        informers.pump()
        sched.schedule_batch()
        sched._drain_bind_completions()
    try:
        return _make_loop(once, stop=stop)()
    finally:
        if exporter is not None:
            exporter.close()
        if membership is not None:
            membership.release()
        if diag is not None:
            diag.close()


def cmd_controller_manager(args) -> int:
    """kube-controller-manager: every controller stepping over the remote
    store (cmd/kube-controller-manager controllermanager.go shape)."""
    from .apiserver import RemoteStore
    from .controllers import (
        CronJobController,
        DaemonSetController,
        DeploymentController,
        DisruptionController,
        GarbageCollector,
        JobController,
        NamespaceController,
        ResourceClaimController,
        ResourceQuotaController,
        StatefulSetController,
        NodeLifecycleController,
        PodGCController,
        ReplicaSetController,
        TaintEvictionController,
        TTLAfterFinishedController,
    )

    store = RemoteStore(args.server)
    ctrls = [
        DeploymentController(store),
        JobController(store),
        CronJobController(store),
        DaemonSetController(store),
        ResourceClaimController(store),
        StatefulSetController(store),
        ReplicaSetController(store),
        NodeLifecycleController(store, grace_s=args.node_monitor_grace),
        TaintEvictionController(store),
        PodGCController(store, terminated_threshold=args.terminated_pod_gc),
        DisruptionController(store),
        GarbageCollector(store),
        TTLAfterFinishedController(store),
        NamespaceController(store),
        ResourceQuotaController(store),
    ]
    for c in ctrls:
        _retry_start(c.start, type(c).__name__)
    is_leader = _maybe_elect(args, store, "kube-controller-manager")
    print(f"kubetpu controller-manager running against {args.server} "
          f"({len(ctrls)} controllers)", flush=True)

    def once():
        if not is_leader():
            return
        for c in ctrls:
            c.step()
    return _make_loop(once, period_s=0.2)()


def cmd_kubelet(args) -> int:
    """The hollow node agent (kubemark tier) against a remote API server."""
    from .api.wrappers import make_node
    from .apiserver import RemoteStore
    from .kubelet import HollowKubelet

    store = RemoteStore(args.server)
    kubelet = HollowKubelet(store, make_node(
        args.node_name, cpu_milli=args.cpu_milli, memory=args.memory,
        pods=args.pods,
    ))
    _retry_start(kubelet.start, f"kubelet {args.node_name}")
    print(f"kubetpu kubelet {args.node_name} registered with {args.server}",
          flush=True)
    return _make_loop(kubelet.pump, period_s=0.2)()


# kubectl-style table printers: kind bucket -> (headers, row fn) — the
# printers registry shape (staging/src/k8s.io/kubectl printers; server-side
# TableConvertor columns per kind)
def _printer_for(bucket: str):
    def pods(key, o):
        return (key, o.phase or "", o.node_name or "<pending>",
                str(getattr(o, "priority", 0)))

    def nodes(key, o):
        status = "SchedulingDisabled" if o.unschedulable else "Ready"
        alloc = o.allocatable_dict()
        return (key, status, str(alloc.get("cpu", "")),
                str(alloc.get("memory", "")))

    def workload(key, o):
        return (key, str(getattr(o, "replicas", "")))

    def jobs(key, o):
        status = ("Complete" if o.complete
                  else "Failed" if o.failed_state else "Running")
        return (key, f"{o.succeeded}/{o.completions}", status)

    def events(key, o):
        return (o.type, o.reason, o.regarding, str(o.count), o.note)

    def quotas(key, o):
        pairs = ", ".join(
            f"{k}: {o.used_dict().get(k, 0)}/{v}" for k, v in o.hard
        )
        return (key, pairs)

    table = {
        "pods": (("NAME", "STATUS", "NODE", "PRIORITY"), pods),
        "nodes": (("NAME", "STATUS", "CPU(m)", "MEMORY"), nodes),
        "replicasets": (("NAME", "REPLICAS"), workload),
        "deployments": (("NAME", "REPLICAS"), workload),
        "statefulsets": (("NAME", "REPLICAS"), workload),
        "jobs": (("NAME", "COMPLETIONS", "STATUS"), jobs),
        "events": (("TYPE", "REASON", "REGARDING", "COUNT", "NOTE"), events),
        "resourcequotas": (("NAME", "USAGE"), quotas),
    }
    return table.get(
        bucket, (("NAME",), lambda key, o: (key,))
    )


def _print_table(bucket: str, items) -> None:
    headers, row_fn = _printer_for(bucket)
    rows = [row_fn(key, obj) for key, obj in items]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    for cols in [headers, *rows]:
        print("  ".join(c.ljust(w) for c, w in zip(cols, widths)).rstrip())


def cmd_get(args) -> int:
    import yaml as _yaml

    from .api import scheme
    from .apiserver import RemoteStore

    store = RemoteStore(args.server)
    if args.key:
        obj, rv = store.get(args.kind, args.key)
        if obj is None:
            print(f"{args.kind}/{args.key} not found", file=sys.stderr)
            return 1
        if args.output == "yaml":
            print(_yaml.safe_dump(scheme.encode(obj), sort_keys=False))
        else:
            print(json.dumps(scheme.encode(obj), indent=2))
        return 0
    selectors = dict(
        label_selector=args.selector or "",
        field_selector=args.field_selector or "",
    )
    items, rv = store.list(args.kind, **selectors)
    if args.output == "json":
        print(json.dumps([scheme.encode(o) for _, o in items], indent=2))
    elif args.output == "yaml":
        print(_yaml.safe_dump([scheme.encode(o) for _, o in items],
                              sort_keys=False))
    else:
        _print_table(args.kind, sorted(items))
    if not args.watch:
        return 0
    # kubectl get -w: follow the (selector-scoped) watch stream
    w = store.watch(args.kind, rv, stream=True, **selectors)
    try:
        import time as _time

        while True:
            for ev in w.poll():
                if ev.type == "DELETED":
                    print(f"{ev.key}\tDELETED", flush=True)
                else:
                    _print_table(args.kind, [(ev.key, ev.obj)])
            _time.sleep(0.2)
    except KeyboardInterrupt:
        return 0
    finally:
        w.close()


def cmd_apply(args) -> int:
    """Create-or-update kind-tagged YAML/JSON documents (kubectl apply)."""
    import yaml

    from .api import scheme
    from .apiserver import RemoteStore
    from .store.memstore import ConflictError

    store = RemoteStore(args.server)
    with open(args.file) as f:
        docs = [d for d in yaml.safe_load_all(f.read()) if d]
    applied = 0
    for doc in docs:
        obj = scheme.decode(doc)
        kind = _kind_buckets().get(type(obj).__name__)
        if kind is None:
            print(f"no bucket for kind {type(obj).__name__}", file=sys.stderr)
            return 1
        key = _object_key(obj)
        try:
            store.create(kind, key, obj)
        except ConflictError:
            store.update(kind, key, obj)
        applied += 1
        print(f"{kind}/{key} applied")
    return 0 if applied else 1


def cmd_delete(args) -> int:
    from .apiserver import RemoteStore

    store = RemoteStore(args.server)
    try:
        store.delete(args.kind, args.key)
    except KeyError:
        print(f"{args.kind}/{args.key} not found", file=sys.stderr)
        return 1
    print(f"{args.kind}/{args.key} deleted")
    return 0


def _render_gang_explain(rec: dict) -> str:
    """A GANG placement record: the topology rationale — the winning
    placement, its slice-alignment score, which slices the search
    considered, the fragmentation delta, and (preemption mode) the ONE
    evicted gang with its member pods."""
    lines = [
        f"Gang {rec['pod']} — status {rec.get('status')}"
        + (f", engine {rec['engine']}" if rec.get("engine") else "")
        + (f", replica {rec['replica']}" if rec.get("replica") else "")
    ]
    lines.append(
        f"  members {rec.get('members')}, quorum need {rec.get('need')}"
    )
    if rec.get("placement") is not None:
        head = f"  decision: {rec['status']} on {rec['placement']}"
        if rec.get("alignment_score") is not None:
            head += f" (alignment {rec['alignment_score']})"
        lines.append(head)
    if rec.get("slices_considered"):
        lines.append(
            "    slices considered: " + ", ".join(rec["slices_considered"])
        )
    if rec.get("fragmentation_delta") is not None:
        lines.append(
            f"    fragmentation delta: {rec['fragmentation_delta']:+d} "
            f"free slice(s) newly opened"
        )
    if rec.get("victim_group"):
        victims = rec.get("preemption_victims") or ()
        lines.append(
            f"  preemption: evicting gang {rec['victim_group']}"
            + (f" (victims: {', '.join(victims)})" if victims else "")
        )
    return "\n".join(lines)


def _render_explain(rec: dict) -> str:
    """One flight-recorder record as the ``kubetpu explain`` report:
    staged timeline + decision reasoning (sched.flightrecorder)."""
    from .metrics.scheduler_metrics import E2E_STAGES

    if rec.get("kind") == "gang":
        return _render_gang_explain(rec)
    lines = [
        f"Pod {rec['pod']} — cycle {rec.get('cycle')}, "
        f"profile {rec.get('profile')}, attempts {rec.get('attempts')}, "
        f"status {rec.get('status')}"
        # federation attribution: which replica made this decision
        # (absent/empty in single-scheduler mode — render nothing)
        + (
            f", replica {rec['replica']}" if rec.get("replica") else ""
        )
    ]
    if rec.get("trace_id"):
        lines.append(f"  trace id: {rec['trace_id']}")
    stages = rec.get("stages_ms") or {}
    if stages:
        parts = [
            f"{st} {stages[st]:.2f}" for st in E2E_STAGES
            if st in stages and st != "e2e"
        ]
        e2e = stages.get("e2e")
        lines.append(
            "  timeline (ms): " + " → ".join(parts)
            + (f"  |  e2e {e2e:.2f}" if e2e is not None else "")
        )
    elif rec.get("queue_wait_s") is not None:
        lines.append(
            f"  queue_wait {rec['queue_wait_s'] * 1000:.2f} ms, "
            f"encode {rec.get('encode_s', 0) * 1000:.2f} ms, "
            f"kernel {rec.get('kernel_s', 0) * 1000:.2f} ms (not yet bound)"
        )
    win = rec.get("win")
    if rec.get("node"):
        head = f"  decision: {rec['status']} on {rec['node']}"
        if win and win.get("score") is not None:
            head += f" (score {win['score']}"
            if win.get("margin") is not None:
                head += f", margin {win['margin']:+d}"
            head += f", {rec.get('view', 'cycle-start')} view)"
        lines.append(head)
    else:
        lines.append("  decision: no feasible node")
    top = rec.get("top_nodes")
    if top:
        lines.append("    top nodes: " + "  ".join(
            f"{t['node']}={t['score']}" for t in top
        ))
    if rec.get("engine") == "packing" and rec.get("objective_value") is not None:
        # packing rationale: the cluster objective this cycle optimized,
        # plus the greedy counterfactual — top_nodes[0] is the cycle-start
        # masked argmax, i.e. what the greedy scan would have picked first
        line = (
            f"  packing: objective {rec['objective_value']:.3f}"
        )
        if rec.get("solver_iters") is not None:
            line += f", {rec['solver_iters']} solver iters"
        counterfactual = top[0]["node"] if top else None
        if counterfactual and rec.get("node"):
            line += (
                f"; greedy would pick {counterfactual}"
                if counterfactual != rec["node"]
                else "; greedy agrees"
            )
        lines.append(line)
    rejected = rec.get("rejected_by")
    if rejected is not None:
        total = rec.get("total_nodes", 0)
        feasible = rec.get("feasible_nodes", 0)
        lines.append(
            f"    filtered: {total - feasible}/{total} nodes infeasible"
            + (
                " — " + ", ".join(
                    f"{plugin} {cnt}"
                    + (
                        f" (e.g. {', '.join(ex)})"
                        if (ex := (rec.get('rejected_examples') or {}).get(
                            plugin
                        )) else ""
                    )
                    for plugin, cnt in sorted(rejected.items())
                ) if rejected else ""
            )
        )
    elif rec.get("skipped_reason"):
        # satellite of the mesh path: the per-plugin rejection kernel is
        # host-gather only, so sharded cycles skip it EXPLICITLY — render
        # the reason instead of an empty breakdown masquerading as
        # "no rejections"
        lines.append(
            "    filtered: per-plugin breakdown skipped "
            f"({rec['skipped_reason']})"
        )
    if rec.get("nominated_node"):
        line = f"  preemption: nominated {rec['nominated_node']}"
        victims = rec.get("preemption_victims")
        if victims:
            line += f" (victims: {', '.join(victims)})"
        lines.append(line)
    for hop in rec.get("requeue", ()):
        lines.append(
            f"  requeued → {hop.get('queue')}"
            + (f" [{', '.join(hop['plugins'])}]" if hop.get("plugins") else "")
            + (" (error status)" if hop.get("error") else "")
        )
    if rec.get("bind_error"):
        lines.append(f"  bind error: {rec['bind_error']}")
    return "\n".join(lines)


def _pod_event_lines(api_url: str, target: str) -> list[str]:
    """The pod's Event timeline from an apiserver ("events" bucket) —
    what every recorder said about it (Scheduled, FailedScheduling, …),
    ordered by last occurrence, aggregation counts shown."""
    import time as _time

    from .apiserver import RemoteStore

    items, _rv = RemoteStore(api_url).list("events")
    evs = [
        o for _k, o in items
        if getattr(o, "regarding", "") == f"Pod/{target}"
    ]
    evs.sort(key=lambda e: getattr(e, "last_timestamp", 0.0) or 0.0)
    lines = []
    for e in evs:
        last = getattr(e, "last_timestamp", 0.0) or 0.0
        ts = _time.strftime("%H:%M:%S", _time.localtime(last)) if last else "-"
        count = getattr(e, "count", 1) or 1
        lines.append(
            f"  {ts}  {e.type:<8} {e.reason:<18} {e.note}"
            + (f"  (x{count})" if count > 1 else "")
            + f"  [{e.reporting_controller}]"
        )
    return lines


def cmd_explain(args) -> int:
    """``kubetpu explain pod/<ns>/<name>``: fetch the pod's decision record
    from a running scheduler's /debug/flightrecorder (--server, the
    diagnostics URL) or a dumped recorder JSON (--file) and render its
    timeline + win/filter reasoning; ``--api URL`` appends the pod's
    Event timeline from the apiserver (the recorders' view)."""
    target = args.target
    if target.startswith("pod/"):
        target = target[len("pod/"):]
    if "/" not in target:
        target = f"default/{target}"
    if args.file:
        with open(args.file, encoding="utf-8") as f:
            body = json.load(f)
    else:
        import urllib.parse
        import urllib.request

        if getattr(args, "collector", ""):
            # the collector's merged view: a pod's record is findable
            # whichever replica scheduled it (one process's
            # /debug/flightrecorder only knows its own decisions)
            url = (
                args.collector.rstrip("/")
                + "/telemetry/flightrecorder?pod="
                + urllib.parse.quote(target, safe="")
            )
        else:
            url = (
                args.server.rstrip("/")
                + "/debug/flightrecorder?pod="
                + urllib.parse.quote(target, safe="")
            )
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                body = json.load(resp)
        except OSError as e:
            print(f"cannot reach {url}: {e}", file=sys.stderr)
            return 2
    if not body.get("enabled", True):
        print("flight recorder is disabled on this scheduler "
              "(--flight-recorder off)", file=sys.stderr)
        return 1
    records = [
        r for r in body.get("records", ()) if r.get("pod") == target
    ]
    event_lines: list[str] = []
    if getattr(args, "api", ""):
        try:
            event_lines = _pod_event_lines(args.api, target)
        except (ConnectionError, OSError) as e:
            print(f"cannot fetch events from {args.api}: {e}",
                  file=sys.stderr)
    if not records:
        if event_lines:
            # no decision record here (other replica, or ring-evicted)
            # but the recorders' Event trail still tells the story
            print(f"no flight-recorder record for pod {target}; "
                  f"event timeline:")
            print("\n".join(event_lines))
            return 0
        print(f"no flight-recorder record for pod {target} "
              f"(evicted from the ring, or never scheduled here)",
              file=sys.stderr)
        return 1
    if args.output == "json":
        print(json.dumps(records if args.all else records[0], indent=2))
        return 0
    for rec in records if args.all else records[:1]:
        print(_render_explain(rec))
    if event_lines:
        print("event timeline:")
        print("\n".join(event_lines))
    return 0


def cmd_store_fsck(args) -> int:
    """``kubetpu store fsck --dir D``: offline integrity report for a
    persistence dir — snapshot validity, per-segment record counts, torn
    tail position, replay-chain continuity. Exit 0 = recovery would
    succeed cleanly."""
    from .api import types  # noqa: F401 — register kinds for decode
    from .store.wal import fsck

    report = fsck(args.dir)
    if args.output == "json":
        print(json.dumps(report, indent=2))
    else:
        print(f"persistence dir {report['dir']}: "
              f"{'OK' if report['ok'] else 'PROBLEMS'} "
              f"(replay chain reaches rv {report.get('resource_version', 0)})")
        for s in report["snapshots"]:
            state = (
                f"{s['objects']} objects" if s.get("valid")
                else f"INVALID: {s.get('error')}"
            )
            print(f"  snapshot {s['file']} @ rv {s['rv']}: {state}")
        for s in report["segments"]:
            extra = ""
            if "torn_at" in s:
                extra = f", torn tail at offset {s['torn_at']}"
            if "error" in s:
                extra += f", ERROR: {s['error']}"
            print(f"  segment {s['file']}: {s['records']} records{extra}")
        for e in report["errors"]:
            print(f"  error: {e}")
    return 0 if report["ok"] else 1


def cmd_store_compact(args) -> int:
    """``kubetpu store compact --dir D``: offline compaction — recover the
    dir into a fresh core, write one snapshot at the recovered revision,
    truncate every superseded segment/snapshot. Run it against a STOPPED
    apiserver's dir to bound the next boot's replay."""
    from .api import types  # noqa: F401 — register kinds for decode
    from .store import MemStore
    from .store.wal import WALError

    try:
        store = MemStore(persistence=args.dir)
    except WALError as e:
        print(f"unrecoverable: {e}", file=sys.stderr)
        return 1
    ri = store.recovery_info
    n_objects = len(store.dump())
    path = store.compact()
    store.close()
    print(f"compacted {args.dir} at rv {ri.resource_version}: "
          f"snapshot {path} ({n_objects} objects; was snapshot@rv"
          f"{ri.snapshot_rv} + {ri.replayed} tail records)")
    return 0


def cmd_version(_args) -> int:
    from . import __version__

    print(f"kubetpu {__version__}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubetpu",
        description="TPU-native scheduling framework (kube-scheduler parity)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    serve = sub.add_parser(
        "serve", help="run the extender webhook bridge from a config file"
    )
    serve.add_argument("--config", default="", help="KubeSchedulerConfiguration file")
    serve.add_argument("--profile", default=None, help="profile (schedulerName) to serve")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=10259)
    serve.set_defaults(fn=cmd_serve)

    api = sub.add_parser(
        "apiserver",
        help="serve the REST+watch object API over an in-memory store",
    )
    api.add_argument("--host", default="127.0.0.1")
    api.add_argument("--port", type=int, default=10250)
    api.add_argument("--wire", default="binary", choices=["binary", "json"],
                     help="wire protocol: 'binary' negotiates the compact "
                          "binary codec per request via Accept/Content-Type "
                          "(JSON clients keep working unchanged); 'json' is "
                          "the escape hatch — a JSON-only server that 415s "
                          "binary bodies, exactly what a pre-binary build "
                          "does")
    api.add_argument("--persistence", default="off", metavar="DIR|off",
                     help="durability: a directory path turns on the "
                          "write-ahead log + compaction snapshots "
                          "(kubetpu.store.wal) — every committed write is "
                          "logged-then-applied and fsync'd before the ack, "
                          "restart recovers snapshot+tail with "
                          "resourceVersion continuity (reconnecting "
                          "watchers take a bounded relist). 'off' (default) "
                          "is the memory-only store, byte-identical to the "
                          "pre-WAL behavior")
    api.add_argument("--telemetry", default="off", metavar="URL|embed|off",
                     help="telemetry plane: a collector URL exports this "
                          "apiserver's server spans + /metrics there on a "
                          "1s cadence; 'embed' mounts the collector ON this "
                          "server (/telemetry/*) and self-ingests — the "
                          "single-process sink; 'off' (default) exports "
                          "nothing and the wire stays byte-identical")
    api.add_argument("--sentinel", default="off", choices=["on", "off"],
                     help="embed the anomaly sentinel: burn-rate/outlier "
                          "rules over this apiserver's own /metrics (WAL "
                          "fsync stalls, encode-cache collapse), alert "
                          "state at /debug/alerts, triggered diagnostic "
                          "bundles at /debug/bundle; 'off' (default) runs "
                          "zero evaluation work")
    api.add_argument("--alert-sink", default="", metavar="file:PATH|webhook:URL",
                     help="out-of-process sentinel alert delivery: "
                          "'file:PATH' appends one ndjson line per alert "
                          "transition; 'webhook:URL' POSTs the transition "
                          "JSON. Delivery failures are counted "
                          "(sentinel_sink_errors), never fatal. Requires "
                          "--sentinel on")
    api.add_argument("--replicated", action="store_true",
                     help="serve as the replicated read plane's LEADER: "
                          "hold the apiserver-writer lease in this store "
                          "(renewals replicate, so the lease doubles as "
                          "the heartbeat) and serve the WAL log-shipping "
                          "feed at /replication/log for followers")
    api.add_argument("--follow", default="", metavar="URL",
                     help="serve as a FOLLOWER of the given leader "
                          "apiserver: bootstrap from its /replication/"
                          "snapshot, tail /replication/log into a local "
                          "replica store, serve reads/lists/watches from "
                          "replayed state at full resourceVersion "
                          "continuity, and 307-redirect writes to the "
                          "leader. On leader death the most-caught-up "
                          "follower wins the writer lease (failover by "
                          "log position)")
    api.add_argument("--peers", default="", metavar="URL,URL,...",
                     help="the full apiserver electorate (leader + all "
                          "followers) — a failing-over follower polls "
                          "these /replication/status endpoints to defer "
                          "to any more-caught-up peer")
    api.add_argument("--replica-index", type=int, default=0,
                     help="this follower's stable index (election "
                          "tie-break: equal log position → lowest index "
                          "wins)")
    api.add_argument("--replicate-from", default="", metavar="URL",
                     help="CHAINED shipping: tail the replication feed "
                          "from this peer (another follower re-serving "
                          "/replication/log) instead of the leader — "
                          "leader egress stays O(direct fan-out). Writes "
                          "still redirect to --follow's leader; a stale "
                          "(fenced-epoch) or dead upstream falls this "
                          "replica back to the leader's feed. Requires "
                          "--follow")
    api.add_argument("--lease-duration", type=float, default=5.0,
                     help="writer-lease duration in seconds — the "
                          "failover detection floor (default 5.0)")
    api.set_defaults(fn=cmd_apiserver)

    check = sub.add_parser("check-config", help="validate a config file")
    check.add_argument("config")
    check.set_defaults(fn=cmd_check_config)

    schd = sub.add_parser(
        "scheduler", help="run the scheduler against a remote API server"
    )
    schd.add_argument("--server", required=True, help="API server base URL")
    schd.add_argument("--config", default="", help="KubeSchedulerConfiguration file")
    schd.add_argument("--engine", default="greedy",
                      choices=["greedy", "batched", "packing"])
    schd.add_argument("--pipeline", default="off", choices=["on", "off"],
                      help="two-stage pipelined cycles with a device-"
                           "resident node block and dirty-row delta "
                           "uploads; assignments stay pod-for-pod "
                           "identical to the serial loop ('off' is the "
                           "debugging escape hatch)")
    schd.add_argument("--encode-cache", default="on", choices=["on", "off"],
                      help="event-time template-keyed pod encoding: static "
                           "tensor rows built at informer delivery and "
                           "gathered at cycle time; cached encodes are "
                           "bit-identical to fresh ones ('off' is the "
                           "debugging escape hatch)")
    schd.add_argument("--bulk", default="on", choices=["on", "off"],
                      help="opportunistic API-plane batching: a cycle's "
                           "binds/status patches flush as bulk RPCs at the "
                           "cycle boundary and the informer bundle polls "
                           "all kinds in one batched request; bindings "
                           "stay pod-for-pod identical to per-call mode "
                           "('off' is the debugging escape hatch)")
    schd.add_argument("--mesh", default="off", choices=["on", "off", "auto"],
                      help="shard the node axis of the scheduling tensors "
                           "over a device mesh (parallel.mesh rules): the "
                           "resident node block becomes a sharded resident "
                           "block with per-shard routed delta uploads, and "
                           "both engines run SPMD with XLA-inserted "
                           "collectives. 'auto' engages when >1 device is "
                           "visible; 'on' requires one; assignments are "
                           "bit-identical to single-device either way")
    schd.add_argument("--topology", default="off",
                      choices=["on", "off", "auto"],
                      help="node-topology axis for scoring + gang "
                           "placement: rack/TPU-slice labels become "
                           "per-node coordinate tensors, gangs land "
                           "alignment-first via per-slice placement "
                           "candidates, the packing objective gains "
                           "slice-fragmentation terms, and preemption "
                           "can evict ONE low-priority gang to free a "
                           "contiguous slice. 'auto' engages only when "
                           "nodes carry topology labels; 'off' (and "
                           "'auto' on unlabeled clusters) is "
                           "bit-identical to before")
    schd.add_argument("--flight-recorder", default="on",
                      choices=["on", "off"],
                      help="scheduling flight recorder + per-pod staged "
                           "latency attribution: bounded ring of decision "
                           "records at /debug/flightrecorder (rendered by "
                           "'kubetpu explain') and the "
                           "scheduler_e2e_scheduling_duration_seconds"
                           "{stage} histograms; 'off' is the overhead "
                           "escape hatch — decisions are identical")
    schd.add_argument("--prewarm", action="store_true",
                      help="compile the assign program for the full "
                           "batch-size bucket ladder at startup, so "
                           "steady state never pays XLA compilation "
                           "mid-cycle")
    schd.add_argument("--replica-id", default="",
                      help="active-active federation stamp (e.g. r0): "
                           "marks this process as one of N replicas racing "
                           "the same apiserver — cycle records, flight-"
                           "recorder entries and the federation conflict "
                           "counter carry it, and the CAS bind path "
                           "arbitrates overlap (409 losers requeue with "
                           "conflict backoff). Empty = single scheduler. "
                           "Contrast --leader-elect, which is "
                           "active/PASSIVE (one leader runs, the rest "
                           "stand by)")
    schd.add_argument("--wire", default="binary", choices=["binary", "json"],
                      help="client wire protocol: 'binary' advertises the "
                           "compact binary codec and switches to it once "
                           "the server confirms the dialect (a 415 falls "
                           "back to JSON permanently — mixed-version pairs "
                           "keep working); 'json' pins the original JSON "
                           "wire")
    schd.add_argument("--partition", default="",
                      choices=["", "race", "hash", "lease"],
                      help="cross-process federation partition mode (with "
                           "--replica-count N): 'race' = every replica "
                           "sees every pod, the CAS bind arbitrates; "
                           "'hash' = static crc32 rank of --replica-count "
                           "(no overlap; a supervisor respawn re-adopts "
                           "the rank's backlog via the informer relist); "
                           "'lease' = epoch-fenced renewable partition "
                           "leases in the SHARED store (expiry/fair-share/"
                           "fencing work across processes). Empty with "
                           "--replica-id = race (backcompat)")
    schd.add_argument("--replica-count", type=int, default=0,
                      help="declared replica count for --partition "
                           "hash|lease (cross-process membership is "
                           "supervisor-declared, not gossiped)")
    schd.add_argument("--partitions", type=int, default=0,
                      help="lease-mode keyspace partitions (default "
                           "2x replica count)")
    schd.add_argument("--max-batch", type=int, default=1024,
                      help="max pods per scheduling cycle batch")
    schd.add_argument("--leader-elect", action="store_true")
    schd.add_argument("--diagnostics-port", default="10251",
                      metavar="N|ephemeral|off",
                      help="side port for /metrics /healthz /readyz /livez "
                           "/trace; 'ephemeral' binds port 0 and publishes "
                           "the real address in the readiness banner (the "
                           "supervisor default — parallel runs never "
                           "collide); 'off' (or 0) disables")
    schd.add_argument("--telemetry", default="off", metavar="URL|off",
                      help="telemetry plane: a collector URL stamps a W3C-"
                           "style traceparent on every RPC (binary envelope "
                           "field or JSON header — the apiserver joins its "
                           "server span to the client span) and exports "
                           "spans + /metrics + flight records there on a 1s "
                           "cadence; 'off' (default) exports nothing and "
                           "every request is byte-identical to a pre-"
                           "telemetry build")
    schd.add_argument("--sentinel", default="off", choices=["on", "off"],
                      help="anomaly sentinel: declarative burn-rate SLO "
                           "rules + robust outlier detection over this "
                           "scheduler's own /metrics, evaluated at the "
                           "cycle boundary (alert lifecycle at "
                           "/debug/alerts, a diagnostic bundle — py "
                           "stacks, queue snapshot, trace slice — "
                           "captured at fire time at /debug/bundle; "
                           "rendered by 'kubetpu alerts'/'kubetpu "
                           "bundle'); 'off' (default) runs zero "
                           "evaluation work")
    schd.add_argument("--alert-sink", default="",
                      metavar="file:PATH|webhook:URL",
                      help="out-of-process sentinel alert delivery: "
                           "'file:PATH' appends one ndjson line per alert "
                           "transition; 'webhook:URL' POSTs the "
                           "transition JSON. Delivery failures are "
                           "counted, never fatal. Requires --sentinel on")
    schd.set_defaults(fn=cmd_scheduler)

    cm = sub.add_parser(
        "controller-manager",
        help="run the controller family against a remote API server",
    )
    cm.add_argument("--server", required=True)
    cm.add_argument("--node-monitor-grace", type=float, default=40.0)
    cm.add_argument("--terminated-pod-gc", type=int, default=0)
    cm.add_argument("--leader-elect", action="store_true")
    cm.set_defaults(fn=cmd_controller_manager)

    kblt = sub.add_parser(
        "kubelet", help="run a hollow node agent (kubemark tier)"
    )
    kblt.add_argument("--server", required=True)
    kblt.add_argument("--node-name", required=True)
    kblt.add_argument("--cpu-milli", type=int, default=4000)
    kblt.add_argument("--memory", type=int, default=16 * 1024**3)
    kblt.add_argument("--pods", type=int, default=110)
    kblt.set_defaults(fn=cmd_kubelet)

    get = sub.add_parser("get", help="list/get objects from an API server")
    get.add_argument("kind")
    get.add_argument("key", nargs="?", default="")
    get.add_argument("--server", required=True)
    get.add_argument("-o", "--output", default="table",
                     choices=("table", "json", "yaml"))
    get.add_argument("-l", "--selector", default="",
                     help="label selector (k=v,k2!=v2)")
    get.add_argument("--field-selector", default="",
                     help="field selector (e.g. spec.nodeName=n0)")
    get.add_argument("-w", "--watch", action="store_true",
                     help="follow the watch stream after listing")
    get.set_defaults(fn=cmd_get)

    apply = sub.add_parser("apply", help="apply kind-tagged YAML documents")
    apply.add_argument("-f", "--file", required=True)
    apply.add_argument("--server", required=True)
    apply.set_defaults(fn=cmd_apply)

    delete = sub.add_parser("delete", help="delete an object")
    delete.add_argument("kind")
    delete.add_argument("key")
    delete.add_argument("--server", required=True)
    delete.set_defaults(fn=cmd_delete)

    explain = sub.add_parser(
        "explain",
        help="render a pod's flight-recorder record: staged latency "
             "timeline + why node Y won / why nodes were filtered",
    )
    explain.add_argument("target", help="pod/<ns>/<name> (or ns/name)")
    explain.add_argument("--server", default="http://127.0.0.1:10251",
                         help="scheduler DIAGNOSTICS base URL "
                              "(the --diagnostics-port listener)")
    explain.add_argument("--file", default="",
                         help="render from a dumped /debug/flightrecorder "
                              "JSON instead of a live scheduler")
    explain.add_argument("--collector", default="",
                         help="fetch the record from a telemetry "
                              "collector's merged view instead "
                              "(/telemetry/flightrecorder) — finds the pod "
                              "whichever scheduler replica decided it")
    explain.add_argument("-o", "--output", default="text",
                         choices=("text", "json"))
    explain.add_argument("--all", action="store_true",
                         help="render every matching record, not just the "
                              "latest")
    explain.add_argument("--api", default="",
                         help="apiserver base URL: append the pod's Event "
                              "timeline (Scheduled / FailedScheduling "
                              "from the recorders, with aggregation "
                              "counts) to the explanation")
    explain.set_defaults(fn=cmd_explain)

    st = sub.add_parser(
        "store",
        help="durable-store tooling: fsck (offline integrity report for a "
             "persistence dir) and compact (fold the WAL into one "
             "snapshot, truncate superseded segments)",
    )
    st_sub = st.add_subparsers(dest="store_command", required=True)
    st_fsck = st_sub.add_parser(
        "fsck", help="report snapshot/segment validity, torn tails, and "
                     "replay-chain continuity without mutating anything",
    )
    st_fsck.add_argument("--dir", required=True,
                         help="the persistence directory "
                              "(apiserver --persistence DIR)")
    st_fsck.add_argument("-o", "--output", default="text",
                         choices=("text", "json"))
    st_fsck.set_defaults(fn=cmd_store_fsck)
    st_compact = st_sub.add_parser(
        "compact", help="offline compaction of a STOPPED apiserver's "
                        "persistence dir (bounds the next boot's replay)",
    )
    st_compact.add_argument("--dir", required=True)
    st_compact.set_defaults(fn=cmd_store_compact)

    bd = sub.add_parser(
        "benchdiff",
        help="compare two bench records metric-by-metric; non-zero exit "
             "on a throughput or staged-p99 regression "
             "(see python -m kubetpu.benchdiff)",
    )
    bd.add_argument("rest", nargs=argparse.REMAINDER)
    bd.set_defaults(fn=None)

    coll = sub.add_parser(
        "collector",
        help="run the telemetry collector: span/metrics/flight-record "
             "ingest from N processes, skew-corrected merged chrome "
             "trace, federated /metrics, and the `kubetpu top` summary",
    )
    coll.add_argument("--host", default="127.0.0.1")
    coll.add_argument("--port", type=int, default=10252)
    coll.set_defaults(fn=cmd_collector)

    top = sub.add_parser(
        "top",
        help="live control-plane console from a collector: per-process "
             "pods/s, queue depth, conflict rate, WAL fsync p99, staged "
             "e2e percentiles",
    )
    top.add_argument("--collector", default="http://127.0.0.1:10252",
                     help="collector base URL (kubetpu collector, or an "
                          "apiserver running --telemetry embed)")
    top.add_argument("-o", "--output", default="text",
                     choices=("text", "json"))
    top.add_argument("-w", "--watch", action="store_true",
                     help="refresh every --interval seconds until ^C")
    top.add_argument("--interval", type=float, default=2.0)
    top.set_defaults(fn=cmd_top)

    al = sub.add_parser(
        "alerts",
        help="the anomaly sentinel's live alert table: one process's "
             "/debug/alerts, or the cluster-wide merge from a "
             "collector's /telemetry/alerts",
    )
    al.add_argument("--server", default="http://127.0.0.1:10251",
                    help="scheduler DIAGNOSTICS base URL "
                         "(the --diagnostics-port listener)")
    al.add_argument("--collector", default="",
                    help="read the merged cluster-wide table from a "
                         "collector instead (one row per rule, worst "
                         "state across processes wins)")
    al.add_argument("-o", "--output", default="text",
                    choices=("text", "json"))
    al.set_defaults(fn=cmd_alerts)

    bu = sub.add_parser(
        "bundle",
        help="triggered diagnostic bundles: summaries, or one full "
             "capture (py stacks, queue snapshot, WAL/cache stats, "
             "chrome-trace slice) with --id",
    )
    bu.add_argument("--server", default="http://127.0.0.1:10251",
                    help="scheduler DIAGNOSTICS base URL")
    bu.add_argument("--collector", default="",
                    help="fetch from a collector's merged store instead")
    bu.add_argument("--id", default="",
                    help="bundle id (from the summary list or an alert's "
                         "bundle_id); omit to list summaries")
    bu.add_argument("--process", default="",
                    help="disambiguate --id by process (collector mode)")
    bu.add_argument("--out", default="",
                    help="write the full bundle JSON to FILE instead of "
                         "stdout")
    bu.add_argument("-o", "--output", default="text",
                    choices=("text", "json"))
    bu.set_defaults(fn=cmd_bundle)

    wd = sub.add_parser(
        "watch-driver",
        help="run N concurrent pod watchers against an apiserver as one "
             "dedicated process (the mp wire ladder's fan-out unit)",
    )
    wd.add_argument("--server", required=True, help="API server base URL")
    wd.add_argument("--watchers", type=int, default=50)
    wd.add_argument("--wire", default="binary", choices=["binary", "json"])
    wd.set_defaults(fn=cmd_watch_driver)

    up = sub.add_parser(
        "up",
        help="run the whole control plane as real OS processes under the "
             "launch supervisor: apiserver + N scheduler replicas "
             "(+ collector / watch-fanout drivers), ephemeral ports via "
             "readiness banners, restart policy, SIGTERM-cascade shutdown",
    )
    up.add_argument("--replicas", type=int, default=1,
                    help="scheduler replica processes")
    up.add_argument("--apiservers", type=int, default=1,
                    help="apiserver processes: 1 (default) is the classic "
                         "single-writer topology, byte-identical to "
                         "before; N>1 runs one leader + N-1 WAL-log-"
                         "shipping follower apiservers — watch-fanout "
                         "drivers spread their read load over the "
                         "followers, and the most-caught-up follower "
                         "takes over on leader death (failover by log "
                         "position)")
    up.add_argument("--replication-chain", action="store_true",
                    help="chain the followers' replication tails (f1 "
                         "tails the leader, f2 tails f1, …) so leader "
                         "replication egress is one follower's worth "
                         "regardless of --apiservers; a stale or dead "
                         "chain link falls its downstream back to the "
                         "leader's feed. Default: every follower tails "
                         "the leader directly")
    up.add_argument("--partition", default="race",
                    choices=["race", "hash", "lease"],
                    help="federation partition mode across the replica "
                         "processes (see kubetpu scheduler --partition)")
    up.add_argument("--wire", default="binary", choices=["binary", "json"],
                    help="wire codec for every child (and the 415-fallback "
                         "escape hatch)")
    up.add_argument("--engine", default="greedy",
                    choices=["greedy", "batched", "packing"])
    up.add_argument("--topology", default="off",
                    choices=["on", "off", "auto"],
                    help="node-topology axis on every scheduler replica "
                         "(see kubetpu scheduler --topology)")
    up.add_argument("--max-batch", type=int, default=1024)
    up.add_argument("--persistence", default="off", metavar="DIR|off",
                    help="apiserver durability dir (WAL + snapshots); the "
                         "SIGTERM cascade rides the graceful close — "
                         "`kubetpu store fsck` passes afterwards")
    up.add_argument("--telemetry", default="off",
                    metavar="off|embed|collector|URL",
                    help="'embed' mounts the collector ON the apiserver "
                         "and points every scheduler's exporter there; "
                         "'collector' spawns a collector child; a URL "
                         "uses an external collector; 'off' exports "
                         "nothing")
    up.add_argument("--watch-fanout", type=int, default=0,
                    help="total extra pod watchers, spread over "
                         "--fanout-procs driver processes")
    up.add_argument("--fanout-procs", type=int, default=0,
                    help="watch-driver processes carrying --watch-fanout")
    up.add_argument("--restart", default="on-failure:2",
                    metavar="never|on-failure[:max]",
                    help="per-scheduler restart policy: a killed replica "
                         "is respawned and re-federates (hash re-adopts "
                         "its rank's backlog, lease re-acquires)")
    up.add_argument("--prewarm", action="store_true",
                    help="schedulers compile the bucket ladder at startup")
    up.set_defaults(fn=cmd_up)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=cmd_version)

    perf = sub.add_parser(
        "perf", help="scheduler_perf harness (see python -m kubetpu.perf)"
    )
    perf.add_argument("rest", nargs=argparse.REMAINDER)
    perf.set_defaults(fn=None)

    analyze = sub.add_parser(
        "analyze",
        help="graftcheck static-analysis suite "
             "(see python -m kubetpu.analysis)",
    )
    analyze.add_argument("rest", nargs=argparse.REMAINDER)
    analyze.set_defaults(fn=None)
    return p


def main(argv: Sequence[str] | None = None) -> int:
    raw = list(argv) if argv is not None else sys.argv[1:]
    if raw and raw[0] == "analyze":
        # dispatch before argparse: REMAINDER drops leading flags
        # (`kubetpu analyze --list-checkers` must reach the sub-CLI intact)
        from .analysis.__main__ import main as analyze_main

        return analyze_main(raw[1:]) or 0
    if raw and raw[0] == "benchdiff":
        # dispatch before argparse: REMAINDER drops leading flags
        # (`kubetpu benchdiff --json a b` must reach the sub-CLI intact)
        from .benchdiff import main as benchdiff_main

        return benchdiff_main(raw[1:])
    args = build_parser().parse_args(argv)
    if args.command == "perf":
        from .perf.__main__ import main as perf_main

        return perf_main(args.rest) or 0
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
