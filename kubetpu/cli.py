"""The kubetpu command line — the cmd/kube-scheduler analog (layer 9).

Reference: cmd/kube-scheduler/app/server.go:93 (``NewSchedulerCommand`` →
``runCommand`` → ``Setup``/``Run``): parse a versioned
KubeSchedulerConfiguration file, build the scheduler, serve healthz +
metrics + configz, optionally leader-elect. Here the serving surface is the
extender webhook bridge (``kubetpu.bridge.server``) — the integration seam
a real kube-scheduler offloads Filter/Prioritize/Bind through — with the
same side endpoints (/healthz, /metrics, /configz).

Commands:
- ``serve``        run the extender bridge from a config file
- ``check-config`` decode + validate a config file, loudly
- ``perf``         the scheduler_perf harness (kubetpu.perf)
- ``version``      print the framework version
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from typing import Any, Sequence


def _config_to_dict(obj: Any) -> Any:
    """Dataclass → plain JSON for /configz (live-config introspection)."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {
            f.name: _config_to_dict(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, (list, tuple)):
        return [_config_to_dict(x) for x in obj]
    if isinstance(obj, dict):
        return {str(k): _config_to_dict(v) for k, v in obj.items()}
    return obj


def cmd_check_config(args) -> int:
    from .framework.configload import ConfigError, load_config

    try:
        cfg = load_config(args.config)
    except (ConfigError, OSError) as e:
        print(f"invalid: {e}", file=sys.stderr)
        return 1
    names = ", ".join(p.name for p in cfg.profiles)
    print(
        f"ok: {len(cfg.profiles)} profile(s) [{names}], "
        f"{len(cfg.extenders)} extender(s)"
    )
    return 0


def cmd_serve(args) -> int:
    from .bridge.server import ExtenderBackend, ExtenderServer
    from .framework import config as C
    from .framework.configload import ConfigError, load_config

    if args.config:
        try:
            cfg = load_config(args.config)
        except (ConfigError, OSError) as e:
            print(f"invalid config: {e}", file=sys.stderr)
            return 1
    else:
        cfg = C.SchedulerConfiguration()
    try:
        profile = cfg.profile(args.profile)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 1
    backend = ExtenderBackend(profile=profile)
    backend.configz_source = lambda: _config_to_dict(cfg)
    server = ExtenderServer(backend, host=args.host, port=args.port).start()
    print(f"kubetpu extender bridge serving on {server.url} "
          f"(profile {profile.name!r}; verbs: /filter /prioritize /bind "
          f"/preempt; /cache/nodes /cache/pods; /healthz /metrics /configz)",
          flush=True)
    try:
        import threading

        threading.Event().wait()   # serve until interrupted
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


def cmd_apiserver(args) -> int:
    from .apiserver import APIServer

    server = APIServer(host=args.host, port=args.port).start()
    print(f"kubetpu apiserver serving on {server.url} "
          f"(REST: /apis/<kind>[/<key>], watch: ?watch=1&resourceVersion=N)",
          flush=True)
    try:
        import threading

        threading.Event().wait()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
    return 0


def cmd_version(_args) -> int:
    from . import __version__

    print(f"kubetpu {__version__}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubetpu",
        description="TPU-native scheduling framework (kube-scheduler parity)",
    )
    sub = p.add_subparsers(dest="command", required=True)

    serve = sub.add_parser(
        "serve", help="run the extender webhook bridge from a config file"
    )
    serve.add_argument("--config", default="", help="KubeSchedulerConfiguration file")
    serve.add_argument("--profile", default=None, help="profile (schedulerName) to serve")
    serve.add_argument("--host", default="127.0.0.1")
    serve.add_argument("--port", type=int, default=10259)
    serve.set_defaults(fn=cmd_serve)

    api = sub.add_parser(
        "apiserver",
        help="serve the REST+watch object API over an in-memory store",
    )
    api.add_argument("--host", default="127.0.0.1")
    api.add_argument("--port", type=int, default=10250)
    api.set_defaults(fn=cmd_apiserver)

    check = sub.add_parser("check-config", help="validate a config file")
    check.add_argument("config")
    check.set_defaults(fn=cmd_check_config)

    ver = sub.add_parser("version", help="print version")
    ver.set_defaults(fn=cmd_version)

    perf = sub.add_parser(
        "perf", help="scheduler_perf harness (see python -m kubetpu.perf)"
    )
    perf.add_argument("rest", nargs=argparse.REMAINDER)
    perf.set_defaults(fn=None)
    return p


def main(argv: Sequence[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "perf":
        from .perf.__main__ import main as perf_main

        return perf_main(args.rest) or 0
    return args.fn(args)


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
