"""Write-ahead log + compacted snapshots + crash recovery for the store.

The reference's layer 0 is durable by construction (etcd: raft WAL +
boltdb snapshots under ``storage.Interface``); the MemStore was the
control plane's last single point of failure — one apiserver crash lost
the cluster. This module closes that gap with the same shape:

- **WAL**: one checksummed, length-prefixed record per committed write
  (create / update / delete / bind — a bind IS a CAS update), appended
  and flushed BEFORE the core applies it and fsync'd before the store
  acks (group commit: a bulk batch's records share one fsync). The
  record payload is the event wire body the serialize-once seam already
  defines (``kubetpu.api.codec.event_wire_bytes`` — byte-identical to
  what the store's body ring caches for watch fan-out), framed with the
  record's kind; the segment header pins the codec and the schema
  fingerprint so a record can never be mis-decoded by a drifted build.
- **Snapshots + truncation**: ``compact()`` writes the full object map
  (with per-object resourceVersions — CAS survives recovery) at revision
  R to a temp file, atomically renames it in, rotates the active
  segment, and deletes every segment/snapshot the new snapshot
  supersedes. The registry generation is re-checked per append: a kind
  registered after the segment opened rotates the segment (binary
  bodies embed schema-table ids — one segment, one schema).
- **Recovery**: ``recover_into(core, dir)`` loads the newest valid
  snapshot (objects + per-object rvs + store rv, compacted_through = R)
  and replays the WAL tail IN ORDER through the core's own write verbs —
  so the event ring repopulates with the tail and resourceVersion
  continuity holds exactly: a watcher reconnecting with a pre-crash
  cursor >= R takes a bounded relist (just the tail events), only a
  cursor older than the compaction horizon 410s into a full relist.
  Replay is rv-gated (records at-or-below the core's revision are
  skipped), which makes double replay — and the mid-truncate crash's
  leftover segments — idempotent. A torn tail on the ACTIVE segment
  (half-written final record: short frame or checksum mismatch) is
  detected and truncated; corruption anywhere else is a loud WALError,
  never a silent partial store.

Fault points (kubetpu.store.faultpoints) instrument every boundary the
claims above depend on; tests/test_wal.py kills-and-recovers at each.

File layout under the persistence dir::

    wal-<seq 16 hex>.log      segments, replayed in seq order
    snap-<rv 16 hex>.snap     compaction snapshots (newest valid wins)

Wire framing (little-endian):

    segment header:  b"KTWL" | u8 version | u8 codec_id | u8 fp_len |
                     fp bytes (ascii schema fingerprint) | u64 base_rv
    snapshot header: b"KTSN" | u8 version | u8 codec_id | u8 fp_len |
                     fp | u64 store_rv | u32 entry_count
    record frame:    u32 payload_len | u32 crc32(payload) | payload
    WAL payload:     u8 kind_len | kind | event wire body
                     (codec.event_wire_bytes: type/key/object/rv)
    snap payload:    u8 kind_len | kind | u64 object_rv | object body
                     (codec.dumps(obj))
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from dataclasses import dataclass
from typing import Any

from ..api import codec
from ..metrics.registry import Histogram, exponential_buckets
from . import faultpoints

SEGMENT_MAGIC = b"KTWL"
SNAPSHOT_MAGIC = b"KTSN"
FORMAT_VERSION = 1

_u32 = struct.Struct("<I")
_u64 = struct.Struct("<Q")

#: sanity cap on one framed payload (a torn length prefix must never make
#: recovery try to allocate gigabytes)
_MAX_RECORD = 1 << 30

_EV_NAMES = codec.EVENT_TYPE_NAMES           # ("ADDED","MODIFIED","DELETED")
_EV_IDS = {n: i for i, n in enumerate(_EV_NAMES)}


class WALError(Exception):
    """Unrecoverable persistence-dir problem: mid-log corruption, a schema
    the running build cannot decode, an rv gap in the replay chain."""


def _codec_id(name: str) -> int:
    try:
        return codec.WIRE_CODEC_IDS[name]
    except KeyError:
        raise WALError(f"unknown WAL codec {name!r}") from None


def _codec_name(cid: int) -> str:
    for name, i in codec.WIRE_CODEC_IDS.items():
        if i == cid:
            return name
    raise WALError(f"unknown WAL codec id {cid}")


def _frame(payload: bytes) -> bytes:
    return _u32.pack(len(payload)) + _u32.pack(
        zlib.crc32(payload) & 0xFFFFFFFF
    ) + payload


def _segment_path(dirpath: str, seq: int) -> str:
    return os.path.join(dirpath, f"wal-{seq:016x}.log")


def _snapshot_path(dirpath: str, rv: int) -> str:
    return os.path.join(dirpath, f"snap-{rv:016x}.snap")


def list_segments(dirpath: str) -> list[tuple[int, str]]:
    """(seq, path) of every segment, seq order."""
    out = []
    for name in os.listdir(dirpath):
        if name.startswith("wal-") and name.endswith(".log"):
            try:
                seq = int(name[4:-4], 16)
            except ValueError:
                continue
            out.append((seq, os.path.join(dirpath, name)))
    return sorted(out)


def list_snapshots(dirpath: str) -> list[tuple[int, str]]:
    """(rv, path) of every snapshot file, rv order (temp files excluded)."""
    out = []
    for name in os.listdir(dirpath):
        if name.startswith("snap-") and name.endswith(".snap"):
            try:
                rv = int(name[5:-5], 16)
            except ValueError:
                continue
            out.append((rv, os.path.join(dirpath, name)))
    return sorted(out)


def _fsync_dir(dirpath: str) -> None:
    """Make renames/unlinks in ``dirpath`` durable (POSIX: directory
    entries have their own durability)."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return                              # platform without dir-fsync
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class DirLock:
    """Single-writer guard on a persistence dir (``flock`` on a lock
    file): a second live opener — a concurrent ``store compact``, a
    second apiserver on the same dir — would rotate the segment chain and
    truncate the live writer's active segment out from under it, silently
    losing every write acked afterwards. The lock dies with the holder's
    file descriptor, so a crashed (or abandoned) store never needs stale-
    lock cleanup; on platforms without ``fcntl`` the guard degrades to
    advisory-nothing rather than blocking the store."""

    def __init__(self, dirpath: str) -> None:
        self.path = os.path.join(dirpath, "wal.lock")
        self._f = open(self.path, "a+")
        try:
            import fcntl
        except ImportError:                 # non-POSIX: no guard
            return
        try:
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            self._f.close()
            self._f = None
            raise WALError(
                f"{dirpath} is locked by another live process — a second "
                "writer would truncate the live log (stop the apiserver "
                "before compact/recovery)"
            ) from None
        self._f.seek(0)
        self._f.truncate()
        self._f.write(str(os.getpid()))
        self._f.flush()

    def release(self) -> None:
        if self._f is not None:
            self._f.close()                 # closing the fd drops the flock
            self._f = None


@dataclass
class RecoveryInfo:
    """What one recovery did — surfaced by fsck and the recovery bench."""

    snapshot_rv: int = 0
    snapshot_objects: int = 0
    replayed: int = 0
    skipped: int = 0            # rv-gated (already covered) records
    segments: int = 0
    pruned_segments: int = 0    # empty (header-only) segments deleted
    truncated_bytes: int = 0    # torn tail removed from the active segment
    truncated_segment: str = ""
    resource_version: int = 0

    def to_json(self) -> dict:
        return {k: v for k, v in self.__dict__.items()}


# --------------------------------------------------------------- the log

class WriteAheadLog:
    """Append side. NOT thread-safe by itself — the owning MemStore calls
    under its store lock (same single-writer contract as the cores)."""

    def __init__(self, dirpath: str, wire: str = codec.BINARY,
                 fsync: bool = True, compact_every: int = 65536,
                 base_rv: int = 0) -> None:
        """``base_rv``: the store revision at open (the owner's recovered
        rv) — stamped into each segment header so a reader can skip whole
        segments without decoding a record."""
        if wire not in codec.WIRE_CODEC_IDS:
            raise WALError(f"wire must be one of "
                           f"{sorted(codec.WIRE_CODEC_IDS)}, got {wire!r}")
        os.makedirs(dirpath, exist_ok=True)
        self.dirpath = dirpath
        self.wire = wire
        self.fsync = fsync
        self.compact_every = compact_every
        self._encoder = codec.event_body_encoder(wire)
        self._f = None
        self._seq = 0
        self._seg_fp: str | None = None     # fingerprint the segment pinned
        self._dirty = False                 # appended-but-not-fsynced bytes
        self._last_rv = base_rv             # highest rv this log has seen
        # counters for /metrics + the WALOverhead bench line
        self.records_appended = 0
        self.bytes_appended = 0
        self.fsyncs = 0
        self.records_since_snapshot = 0
        # store_wal_fsync_duration_seconds: the durability tax per group
        # commit (10 µs … ~1.3 s — a battery-backed controller acks in
        # tens of µs, a contended spindle can take hundreds of ms); the
        # apiserver mounts it on /metrics and WALOverhead_* bench records
        # embed its p99
        self.fsync_hist = Histogram(
            "store_wal_fsync_duration_seconds",
            "WAL group-commit fsync latency in seconds.",
            buckets=exponential_buckets(0.00001, 2, 18),
        )
        # store_snapshot_age_seconds anchor: the newest on-disk snapshot's
        # mtime (a dir that has never compacted ages from open time)
        snaps = list_snapshots(dirpath)
        self.last_snapshot_wall = (
            os.path.getmtime(snaps[-1][1]) if snaps else time.time()
        )
        self._open_segment()

    # ------------------------------------------------------------ segments
    def _next_seq(self) -> int:
        segs = list_segments(self.dirpath)
        return (segs[-1][0] + 1) if segs else 1

    def _open_segment(self) -> None:
        """Start a FRESH segment (boot and rotation both do — appending to
        a recovered segment would re-open the torn-tail question the
        recovery just settled)."""
        if self._f is not None:
            self._close_file()
        self._seq = self._next_seq()
        self._seg_fp = (
            codec.schema_fingerprint() if self.wire == codec.BINARY else ""
        )
        fp = self._seg_fp.encode()
        path = _segment_path(self.dirpath, self._seq)
        self._f = open(path, "xb")
        self._f.write(
            SEGMENT_MAGIC + bytes((FORMAT_VERSION, _codec_id(self.wire),
                                   len(fp))) + fp
            + _u64.pack(self._last_rv)
        )
        self._f.flush()
        self._sync_file()
        _fsync_dir(self.dirpath)

    def _close_file(self) -> None:
        try:
            self._f.flush()
            self._sync_file()
        finally:
            self._f.close()
            self._f = None

    def _sync_file(self) -> None:
        if self.fsync and self._f is not None:
            t0 = time.perf_counter()
            os.fsync(self._f.fileno())
            self.fsync_hist.observe(time.perf_counter() - t0)
            self.fsyncs += 1
        self._dirty = False

    def _check_generation(self) -> None:
        """Binary bodies embed schema-table ids; a kind registered after
        this segment opened would make its later records undecodable under
        the header's fingerprint — one segment, one schema, so rotate."""
        if self.wire != codec.BINARY:
            return
        if codec.schema_fingerprint() != self._seg_fp:
            self._open_segment()

    # ------------------------------------------------------------- append
    def append(self, ev_type: int, kind: str, key: str, obj: Any,
               rv: int) -> None:
        """Frame + write + flush ONE committed write's record (to the OS;
        durability lands at the next ``commit``). ``ev_type`` is the ring
        id (0 ADDED / 1 MODIFIED / 2 DELETED); ``rv`` is the revision the
        core WILL assign — the caller appends before applying
        (write-ahead), so a post-append crash replays the write whose ack
        was lost."""
        self._check_generation()
        faultpoints.fire("wal-pre-append")
        body = self._encoder(ev_type, key, obj, rv)
        kind_b = kind.encode()
        if len(kind_b) > 255:
            raise WALError(f"kind too long for the WAL frame: {kind!r}")
        rec = _frame(bytes((len(kind_b),)) + kind_b + body)
        if faultpoints.due("wal-mid-record"):
            # the torn write: half the frame reaches the OS, then death
            self._f.write(rec[: max(1, len(rec) // 2)])
            self._f.flush()
            faultpoints.crash("wal-mid-record")
        self._f.write(rec)
        self._f.flush()
        self._dirty = True
        self._last_rv = rv
        self.records_appended += 1
        self.records_since_snapshot += 1
        self.bytes_appended += len(rec)

    def commit(self) -> None:
        """Group commit: fsync everything appended since the last commit —
        the store calls this once per lock round (one write = one fsync, a
        bulk batch = one fsync for the batch), BEFORE any caller is
        acked. A round that appended nothing (read-only bulk, all-conflict
        batch) costs nothing."""
        if self._dirty:
            self._sync_file()

    @property
    def wants_compaction(self) -> bool:
        return self.records_since_snapshot >= self.compact_every

    # ----------------------------------------------------------- snapshot
    def snapshot(self, items: "list[tuple[str, str, Any, int]]",
                 rv: int) -> str:
        """Write a compaction snapshot of the full object map at revision
        ``rv`` (atomic: temp + rename), rotate the active segment, then
        delete every superseded segment and snapshot. ``items`` is the
        core's dump — (kind, key, obj, object_rv) in insertion order."""
        self._check_generation()
        path = _snapshot_path(self.dirpath, rv)
        tmp = f"{path}.tmp.{os.getpid()}"
        fp = (
            codec.schema_fingerprint() if self.wire == codec.BINARY else ""
        ).encode()
        half = len(items) // 2
        with open(tmp, "wb") as f:
            f.write(
                SNAPSHOT_MAGIC + bytes((FORMAT_VERSION,
                                        _codec_id(self.wire), len(fp))) + fp
                + _u64.pack(rv) + _u32.pack(len(items))
            )
            for i, (kind, key, obj, obj_rv) in enumerate(items):
                if i == half and faultpoints.due("wal-mid-snapshot"):
                    f.flush()   # the half-written temp file is the debris
                    faultpoints.crash("wal-mid-snapshot")
                kind_b = kind.encode()
                body = self._encoder(0, key, obj, obj_rv)
                f.write(_frame(
                    bytes((len(kind_b),)) + kind_b + _u64.pack(obj_rv)
                    + body
                ))
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.dirpath)
        self.last_snapshot_wall = time.time()
        # the snapshot is durable: everything at-or-below rv is redundant
        self._last_rv = max(self._last_rv, rv)
        self._open_segment()
        self.records_since_snapshot = 0
        self._truncate_through(rv, keep_snapshot=path)
        return path

    def _truncate_through(self, rv: int, keep_snapshot: str) -> None:
        """Delete segments older than the active one and snapshots older
        than ``keep_snapshot``. A crash midway (fault point) leaves extra
        files recovery skips idempotently — never a hole."""
        doomed = [
            p for seq, p in list_segments(self.dirpath) if seq < self._seq
        ] + [
            p for srv, p in list_snapshots(self.dirpath)
            if p != keep_snapshot and srv <= rv
        ]
        half = len(doomed) // 2
        for i, p in enumerate(doomed):
            if i == half and faultpoints.due("wal-mid-truncate"):
                faultpoints.crash("wal-mid-truncate")
            try:
                os.unlink(p)
            except OSError:
                pass
        _fsync_dir(self.dirpath)

    def close(self) -> None:
        """Flush + fsync + close — the graceful-shutdown path: a clean
        stop NEVER leaves a torn tail for recovery to truncate."""
        if self._f is not None:
            self._close_file()


# ------------------------------------------------------------- read side

def _read_exact(f, n: int) -> bytes:
    data = f.read(n)
    return data if data is not None else b""


def _read_header(f, magic: bytes, path: str):
    """→ (codec_name, fingerprint). Raises WALError on a file too
    short/foreign to even carry a header."""
    head = _read_exact(f, len(magic) + 3)
    if len(head) < len(magic) + 3 or head[: len(magic)] != magic:
        raise WALError(f"{path}: bad or missing header magic")
    version, cid, fp_len = head[len(magic):]
    if version != FORMAT_VERSION:
        raise WALError(f"{path}: format version {version} unsupported")
    fp = _read_exact(f, fp_len).decode("ascii", errors="replace")
    return _codec_name(cid), fp


def _check_fingerprint(wire: str, fp: str, path: str) -> None:
    if wire == codec.BINARY and fp != codec.schema_fingerprint():
        raise WALError(
            f"{path}: binary schema fingerprint {fp!r} != this build's "
            f"{codec.schema_fingerprint()!r} — the log cannot be decoded "
            "by a drifted registry (recover with the writing build, or "
            "discard the persistence dir and full-resync)"
        )


def _iter_frames(f, path: str):
    """Yield (offset, payload) for each well-formed frame; stop at EOF.
    A torn frame (short prefix/payload or crc mismatch) yields a final
    ("torn", offset) marker instead of raising — the caller decides
    whether that position is a truncatable tail."""
    while True:
        offset = f.tell()
        head = _read_exact(f, 8)
        if not head:
            return
        if len(head) < 8:
            yield ("torn", offset)
            return
        (length,) = _u32.unpack(head[:4])
        (crc,) = _u32.unpack(head[4:])
        # length 0 is the zero-fill crash artifact (file size extended,
        # data blocks never written): crc32(b"") == 0, so an all-NUL tail
        # would otherwise parse as an endless run of "valid" empty frames
        # — no real record is ever empty (the payload carries at least
        # the kind-length byte), so treat it as torn
        if length == 0 or length > _MAX_RECORD:
            yield ("torn", offset)
            return
        payload = _read_exact(f, length)
        if len(payload) < length or (
            zlib.crc32(payload) & 0xFFFFFFFF
        ) != crc:
            yield ("torn", offset)
            return
        yield (offset, payload)


def _decode_wal_payload(payload: bytes, wire: str, path: str):
    """→ (ev_type_id, kind, key, obj, rv)."""
    try:
        kind_len = payload[0]
        kind = payload[1: 1 + kind_len].decode()
        body = payload[1 + kind_len:]
        msg = codec.loads(body, wire)
    except (codec.UnsupportedWireError, IndexError,
            UnicodeDecodeError) as e:
        raise WALError(f"{path}: undecodable record body: {e}") from None
    ev = _EV_IDS.get(msg.get("type"))
    if ev is None:
        raise WALError(f"{path}: record carries no event type")
    return ev, kind, msg["key"], codec.as_object(msg.get("object")), \
        msg["resourceVersion"]


def _read_snapshot_stream(f, path: str):
    """The snapshot format's ONE reader (file or shipped bytes): header +
    entry frames → (rv, [(kind, key, obj, obj_rv), …]). Raises WALError
    on anything short, torn, undecodable, or count-mismatched."""
    wire, fp = _read_header(f, SNAPSHOT_MAGIC, path)
    _check_fingerprint(wire, fp, path)
    tail = _read_exact(f, 12)
    if len(tail) < 12:
        raise WALError(f"{path}: truncated snapshot header")
    (rv,) = _u64.unpack(tail[:8])
    (count,) = _u32.unpack(tail[8:])
    items = []
    for entry in _iter_frames(f, path):
        if entry[0] == "torn":
            raise WALError(f"{path}: torn snapshot entry")
        _off, payload = entry
        kind_len = payload[0]
        kind = payload[1: 1 + kind_len].decode()
        (obj_rv,) = _u64.unpack(payload[1 + kind_len: 9 + kind_len])
        body = payload[9 + kind_len:]
        try:
            msg = codec.loads(body, wire)
        except codec.UnsupportedWireError as e:
            raise WALError(f"{path}: undecodable snapshot entry: {e}") \
                from None
        items.append((kind, msg["key"],
                      codec.as_object(msg.get("object")), obj_rv))
    if len(items) != count:
        raise WALError(
            f"{path}: snapshot carries {len(items)} entries, "
            f"header promised {count}"
        )
    return rv, items


def load_snapshot_items(path: str):
    """→ (rv, [(kind, key, obj, obj_rv), …]) or raises WALError."""
    with open(path, "rb") as f:
        return _read_snapshot_stream(f, path)


# ------------------------------------------------- replication streaming
# The log-shipping wire (kubetpu.store.replication) IS the WAL format:
# shipped records are the exact frames `append` writes, the bootstrap
# snapshot is the exact byte layout `snapshot` writes — one copy of the
# format rules, so a drifted build refuses a ship the same way it refuses
# a foreign persistence dir (the fingerprint check above).

def frame_record(kind: str, body: bytes) -> bytes:
    """Frame ONE record from a kind + an event wire body
    (``codec.event_wire_bytes`` — what the store's body ring caches) —
    byte-identical to what ``WriteAheadLog.append`` writes."""
    kind_b = kind.encode()
    if len(kind_b) > 255:
        raise WALError(f"kind too long for the WAL frame: {kind!r}")
    return _frame(bytes((len(kind_b),)) + kind_b + body)


def iter_log_stream(data: bytes, wire: str,
                    source: str = "<replication>"):
    """Decode a shipped run of record frames (a /replication/log body):
    yields (ev_type_id, kind, key, obj, rv) in order. A torn frame is a
    loud WALError — HTTP delivers the body whole or not at all, so unlike
    a crashed segment there is no truncatable-tail policy here."""
    import io

    for entry in _iter_frames(io.BytesIO(data), source):
        if entry[0] == "torn":
            raise WALError(f"{source}: torn replication frame")
        yield _decode_wal_payload(entry[1], wire, source)


def encode_snapshot_stream(items, rv: int, wire: str = codec.BINARY) -> bytes:
    """A full object map in the WAL snapshot format, as bytes — the
    leader's /replication/snapshot body (follower bootstrap). ``items``
    is a core dump: (kind, key, obj, obj_rv) in insertion order."""
    import io

    encoder = codec.event_body_encoder(wire)
    fp = (
        codec.schema_fingerprint() if wire == codec.BINARY else ""
    ).encode()
    f = io.BytesIO()
    f.write(
        SNAPSHOT_MAGIC + bytes((FORMAT_VERSION, _codec_id(wire), len(fp)))
        + fp + _u64.pack(rv) + _u32.pack(len(items))
    )
    for kind, key, obj, obj_rv in items:
        kind_b = kind.encode()
        body = encoder(0, key, obj, obj_rv)
        f.write(_frame(
            bytes((len(kind_b),)) + kind_b + _u64.pack(obj_rv) + body
        ))
    return f.getvalue()


def decode_snapshot_stream(data: bytes,
                           source: str = "<replication>"):
    """→ (rv, items) from an ``encode_snapshot_stream`` body — the same
    walk (and the same fingerprint refusal) as ``load_snapshot_items``."""
    import io

    return _read_snapshot_stream(io.BytesIO(data), source)


def iter_segment(path: str):
    """ONE copy of the segment format rules, consumed by both recovery
    and fsck (their policies differ — apply vs report — but the walk must
    never drift). Yields, in order: ``("base", base_rv)`` once, then per
    frame either ``("record", (offset, ev_type, kind, key, obj, rv))`` or
    a final ``("torn", offset)``. Header, fingerprint, and crc-valid-but-
    undecodable problems raise WALError."""
    with open(path, "rb") as f:
        wire, fp = _read_header(f, SEGMENT_MAGIC, path)
        _check_fingerprint(wire, fp, path)
        base = _read_exact(f, 8)
        if len(base) < 8:
            raise WALError(f"{path}: truncated segment header")
        yield ("base", _u64.unpack(base)[0])
        for entry in _iter_frames(f, path):
            if entry[0] == "torn":
                yield ("torn", entry[1])
                return
            offset, payload = entry
            yield (
                "record",
                (offset, *_decode_wal_payload(payload, wire, path)),
            )


def _latest_valid_snapshot(dirpath: str):
    """Newest snapshot that loads cleanly (an older valid one shadows a
    newer corrupt one — a mid-snapshot crash before the atomic rename can
    only leave temp debris, but belt-and-braces). Returns (rv, items,
    path) or (0, [], ""); with NO usable snapshot the replay chain's
    rv-gap check decides loudly whether the segments alone suffice."""
    for rv, path in reversed(list_snapshots(dirpath)):
        try:
            srv, items = load_snapshot_items(path)
            return srv, items, path
        except WALError:
            continue
    return 0, [], ""


def recover_into(core, dirpath: str,
                 truncate_torn_tail: bool = True) -> RecoveryInfo:
    """Rebuild ``core`` (a store core — native or the Python twin, the
    same micro-interface) from the persistence dir: newest valid snapshot
    loaded wholesale (objects + per-object rvs, store rv, compaction
    horizon), then every WAL segment replayed in order through the core's
    own write verbs so the event ring and resourceVersion continuity come
    back exactly. Torn tail on the final segment is truncated (the
    crash's half-record); corruption elsewhere raises WALError."""
    info = RecoveryInfo()
    if not os.path.isdir(dirpath):
        return info
    # sweep mid-snapshot crash debris: half-written temp files were never
    # renamed in (the atomic-rename protocol), so they are dead weight —
    # one full-object-map-sized orphan per crash otherwise accretes
    for name in os.listdir(dirpath):
        if ".tmp." in name:
            try:
                os.unlink(os.path.join(dirpath, name))
            except OSError:
                pass
    snap_rv, items, _snap_path = _latest_valid_snapshot(dirpath)
    if snap_rv:
        core.load_snapshot(items, snap_rv)
        info.snapshot_rv = snap_rv
        info.snapshot_objects = len(items)
    segments = list_segments(dirpath)
    info.segments = len(segments)
    empty: list[str] = []
    for idx, (_seq, path) in enumerate(segments):
        last = idx == len(segments) - 1
        records_here = 0
        for tag, payload in iter_segment(path):
            if tag == "base":
                continue
            if tag == "torn":
                offset = payload
                if not (last and truncate_torn_tail):
                    raise WALError(
                        f"{path}: torn record at offset {offset} in a "
                        "non-final segment — mid-log corruption"
                    )
                size = os.path.getsize(path)
                with open(path, "r+b") as tf:
                    tf.truncate(offset)
                _fsync_dir(dirpath)
                info.truncated_bytes = size - offset
                info.truncated_segment = os.path.basename(path)
                break
            _off, ev, kind, key, obj, rv = payload
            records_here += 1
            have = core.resource_version()
            if rv <= have:
                info.skipped += 1           # double replay / leftover seg
                continue
            if rv != have + 1:
                raise WALError(
                    f"{path}: replay gap — record rv {rv} after store "
                    f"rv {have} (a segment is missing)"
                )
            if ev == 2:
                got = core.delete(kind, key)
            else:
                got = core.update(kind, key, obj, -1)
            if got != rv:
                raise WALError(
                    f"{path}: replay applied {kind}/{key} at rv {got}, "
                    f"record said {rv}"
                )
            info.replayed += 1
        if records_here == 0:
            empty.append(path)
    # prune header-only segments: every boot rotates to a fresh segment,
    # so a restart loop would otherwise accrete one empty file per boot
    # forever (they carry nothing — deleting them cannot touch the chain;
    # segments with rv-covered records stay until a compaction folds them)
    for path in empty:
        try:
            os.unlink(path)
            info.pruned_segments += 1
        except OSError:
            pass
    if empty:
        _fsync_dir(dirpath)
    info.resource_version = core.resource_version()
    return info


# ------------------------------------------------------------------ fsck

def fsck(dirpath: str) -> dict:
    """Offline integrity report for a persistence dir — what recovery
    WOULD do, without mutating anything (except nothing): per-snapshot
    validity, per-segment record counts, torn-tail position, replay-chain
    continuity. ``ok`` is False on anything recovery would refuse."""
    report: dict[str, Any] = {
        "dir": dirpath, "ok": True, "snapshots": [], "segments": [],
        "errors": [],
    }
    if not os.path.isdir(dirpath):
        report["ok"] = False
        report["errors"].append("not a directory")
        return report
    best_rv = 0
    for rv, path in list_snapshots(dirpath):
        entry = {"file": os.path.basename(path), "rv": rv}
        try:
            srv, items = load_snapshot_items(path)
            entry.update(valid=True, objects=len(items))
            best_rv = max(best_rv, srv)
        except WALError as e:
            entry.update(valid=False, error=str(e))
            report["ok"] = False
        report["snapshots"].append(entry)
    segments = list_segments(dirpath)
    chain_rv = best_rv
    for idx, (seq, path) in enumerate(segments):
        last = idx == len(segments) - 1
        entry: dict[str, Any] = {
            "file": os.path.basename(path), "seq": seq, "records": 0,
        }
        try:
            # same walk as recovery (iter_segment — one copy of the
            # format rules), report-don't-apply policy
            for tag, payload in iter_segment(path):
                if tag == "base":
                    entry["base_rv"] = payload
                    continue
                if tag == "torn":
                    entry["torn_at"] = payload
                    if not last:
                        report["ok"] = False
                        report["errors"].append(
                            f"{os.path.basename(path)}: torn record in "
                            "a non-final segment"
                        )
                    break
                _off, _ev, _kind, _key, _obj, rv = payload
                entry["records"] += 1
                if rv <= chain_rv:
                    continue
                if rv != chain_rv + 1:
                    report["ok"] = False
                    report["errors"].append(
                        f"{os.path.basename(path)}: replay gap "
                        f"({chain_rv} -> {rv})"
                    )
                chain_rv = rv
        except WALError as e:
            entry["error"] = str(e)
            report["ok"] = False
            report["errors"].append(str(e))
        report["segments"].append(entry)
    report["resource_version"] = chain_rv
    return report
