"""Versioned object storage + watch (the etcd3 / watch-cache layer)."""

from .memstore import CompactedError, MemStore, WatchEvent, Watcher  # noqa: F401
