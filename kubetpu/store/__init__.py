"""Versioned object storage + watch (the etcd3 / watch-cache layer) —
durable behind ``MemStore(persistence=dir)``: write-ahead log + compacted
snapshots + crash recovery (``kubetpu.store.wal``), with a deterministic
crash-point fault harness (``kubetpu.store.faultpoints``)."""

from .memstore import CompactedError, MemStore, WatchEvent, Watcher  # noqa: F401

#: wal.py imports the codec seam at module top; exporting it lazily keeps
#: `from kubetpu.store import MemStore` as light as before persistence
#: existed (memstore defers its own codec import for the same reason)
_WAL_EXPORTS = (
    "RecoveryInfo", "WALError", "WriteAheadLog", "fsck", "recover_into",
)


def __getattr__(name: str):
    if name in _WAL_EXPORTS:
        from . import wal

        return getattr(wal, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
