"""WAL log-shipping replication — leader/follower read plane for the store.

The reference's deployment shape is N apiservers over ONE durable log
(etcd): every apiserver serves reads/lists/watches from its own watch
cache, writes funnel through the raft leader, and failover promotes the
most-caught-up member by log position. The PR-13 wire ladder showed the
single-process ceiling (~60 pods/s at 5k nodes with 200 watchers —
throughput PARITY across 1..4 schedulers because every read frame funnels
through one apiserver); this module is the fix, built on the seams PR-11
already laid:

- **The wire IS the WAL.** A shipped record is the exact frame
  ``WriteAheadLog.append`` writes (u32 len | u32 crc | u8 kind_len | kind
  | event wire body); the bootstrap snapshot is the exact byte layout a
  compaction snapshot has. One copy of the format rules (kubetpu.store
  .wal), one fingerprint refusal for drifted builds.
- **Serialize-once, three consumers.** The leader's feed
  (``MemStore.replication_records``) drains the SAME per-event body ring
  that watch fan-out and the WAL share — one encode per event serves
  every watcher, the local log, and every follower.
- **Replay is recovery, live.** A follower applies shipped records
  through ``MemStore.apply_replicated`` — rv-gated exactly like
  ``recover_into`` (at-or-below: idempotent skip; a gap: loud resync),
  routed through the ``_commit_locked`` seam so the follower's event
  ring, resourceVersion continuity, and watch semantics are identical to
  having taken the writes itself. A follower watcher relists (410) only
  across a snapshot bootstrap — the same bounded contract as recovery.
- **Failover is by log position, fenced by the writer lease.** The
  leader holds the ``apiserver-writer`` lease IN ITS OWN STORE (the
  sched.leaderelection machinery over StoreLeaseClient), so every lease
  renewal replicates — the heartbeat IS a log record. On leader loss a
  follower polls its peers' /replication/status and promotes only when
  its position is the maximum (ties break by replica index); promotion
  flips the store writable and takes the lease, bumping
  ``leader_transitions`` — the fencing epoch. A ship carrying an epoch
  below a follower's observed epoch is refused loudly
  (``StaleEpochError``): a resurrected old leader cannot feed anyone.

Fault points (kubetpu.store.faultpoints, the ``rep-*`` tuple) instrument
the ship/apply/election boundaries; tests/test_replication.py kills the
leader at each and asserts exactly-once binding parity on the survivor.
"""

from __future__ import annotations

import http.client
import threading
import time
from typing import Callable
from urllib.parse import urlsplit

from ..api import codec
from . import faultpoints
from .memstore import MemStore, ReplicationGapError
from .wal import WALError, decode_snapshot_stream, frame_record, \
    iter_log_stream

#: replication endpoints' media type (the body is WAL frames / a WAL
#: snapshot stream — not a negotiated API object)
CT_WAL = "application/x-kubetpu-wal"

#: response headers carrying the feed's position + fencing state
H_CURSOR = "X-Kubetpu-Rep-Cursor"
H_EPOCH = "X-Kubetpu-Rep-Epoch"
H_CODEC = "X-Kubetpu-Rep-Codec"

#: the writer lease (sched.leaderelection over the replicated store):
#: ONE name both the leader's renewer and every follower's candidate use
LEASE_NAMESPACE = "kube-system"
LEASE_NAME = "apiserver-writer"


class ReplicationError(Exception):
    """Replication protocol failure (bad response, undecodable ship)."""


class StaleEpochError(ReplicationError):
    """A ship arrived from a leader whose epoch is BELOW the observed
    fencing epoch — a deposed leader still feeding. Refused loudly,
    never applied (the split-brain guard)."""


def build_log_body(store: MemStore, after_rv: int,
                   wire: str = codec.BINARY) -> tuple[bytes, int, int]:
    """The leader's ship: every event after ``after_rv`` as WAL frames
    off the serialize-once body ring → (body, cursor, record count).
    Raises CompactedError when the follower's cursor predates the ring
    (it must bootstrap from a snapshot instead)."""
    records, cursor = store.replication_records(after_rv, wire)
    faultpoints.fire("rep-mid-ship")
    return (
        b"".join(frame_record(kind, body) for kind, body in records),
        cursor, len(records),
    )


def default_clock() -> float:
    """Injectable-clock seam (the leaderelection discipline): replication
    timing — grace judgments, lag measurement — reads time only through
    a clock the tests can step."""
    return time.monotonic()


# ---------------------------------------------------------------- leader

class LeaderLease:
    """The leader half of the failover contract: hold the writer lease in
    the leader's OWN store and renew it on a cadence thread — every renew
    is an ordinary store write, so the lease record REPLICATES and a
    follower's view of it doubles as the leader heartbeat. The epoch the
    replication endpoints stamp on every ship is
    ``lease.leader_transitions + 1`` (first leader: transitions 0 →
    epoch 1; each failover bumps it — the fence)."""

    role = "leader"

    def __init__(self, store: MemStore, identity: str,
                 lease_duration_s: float = 5.0,
                 clock: Callable[[], float] = default_clock) -> None:
        from ..sched.leaderelection import LeaderElector, StoreLeaseClient

        self.store = store
        self.identity = identity
        self.lease_duration_s = lease_duration_s
        self._elector = LeaderElector(
            client=StoreLeaseClient(store),
            identity=identity,
            name=LEASE_NAME, namespace=LEASE_NAMESPACE,
            lease_duration_s=lease_duration_s,
            renew_deadline_s=lease_duration_s * (2.0 / 3.0),
            retry_period_s=lease_duration_s / 3.0,
            clock=clock,
        )
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="kubetpu-writer-lease", daemon=True
        )

    @property
    def epoch(self) -> int:
        return self._elector.observed_epoch() + 1

    @property
    def leader_url(self) -> str:
        return self.identity

    def start(self) -> "LeaderLease":
        self._elector.tick()            # acquire before serving writes
        self._thread.start()
        return self

    def _run(self) -> None:
        period = max(self.lease_duration_s / 3.0, 0.05)
        while not self._stop.wait(period):
            try:
                self._elector.tick()
            except Exception:  # noqa: BLE001 — renew must never kill serving
                pass

    def status(self) -> dict:
        return {
            "role": self.role,
            "leader": self.identity,
            "epoch": self.epoch,
            "resourceVersion": self.store.resource_version,
        }

    def metrics_text(self) -> str:
        return (
            "# HELP store_replication_epoch The writer-lease fencing "
            "epoch this process serves under.\n"
            "# TYPE store_replication_epoch gauge\n"
            f"store_replication_epoch {self.epoch}\n"
        )

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2)
        try:
            self._elector.release()
        except Exception:  # noqa: BLE001 — the store may already be closed
            pass


# -------------------------------------------------------------- follower

class _RepClient:
    """Minimal raw-bytes HTTP GET client for the replication endpoints
    (one persistent connection per base; used only from the replicator
    thread). The negotiated-codec machinery in RemoteStore is for API
    objects — shipped bytes are opaque WAL frames, decoded by wal.py."""

    def __init__(self, timeout_s: float = 10.0) -> None:
        self.timeout_s = timeout_s
        self._conns: dict[str, http.client.HTTPConnection] = {}

    def get(self, base: str, path: str,
            timeout_s: float | None = None):
        """→ (status, headers, body bytes); raises ConnectionError-family
        on transport failure (the caller's liveness signal)."""
        base = base.rstrip("/")
        conn = self._conns.get(base)
        fresh = conn is None
        if fresh:
            u = urlsplit(base)
            conn = http.client.HTTPConnection(
                u.hostname, u.port, timeout=timeout_s or self.timeout_s
            )
            self._conns[base] = conn
        try:
            if timeout_s is not None and conn.sock is not None:
                conn.sock.settimeout(timeout_s)
            conn.request("GET", path)
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        except (ConnectionError, TimeoutError, OSError,
                http.client.HTTPException):
            self.drop(base)
            if fresh:
                raise
            # keep-alive idle-close race: one retry on a fresh socket
            # (GETs are idempotent here — the cursor only moves on a
            # delivered, decoded, applied reply)
            return self.get(base, path, timeout_s)

    def drop(self, base: str) -> None:
        conn = self._conns.pop(base.rstrip("/"), None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass

    def close(self) -> None:
        for base in list(self._conns):
            self.drop(base)


class FollowerReplicator:
    """The follower half: a daemon thread tailing the leader's log into
    this process's follower store — bootstrap from a leader snapshot when
    the cursor predates the leader's ring, long-poll /replication/log
    otherwise, apply batches through the rv-gated seam, and measure lag.
    On sustained leader silence, run the election: compare log positions
    across peers, promote only as the most-caught-up (ties break by
    replica index), take the writer lease (epoch bump = fence). After
    promotion the same thread keeps renewing the lease — the object's
    ``role`` flips to "leader" and the owning apiserver stops
    redirecting writes."""

    def __init__(self, store: MemStore, leader_url: str,
                 wire: str = codec.BINARY,
                 self_url: str = "", peers: tuple = (),
                 replica_index: int = 0,
                 poll_timeout_s: float = 2.0,
                 grace_s: float = 6.0,
                 lease_duration_s: float = 5.0,
                 clock: Callable[[], float] = default_clock,
                 elect: bool = True,
                 upstream_url: str = "") -> None:
        """``peers``: every apiserver URL in the cluster (leader +
        followers, self included) — the election's electorate. ``elect``
        False pins this replica as a permanent follower (it re-targets a
        new leader but never promotes). ``upstream_url``: CHAINED
        shipping — tail this peer (another follower re-serving the feed)
        instead of the leader, so the leader's replication egress is
        O(direct fan-out) instead of O(followers). Writes still redirect
        to ``leader_url`` and elections still canvas ``peers``; a stale
        (fenced-epoch) or unreachable upstream falls this replica back to
        tailing the leader directly — chaining is an egress optimization,
        never a correctness dependency."""
        from ..sched.leaderelection import LeaderElector, StoreLeaseClient

        if not store.follower:
            raise ValueError("FollowerReplicator needs a follower store")
        self.store = store
        self.leader_url = leader_url.rstrip("/")
        self.upstream_url = upstream_url.rstrip("/")
        if self.upstream_url in (self.leader_url, self_url.rstrip("/")):
            self.upstream_url = ""      # self/leader chains degenerate
        #: where the tail/bootstrap GETs actually go (the chain link);
        #: cleared back to the leader on a stale or dead upstream
        self._tail_base = self.upstream_url or self.leader_url
        self.wire = wire
        self.self_url = self_url.rstrip("/")
        self.peers = tuple(p.rstrip("/") for p in peers)
        self.replica_index = replica_index
        self.poll_timeout_s = poll_timeout_s
        self.grace_s = max(grace_s, lease_duration_s)
        self.lease_duration_s = lease_duration_s
        self.clock = clock
        self.elect = elect
        # the candidate elector observes the REPLICATED writer lease in
        # this replica's own store: while the leader lives its renewals
        # replicate and keep the observation fresh; once the record
        # freezes past the lease duration the elector will usurp — but
        # the usurp WRITE can only land after promote() (the follower
        # guard refuses it before), so taking the lease is inseparable
        # from winning by log position
        self._elector = LeaderElector(
            client=StoreLeaseClient(store),
            identity=self_url or f"replica-{replica_index}",
            name=LEASE_NAME, namespace=LEASE_NAMESPACE,
            lease_duration_s=lease_duration_s,
            renew_deadline_s=lease_duration_s * (2.0 / 3.0),
            retry_period_s=lease_duration_s / 3.0,
            clock=clock,
        )
        self._client = _RepClient(timeout_s=max(poll_timeout_s * 3, 10.0))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="kubetpu-follower-replicator",
            daemon=True,
        )
        self._mu = threading.Lock()
        # fencing + lag state (guarded: the tail thread writes, the
        # status endpoint / metrics scrape read)
        self.observed_epoch = 0
        self.lag_records = 0
        self.lag_ms = 0.0
        self.records_applied = 0
        self.batches = 0
        self.resyncs = 0
        self.stale_refusals = 0
        self.gap_resyncs = 0
        self.promotions = 0
        self.upstream_fallbacks = 0
        self._last_contact = clock()
        self._bootstrapped = False

    # ---------------------------------------------------------- plumbing
    @property
    def role(self) -> str:
        # the store is the source of truth: promote() flips it writable
        return "follower" if self.store.follower else "leader"

    @property
    def epoch(self) -> int:
        if self.role == "leader":
            return self._elector.observed_epoch() + 1
        with self._mu:
            return self.observed_epoch

    def start(self) -> "FollowerReplicator":
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=max(self.poll_timeout_s * 3, 5.0))
        self._client.close()

    def _note_epoch(self, headers: dict) -> int:
        """Check + adopt a response's fencing epoch. A ship below the
        observed epoch is a deposed leader — refuse it loudly."""
        try:
            ep = int(headers.get(H_EPOCH, 0))
        except (TypeError, ValueError):
            ep = 0
        with self._mu:
            if ep < self.observed_epoch:
                self.stale_refusals += 1
                raise StaleEpochError(
                    f"ship from epoch {ep} refused — observed fencing "
                    f"epoch is {self.observed_epoch} (deposed leader?)"
                )
            self.observed_epoch = ep
        return ep

    # -------------------------------------------------------- tail follow
    def _fallback_to_leader(self, why: str) -> None:
        """Abandon a chained upstream and tail the leader directly (the
        chain is an optimization — a stale or dead link must never stall
        this replica's reads). One-way for this process's lifetime: the
        topology degrades to a star, which is always correct."""
        if self._tail_base == self.leader_url:
            return
        self._client.drop(self._tail_base)
        self._tail_base = self.leader_url
        with self._mu:
            self.upstream_fallbacks += 1
        self._last_contact = self.clock()   # re-arm the election grace

    def _bootstrap(self) -> None:
        """Full resync: load the feed's snapshot wholesale (watchers on
        this replica take the bounded 410 relist — recovery's contract).
        A chained replica bootstraps from its upstream too — the
        snapshot egress rides the chain like the log does."""
        base = self._tail_base
        status, headers, body = self._client.get(
            base, "/replication/snapshot"
        )
        if status != 200:
            raise ReplicationError(
                f"snapshot bootstrap: HTTP {status} from {base}"
            )
        self._note_epoch(headers)
        rv, items = decode_snapshot_stream(
            body, f"{base}/replication/snapshot"
        )
        self.store.load_replica_snapshot(items, rv)
        with self._mu:
            self.resyncs += 1
        self._bootstrapped = True

    def _tail_once(self) -> int:
        """One long-poll round: fetch → fence-check → decode → apply →
        measure. Returns records applied."""
        after = self.store.resource_version
        base = self._tail_base
        status, headers, body = self._client.get(
            base,
            f"/replication/log?after={after}"
            f"&timeoutSeconds={self.poll_timeout_s}"
            f"&codec={self.wire}",
            timeout_s=self.poll_timeout_s + self._client.timeout_s,
        )
        t_recv = time.perf_counter()
        if status == 410:
            self._bootstrap()
            self._last_contact = self.clock()
            return 0
        if status != 200:
            raise ReplicationError(
                f"log tail: HTTP {status} from {base}"
            )
        self._note_epoch(headers)
        self._last_contact = self.clock()
        wire = headers.get(H_CODEC, self.wire)
        try:
            cursor = int(headers.get(H_CURSOR, after))
        except (TypeError, ValueError):
            cursor = after
        if not body:
            with self._mu:
                self.lag_records = max(0, cursor - after)
                self.lag_ms = 0.0
            return 0
        faultpoints.fire("rep-post-ship-pre-apply")
        try:
            applied = self.store.apply_replicated_batch(
                iter_log_stream(body, wire, f"{base}/log")
            )
        except ReplicationGapError:
            # the feed skipped revisions (leader compacted under us mid-
            # flight): resync from a snapshot, exactly recovery's answer
            with self._mu:
                self.gap_resyncs += 1
            self._bootstrap()
            return 0
        with self._mu:
            self.batches += 1
            self.records_applied += applied
            self.lag_records = max(
                0, cursor - self.store.resource_version
            )
            # receipt→applied: how far behind a read served NOW is,
            # measured on one clock (no cross-process clock needed)
            self.lag_ms = (time.perf_counter() - t_recv) * 1000.0
        return applied

    # ----------------------------------------------------------- election
    def _peer_positions(self) -> dict:
        """Every reachable peer's /replication/status (self excluded)."""
        out: dict[str, dict] = {}
        for url in self.peers:
            if url and url != self.self_url:
                try:
                    status, _h, body = self._client.get(
                        url, "/replication/status",
                        timeout_s=max(self.poll_timeout_s, 1.0),
                    )
                    if status == 200:
                        out[url] = codec.loads(body, codec.JSON)
                except (ConnectionError, TimeoutError, OSError,
                        http.client.HTTPException,
                        codec.UnsupportedWireError):
                    continue
        return out

    def _try_election(self) -> bool:
        """The failover decision, by log position: promote only when no
        live peer claims a fresher epoch, no live peer is ahead of us,
        and no tied peer outranks us (lower replica index wins). Then
        the lease: promote() flips the store writable and the elector's
        usurp CAS takes the writer lease, bumping leader_transitions —
        the epoch every subsequent ship is fenced by."""
        if not self.elect:
            return False
        my_rv = self.store.resource_version
        with self._mu:
            my_epoch = self.observed_epoch
        peers = self._peer_positions()
        for url, st in peers.items():
            ep = int(st.get("epoch", 0))
            if st.get("role") == "leader" and ep >= my_epoch:
                # someone already won: follow them
                self._retarget(url, ep)
                return False
            peer_rv = int(st.get("resourceVersion", 0))
            peer_idx = int(st.get("replicaIndex", 1 << 30))
            if peer_rv > my_rv:
                return False            # log position: they win
            if peer_rv == my_rv and peer_idx < self.replica_index:
                return False            # tie: lower index wins
        faultpoints.fire("rep-mid-election")
        # the lease CAS is the commit point: promote, then take it
        self.store.promote()
        deadline = self.clock() + self.lease_duration_s
        won = False
        while not won and self.clock() < deadline and not self._stop.is_set():
            try:
                won = self._elector.tick()
            except Exception:  # noqa: BLE001 — lease store hiccup: retry
                won = False
            if not won:
                self._stop.wait(min(self.lease_duration_s / 10.0, 0.2))
        if not won:
            # could not take the lease (another candidate raced us there):
            # step back down — and RESYNC, because any write accepted
            # during the candidacy window diverges from the real winner's
            # log at an equal-or-higher rv the rv-gate alone cannot see
            self.store.demote()
            try:
                self._bootstrap()
            except Exception:  # noqa: BLE001 — the tail loop retries/retargets
                pass
            return False
        with self._mu:
            self.promotions += 1
            self.observed_epoch = self._elector.observed_epoch() + 1
        return True

    def _retarget(self, url: str, epoch: int) -> None:
        """Follow a new leader (post-failover): adopt its epoch and point
        the tail at it; the rv-gated apply + snapshot resync make the
        switch safe wherever our cursor lands. A chained upstream is
        abandoned here — it was a link toward the OLD leader, and any
        stale feed it still serves would be fenced anyway."""
        self._client.drop(self._tail_base)
        self._client.drop(self.leader_url)
        if self._tail_base != self.leader_url:
            with self._mu:
                self.upstream_fallbacks += 1
        self.leader_url = url
        self._tail_base = url
        with self._mu:
            self.observed_epoch = max(self.observed_epoch, epoch)

    # --------------------------------------------------------------- loop
    def _run(self) -> None:
        while not self._stop.is_set():
            if self.role == "leader":
                # post-promotion: this thread becomes the lease renewer
                try:
                    self._elector.tick()
                except Exception:  # noqa: BLE001
                    pass
                self._stop.wait(max(self.lease_duration_s / 3.0, 0.05))
                continue
            try:
                # observe the replicated writer lease (read-only while the
                # leader lives; the usurp write below the follower guard
                # can only land after promote)
                try:
                    self._elector.tick()
                except Exception:  # noqa: BLE001 — FollowerWriteError et al.
                    pass
                self._tail_once()
            except StaleEpochError:
                if self._tail_base != self.leader_url:
                    # the CHAIN is stale (a link still serving a fenced
                    # epoch), not necessarily the leader: drop to the
                    # leader's feed before judging leader liveness
                    self._fallback_to_leader("stale-epoch")
                    continue
                # deposed leader still feeding: find the real one
                self._try_election()
            except (ConnectionError, TimeoutError, OSError,
                    http.client.HTTPException, ReplicationError,
                    WALError):
                if self._tail_base != self.leader_url:
                    # a dead upstream link must not read as leader
                    # silence — re-tail the leader and re-arm the grace
                    self._fallback_to_leader("unreachable")
                    continue
                if self.clock() - self._last_contact > self.grace_s:
                    if self._try_election():
                        continue
                    self._last_contact = self.clock()   # re-arm the grace
                self._stop.wait(min(self.poll_timeout_s / 4.0, 0.25))

    # ----------------------------------------------------- observability
    def status(self) -> dict:
        with self._mu:
            return {
                "role": self.role,
                "leader": (
                    self.self_url if self.role == "leader"
                    else self.leader_url
                ),
                "epoch": (
                    self._elector.observed_epoch() + 1
                    if self.role == "leader" else self.observed_epoch
                ),
                "resourceVersion": self.store.resource_version,
                "replicaIndex": self.replica_index,
                "lagRecords": self.lag_records,
                "lagMs": round(self.lag_ms, 3),
                "recordsApplied": self.records_applied,
                "resyncs": self.resyncs,
                "staleRefusals": self.stale_refusals,
                "promotions": self.promotions,
                # chained shipping: where the tail actually points ("" =
                # the leader itself), and how often a chain link died
                "upstream": (
                    self._tail_base
                    if self._tail_base != self.leader_url else ""
                ),
                "upstreamFallbacks": self.upstream_fallbacks,
            }

    def metrics_text(self) -> str:
        """The follower's Prometheus set — mounted on the owning
        apiserver's /metrics; the sentinel's ``replication_lag`` rule
        watches these series (absent entirely on a non-replicated
        server, so the rule stays dormant there)."""
        with self._mu:
            lines = [
                "# HELP store_replication_lag_records Records the leader "
                "has committed that this replica has not applied.\n"
                "# TYPE store_replication_lag_records gauge\n"
                f"store_replication_lag_records {self.lag_records}\n"
                "# HELP store_replication_lag_ms Receipt-to-applied "
                "latency of the last shipped batch in milliseconds.\n"
                "# TYPE store_replication_lag_ms gauge\n"
                f"store_replication_lag_ms {round(self.lag_ms, 3)}\n"
                "# HELP store_replication_applied_total Shipped records "
                "applied through the replication seam.\n"
                "# TYPE store_replication_applied_total counter\n"
                f"store_replication_applied_total {self.records_applied}\n"
                "# HELP store_replication_resyncs_total Snapshot "
                "bootstraps/resyncs this replica has taken.\n"
                "# TYPE store_replication_resyncs_total counter\n"
                f"store_replication_resyncs_total {self.resyncs}\n"
                "# HELP store_replication_stale_refusals_total Ships "
                "refused for carrying a fenced (stale) epoch.\n"
                "# TYPE store_replication_stale_refusals_total counter\n"
                f"store_replication_stale_refusals_total "
                f"{self.stale_refusals}\n"
                "# HELP store_replication_epoch The fencing epoch this "
                "replica last observed (or serves under, once leader).\n"
                "# TYPE store_replication_epoch gauge\n"
                f"store_replication_epoch {self.observed_epoch}\n"
                "# HELP store_replication_upstream_fallbacks_total Times "
                "this replica abandoned a chained upstream for the "
                "leader's feed (stale epoch, dead link, or failover).\n"
                "# TYPE store_replication_upstream_fallbacks_total "
                "counter\n"
                f"store_replication_upstream_fallbacks_total "
                f"{self.upstream_fallbacks}\n"
            ]
        return "".join(lines)
