"""Deterministic crash-point fault harness for the durable store.

The WAL's correctness claims are all about WHERE a crash lands relative to
the append/fsync/apply/snapshot/truncate boundaries — claims a wall-clock
kill can only sample, never pin. This module gives the write path NAMED
injection points; tests (and the perf runner's recovery stage) arm a point
with a hit count and the instrumented site raises ``CrashPoint`` exactly
there, simulating the process dying at that instruction. The store object
is then abandoned (its in-memory state is the "lost" state) and recovery
is exercised against the on-disk artifact the crash left behind.

``CrashPoint`` derives from ``BaseException`` deliberately: the store and
apiserver paths contain broad ``except Exception`` containment (a 500
handler, a bulk-op ladder), and a simulated process death must never be
swallowed into a 500 reply — a real SIGKILL would not be.

The points (see kubetpu.store.wal for the exact sites):

========================== =================================================
``wal-pre-append``         before any record byte reaches the segment file:
                           the write is lost entirely (never acked, never
                           durable) — recovery must equal the pre-crash
                           state exactly.
``wal-mid-record``         a TORN write: half the framed record hits the
                           file, then death. Recovery must detect the torn
                           tail (length/checksum) and truncate it.
``wal-post-append-pre-apply`` the record is appended AND fsync'd but the
                           core never applied it: the one case where
                           recovery legitimately knows MORE than the dead
                           process's memory — replay applies the record
                           (the write was durable; its ack was lost).
``wal-mid-snapshot``       death halfway through writing a compaction
                           snapshot: the temp file is abandoned, the
                           previous snapshot + full segment chain must
                           still recover.
``wal-mid-truncate``       death after the new snapshot landed but midway
                           through deleting superseded segments/snapshots:
                           recovery must skip already-covered records
                           idempotently (replay is rv-gated).
``rep-mid-ship``           the leader dies while assembling/serving a
                           replication batch: followers saw none or part of
                           the batch — failover must preserve exactly-once
                           apply of every ACKED write (the shipped-but-
                           unacked tail is the old leader's to lose).
``rep-post-ship-pre-apply`` the follower received a batch but dies (or the
                           leader dies) before ``apply_replicated`` ran:
                           the re-fetched batch must apply idempotently
                           (replication apply is rv-gated like recovery).
``rep-mid-election``       death between choosing to promote (log position
                           won) and completing the promotion: the next
                           election round must converge on A leader with
                           the fenced epoch, never two.
========================== =================================================

The harness is process-global and OFF by default: ``fire()`` is a single
dict lookup when nothing is armed, so the production write path pays ~0.
"""

from __future__ import annotations

import threading

#: every named injection point, in write-path order (the torture loop in
#: tests/test_wal.py iterates this tuple — a new point added to the WAL
#: must be registered here or arming it raises)
FAULT_POINTS = (
    "wal-pre-append",
    "wal-mid-record",
    "wal-post-append-pre-apply",
    "wal-mid-snapshot",
    "wal-mid-truncate",
)

#: replication-path injection points (kubetpu.store.replication) — a
#: SEPARATE tuple because the WAL torture loop above fires each of its
#: points on a plain store write, which never traverses the replication
#: path (tests/test_replication.py drives these)
REPLICATION_FAULT_POINTS = (
    "rep-mid-ship",
    "rep-post-ship-pre-apply",
    "rep-mid-election",
)

ALL_FAULT_POINTS = FAULT_POINTS + REPLICATION_FAULT_POINTS


class CrashPoint(BaseException):
    """A simulated process death at a named fault point. BaseException so
    no ``except Exception`` containment on the write path can turn a
    "crash" into a handled error (a real kill would not be handled)."""

    def __init__(self, name: str) -> None:
        super().__init__(f"simulated crash at fault point {name!r}")
        self.point = name


_lock = threading.Lock()
_armed: dict[str, int] = {}     # point -> remaining traversals before firing
_hits: dict[str, int] = {}      # point -> traversals observed (armed or not)
_fired: list[str] = []          # points that actually crashed, in order


def arm(name: str, at_hit: int = 1) -> None:
    """Arm ``name`` to crash on its ``at_hit``-th traversal (1 = next)."""
    if name not in ALL_FAULT_POINTS:
        raise ValueError(f"unknown fault point {name!r}")
    if at_hit < 1:
        raise ValueError("at_hit must be >= 1")
    with _lock:
        _armed[name] = at_hit


def disarm(name: str) -> None:
    with _lock:
        _armed.pop(name, None)


def reset() -> None:
    """Disarm everything and zero the counters (test teardown)."""
    with _lock:
        _armed.clear()
        _hits.clear()
        _fired.clear()


def hits(name: str) -> int:
    """Traversals observed WHILE the harness was armed (the unarmed fast
    path deliberately does not count — see ``due``)."""
    with _lock:
        return _hits.get(name, 0)


def fired() -> tuple:
    with _lock:
        return tuple(_fired)


def due(name: str) -> bool:
    """One traversal of ``name``; True when the armed countdown just
    reached zero (the caller performs any pre-crash action — e.g. the torn
    half-record write — then calls ``crash``). Sites without a pre-crash
    action use ``fire`` instead. The unarmed path is ONE dict truthiness
    check with no lock and no counting — these sites sit inside the
    store's per-write critical section, so the production cost must stay
    ~0 (``hits`` only observes traversals made while something is armed)."""
    if not _armed:          # fast path: harness off
        return False
    with _lock:
        _hits[name] = _hits.get(name, 0) + 1
        remaining = _armed.get(name)
        if remaining is None:
            return False
        remaining -= 1
        if remaining > 0:
            _armed[name] = remaining
            return False
        del _armed[name]    # one-shot: firing consumes the arming
        return True


def crash(name: str) -> None:
    """Raise the simulated death for ``name`` (after ``due`` said so)."""
    with _lock:
        _fired.append(name)
    raise CrashPoint(name)


def fire(name: str) -> None:
    """Count a traversal and crash if the point is due — the plain
    instrumentation call for sites with no pre-crash action."""
    if due(name):
        crash(name)
