"""Versioned in-memory object store with watch — layer 0 of the stack.

Reference semantics mirrored (storage is host-side by design, SURVEY §2.9 —
the device-resident tensors are the hot store; THIS layer is the source of
truth every component watches):

- etcd3 store (apiserver/pkg/storage/etcd3/store.go): every write bumps one
  monotonically increasing resourceVersion; Create fails on exists (:269),
  ``GuaranteedUpdate`` does optimistic CAS on resourceVersion (:458);
  GetList returns the store's current revision (:733).
- Watch cache (apiserver/pkg/storage/cacher/cacher.go:263): one ring buffer
  of events fans out to N watchers; a watcher asking for a revision older
  than the buffer gets "too old" (HTTP 410 Gone) and must relist —
  ``CompactedError`` here, consumed by the Reflector's relist loop
  (client-go reflector.go ListAndWatch).

Watchers are PULL-based (``Watcher.poll``): the schedulers/controllers in
this framework fold their pumps into their loops (same shape as the queue's
flush timers); ``wait_for`` provides the blocking form for threads.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import dataclass
from typing import Any, Iterable

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


class CompactedError(Exception):
    """The requested resourceVersion predates the event buffer (the watch
    cache's 'too old resource version' / HTTP 410 — relist required)."""


class ConflictError(Exception):
    """CAS failure: the object moved past the expected resourceVersion."""


@dataclass(frozen=True)
class WatchEvent:
    type: str              # ADDED | MODIFIED | DELETED
    kind: str              # resource bucket ("nodes", "pods", …)
    key: str
    obj: Any               # the object AFTER the change (before, for DELETED)
    resource_version: int


class MemStore:
    """See module docstring. Thread-safe; writes are serialized."""

    def __init__(self, history: int = 8192) -> None:
        self._lock = threading.Condition()
        self._rv = 0
        # (kind, key) -> (obj, rv)
        self._objects: dict[tuple[str, str], tuple[Any, int]] = {}
        self._events: collections.deque[WatchEvent] = collections.deque(
            maxlen=history
        )
        self._compacted_through = 0   # highest rv dropped from the buffer

    # ------------------------------------------------------------- writes
    def _emit(self, ev: WatchEvent) -> None:
        if len(self._events) == self._events.maxlen:
            self._compacted_through = self._events[0].resource_version
        self._events.append(ev)
        self._lock.notify_all()

    def create(self, kind: str, key: str, obj: Any) -> int:
        with self._lock:
            if (kind, key) in self._objects:
                raise ConflictError(f"{kind}/{key} already exists")
            self._rv += 1
            self._objects[(kind, key)] = (obj, self._rv)
            self._emit(WatchEvent(ADDED, kind, key, obj, self._rv))
            return self._rv

    def update(
        self, kind: str, key: str, obj: Any, expect_rv: int | None = None
    ) -> int:
        """GuaranteedUpdate: CAS when ``expect_rv`` is given; upsert when the
        object is absent and no CAS was requested."""
        with self._lock:
            got = self._objects.get((kind, key))
            if expect_rv is not None:
                if got is None or got[1] != expect_rv:
                    raise ConflictError(
                        f"{kind}/{key}: expected rv {expect_rv}, "
                        f"have {got[1] if got else 'absent'}"
                    )
            self._rv += 1
            self._objects[(kind, key)] = (obj, self._rv)
            self._emit(WatchEvent(
                ADDED if got is None else MODIFIED, kind, key, obj, self._rv
            ))
            return self._rv

    def delete(self, kind: str, key: str) -> int:
        with self._lock:
            got = self._objects.pop((kind, key), None)
            if got is None:
                raise KeyError(f"{kind}/{key} not found")
            self._rv += 1
            self._emit(WatchEvent(DELETED, kind, key, got[0], self._rv))
            return self._rv

    # -------------------------------------------------------------- reads
    def get(self, kind: str, key: str):
        with self._lock:
            got = self._objects.get((kind, key))
            return (None, 0) if got is None else got

    def list(self, kind: str) -> tuple[list[tuple[str, Any]], int]:
        """GetList: items + the revision the list is consistent at."""
        with self._lock:
            items = [
                (key, obj)
                for (k, key), (obj, _rv) in self._objects.items()
                if k == kind
            ]
            return items, self._rv

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._rv

    # -------------------------------------------------------------- watch
    def watch(self, kind: str | None, since_rv: int) -> "Watcher":
        """A pull watcher for events AFTER ``since_rv`` (``kind`` None =
        all buckets). Raises CompactedError immediately when the start
        revision predates the buffer."""
        with self._lock:
            if since_rv < self._compacted_through:
                raise CompactedError(
                    f"rv {since_rv} compacted (through "
                    f"{self._compacted_through})"
                )
        return Watcher(self, kind, since_rv)

    def _events_since(
        self, kind: str | None, rv: int
    ) -> tuple[list[WatchEvent], int]:
        """Returns ``(matching events, new cursor)`` — the cursor covers
        every event examined (matching or not), so a kind-filtered watcher
        never re-scans other kinds' events."""
        with self._lock:
            if rv < self._compacted_through:
                raise CompactedError(
                    f"rv {rv} compacted (through {self._compacted_through})"
                )
            # hot path: N reflectors poll every cycle; an up-to-date cursor
            # must be O(1), and a behind cursor must only touch events NEWER
            # than it (events are rv-ordered) — never the whole ring buffer
            if not self._events or self._events[-1].resource_version <= rv:
                return [], rv
            cursor = self._events[-1].resource_version
            out: list[WatchEvent] = []
            for e in reversed(self._events):
                if e.resource_version <= rv:
                    break
                if kind is None or e.kind == kind:
                    out.append(e)
            out.reverse()
            return out, cursor

    def wait_for(self, rv: int, timeout: float | None = None) -> bool:
        """Block until the store moves past ``rv`` (thread form)."""
        with self._lock:
            return self._lock.wait_for(
                lambda: self._rv > rv, timeout=timeout
            )


class Watcher:
    """One watch stream: ``poll()`` drains events after the cursor."""

    def __init__(self, store: MemStore, kind: str | None, since_rv: int) -> None:
        self._store = store
        self._kind = kind
        self._rv = since_rv

    @property
    def resource_version(self) -> int:
        return self._rv

    def poll(self) -> list[WatchEvent]:
        """New events since the cursor; raises CompactedError when the
        cursor fell behind the ring buffer (caller relists)."""
        events, self._rv = self._store._events_since(self._kind, self._rv)
        return events
