"""Versioned in-memory object store with watch — layer 0 of the stack.

Reference semantics mirrored (storage is host-side by design, SURVEY §2.9 —
the device-resident tensors are the hot store; THIS layer is the source of
truth every component watches):

- etcd3 store (apiserver/pkg/storage/etcd3/store.go): every write bumps one
  monotonically increasing resourceVersion; Create fails on exists (:269),
  ``GuaranteedUpdate`` does optimistic CAS on resourceVersion (:458);
  GetList returns the store's current revision (:733).
- Watch cache (apiserver/pkg/storage/cacher/cacher.go:263): one ring buffer
  of events fans out to N watchers; a watcher asking for a revision older
  than the buffer gets "too old" (HTTP 410 Gone) and must relist —
  ``CompactedError`` here, consumed by the Reflector's relist loop
  (client-go reflector.go ListAndWatch).

Two interchangeable CORES behind one locking wrapper (the reference's
storage engine is native code — etcd; kubetpu.native/memstore_core.cpp is
this framework's equivalent):

- the C++ ``StoreCore`` (kubetpu.native), compiled on first use, and
- ``_PyCore``, the pure-Python fallback (``KUBETPU_NO_NATIVE=1`` or no
  compiler).

Both expose the same micro-interface and exception mapping; the wrapper
owns the Condition lock (serializing every call — the native core is
single-writer by construction) and the blocking ``wait_for``.

Watchers are PULL-based (``Watcher.poll``): the schedulers/controllers in
this framework fold their pumps into their loops (same shape as the queue's
flush timers); ``wait_for`` provides the blocking form for threads.
"""

from __future__ import annotations

import collections
import os
import threading
from dataclasses import dataclass
from typing import Any

from . import faultpoints

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

_EVENT_TYPES = (ADDED, MODIFIED, DELETED)

_WIRE_ENCODERS: dict[str, Any] = {}


def _wire_ids() -> dict:
    """codec name → dense slot id in the cores' per-event body ring.
    ONE authoritative Python-side table (kubetpu.api.codec.WIRE_CODEC_IDS
    — the native Event struct's fixed kNumCodecs array must stay aligned
    with it), imported lazily so layer 0 imports stay light."""
    from ..api.codec import WIRE_CODEC_IDS

    return WIRE_CODEC_IDS


def _wire_encoder(codec: str):
    """(encoder, codec id) for the body ring's miss path."""
    got = _WIRE_ENCODERS.get(codec)
    if got is None:
        from ..api.codec import event_body_encoder

        got = (event_body_encoder(codec), _wire_ids()[codec])
        _WIRE_ENCODERS[codec] = got
    return got


class CompactedError(Exception):
    """The requested resourceVersion predates the event buffer (the watch
    cache's 'too old resource version' / HTTP 410 — relist required)."""


class ConflictError(Exception):
    """CAS failure: the object moved past the expected resourceVersion, or
    Create hit an existing object."""


class FollowerWriteError(Exception):
    """A local write reached a replication FOLLOWER store. Followers are
    read-only replicas — every mutation must land on the leader (the
    apiserver answers with a redirect carrying the leader's URL); the only
    paths that may move a follower's core are ``apply_replicated`` and
    ``load_replica_snapshot`` (graftcheck RP001 pins the seam)."""


class ReplicationGapError(Exception):
    """The replication feed skipped revisions — a shipped record's rv is
    not contiguous with the follower's store. The follower must resync
    from a leader snapshot (the live-replay twin of recovery's WALError
    'replay gap'; never silently applied out of order)."""


def bulk_result_error(res: dict) -> Exception | None:
    """Map one bulk-op result (the ``{"status": …, "error": …}`` dicts
    ``MemStore.bulk``/``RemoteStore.bulk`` return) to the exception the
    matching single-op verb would have raised — one mapping for both
    deployment shapes, so callers of either surface handle conflicts and
    absences identically."""
    status = res.get("status", 500)
    if status < 400:
        return None
    reason = res.get("error", f"status {status}")
    if status == 409:
        return ConflictError(reason)
    if status == 404:
        return KeyError(reason)
    if status in (400, 422):
        return ValueError(reason)
    if status == 403:
        return PermissionError(reason)
    return RuntimeError(f"{status}: {reason}")


@dataclass(frozen=True)
class WatchEvent:
    type: str              # ADDED | MODIFIED | DELETED
    kind: str              # resource bucket ("nodes", "pods", …)
    key: str
    obj: Any               # the object AFTER the change (before, for DELETED)
    resource_version: int


class _PyCore:
    """Pure-Python core: the same micro-interface as the native StoreCore
    (create/update/delete/get/list/events_since[+bulk]/
    event_bodies_since[+bulk]/resource_version), same exception types
    (KeyError/ValueError/LookupError — mapped by the wrapper).

    Ring entries are 6-slot lists — the 6th slot is the per-event WIRE
    BODY cache ({codec id: bytes}, the serialize-once body ring): an
    event's wire encoding is immutable (store writes replace objects,
    never mutate them), so a cached body can never go stale and dies with
    its ring entry."""

    def __init__(self, history: int = 8192) -> None:
        self._rv = 0
        # (obj, rv, seq) — seq is the insertion order (stable across
        # updates), the paged list walk's cursor axis; matches the native
        # core's Entry.seq
        self._objects: dict[tuple[str, str], tuple[Any, int, int]] = {}
        self._seq = 0
        self._events: collections.deque = collections.deque(maxlen=history)
        self._compacted_through = 0
        self._body_hits = [0, 0]      # per codec id (0 json, 1 binary)
        self._body_misses = [0, 0]

    def _emit(self, ev_type: int, kind: str, key: str, obj: Any) -> None:
        if len(self._events) == self._events.maxlen:
            self._compacted_through = self._events[0][4]
        self._events.append([ev_type, kind, key, obj, self._rv, {}])

    def create(self, kind: str, key: str, obj: Any) -> int:
        if (kind, key) in self._objects:
            raise KeyError(f"{kind}/{key} already exists")
        self._rv += 1
        self._seq += 1
        self._objects[(kind, key)] = (obj, self._rv, self._seq)
        self._emit(0, kind, key, obj)
        return self._rv

    def update(self, kind: str, key: str, obj: Any, expect: int = -1) -> int:
        got = self._objects.get((kind, key))
        if expect >= 0:
            have = got[1] if got is not None else -1
            if got is None or have != expect:
                raise ValueError(
                    f"{kind}/{key}: expected rv {expect}, have "
                    f"{have if got is not None else 'absent'}"
                )
        self._rv += 1
        if got is None:
            self._seq += 1
            seq = self._seq
        else:
            seq = got[2]                 # updates do not reorder
        self._objects[(kind, key)] = (obj, self._rv, seq)
        self._emit(0 if got is None else 1, kind, key, obj)
        return self._rv

    def delete(self, kind: str, key: str) -> int:
        got = self._objects.pop((kind, key), None)
        if got is None:
            raise KeyError(f"{kind}/{key} not found")
        self._rv += 1
        self._emit(2, kind, key, got[0])
        return self._rv

    def get(self, kind: str, key: str):
        got = self._objects.get((kind, key))
        return (None, 0) if got is None else (got[0], got[1])

    def list(self, kind: str, label_terms: tuple = (),
             field_terms: tuple = ()):
        items = [
            (key, obj)
            for (k, key), (obj, _rv, _seq) in self._objects.items()
            if k == kind
        ]
        if label_terms or field_terms:
            from ..api.selectors import object_matches_selectors

            items = [
                (k, o) for k, o in items
                if object_matches_selectors(o, label_terms, field_terms)
            ]
        return items, self._rv

    def list_page(self, kind: str, label_terms: tuple = (),
                  field_terms: tuple = (), limit: int = 0,
                  after_seq: int = 0, through_seq: int = 0):
        """One bounded page of the seq-ordered list walk — the pagination
        primitive behind ``MemStore._list_page_locked``; returns
        ``(items [(key, obj, rv)], store_rv, next_seq, has_more,
        through_seq)``. Seq order is insertion order and updates never
        reorder, so a walk resumed at ``next_seq`` can neither duplicate
        nor skip an object that existed across the whole walk.
        ``through_seq`` caps the walk at a seq bound so objects CREATED
        mid-walk never splice into later pages (the snapshot-cut half of
        the continue-token contract); ``through_seq <= 0`` captures the
        current max seq and echoes it back for the caller's token.
        ``limit <= 0`` is unbounded (the full-list form).
        Selector-filtered candidates still advance ``next_seq`` (a
        filtered walk always makes progress); ``has_more`` reports
        whether any in-bound candidate of the kind remains past this
        page."""
        matcher = None
        if label_terms or field_terms:
            from ..api.selectors import object_matches_selectors

            matcher = object_matches_selectors
        bound = through_seq if through_seq > 0 else self._seq
        items: list = []
        next_seq = after_seq
        has_more = False
        # dict insertion order IS seq order (updates keep both), so no sort
        for (k, key), (obj, rv, seq) in self._objects.items():
            if k != kind or seq <= after_seq or seq > bound:
                continue
            if limit > 0 and len(items) >= limit:
                has_more = True
                break
            if matcher is None or matcher(obj, label_terms, field_terms):
                items.append((key, obj, rv))
            next_seq = seq
        return items, self._rv, next_seq, has_more, bound

    def _collect_since(self, kind: str | None, rv: int):
        """Ring entries newer than ``rv`` for ``kind`` + the new cursor
        (oldest first)."""
        if not self._events or self._events[-1][4] <= rv:
            return [], rv
        cursor = self._events[-1][4]
        out = []
        for e in reversed(self._events):
            if e[4] <= rv:
                break
            if kind is None or e[1] == kind:
                out.append(e)
        out.reverse()
        return out, cursor

    def events_since(self, kind: str | None, rv: int):
        if rv < self._compacted_through:
            raise LookupError(
                f"rv {rv} compacted (through {self._compacted_through})"
            )
        hits, cursor = self._collect_since(kind, rv)
        return [tuple(e[:5]) for e in hits], cursor

    def events_since_bulk(self, cursors: dict):
        """Every kind's cursor drained in one call (None marks a
        compacted kind); second value is the revision at the drain."""
        out: dict = {}
        for kind, rv in cursors.items():
            if rv < self._compacted_through:
                out[kind] = None
                continue
            out[kind] = self.events_since(kind, rv)
        return out, self._rv

    def _event_body(self, e: list, codec_id: int, encoder) -> bytes:
        body = e[5].get(codec_id)
        if body is not None:
            self._body_hits[codec_id] += 1
            return body
        body = encoder(e[0], e[2], e[3], e[4])
        e[5][codec_id] = body
        self._body_misses[codec_id] += 1
        return body

    def event_bodies_since(self, kind: str | None, rv: int,
                           codec_id: int, encoder):
        """The serialize-once fan-out path: cached wire bodies for every
        event newer than ``rv`` (encoded once per event per codec via
        ``encoder(type_id, key, obj, rv) -> bytes`` on first sight)."""
        if rv < self._compacted_through:
            raise LookupError(
                f"rv {rv} compacted (through {self._compacted_through})"
            )
        hits, cursor = self._collect_since(kind, rv)
        return (
            [self._event_body(e, codec_id, encoder) for e in hits],
            cursor,
        )

    def event_bodies_since_bulk(self, cursors: dict, codec_id: int,
                                encoder):
        out: dict = {}
        for kind, rv in cursors.items():
            if rv < self._compacted_through:
                out[kind] = None
                continue
            out[kind] = self.event_bodies_since(kind, rv, codec_id, encoder)
        return out, self._rv

    def clear_event_bodies(self) -> None:
        """Drop every cached wire body (the ring events stay) — the
        registry-generation flush: binary bodies embed schema-table ids
        that shift when a kind registers late."""
        for e in self._events:
            e[5].clear()

    def body_cache_stats(self) -> dict:
        return {
            cid: (self._body_hits[cid], self._body_misses[cid])
            for cid in (0, 1)
        }

    def resource_version(self) -> int:
        return self._rv

    def compacted_through(self) -> int:
        return self._compacted_through

    # ------------------------------------------------- durability surface
    def dump(self):
        """Every object as (kind, key, obj, rv) in insertion order — the
        compaction snapshot's input (and the recovery tests' parity
        probe). Insertion order matters: ``load_snapshot`` must rebuild
        the same list() ordering both cores guarantee."""
        return [
            (kind, key, obj, rv)
            for (kind, key), (obj, rv, _seq) in self._objects.items()
        ]

    def load_snapshot(self, items, rv: int) -> None:
        """Reset to a snapshot: objects with their per-object rvs (CAS
        survives recovery), store revision ``rv``, event ring EMPTY with
        the compaction horizon at ``rv`` — a watcher cursor below the
        snapshot predates everything replayable and must 410 into a full
        relist; the replayed WAL tail then repopulates the ring."""
        self._objects = {
            (kind, key): (obj, obj_rv, seq)
            for seq, (kind, key, obj, obj_rv) in enumerate(items, start=1)
        }
        self._seq = len(self._objects)
        self._rv = rv
        self._events.clear()
        self._compacted_through = rv


class MemStore:
    """See module docstring. Thread-safe; writes are serialized under one
    Condition, which also backs the blocking ``wait_for``."""

    def __init__(self, history: int = 8192, native: bool | None = None,
                 persistence: "str | None" = None,
                 wal_wire: str = "binary", wal_fsync: bool = True,
                 compact_every: int = 65536,
                 follower: bool = False) -> None:
        """``persistence``: a directory path turns on the write-ahead log
        + snapshot durability (kubetpu.store.wal) — recover-on-start
        replays snapshot+tail into the core, every committed write is
        logged-then-applied, and compaction runs automatically every
        ``compact_every`` records. None (the default, ``--persistence
        off``) is byte-identical to the memory-only store. ``wal_wire``
        picks the record codec (binary default — the compact wire the
        body ring speaks); ``wal_fsync=False`` is the benchmark escape
        hatch (flush-to-OS only). ``follower`` makes this store a
        replication replica: local writes raise FollowerWriteError and
        the core moves ONLY through ``apply_replicated`` /
        ``load_replica_snapshot`` (kubetpu.store.replication tails the
        leader's log into this seam) until ``promote()``."""
        if follower and persistence:
            raise ValueError(
                "a follower store is a memory replica — its durability is "
                "the leader's WAL (bootstrap loads a snapshot the local "
                "log never saw, so a follower-side WAL could not recover)"
            )
        self._follower = follower
        self._applying = False      # True only inside the replication seam
        self._lock = threading.Condition()
        core_cls = None
        if native is not False and not os.environ.get("KUBETPU_NO_NATIVE"):
            from ..native import store_core

            core_cls = store_core()
        if native is True and core_cls is None:
            raise RuntimeError("native store core unavailable")
        self._core = core_cls(history) if core_cls is not None else _PyCore(history)
        self.native = core_cls is not None
        # list-walk continuity domain: seqs are only comparable within one
        # of these. Snapshot loads (crash recovery below, replica
        # bootstrap/resync) renumber seqs densely, so a continue token
        # minted before a load could silently skip or duplicate entries
        # where deletions had left gaps — the token carries this stamp and
        # the server 410s on mismatch. Random (not monotonic) so a token
        # that survives a process restart also misses.
        self._list_gen = int.from_bytes(os.urandom(4), "big") or 1
        # scheme-registry generation the cached wire bodies were encoded
        # under (None until the first body drain); a move flushes the ring
        self._body_gen: "int | None" = None
        self._wal = None
        self._wal_closed = False
        self._wal_lock = None
        self.recovery_info = None
        if persistence:
            from .wal import DirLock, WriteAheadLog, recover_into

            # single-writer guard FIRST (a concurrent opener would rotate
            # + truncate the live log), then recover (torn tails
            # truncated, snapshot+tail replayed into the core with rv
            # continuity), then open a fresh append segment; a replay
            # longer than the compaction interval compacts immediately so
            # boot chains stay bounded
            os.makedirs(persistence, exist_ok=True)
            self._wal_lock = DirLock(persistence)
            try:
                self.recovery_info = recover_into(self._core, persistence)
                self._wal = WriteAheadLog(
                    persistence, wire=wal_wire, fsync=wal_fsync,
                    compact_every=compact_every,
                    base_rv=self._core.resource_version(),
                )
                if self.recovery_info.replayed >= compact_every:
                    self._wal.snapshot(
                        self._core.dump(), self._core.resource_version()
                    )
            except BaseException:
                self._wal_lock.release()
                raise

    # ------------------------------------------------------------- writes
    # THE WAL append seam: every core mutation — single verbs, the bulk
    # verb, the finalizer/soft-delete sub-writes — routes through
    # ``_commit_locked``, which appends the write's record to the WAL
    # (flushed, write-AHEAD) before the core applies it. graftcheck WL001
    # pins this: a core mutation outside the seam is a durability hole.

    def _commit_locked(self, verb: str, kind: str, key: str,
                       obj: Any = None, expect: int = -1) -> int:
        """Apply ONE write to the core, WAL-logged first when persistence
        is on. The peek mirrors the core's own failure rules exactly so a
        doomed write raises the CANONICAL core error without ever being
        logged (a logged-but-failed write would corrupt the replay
        chain); caller holds the store lock."""
        if self._follower and not self._applying:
            # the follower guard sits at THE choke point every mutation
            # routes through (WL001's seam), so no write verb — present or
            # future — can slip a local write into a replica
            raise FollowerWriteError(
                "store is a replication follower — writes must go to the "
                "leader apiserver"
            )
        if self._wal_closed:
            # the WAL was flushed and closed (graceful shutdown): an ack'd
            # write from here on would be silently non-durable — refuse
            # loudly instead of punching a hole in the recovery chain
            raise RuntimeError(
                "persistent store is closed — writes after close() would "
                "never reach the WAL"
            )
        core = self._core
        wal = self._wal
        if wal is not None:
            cur, cur_rv = core.get(kind, key)
            if verb == "create":
                if cur is not None:
                    return core.create(kind, key, obj)   # canonical raise
                ev = 0
            elif verb == "update":
                if expect >= 0 and (cur is None or cur_rv != expect):
                    return core.update(kind, key, obj, expect)
                ev = 0 if cur is None else 1
            else:                                        # delete
                if cur is None:
                    return core.delete(kind, key)        # canonical raise
                ev, obj = 2, cur
            wal.append(ev, kind, key, obj, core.resource_version() + 1)
            faultpoints.fire("wal-post-append-pre-apply")
        if verb == "create":
            return core.create(kind, key, obj)
        if verb == "update":
            return core.update(kind, key, obj, expect)
        return core.delete(kind, key)

    def _wal_commit_locked(self) -> None:
        """Group commit at the end of one lock round — fsync everything
        appended (one write = one fsync; a bulk batch shares one), BEFORE
        any caller is acked/notified — then compact when the record
        budget since the last snapshot is spent."""
        wal = self._wal
        if wal is None:
            return
        wal.commit()
        if wal.wants_compaction:
            wal.snapshot(self._core.dump(), self._core.resource_version())

    def create(self, kind: str, key: str, obj: Any) -> int:
        with self._lock:
            try:
                rv = self._commit_locked("create", kind, key, obj)
            except KeyError as e:
                raise ConflictError(str(e).strip("'\"")) from None
            self._wal_commit_locked()
            self._lock.notify_all()
            return rv

    def update(
        self, kind: str, key: str, obj: Any, expect_rv: int | None = None
    ) -> int:
        """GuaranteedUpdate: CAS when ``expect_rv`` is given; upsert when the
        object is absent and no CAS was requested.

        Finalizer gate (registry/store.go deleteForEmptyFinalizers): an
        update that leaves a TERMINATING object (deletion_timestamp set)
        with no finalizers completes the deletion — the object is removed
        and a DELETED event fires instead of MODIFIED."""
        with self._lock:
            rv = self._update_locked(kind, key, obj, expect_rv)
            self._wal_commit_locked()
            self._lock.notify_all()
            return rv

    def _update_locked(
        self, kind: str, key: str, obj: Any, expect_rv: int | None
    ) -> int:
        """The update body, caller holds the lock (shared by the single-op
        verb and ``bulk``; the caller notifies)."""
        if (
            getattr(obj, "deletion_timestamp", None) is not None
            and not getattr(obj, "finalizers", ())
        ):
            current, have_rv = self._core.get(kind, key)
            if current is None:
                raise ConflictError(f"{kind}/{key}: gone")
            if expect_rv is not None and have_rv != expect_rv:
                raise ConflictError(
                    f"{kind}/{key}: expected rv {expect_rv}, have {have_rv}"
                )
            return self._commit_locked("delete", kind, key)
        try:
            return self._commit_locked(
                "update", kind, key, obj,
                -1 if expect_rv is None else expect_rv,
            )
        except ValueError as e:
            raise ConflictError(str(e)) from None

    def delete(self, kind: str, key: str) -> int:
        """Remove the object. GRACEFUL path (pkg/registry/core/pod —
        pods delete via deletionTimestamp): an object carrying finalizers
        is soft-deleted — ``deletion_timestamp`` is stamped and the object
        retained (MODIFIED event) until every finalizer is cleared; a
        repeat delete of a terminating object is a no-op returning the
        current revision."""
        with self._lock:
            rv = self._delete_locked(kind, key)
            self._wal_commit_locked()
            self._lock.notify_all()
            return rv

    def _delete_locked(self, kind: str, key: str) -> int:
        """The delete body, caller holds the lock (shared by the single-op
        verb and ``bulk``; the caller notifies)."""
        current, rv = self._core.get(kind, key)
        if current is not None and getattr(current, "finalizers", ()):
            import dataclasses
            import time as _time

            if getattr(current, "deletion_timestamp", None) is not None:
                return self._core.resource_version()   # already going
            doomed = dataclasses.replace(
                current, deletion_timestamp=_time.time()
            )
            return self._commit_locked("update", kind, key, doomed, -1)
        return self._commit_locked("delete", kind, key)  # KeyError propagates

    # --------------------------------------------------------------- bulk
    def bulk(self, kind: str, ops: list[dict]) -> list[dict]:
        """Apply a list of create/update/delete/get ops under ONE lock
        acquisition (the bulk verb's storage half: N writes pay one lock
        round instead of N). Ops are dicts ``{"op": "create|update|delete|
        get", "key": …, "object": …, "expect_rv": …}``; the result list is
        positional, one ``{"status", "resourceVersion", "error"?,
        "object"?}`` per op with the SAME per-object conflict/absence
        semantics as the single-op verbs (a mid-batch conflict fails only
        its own op — later ops still apply)."""
        out: list[dict] = []
        with self._lock:
            for op in ops:
                verb, key = op.get("op"), op.get("key")
                try:
                    if verb == "create":
                        try:
                            rv = self._commit_locked(
                                "create", kind, key, op["object"]
                            )
                        except KeyError as e:
                            raise ConflictError(
                                str(e).strip("'\"")
                            ) from None
                        out.append({"status": 201, "resourceVersion": rv})
                    elif verb == "update":
                        rv = self._update_locked(
                            kind, key, op["object"], op.get("expect_rv")
                        )
                        out.append({"status": 200, "resourceVersion": rv})
                    elif verb == "delete":
                        rv = self._delete_locked(kind, key)
                        out.append({"status": 200, "resourceVersion": rv})
                    elif verb == "get":
                        obj, rv = self._core.get(kind, key)
                        if obj is None:
                            out.append({
                                "status": 404, "resourceVersion": 0,
                                "error": f"{kind}/{key} not found",
                            })
                        else:
                            out.append({
                                "status": 200, "resourceVersion": rv,
                                "object": obj,
                            })
                    else:
                        out.append({
                            "status": 400, "resourceVersion": 0,
                            "error": f"unknown bulk op {verb!r}",
                        })
                except ConflictError as e:
                    out.append({
                        "status": 409, "resourceVersion": 0, "error": str(e),
                    })
                except KeyError as e:
                    out.append({
                        "status": 404, "resourceVersion": 0,
                        "error": str(e).strip("'\""),
                    })
            # one fsync for the whole batch (group commit), before any
            # caller sees the results
            self._wal_commit_locked()
            self._lock.notify_all()
        return out

    def events_since_bulk(
        self, cursors: dict[str, int]
    ) -> tuple[dict, int]:
        """Drain several kinds' watch cursors under ONE lock acquisition
        AND one core call (the server half of the batched watch poll):
        per kind, the same (events, new cursor) a ``_events_since`` would
        return — or a CompactedError value (not raised: one compacted
        kind relists, the others' deliveries still land). The second
        return value is the store's revision AT THE DRAIN, captured under
        the same lock — the long-poll must wait on this, not on a
        revision read afterwards, or a write landing between drain and
        wait stalls for the full timeout."""
        with self._lock:
            raw, drain_rv = self._core.events_since_bulk(cursors)
            compacted = self._core.compacted_through()
        out: dict[str, Any] = {}
        for kind, res in raw.items():
            if res is None:
                out[kind] = CompactedError(
                    f"rv {cursors[kind]} compacted (through {compacted})"
                )
                continue
            events, cursor = res
            out[kind] = (
                [
                    WatchEvent(_EVENT_TYPES[t], k, key, obj, erv)
                    for (t, k, key, obj, erv) in events
                ],
                cursor,
            )
        return out, drain_rv

    # --------------------------------------------- serialize-once bodies
    # The fan-out hot path: pre-encoded event WIRE BODIES straight off the
    # core's per-event body ring — the apiserver's unscoped watch paths
    # splice these into reply envelopes without ever materializing a
    # WatchEvent (kubetpu.api.codec's splice-safe encoding). Bodies are
    # encoded ON MISS under the store lock — once per event per codec,
    # against an encoder that never re-enters the store — so steady-state
    # fan-out is all hits.

    def _check_body_gen_locked(self) -> None:
        """Binary bodies embed schema-table ids derived from the scheme
        registry — a kind registered AFTER bodies were cached shifts the
        ids (and the negotiated fingerprint), so a generation move
        flushes every cached body before the next drain can splice a
        stale encoding into a new-fingerprint reply."""
        from ..api.scheme import registry_generation

        gen = registry_generation()
        if self._body_gen != gen:
            if self._body_gen is not None:
                self._core.clear_event_bodies()
            self._body_gen = gen

    def events_body_since(
        self, kind: str | None, rv: int, codec: str = "json"
    ) -> tuple[list[bytes], int]:
        enc, cid = _wire_encoder(codec)
        with self._lock:
            self._check_body_gen_locked()
            try:
                return self._core.event_bodies_since(kind, rv, cid, enc)
            except LookupError as e:
                raise CompactedError(str(e)) from None

    def events_body_since_bulk(
        self, cursors: dict[str, int], codec: str = "json"
    ) -> tuple[dict, int]:
        """Bulk form: ({kind: (bodies, cursor) | CompactedError}, drain
        revision) — the batched watch poll's one-lock-round body drain."""
        enc, cid = _wire_encoder(codec)
        with self._lock:
            self._check_body_gen_locked()
            raw, drain_rv = self._core.event_bodies_since_bulk(
                cursors, cid, enc
            )
            compacted = self._core.compacted_through()
        out: dict[str, Any] = {}
        for kind, res in raw.items():
            out[kind] = (
                CompactedError(
                    f"rv {cursors[kind]} compacted (through {compacted})"
                )
                if res is None else res
            )
        return out, drain_rv

    def body_cache_stats(self) -> dict:
        """{codec name: (hits, misses)} from the core's body ring."""
        with self._lock:
            stats = self._core.body_cache_stats()
        names = {v: k for k, v in _wire_ids().items()}
        return {names[cid]: tuple(hm) for cid, hm in stats.items()}

    # -------------------------------------------------------------- reads
    def get(self, kind: str, key: str):
        with self._lock:
            return self._core.get(kind, key)

    @staticmethod
    def _parse_selectors(label_selector: str, field_selector: str):
        lt: tuple = ()
        ft: tuple = ()
        if label_selector or field_selector:
            from ..api.selectors import parse_simple_selector

            lt = parse_simple_selector(label_selector)
            ft = parse_simple_selector(field_selector)
        return lt, ft

    def _list_page_locked(self, kind: str, lt: tuple, ft: tuple,
                          limit: int, after_seq: int,
                          through_seq: int = 0):
        """THE pagination seam: every full-store list materialization —
        paged or not — walks the core through here (graftcheck LS001 pins
        it: a ``core.list``/``core.list_page`` call anywhere else in the
        apiserver/store modules is an unbounded read the continue-token
        protocol cannot see). Caller holds the store lock. Returns
        ``(items [(key, obj, rv)], store_rv, next_seq, has_more,
        through_seq)``."""
        return self._core.list_page(kind, lt, ft, limit, after_seq,
                                    through_seq)

    def list(
        self, kind: str,
        label_selector: str = "", field_selector: str = "",
    ):
        """GetList: items + the revision the list is consistent at.
        ``label_selector``/``field_selector`` are the reference's list
        options (``k=v,k2!=v2`` strings) applied server-side — an informer
        with a selector never receives the objects it filtered out.
        Selector matching runs INSIDE the core (the native list filter):
        the terms are parsed here (a malformed selector 400s before the
        lock) and evaluated per object in the core's list walk."""
        lt, ft = self._parse_selectors(label_selector, field_selector)
        with self._lock:
            items, rv, _seq, _more, _bound = self._list_page_locked(
                kind, lt, ft, 0, 0
            )
        return [(key, obj) for key, obj, _rv in items], rv

    def list_page(
        self, kind: str,
        label_selector: str = "", field_selector: str = "",
        limit: int = 0, after_seq: int = 0, through_seq: int = 0,
    ):
        """One bounded page of the list walk (the apiserver's
        ``limit``/``continue`` serving path): ``(items [(key, obj, rv)],
        store_rv, next_seq, has_more, through_seq)``. A walk resumed at
        ``next_seq`` with the echoed ``through_seq`` bound neither
        duplicates nor skips an object present across the whole walk AND
        never splices in an object created after the walk's first page
        (the bound is the snapshot cut); per-item rvs feed the
        serialize-once list-item encode cache."""
        lt, ft = self._parse_selectors(label_selector, field_selector)
        with self._lock:
            return self._list_page_locked(kind, lt, ft, limit, after_seq,
                                          through_seq)

    @property
    def resource_version(self) -> int:
        with self._lock:
            return self._core.resource_version()

    @property
    def compacted_through(self) -> int:
        """The event ring's compaction horizon — the continue-token
        expiry watermark: a paged walk pinned to a snapshot rv below this
        can no longer promise a gapless watch-from-snapshot resume, so
        the server 410s the token into a fresh walk."""
        with self._lock:
            return self._core.compacted_through()

    @property
    def list_generation(self) -> int:
        """The seq-continuity domain stamp continue tokens carry. A
        snapshot load (crash recovery, replica bootstrap/resync)
        renumbers seqs densely, so a cursor from before the load is
        meaningless even when its snapshot rv clears the compaction
        horizon — the server 410s a token whose stamp mismatches."""
        with self._lock:
            return self._list_gen

    # -------------------------------------------------------------- watch
    def watch(
        self, kind: str | None, since_rv: int,
        label_selector: str = "", field_selector: str = "",
    ) -> "Watcher":
        """A pull watcher for events AFTER ``since_rv`` (``kind`` None =
        all buckets). Raises CompactedError immediately when the start
        revision predates the buffer (an O(1) watermark check — no event
        materialization; the first poll() fetches them). With selectors,
        non-matching ADDED/MODIFIED events are rewritten to DELETED
        tombstones (the watch cache's selector watchers: an object leaving
        the selection must vanish from the client's cache; one that never
        matched makes the tombstone a no-op)."""
        with self._lock:
            compacted = self._core.compacted_through()
        if since_rv < compacted:
            raise CompactedError(
                f"rv {since_rv} compacted (through {compacted})"
            )
        return Watcher(self, kind, since_rv, label_selector, field_selector)

    def _events_since(
        self, kind: str | None, rv: int
    ) -> tuple[list[WatchEvent], int]:
        """Returns ``(matching events, new cursor)`` — the cursor covers
        every event examined (matching or not), so a kind-filtered watcher
        never re-scans other kinds' events."""
        with self._lock:
            try:
                raw, cursor = self._core.events_since(kind, rv)
            except LookupError as e:
                raise CompactedError(str(e)) from None
        return (
            [
                WatchEvent(_EVENT_TYPES[t], k, key, obj, erv)
                for (t, k, key, obj, erv) in raw
            ],
            cursor,
        )

    def wait_for(self, rv: int, timeout: float | None = None) -> bool:
        """Block until the store moves past ``rv`` (thread form)."""
        with self._lock:
            return self._lock.wait_for(
                lambda: self._core.resource_version() > rv, timeout=timeout
            )

    # -------------------------------------------------------- replication
    # Log-shipping (kubetpu.store.replication): the leader serves ordered
    # (kind, wire body) records straight off the serialize-once body ring;
    # a follower replays them through apply_replicated — the live twin of
    # WAL recovery's rv-gated replay, routed through _commit_locked so the
    # follower's ring/rv continuity is identical to having taken the
    # writes itself.

    @property
    def follower(self) -> bool:
        return self._follower

    def replication_records(
        self, rv: int, codec: str = "binary"
    ) -> tuple[list[tuple[str, bytes]], int]:
        """Ordered ``(kind, event wire body)`` for every event after
        ``rv`` + the new cursor — the leader's ship feed. Bodies come off
        the core's serialize-once ring (shared with watch fan-out: one
        encode serves watchers AND replication); kinds ride the ring
        metadata from the SAME lock round, so the two walks pair 1:1.
        Raises CompactedError when ``rv`` predates the ring — the
        follower must bootstrap from a snapshot instead."""
        enc, cid = _wire_encoder(codec)
        with self._lock:
            self._check_body_gen_locked()
            try:
                meta, cursor = self._core.events_since(None, rv)
                bodies, _ = self._core.event_bodies_since(None, rv, cid, enc)
            except LookupError as e:
                raise CompactedError(str(e)) from None
        return [(m[1], b) for m, b in zip(meta, bodies)], cursor

    def _apply_replicated_locked(self, ev_type: int, kind: str, key: str,
                                 obj: Any, rv: int) -> bool:
        """One shipped record into the core — rv-gated exactly like WAL
        replay (at-or-below: idempotent skip; a gap: loud resync error),
        routed through _commit_locked under the ``_applying`` flag so the
        follower guard stands for every other caller."""
        have = self._core.resource_version()
        if rv <= have:
            return False                     # double ship / re-fetch
        if rv != have + 1:
            raise ReplicationGapError(
                f"shipped record rv {rv} after store rv {have} — "
                "resync from a leader snapshot required"
            )
        self._applying = True
        try:
            if ev_type == 2:
                got = self._commit_locked("delete", kind, key)
            else:
                got = self._commit_locked("update", kind, key, obj, -1)
        finally:
            self._applying = False
        if got != rv:
            raise ReplicationGapError(
                f"replicated {kind}/{key} applied at rv {got}, "
                f"record said {rv}"
            )
        return True

    def apply_replicated(self, ev_type: int, kind: str, key: str,
                         obj: Any, rv: int) -> bool:
        """Apply ONE shipped record (``ev_type`` is the ring id: 0 ADDED /
        1 MODIFIED / 2 DELETED). True when applied, False when rv-gated
        away. Follower-only."""
        with self._lock:
            if not self._follower:
                raise RuntimeError(
                    "apply_replicated on a non-follower store"
                )
            applied = self._apply_replicated_locked(
                ev_type, kind, key, obj, rv
            )
            if applied:
                self._lock.notify_all()
            return applied

    def apply_replicated_batch(self, records) -> int:
        """A shipped batch under ONE lock round (the tail-follow hot
        path: a write storm's batch pays one lock acquisition and one
        notify, like ``bulk`` on the leader). ``records`` yields
        (ev_type, kind, key, obj, rv); returns how many applied."""
        applied = 0
        with self._lock:
            if not self._follower:
                raise RuntimeError(
                    "apply_replicated on a non-follower store"
                )
            for ev_type, kind, key, obj, rv in records:
                if self._apply_replicated_locked(ev_type, kind, key, obj, rv):
                    applied += 1
            if applied:
                self._lock.notify_all()
        return applied

    def load_replica_snapshot(self, items, rv: int) -> None:
        """Bootstrap/resync: reset the replica to a leader snapshot
        (objects + per-object rvs, store revision ``rv``, event ring
        empty with the compaction horizon at ``rv`` — a watcher holding
        an older cursor takes the bounded 410 relist, exactly recovery's
        contract)."""
        with self._lock:
            if not self._follower:
                raise RuntimeError(
                    "load_replica_snapshot on a non-follower store"
                )
            self._core.load_snapshot(list(items), rv)
            # the load renumbered seqs — invalidate every outstanding
            # continue token (they 410 into a fresh walk)
            self._list_gen = int.from_bytes(os.urandom(4), "big") or 1
            self._lock.notify_all()

    def promote(self) -> int:
        """Failover: flip the replica into a writable leader store at its
        replayed position (no recovery replay — the state is already
        live). Returns the revision the new leader starts serving at."""
        with self._lock:
            self._follower = False
            self._lock.notify_all()
            return self._core.resource_version()

    def demote(self) -> None:
        """The inverse of ``promote`` — an election candidate that
        promoted but lost the writer-lease CAS steps back down before
        any local write could land."""
        with self._lock:
            self._follower = True

    # --------------------------------------------------------- durability
    @property
    def persistent(self) -> bool:
        return self._wal is not None

    def dump(self) -> list:
        """Every object as (kind, key, obj, rv), insertion order — the
        recovery tests' parity probe and ``compact``'s snapshot input."""
        with self._lock:
            return self._core.dump()

    def dump_with_rv(self) -> tuple[list, int]:
        """(dump, store revision) from ONE lock round — the consistent
        pair a replication bootstrap snapshot needs (a dump and a
        revision read separately could straddle a write)."""
        with self._lock:
            return self._core.dump(), self._core.resource_version()

    def compact(self) -> "str | None":
        """Force a compaction snapshot now (snapshot at the current rv,
        segment rotation, truncation of superseded files). No-op without
        persistence. Returns the snapshot path."""
        with self._lock:
            if self._wal is None:
                return None
            return self._wal.snapshot(
                self._core.dump(), self._core.resource_version()
            )

    def close(self) -> None:
        """Flush + fsync + close the WAL — the graceful-shutdown path
        (apiserver close, perf-runner finally): a clean stop never leaves
        a torn tail for the next boot's recovery to truncate. A
        persistent store refuses writes after close (they could never be
        logged); a memory-only store is unaffected."""
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None
                self._wal_closed = True
            if self._wal_lock is not None:
                self._wal_lock.release()
                self._wal_lock = None

    def wal_stats(self) -> "dict | None":
        """Append-side counters for metrics/bench (None when off)."""
        import math

        with self._lock:
            wal = self._wal
            if wal is None:
                return None
            p50 = wal.fsync_hist.quantile(0.50)
            p99 = wal.fsync_hist.quantile(0.99)
            return {
                "records_appended": wal.records_appended,
                "bytes_appended": wal.bytes_appended,
                "fsyncs": wal.fsyncs,
                "records_since_snapshot": wal.records_since_snapshot,
                # the WALOverhead_* bench records embed this: the p99
                # group-commit fsync in ms (None before the first fsync).
                # p50 rides along as the sentinel bundle's WAL stat feed —
                # a stall diagnosis needs the baseline next to the tail
                "fsync_p50_ms": (
                    None if math.isnan(p50) else round(p50 * 1000.0, 3)
                ),
                "fsync_p99_ms": (
                    None if math.isnan(p99) else round(p99 * 1000.0, 3)
                ),
            }

    def wal_metrics_text(self) -> str:
        """The durable store's Prometheus text — mounted on the owning
        apiserver's /metrics: the ``store_wal_fsync_duration_seconds``
        histogram plus segment/byte/snapshot-age gauges. Empty without
        persistence (a memory-only scrape stays byte-identical)."""
        import time as _time

        from ..metrics.registry import Registry
        from .wal import list_segments

        with self._lock:
            wal = self._wal
            if wal is None:
                return ""
            hist = wal.fsync_hist
            dirpath = wal.dirpath
            bytes_total = wal.bytes_appended
            snap_age = max(_time.time() - wal.last_snapshot_wall, 0.0)
        # directory I/O and exposition both OUTSIDE the store lock: a 1 s
        # exporter cadence must never park every store write behind an
        # os.listdir (the histogram carries its own lock; dirpath is
        # immutable for the WAL's lifetime)
        try:
            segments = len(list_segments(dirpath))
        except OSError:
            segments = 0        # dir vanished under a concurrent close
        r = Registry()
        r.register(hist)
        r.gauge(
            "store_wal_segments",
            "WAL segment files currently on disk (compaction truncates).",
        ).set(segments)
        r.counter(
            "store_wal_bytes_total",
            "Bytes appended to the write-ahead log since open.",
        ).inc(bytes_total)
        r.gauge(
            "store_snapshot_age_seconds",
            "Seconds since the newest compaction snapshot was written.",
        ).set(round(snap_age, 3))
        return r.expose()


class SelectorView:
    """Stateful selector filter for ONE watch stream (the watch cache's
    per-watcher selector view): matching events pass and mark the key
    delivered; an event LEAVING the selection becomes one DELETED
    tombstone; further events for a key the client provably does not hold
    are dropped outright — so a kubelet watching ``spec.nodeName=<self>``
    pays one tombstone per foreign pod, not one per foreign event.

    An event for an UNKNOWN non-matching key still tombstones once: the
    client's initial (selector-scoped) list may contain objects that left
    the selection before their first watch event, and the view cannot
    distinguish them from never-matched objects."""

    def __init__(self, label_selector: str, field_selector: str) -> None:
        from ..api.selectors import parse_simple_selector

        self._lt = parse_simple_selector(label_selector)
        self._ft = parse_simple_selector(field_selector)
        self._matched: set[str] = set()     # keys delivered as matching
        self._tombstoned: set[str] = set()  # foreign keys already tombstoned

    def filter(self, events: list[WatchEvent]) -> list[WatchEvent]:
        from ..api.selectors import object_matches_selectors

        out: list[WatchEvent] = []
        for e in events:
            if e.type == DELETED:
                if e.key in self._tombstoned:
                    self._tombstoned.discard(e.key)
                    continue               # client never held it
                self._matched.discard(e.key)
                out.append(e)
                continue
            if object_matches_selectors(e.obj, self._lt, self._ft):
                self._matched.add(e.key)
                self._tombstoned.discard(e.key)
                out.append(e)
                continue
            if e.key in self._tombstoned:
                continue                   # repeat foreign event: dropped
            self._matched.discard(e.key)
            self._tombstoned.add(e.key)
            out.append(
                WatchEvent(DELETED, e.kind, e.key, e.obj, e.resource_version)
            )
        return out


class Watcher:
    """One watch stream: ``poll()`` drains events after the cursor."""

    def __init__(
        self, store: MemStore, kind: str | None, since_rv: int,
        label_selector: str = "", field_selector: str = "",
    ) -> None:
        self._store = store
        self._kind = kind
        self._rv = since_rv
        self._view = (
            SelectorView(label_selector, field_selector)
            if (label_selector or field_selector) else None
        )

    @property
    def resource_version(self) -> int:
        return self._rv

    def poll(self) -> list[WatchEvent]:
        """New events since the cursor; raises CompactedError when the
        cursor fell behind the ring buffer (caller relists)."""
        events, self._rv = self._store._events_since(self._kind, self._rv)
        if self._view is not None:
            events = self._view.filter(events)
        return events
