"""Hollow kubelet — the kubemark tier (layer 7, scale-test shape).

Reference: ``pkg/kubemark/hollow_kubelet.go:62`` — real kubelet wiring
against a fake CRI so thousands of nodes run on a few machines; the
scheduler-facing duties are what matter: register the Node object,
heartbeat its lease (pkg/kubelet/nodelease), watch for pods bound to it,
and report them Running (status sync, pkg/kubelet/status). That envelope is
exactly what this HollowKubelet implements over the store — enough to run a
full closed loop (scheduler + controllers + N hollow nodes) in one process,
the way scheduler_perf/kubemark test multi-node behavior without a cluster
(SURVEY §4 'Multi-node without a real cluster').

A DRA-capable hollow node also publishes its ResourceSlice (the node
driver's kubelet plugin half).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ..api import types as t
from ..client.informers import NODES, PODS
from ..client.reflector import Reflector, SharedInformer
from ..controllers.nodelifecycle import heartbeat as nl_heartbeat
from ..store.memstore import ConflictError, MemStore


class HollowKubelet:
    def __init__(
        self,
        store: MemStore,
        node: t.Node,
        resource_slice: t.ResourceSlice | None = None,
        clock: Callable[[], float] | None = None,
    ) -> None:
        import time

        self.store = store
        self.node = node
        self.resource_slice = resource_slice
        self.clock = clock or time.monotonic
        self._pods = SharedInformer(PODS)
        self._r = Reflector(store, self._pods)
        self.alive = True
        self.running: set[str] = set()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Register the node (+ its device inventory) and begin watching."""
        self.store.update(NODES, self.node.name, self.node)
        if self.resource_slice is not None:
            self.store.update(
                "resourceslices", self.resource_slice.name,
                self.resource_slice,
            )
        self._r.sync()
        self.heartbeat()

    def stop(self) -> None:
        """Simulate kubelet death: heartbeats cease (the node object
        remains — nodelifecycle will taint it)."""
        self.alive = False

    def heartbeat(self) -> None:
        if self.alive:
            nl_heartbeat(self.store, self.node.name, self.clock())

    # --------------------------------------------------------------- sync
    def pump(self) -> int:
        """One syncLoop iteration: heartbeat + mark newly bound pods
        Running (syncLoopIteration's HandlePodAdditions → status sync)."""
        self.heartbeat()
        if not self.alive:
            return 0
        self._r.step()
        moved = 0
        for key, pod in list(self._pods.store.items()):
            if pod.node_name != self.node.name:
                self.running.discard(key)
                continue
            if pod.phase == "Running" and pod.terminates:
                # run-to-completion workloads (restartPolicy: Never) finish
                # on a later sync pass (kuberuntime's exited-container path)
                live, rv = self.store.get(PODS, key)
                if live is not None and live.phase == "Running":
                    try:
                        self.store.update(
                            PODS, key,
                            dataclasses.replace(live, phase="Succeeded"),
                            expect_rv=rv,
                        )
                        self.running.discard(key)
                        moved += 1
                    except ConflictError:
                        pass
                continue
            if key in self.running or pod.phase != "Pending":
                continue
            # status write through the LIVE object (not the informer copy),
            # and only if the pod is still bound here
            live, rv = self.store.get(PODS, key)
            if live is None or live.node_name != self.node.name:
                continue
            try:
                self.store.update(
                    PODS, key,
                    dataclasses.replace(live, phase="Running"),
                    expect_rv=rv,
                )
            except ConflictError:
                continue
            self.running.add(key)
            moved += 1
        return moved


class HollowCluster:
    """N hollow nodes + one pump loop (start-kubemark.sh in a for-loop)."""

    def __init__(
        self,
        store: MemStore,
        nodes: list[t.Node],
        slices: dict[str, t.ResourceSlice] | None = None,
        clock: Callable[[], float] | None = None,
    ) -> None:
        self.kubelets = [
            HollowKubelet(
                store, n,
                resource_slice=(slices or {}).get(n.name),
                clock=clock,
            )
            for n in nodes
        ]

    def start(self) -> None:
        for k in self.kubelets:
            k.start()

    def pump(self) -> int:
        return sum(k.pump() for k in self.kubelets)

    def kubelet(self, node_name: str) -> HollowKubelet:
        for k in self.kubelets:
            if k.node.name == node_name:
                return k
        raise KeyError(node_name)
