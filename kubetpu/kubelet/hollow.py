"""Hollow kubelet — the kubemark tier (layer 7, scale-test shape).

Reference: ``pkg/kubemark/hollow_kubelet.go:62`` — real kubelet wiring
against a fake CRI so thousands of nodes run on a few machines; the
scheduler-facing duties are what matter: register the Node object,
heartbeat its lease (pkg/kubelet/nodelease), watch for pods bound to it,
and report them Running (status sync, pkg/kubelet/status). That envelope is
exactly what this HollowKubelet implements over the store — enough to run a
full closed loop (scheduler + controllers + N hollow nodes) in one process,
the way scheduler_perf/kubemark test multi-node behavior without a cluster
(SURVEY §4 'Multi-node without a real cluster').

A DRA-capable hollow node also publishes its ResourceSlice (the node
driver's kubelet plugin half).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from ..api import types as t
from ..client.informers import NODES, PODS
from ..client.reflector import Reflector, SharedInformer
from ..controllers.nodelifecycle import heartbeat as nl_heartbeat
from ..store.memstore import ConflictError, MemStore


class HollowKubelet:
    def __init__(
        self,
        store: MemStore,
        node: t.Node,
        resource_slice: t.ResourceSlice | None = None,
        clock: Callable[[], float] | None = None,
        start_delay_s: float = 0.0,
    ) -> None:
        import time

        self.store = store
        self.node = node
        self.resource_slice = resource_slice
        self.clock = clock or time.monotonic
        # probe-analog: a bound pod stays Pending for this long before the
        # kubelet reports Running (container start + readiness window —
        # pkg/kubelet/prober); 0 = the old immediate transition
        self.start_delay_s = start_delay_s
        self._pending_since: dict[str, float] = {}
        self._pods = SharedInformer(PODS)
        # spec.nodeName field selector: this kubelet receives only ITS pods
        # (the real kubelet's apiserver pod source — config/apiserver.go
        # NewSourceApiserver's fields.OneTermEqualSelector), so an N-node
        # cluster doesn't ship every pod to every node agent
        self._r = Reflector(
            store, self._pods,
            field_selector=f"spec.nodeName={node.name}",
        )
        self.alive = True
        self.running: set[str] = set()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Register the node (+ its device inventory) and begin watching."""
        self.store.update(NODES, self.node.name, self.node)
        if self.resource_slice is not None:
            self.store.update(
                "resourceslices", self.resource_slice.name,
                self.resource_slice,
            )
        self._r.sync()
        self.heartbeat()

    def stop(self) -> None:
        """Simulate kubelet death: heartbeats cease (the node object
        remains — nodelifecycle will taint it)."""
        self.alive = False

    def heartbeat(self) -> None:
        if self.alive:
            nl_heartbeat(self.store, self.node.name, self.clock())

    # --------------------------------------------------------------- sync
    def pump(self) -> int:
        """One syncLoop iteration: heartbeat + the pod lifecycle state
        machine (syncLoopIteration → pod workers, pod_workers.go):
        Pending → (start_delay_s probe window) → Running →
        Succeeded (terminates) — and for TERMINATING pods
        (deletion_timestamp set: graceful deletion) the wind-down to a
        terminal phase; the store removes the object once its finalizers
        clear (the final status sync the real kubelet sends)."""
        self.heartbeat()
        if not self.alive:
            return 0
        self._r.step()
        moved = 0
        for key, pod in list(self._pods.store.items()):
            if pod.node_name != self.node.name:
                self.running.discard(key)
                self._pending_since.pop(key, None)
                continue
            if pod.deletion_timestamp is not None:
                # graceful deletion: kill the workload, report the terminal
                # phase (the object itself lives until finalizers clear)
                if pod.phase in ("Pending", "Running"):
                    live, rv = self.store.get(PODS, key)
                    if (
                        live is None
                        or live.node_name != self.node.name
                        or live.phase not in ("Pending", "Running")
                    ):
                        continue
                    # a gracefully-deleted pod was KILLED, not completed —
                    # killed containers report Failed (kuberuntime's
                    # termination status), never a phantom Succeeded that
                    # Job accounting would count as a completion
                    final = "Failed"
                    try:
                        self.store.update(
                            PODS, key,
                            dataclasses.replace(live, phase=final),
                            expect_rv=rv,
                        )
                        moved += 1
                    except ConflictError:
                        pass
                self.running.discard(key)
                self._pending_since.pop(key, None)
                continue
            if pod.phase == "Running" and pod.terminates:
                # run-to-completion workloads (restartPolicy: Never) finish
                # on a later sync pass (kuberuntime's exited-container path)
                live, rv = self.store.get(PODS, key)
                if live is not None and live.phase == "Running":
                    try:
                        self.store.update(
                            PODS, key,
                            dataclasses.replace(live, phase="Succeeded"),
                            expect_rv=rv,
                        )
                        self.running.discard(key)
                        moved += 1
                    except ConflictError:
                        pass
                continue
            if key in self.running or pod.phase != "Pending":
                continue
            # probe-analog startup window: observed-bound time + delay
            if self.start_delay_s > 0:
                since = self._pending_since.setdefault(key, self.clock())
                if self.clock() - since < self.start_delay_s:
                    continue
            # status write through the LIVE object (not the informer copy),
            # and only if the pod is still bound here
            live, rv = self.store.get(PODS, key)
            if live is None or live.node_name != self.node.name:
                continue
            try:
                self.store.update(
                    PODS, key,
                    dataclasses.replace(live, phase="Running"),
                    expect_rv=rv,
                )
            except ConflictError:
                continue
            self.running.add(key)
            self._pending_since.pop(key, None)
            moved += 1
        # pods gone from the cache (DELETED events) free their slots — a
        # same-key replacement (daemonset/statefulset identity reuse) must
        # not be skipped by a stale `running` entry
        live_keys = self._pods.store.keys()
        self.running.intersection_update(live_keys)
        for k in list(self._pending_since):
            if k not in live_keys:
                del self._pending_since[k]
        return moved


class HollowCluster:
    """N hollow nodes + one pump loop (start-kubemark.sh in a for-loop)."""

    def __init__(
        self,
        store: MemStore,
        nodes: list[t.Node],
        slices: dict[str, t.ResourceSlice] | None = None,
        clock: Callable[[], float] | None = None,
        start_delay_s: float = 0.0,
    ) -> None:
        self.kubelets = [
            HollowKubelet(
                store, n,
                resource_slice=(slices or {}).get(n.name),
                clock=clock,
                start_delay_s=start_delay_s,
            )
            for n in nodes
        ]

    def start(self) -> None:
        for k in self.kubelets:
            k.start()

    def pump(self) -> int:
        return sum(k.pump() for k in self.kubelets)

    def kubelet(self, node_name: str) -> HollowKubelet:
        for k in self.kubelets:
            if k.node.name == node_name:
                return k
        raise KeyError(node_name)
