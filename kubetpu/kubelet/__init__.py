"""Node agent tier: the hollow kubelet (kubemark analog)."""

from .hollow import HollowKubelet, HollowCluster  # noqa: F401
