"""Named health checks — the component-base/healthz analog.

Reference: ``staging/src/k8s.io/apiserver/pkg/server/healthz`` serves
``/healthz``, ``/readyz`` and ``/livez``, each an ordered set of NAMED
checks (``PingHealthz``, ``InformerSync``, shutdown hooks …) rendered as

    [+]ping ok
    [-]informer-sync failed: reason withheld
    healthz check failed

with per-check sub-paths (``/healthz/<check>``) and ``?verbose`` forcing
the breakdown even when healthy, and ``?exclude=<name>`` dropping a check
from one probe. Here one ``HealthChecks`` object backs all three endpoints:
checks register with the endpoint groups they participate in (a not-ready
server is still alive, so readyz usually carries more checks than livez —
the reference's ``installable`` split).

A check is any callable: return None (or True) = healthy; raise, or return
False / an error string = unhealthy. Checks run on the serving thread, so
they must be cheap (the reference's contract too).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Iterable

ENDPOINTS = ("healthz", "readyz", "livez")


@dataclass(frozen=True)
class CheckResult:
    name: str
    healthy: bool
    reason: str = ""


class HealthChecks:
    """Named, registrable health checks behind /healthz /readyz /livez."""

    def __init__(self, ping: bool = True) -> None:
        # endpoint -> ordered {name: fn}; registration order is render order
        self._checks: dict[str, dict[str, Callable]] = {
            ep: {} for ep in ENDPOINTS
        }
        self._lock = threading.Lock()
        if ping:
            self.add_check("ping", lambda: None)

    def add_check(
        self,
        name: str,
        fn: Callable[[], object],
        endpoints: Iterable[str] = ENDPOINTS,
    ) -> None:
        """Register ``fn`` under ``name`` on the given endpoint groups
        (default: all three). Re-registering a name replaces the check."""
        with self._lock:
            for ep in endpoints:
                if ep not in self._checks:
                    raise ValueError(f"unknown endpoint {ep!r}")
                self._checks[ep][name] = fn

    def names(self, endpoint: str = "healthz") -> list[str]:
        with self._lock:
            return list(self._checks[endpoint])

    # ------------------------------------------------------------- running
    @staticmethod
    def _run_one(name: str, fn: Callable[[], object]) -> CheckResult:
        try:
            out = fn()
        except Exception as e:  # noqa: BLE001 — an unhealthy check
            return CheckResult(name, False, f"{type(e).__name__}: {e}")
        if out is None or out is True:
            return CheckResult(name, True)
        if out is False:
            return CheckResult(name, False, "check returned false")
        return CheckResult(name, False, str(out))

    def run(
        self, endpoint: str = "healthz", exclude: Iterable[str] = ()
    ) -> list[CheckResult]:
        skip = set(exclude)
        with self._lock:
            checks = list(self._checks[endpoint].items())
        return [
            self._run_one(name, fn)
            for name, fn in checks
            if name not in skip
        ]

    # ------------------------------------------------------------- serving
    def handle(
        self, path: str, query: dict | None = None
    ) -> tuple[int, str] | None:
        """Answer one health request: ``path`` is ``/healthz``,
        ``/healthz/<check>``, ``/readyz``, ``/livez`` (+ sub-checks).
        Returns (status, text/plain body), or None when the path is not a
        health endpoint. 200 when every check passes, 503 otherwise —
        the component-base response shape."""
        q = query or {}
        parts = path.strip("/").split("/")
        if not parts or parts[0] not in ENDPOINTS:
            return None
        endpoint = parts[0]
        if len(parts) > 2:            # /healthz/<check>/extra: not a thing
            return 404, "unknown health path\n"
        if len(parts) == 2:           # /healthz/<check>: one check, terse
            with self._lock:
                fn = self._checks[endpoint].get(parts[1])
            if fn is None:
                return 404, f"no check named {parts[1]!r}\n"
            res = self._run_one(parts[1], fn)
            if res.healthy:
                return 200, "ok\n"
            return 503, f"internal server error: {res.reason}\n"
        exclude = [
            e for raw in _as_list(q.get("exclude")) for e in raw.split(",") if e
        ]
        results = self.run(endpoint, exclude=exclude)
        healthy = all(r.healthy for r in results)
        verbose = "verbose" in q or not healthy
        if not verbose:
            return 200, "ok\n"
        lines = [
            f"[+]{r.name} ok" if r.healthy
            # aggregate endpoints withhold the reason (component-base does
            # too — they may face unauthenticated probers); the per-check
            # sub-path /<endpoint>/<name> carries the real error
            else f"[-]{r.name} failed: reason withheld"
            for r in results
        ]
        lines.append(
            f"{endpoint} check passed" if healthy
            else f"{endpoint} check failed"
        )
        return (200 if healthy else 503), "\n".join(lines) + "\n"


def _as_list(v) -> list[str]:
    if v is None:
        return []
    if isinstance(v, str):
        return [v]
    return [str(x) for x in v]
