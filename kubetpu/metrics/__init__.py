"""Metrics — Prometheus-shaped counters/gauges/histograms with a registry
and text exposition (the component-base/metrics analog, SURVEY §5)."""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    exponential_buckets,
)
from .scheduler_metrics import SchedulerMetricsRegistry  # noqa: F401
