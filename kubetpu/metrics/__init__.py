"""Metrics — Prometheus-shaped counters/gauges/histograms with a registry
and text exposition (the component-base/metrics analog, SURVEY §5), plus
the rest of the observability plane: named health checks (healthz/readyz/
livez), the client-go workqueue metric set, device-side TPU counters, and
a minimal exposition-text parser for scrape round-trips."""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    exponential_buckets,
)
from .health import CheckResult, HealthChecks  # noqa: F401
from .scheduler_metrics import (  # noqa: F401
    E2E_STAGES,
    SchedulerMetricsRegistry,
    window_quantile_ms,
)
from .textparse import ParsedMetrics, parse_prometheus_text  # noqa: F401
from .tpu import TPUBackendMetrics, batch_nbytes, jit_cache_size  # noqa: F401
from .workqueue import (  # noqa: F401
    QueueMetrics,
    WorkqueueMetricsProvider,
    default_provider,
)
