"""Shared diagnostics mux — ONE implementation of the /metrics + health
endpoint surface that both HTTP fronts mount (the apiserver's sidecar
routes and the scheduler's DiagnosticsServer), so content types, path
normalization, and health dispatch cannot drift between them."""

from __future__ import annotations

from typing import Callable, Iterable, Mapping

from .health import HealthChecks

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
TEXT_CONTENT_TYPE = "text/plain; charset=utf-8"


def diagnostics_response(
    path: str,
    query: Mapping | None = None,
    metrics_sources: Iterable[Callable[[], str]] = (),
    health: HealthChecks | None = None,
    extra: Mapping[str, Callable[[], tuple[str, str]]] | None = None,
) -> tuple[int, str, str] | None:
    """Answer one diagnostics request: ``/metrics`` (the joined Prometheus
    text of every source), the health endpoints (delegated to
    ``health.handle``), or an ``extra`` route mapping path →
    ``(query) -> (content_type, body)`` (the parsed query mapping is
    passed through so routes like /debug/flightrecorder?pod=… can scope
    their body). Returns (status, content_type, body), or None when the
    path belongs to none of them (the caller keeps its own 404 shape)."""
    path = "/" + path.strip("/")
    if path == "/metrics":
        return 200, PROM_CONTENT_TYPE, "".join(s() for s in metrics_sources)
    if extra is not None:
        fn = extra.get(path)
        if fn is not None:
            content_type, body = fn(query or {})
            return 200, content_type, body
    if health is not None:
        res = health.handle(path, query)
        if res is not None:
            status, body = res
            return status, TEXT_CONTENT_TYPE, body
    return None
