"""Device-side counters — the TPU half of the observability plane.

The reference judges itself on host-side scheduler histograms; kubetpu's
hot path is a fused XLA program, so the equivalent diagnosis surface is
device-shaped: how big the batches are, whether the XLA compile cache is
hitting (a miss stalls a cycle by seconds), how many bytes the host→device
encode ships, and where device wall time goes per cycle. ``SURVEY §5``'s
span-per-cycle design joins these to the host trace by CYCLE ID: every
``record_cycle`` keeps a join record the trace exporter and the perf
harness dump next to the bench JSON.

Metric set (labels ``engine`` = greedy | batched):

- ``tpu_batch_size`` histogram — pods per device cycle
- ``tpu_jit_cache_hits_total`` / ``tpu_jit_cache_misses_total`` counters —
  per-cycle compile-cache outcome of the assignment program (a miss means
  XLA compiled a new (shape, params) variant this cycle)
- ``tpu_host_to_device_transfer_bytes_total`` counter — bytes ACTUALLY
  shipped host→device for the cycle (pod block + node-state delta rows;
  signature compression and device residency are what keep this small)
- ``scheduler_device_resident_bytes`` gauge — bytes of cluster node state
  living on device ACROSS cycles (pipeline mode); dashboards read resident
  state and per-cycle traffic as separate series
- ``tpu_device_kernel_wall_seconds`` histogram — wall time of the device
  assignment program incl. the blocking fetch of its outputs
- ``scheduler_encode_cache_hits_total`` / ``…_misses_total`` counters
  (label ``kind`` = filter | score | request | pod_sig) and
  ``scheduler_encode_cache_entries`` gauge — the template-keyed encode
  cache (state.encode_cache): a high steady-state hit rate is what keeps
  host encode off the cycle critical path
- ``tpu_shard_host_to_device_transfer_bytes_total{engine,shard}`` counter
  and ``tpu_shard_device_resident_bytes{engine,shard}`` gauge — the
  per-shard view of the SHARDED resident node block (delta uploads are
  routed to the owning shard on the host, so these are real per-chip
  bytes, not an even split of a broadcast)
- ``tpu_mesh_collective_wall_seconds{engine}`` gauge — one-shot cross-
  shard argmax probe on the scheduler's mesh: the collective tax the
  sharded kernel walls include (MULTICHIP evidence carries its context)
"""

from __future__ import annotations

import collections
from dataclasses import asdict, dataclass

from .registry import Registry, exponential_buckets


@dataclass(frozen=True)
class CycleRecord:
    """Per-cycle device-side observation, joined to host spans by cycle id
    (+ ``profile``: a mixed-profile batch runs one device program per
    profile under ONE cycle id, and the matching scheduling-cycle span
    carries the same profile attribute). ``compile_miss`` is None when the
    backend exposes no compile-cache introspection — unmeasured, not a
    hit."""

    cycle: int
    engine: str
    batch_size: int
    transfer_bytes: int
    kernel_wall_s: float
    compile_miss: bool | None
    profile: str = ""
    # full encoded-batch pytree bytes — what a residency-less cycle would
    # have shipped; transfer_bytes < batch_bytes is the delta-upload win
    batch_bytes: int = 0
    # device-resident node-state bytes backing this cycle (0 = no residency)
    resident_bytes: int = 0
    # True when this cycle ran in the two-stage pipeline (encode overlapped
    # the previous cycle's device program)
    pipelined: bool = False
    # mesh the cycle ran under: device-mesh shape (() = single device) and
    # the per-shard routed delta-upload bytes (None when unsharded) — the
    # per-chip attribution MULTICHIP evidence is judged on
    mesh_shape: tuple = ()
    shard_transfer_bytes: "list[int] | None" = None
    # cross-shard reduction probe for this scheduler's mesh (seconds; None
    # when unsharded) — the collective tax the kernel walls include
    collective_wall_s: "float | None" = None
    # federation stamp: which scheduler replica ran this cycle ("" =
    # single-scheduler mode) — multi-replica cycle streams against one
    # cluster stay attributable per record
    replica: str = ""
    # packing-engine solve diagnostics (assign.packing; None for the
    # other engines): the cycle's cluster-objective value and how many
    # projection-loop iterations the warm-started solver needed
    objective_value: "float | None" = None
    solver_iters: "int | None" = None

    def to_json(self) -> dict:
        out = asdict(self)
        out["mesh_shape"] = list(self.mesh_shape)
        return out


def batch_nbytes(device_batch) -> int:
    """Total bytes of a device pytree's array leaves — the host→device
    transfer upper bound for one encoded batch (every leaf is shipped by
    ``jnp.asarray`` at encode time; cached node rows make this an upper
    bound, which is the honest direction for a transfer budget)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(device_batch):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def jit_cache_size(fn) -> int | None:
    """Compiled-variant count of a jitted callable (None when the backend
    does not expose it) — sampled before/after a call to classify the call
    as compile-cache hit or miss."""
    size = getattr(fn, "_cache_size", None)
    if size is None:
        return None
    try:
        return int(size())
    except Exception:  # pragma: no cover - backend quirk
        return None


class TPUBackendMetrics:
    """See module docstring. Registers on a shared Registry so one
    /metrics exposition carries host and device metrics together."""

    def __init__(self, registry: Registry | None = None,
                 max_records: int = 4096) -> None:
        r = registry if registry is not None else Registry()
        self.registry = r
        self.batch_size = r.histogram(
            "tpu_batch_size",
            "Pods per device scheduling cycle.",
            labels=("engine",),
            buckets=exponential_buckets(1, 2, 14),
        )
        self.jit_cache_hits = r.counter(
            "tpu_jit_cache_hits_total",
            "Device cycles served from the XLA compile cache.",
            labels=("engine",),
        )
        self.jit_cache_misses = r.counter(
            "tpu_jit_cache_misses_total",
            "Device cycles that compiled a new XLA program variant.",
            labels=("engine",),
        )
        self.transfer_bytes = r.counter(
            "tpu_host_to_device_transfer_bytes_total",
            "Bytes actually shipped host to device per cycle "
            "(pod block + node-state delta).",
            labels=("engine",),
        )
        self.resident_bytes = r.gauge(
            "scheduler_device_resident_bytes",
            "Cluster node-state bytes resident on device across cycles.",
            labels=("engine",),
        )
        self.kernel_wall = r.histogram(
            "tpu_device_kernel_wall_seconds",
            "Wall time of the device assignment program per cycle, "
            "including the blocking output fetch.",
            labels=("engine",),
            buckets=exponential_buckets(0.0001, 2, 18),
        )
        self.encode_cache_hits = r.counter(
            "scheduler_encode_cache_hits_total",
            "Static encode rows served from the template-keyed encode "
            "cache (gathered, not rebuilt).",
            labels=("kind",),
        )
        self.encode_cache_misses = r.counter(
            "scheduler_encode_cache_misses_total",
            "Static encode rows built fresh (first sight of a template, "
            "or after a node-event invalidation).",
            labels=("kind",),
        )
        self.encode_cache_entries = r.gauge(
            "scheduler_encode_cache_entries",
            "Entries resident in the encode cache (LRU-bounded).",
        )
        # --- mesh-sharded assignment (parallel.mesh) ---------------------
        self.shard_transfer_bytes = r.counter(
            "tpu_shard_host_to_device_transfer_bytes_total",
            "Bytes routed to one shard of the sharded resident node block "
            "(delta uploads grouped by owning shard on the host).",
            labels=("engine", "shard"),
        )
        self.shard_resident_bytes = r.gauge(
            "tpu_shard_device_resident_bytes",
            "Per-shard bytes of the device-resident node block.",
            labels=("engine", "shard"),
        )
        self.collective_wall = r.gauge(
            "tpu_mesh_collective_wall_seconds",
            "Cross-shard argmax reduction probe on the scheduler's mesh "
            "(the collective tax included in sharded kernel walls).",
            labels=("engine",),
        )
        self.records: collections.deque[CycleRecord] = collections.deque(
            maxlen=max_records
        )

    def record_cycle(
        self,
        cycle: int,
        engine: str,
        batch_size: int,
        transfer_bytes: int,
        kernel_wall_s: float,
        compile_miss: bool | None,
        profile: str = "",
        batch_bytes: int = 0,
        resident_bytes: int = 0,
        pipelined: bool = False,
        mesh_shape: tuple = (),
        shard_transfer_bytes: "list[int] | None" = None,
        shard_resident_bytes: "list[int] | None" = None,
        collective_wall_s: "float | None" = None,
        replica: str = "",
        objective_value: "float | None" = None,
        solver_iters: "int | None" = None,
    ) -> CycleRecord:
        self.batch_size.labels(engine).observe(batch_size)
        self.transfer_bytes.labels(engine).inc(transfer_bytes)
        self.resident_bytes.labels(engine).set(resident_bytes)
        self.kernel_wall.labels(engine).observe(kernel_wall_s)
        if shard_transfer_bytes:
            for s, b in enumerate(shard_transfer_bytes):
                if b:
                    self.shard_transfer_bytes.labels(engine, str(s)).inc(b)
        if shard_resident_bytes:
            # honest placement, not an even split: the single-device
            # fallback reports everything on shard 0 (runtime.
            # ResidentNodeState.nbytes_per_shard)
            for s, b in enumerate(shard_resident_bytes):
                self.shard_resident_bytes.labels(engine, str(s)).set(b)
        if collective_wall_s is not None:
            self.collective_wall.labels(engine).set(collective_wall_s)
        if compile_miss is not None:
            if compile_miss:
                self.jit_cache_misses.labels(engine).inc()
            else:
                self.jit_cache_hits.labels(engine).inc()
        rec = CycleRecord(
            cycle=cycle, engine=engine, batch_size=batch_size,
            transfer_bytes=transfer_bytes, kernel_wall_s=kernel_wall_s,
            compile_miss=(
                None if compile_miss is None else bool(compile_miss)
            ),
            profile=profile,
            batch_bytes=batch_bytes or transfer_bytes,
            resident_bytes=resident_bytes,
            pipelined=pipelined,
            mesh_shape=tuple(mesh_shape),
            shard_transfer_bytes=shard_transfer_bytes,
            collective_wall_s=collective_wall_s,
            replica=replica,
            objective_value=objective_value,
            solver_iters=solver_iters,
        )
        self.records.append(rec)
        return rec

    def records_json(self) -> list[dict]:
        return [r.to_json() for r in self.records]
