"""Minimal Prometheus text-format (0.0.4) parser.

The consumer side of ``Registry.expose()``: enough of
``prometheus/common/expfmt`` to round-trip a scrape in tests and to build
the perf harness's post-run metric snapshots from the same text a real
Prometheus server would ingest — names, HELP/TYPE metadata, label sets
(with escaped quotes), and float values (incl. ``+Inf``/``NaN``).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Sample:
    name: str                       # full sample name incl. _bucket/_sum/_count
    labels: tuple[tuple[str, str], ...]
    value: float

    def label(self, key: str) -> str | None:
        for k, v in self.labels:
            if k == key:
                return v
        return None


@dataclass
class MetricFamily:
    name: str                       # family name (no histogram suffixes)
    kind: str = "untyped"
    help: str = ""
    samples: list[Sample] = field(default_factory=list)


class ParseError(ValueError):
    pass


_SUFFIXES = ("_bucket", "_sum", "_count")


def _family_name(sample_name: str, families: dict[str, MetricFamily]) -> str:
    if sample_name in families:
        return sample_name
    for suf in _SUFFIXES:
        if sample_name.endswith(suf) and sample_name[: -len(suf)] in families:
            return sample_name[: -len(suf)]
    return sample_name


def _parse_value(raw: str) -> float:
    raw = raw.strip()
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    return float(raw)


def _parse_labels(body: str, line: str) -> tuple[tuple[str, str], ...]:
    out: list[tuple[str, str]] = []
    i = 0
    n = len(body)
    while i < n:
        while i < n and body[i] in ", \t":
            i += 1              # separators; a trailing comma is legal 0.0.4
        if i >= n:
            break
        try:
            eq = body.index("=", i)
        except ValueError as e:
            raise ParseError(f"malformed labels in: {line}") from e
        key = body[i:eq].strip()
        if eq + 1 >= n or body[eq + 1] != '"':
            raise ParseError(f"unquoted label value in: {line}")
        j = eq + 2
        buf: list[str] = []
        while j < n:
            c = body[j]
            if c == "\\" and j + 1 < n:
                nxt = body[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
                continue
            if c == '"':
                break
            buf.append(c)
            j += 1
        else:
            raise ParseError(f"unterminated label value in: {line}")
        out.append((key, "".join(buf)))
        i = j + 1
    return tuple(out)


class ParsedMetrics:
    """Scrape result: metric families keyed by family name."""

    def __init__(self, families: dict[str, MetricFamily]) -> None:
        self.families = families

    def __contains__(self, name: str) -> bool:
        return name in self.families

    def samples(self, name: str) -> list[Sample]:
        fam = self.families.get(name)
        return list(fam.samples) if fam else []

    def value(self, sample_name: str, **labels: str) -> float | None:
        """The value of the first sample matching ``sample_name`` whose
        label set CONTAINS ``labels`` (a PromQL instant-selector lookup)."""
        fam = self.families.get(_family_name(sample_name, self.families))
        if fam is None:
            return None
        want = {(k, str(v)) for k, v in labels.items()}
        for s in fam.samples:
            if s.name == sample_name and want <= set(s.labels):
                return s.value
        return None


def parse_prometheus_text(text: str) -> ParsedMetrics:
    """Parse exposition text into families; malformed lines raise
    ``ParseError`` (a scrape either round-trips or fails loudly)."""
    families: dict[str, MetricFamily] = {}

    def family(name: str) -> MetricFamily:
        fam = families.get(name)
        if fam is None:
            fam = families[name] = MetricFamily(name)
        return fam

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_text = rest.partition(" ")
            family(name).help = help_text
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            family(name).kind = kind.strip()
            continue
        if line.startswith("#"):
            continue
        # sample: name[{labels}] value
        if "{" in line:
            name, _, rest = line.partition("{")
            body, _, value_part = rest.rpartition("}")
            labels = _parse_labels(body, line)
        else:
            name, _, value_part = line.partition(" ")
            labels = ()
        name = name.strip()
        if not name or not value_part.strip():
            raise ParseError(f"malformed sample line: {line}")
        try:
            value = _parse_value(value_part)
        except ValueError as e:
            raise ParseError(f"bad value in: {line}") from e
        family(_family_name(name, families)).samples.append(
            Sample(name, labels, value)
        )
    return ParsedMetrics(families)
