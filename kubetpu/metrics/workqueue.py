"""client-go workqueue metrics (util/workqueue/metrics.go + the
prometheus provider in k8s.io/component-base/metrics/prometheus/workqueue).

The reference metric set, per queue ``name``:

- ``workqueue_depth`` — current READY depth (client-go's gauge is
  ready-only too; keys parked in backoff surface when they drain)
- ``workqueue_adds_total`` — keys accepted by Add (dirty dedup excluded)
- ``workqueue_queue_duration_seconds`` — Add → Get latency
  (ExponentialBuckets(1e-08, 10, 10), nanoseconds → ~100 s)
- ``workqueue_work_duration_seconds`` — Get → Done latency (same buckets)
- ``workqueue_retries_total`` — AddRateLimited calls
- ``workqueue_unfinished_work_seconds`` — summed age of in-flight keys
- ``workqueue_longest_running_processor_seconds`` — oldest in-flight key

One process-wide provider (``default_provider``) mirrors client-go's
global ``metrics.SetProvider``: every ``QueueController`` queue lands in
it unless the owner injects its own, so a single /metrics exposition
covers the whole controller family.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Callable

from .registry import Registry, exponential_buckets

# prometheus.ExponentialBuckets(10e-9, 10, 10): 10 ns … 100 s
QUEUE_LATENCY_BUCKETS = exponential_buckets(1e-08, 10, 10)


class QueueMetrics:
    """Per-queue recorder the WorkQueue calls into — the reference's
    ``queueMetrics``. Tracks per-key add/processing timestamps so the
    latency histograms and the in-flight gauges need no queue internals."""

    def __init__(self, name: str, provider: "WorkqueueMetricsProvider",
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.name = name
        self.clock = clock
        p = provider
        self._depth = p.depth.labels(name)
        self._adds = p.adds.labels(name)
        self._retries = p.retries.labels(name)
        self._queue_duration = p.queue_duration.labels(name)
        self._work_duration = p.work_duration.labels(name)
        self._unfinished = p.unfinished_work.labels(name)
        self._longest = p.longest_running.labels(name)
        self._added_at: dict = {}
        self._started_at: dict = {}
        # a scrape thread refreshes the in-flight gauges while the owner
        # loop mutates the timestamp dicts
        self._lock = threading.Lock()

    def add(self, key, depth: int) -> None:
        self._adds.inc()
        with self._lock:
            self._added_at.setdefault(key, self.clock())
        self._depth.set(depth)

    def retry(self, key) -> None:
        self._retries.inc()

    def get(self, key, depth: int) -> None:
        self._depth.set(depth)
        now = self.clock()
        with self._lock:
            added = self._added_at.pop(key, None)
            self._started_at[key] = now
            self._update_inflight(now)
        if added is not None:
            self._queue_duration.observe(max(now - added, 0.0))

    def done(self, key, depth: int) -> None:
        now = self.clock()
        with self._lock:
            started = self._started_at.pop(key, None)
            self._update_inflight(now)
        if started is not None:
            self._work_duration.observe(max(now - started, 0.0))
        self._depth.set(depth)

    def refresh_inflight(self) -> None:
        """Recompute the in-flight gauges NOW — called at scrape time so a
        wedged processor's age keeps growing on the dashboard instead of
        freezing at its last get() (client-go's updateUnfinishedWorkLoop
        tick)."""
        with self._lock:
            self._update_inflight(self.clock())

    def _update_inflight(self, now: float) -> None:
        if self._started_at:
            ages = [max(now - t0, 0.0) for t0 in self._started_at.values()]
            self._unfinished.set(sum(ages))
            self._longest.set(max(ages))
        else:
            self._unfinished.set(0.0)
            self._longest.set(0.0)


class WorkqueueMetricsProvider:
    """Owns the workqueue metric vectors on one Registry; ``for_queue``
    hands out per-name recorders (client-go's MetricsProvider)."""

    def __init__(self, registry: Registry | None = None) -> None:
        r = registry if registry is not None else Registry()
        self.registry = r
        # live recorders, refreshed at scrape time (weak: a recorder dies
        # with its queue); WeakSet is not thread-safe and scrape threads
        # iterate while owners register, so guard it
        self._recorders: "weakref.WeakSet[QueueMetrics]" = weakref.WeakSet()
        self._recorders_lock = threading.Lock()
        self.depth = r.gauge(
            "workqueue_depth", "Current depth of workqueue", labels=("name",)
        )
        self.adds = r.counter(
            "workqueue_adds_total",
            "Total number of adds handled by workqueue",
            labels=("name",),
        )
        self.queue_duration = r.histogram(
            "workqueue_queue_duration_seconds",
            "How long in seconds an item stays in workqueue before being "
            "requested.",
            labels=("name",),
            buckets=QUEUE_LATENCY_BUCKETS,
        )
        self.work_duration = r.histogram(
            "workqueue_work_duration_seconds",
            "How long in seconds processing an item from workqueue takes.",
            labels=("name",),
            buckets=QUEUE_LATENCY_BUCKETS,
        )
        self.retries = r.counter(
            "workqueue_retries_total",
            "Total number of retries handled by workqueue",
            labels=("name",),
        )
        self.unfinished_work = r.gauge(
            "workqueue_unfinished_work_seconds",
            "How many seconds of work has been done that is in progress and "
            "hasn't been observed by work_duration.",
            labels=("name",),
        )
        self.longest_running = r.gauge(
            "workqueue_longest_running_processor_seconds",
            "How many seconds has the longest running processor for "
            "workqueue been running.",
            labels=("name",),
        )

    def for_queue(
        self, name: str, clock: Callable[[], float] = time.monotonic
    ) -> QueueMetrics:
        m = QueueMetrics(name, self, clock=clock)
        with self._recorders_lock:
            self._recorders.add(m)
        return m

    def expose(self) -> str:
        with self._recorders_lock:
            recorders = list(self._recorders)
        for rec in recorders:
            rec.refresh_inflight()
        return self.registry.expose()


_default: WorkqueueMetricsProvider | None = None
_default_lock = threading.Lock()


def default_provider() -> WorkqueueMetricsProvider:
    """The process-wide provider every controller queue registers with by
    default (client-go's global prometheus provider). Locked: two
    controllers constructed concurrently must not mint two providers, or
    the loser's queues record into a registry no scrape ever exposes."""
    global _default
    with _default_lock:
        if _default is None:
            _default = WorkqueueMetricsProvider()
        return _default
