"""Prometheus-shaped metric primitives + registry + text exposition.

The analog of staging/src/k8s.io/component-base/metrics (which wraps
client_golang): Counter/Gauge/Histogram vectors keyed by label values, a
Registry for /metrics exposition (Prometheus text format 0.0.4), and
``exponential_buckets`` matching prometheus.ExponentialBuckets — the bucket
layouts in pkg/scheduler/metrics/metrics.go are reproduced exactly so
dashboards built for the reference read identically.

Histogram quantiles use the Prometheus histogram_quantile estimation
(linear interpolation within the bucket), so the perf harness's p99 numbers
come from the same math a PromQL query would produce.
"""

from __future__ import annotations

import bisect
import math
import threading
from dataclasses import dataclass, field


def exponential_buckets(start: float, factor: float, count: int) -> list[float]:
    """prometheus.ExponentialBuckets: count buckets, start * factor^i."""
    return [start * (factor ** i) for i in range(count)]


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", labels: tuple[str, ...] = (),
                 declared: dict[str, tuple] | None = None):
        self.name = name
        self.help = help
        self.label_names = tuple(labels)
        # label name -> tuple of the ONLY legal values (the staged-latency
        # {stage} contract): an unknown value raises at .labels() time, and
        # the graftcheck MR004 checker enforces the same set at parse time
        # for literal call sites — declared sets cannot drift silently.
        self.declared = {
            k: tuple(v) for k, v in (declared or {}).items()
        }
        self._children: dict[tuple, "_Metric"] = {}
        self._lock = threading.Lock()

    def labels(self, *values: str):
        """Child metric for one label-value combination (Vec semantics)."""
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, got {values}"
            )
        key = tuple(str(v) for v in values)
        for name, value in zip(self.label_names, key):
            allowed = self.declared.get(name)
            if allowed is not None and value not in allowed:
                raise ValueError(
                    f"{self.name}: label {name}={value!r} outside the "
                    f"declared set {allowed}"
                )
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _make_child(self):
        raise NotImplementedError

    def _children_snapshot(self) -> list[tuple]:
        """Stable view for iteration — labels() may insert concurrently
        (the scheduler thread observes while a /metrics scrape walks)."""
        with self._lock:
            return list(self._children.items())

    def samples(self):
        """Yield (suffix, label_values, extra_label_pairs, value)."""
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labels=(), declared=None):
        super().__init__(name, help, labels, declared)
        self.value = 0.0

    def _make_child(self):
        return Counter(self.name)

    def inc(self, amount: float = 1.0) -> None:
        # locked: apiserver handler threads and the scheduler loop mutate
        # concurrently (ThreadingHTTPServer); a bare += is a lost-update
        # race across threads
        with self._lock:
            self.value += amount

    def samples(self):
        if self.label_names:
            for key, child in self._children_snapshot():
                yield "", key, child.value
        else:
            yield "", (), self.value


class Gauge(Counter):
    kind = "gauge"

    def _make_child(self):
        return Gauge(self.name)

    def set(self, value: float) -> None:
        with self._lock:
            self.value = value

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self.value -= amount


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labels=(), buckets=None, declared=None):
        super().__init__(name, help, labels, declared)
        self.buckets = list(buckets if buckets is not None
                            else exponential_buckets(0.001, 2, 15))
        self.counts = [0] * (len(self.buckets) + 1)   # +Inf tail
        self.total = 0
        self.sum = 0.0

    def _make_child(self):
        return Histogram(self.name, buckets=self.buckets)

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += 1
            self.total += 1
            self.sum += value

    def observe_n(self, value: float, n: int) -> None:
        """n identical observations in O(1) — batch cycles record one
        duration for every pod of the batch."""
        if n <= 0:
            return
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self.counts[i] += n
            self.total += n
            self.sum += value * n

    def _consistent_state(self) -> tuple[list[int], int, float]:
        """counts/total/sum copied under the lock — a reader racing an
        observe() must not see counts moved but total not (a torn,
        non-monotonic histogram breaks histogram_quantile)."""
        with self._lock:
            return list(self.counts), self.total, self.sum

    def merged(self) -> "Histogram":
        """Aggregate across children (and self) — what a PromQL sum() over
        label dimensions sees."""
        out = Histogram(self.name, buckets=self.buckets)
        children = [c for _, c in self._children_snapshot()]
        sources = children or [self]
        if children and self.total:
            sources.append(self)
        for src in sources:
            counts, total, s = src._consistent_state()
            for i, c in enumerate(counts):
                out.counts[i] += c
            out.total += total
            out.sum += s
        return out

    def since(self, earlier: "Histogram") -> "Histogram":
        """The delta histogram vs an earlier ``merged()`` snapshot — scopes
        quantiles to a measurement window (the perf harness's per-workload
        p99)."""
        h = self.merged()
        out = Histogram(self.name, buckets=self.buckets)
        out.counts = [a - b for a, b in zip(h.counts, earlier.counts)]
        out.total = h.total - earlier.total
        out.sum = h.sum - earlier.sum
        return out

    def quantile(self, q: float) -> float:
        """histogram_quantile(q, …): linear interpolation inside the target
        bucket; NaN when empty; the last bucket's upper bound caps +Inf."""
        # merged() copies under the lock even without children, so a racing
        # observe() cannot tear the read
        h = self.merged()
        if h.total == 0:
            return float("nan")
        rank = q * h.total
        acc = 0
        for i, c in enumerate(h.counts):
            acc += c
            if acc >= rank and c > 0:
                lo = h.buckets[i - 1] if i > 0 else 0.0
                hi = h.buckets[i] if i < len(h.buckets) else h.buckets[-1]
                frac = (rank - (acc - c)) / c
                return lo + (hi - lo) * frac
        return h.buckets[-1]

    def samples(self):
        def rows(child, key):
            counts, total, s = child._consistent_state()
            acc = 0
            for i, ub in enumerate(child.buckets):
                acc += counts[i]
                yield "_bucket", key + (("le", _fmt(ub)),), acc
            yield "_bucket", key + (("le", "+Inf"),), total
            yield "_sum", key, s
            yield "_count", key, total

        if self.label_names:
            for key, child in self._children_snapshot():
                labeled = tuple(zip(self.label_names, key))
                yield from rows(child, labeled)
        else:
            yield from rows(self, ())


def _fmt(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == int(v):
        return str(int(v))
    return repr(v)


@dataclass
class Registry:
    """Named metric registry + Prometheus text exposition (the legacy
    registry + /metrics handler of component-base)."""

    metrics: dict[str, _Metric] = field(default_factory=dict)

    def register(self, metric: _Metric) -> _Metric:
        if metric.name in self.metrics:
            raise ValueError(f"metric {metric.name!r} already registered")
        self.metrics[metric.name] = metric
        return metric

    def counter(self, name, help="", labels=(), declared=None) -> Counter:
        return self.register(Counter(name, help, labels, declared))

    def gauge(self, name, help="", labels=(), declared=None) -> Gauge:
        return self.register(Gauge(name, help, labels, declared))

    def histogram(self, name, help="", labels=(), buckets=None,
                  declared=None) -> Histogram:
        return self.register(Histogram(name, help, labels, buckets, declared))

    def get(self, name: str) -> _Metric | None:
        return self.metrics.get(name)

    def expose(self) -> str:
        """Prometheus text format 0.0.4."""
        out: list[str] = []
        for name in sorted(self.metrics):
            m = self.metrics[name]
            out.append(f"# HELP {name} {m.help}")
            out.append(f"# TYPE {name} {m.kind}")
            for suffix, label_pairs, value in m.samples():
                if isinstance(label_pairs, tuple) and label_pairs and (
                    not isinstance(label_pairs[0], tuple)
                ):
                    # bare child key from a vec Counter/Gauge
                    label_pairs = tuple(zip(m.label_names, label_pairs))
                if label_pairs:
                    body = ",".join(
                        f'{k}="{_esc_label(v)}"' for k, v in label_pairs
                    )
                    out.append(f"{name}{suffix}{{{body}}} {_num(value)}")
                else:
                    out.append(f"{name}{suffix} {_num(value)}")
        return "\n".join(out) + "\n"


def _esc_label(v) -> str:
    """Exposition-format label-value escaping (text format 0.0.4): label
    values may carry any UTF-8, so backslash, double-quote, and newline
    must be escaped or one hostile value corrupts the whole scrape page."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _num(v) -> str:
    f = float(v)
    if f == int(f):
        return str(int(f))
    return repr(f)
