"""The scheduler's metric set, with the reference's names and bucket
layouts (pkg/scheduler/metrics/metrics.go):

- scheduling_attempt_duration_seconds{result, profile} (:247, STABLE,
  ExponentialBuckets(0.001, 2, 15))
- scheduling_algorithm_duration_seconds (:252, same buckets)
- pod_scheduling_sli_duration_seconds{attempts} (:316, BETA,
  ExponentialBuckets(0.01, 2, 20)) — e2e from queue entry to bind dispatch
- pod_scheduling_attempts (:327, ExponentialBuckets(1, 2, 5))
- framework_extension_point_duration_seconds{extension_point, status,
  profile} (:344, ExponentialBuckets(0.0001, 2, 12))
- plugin_execution_duration_seconds{plugin, extension_point, status}
  (:353, ExponentialBuckets(0.00001, 1.5, 20)) — per host-side lifecycle
  plugin call; the fused device Filter+Score program cannot be timed
  per-plugin (it is ONE XLA program), so its wall time lands on
  extension_point="Filter+Score" at the framework level instead
- schedule_attempts_total{result, profile}, preemption_attempts_total,
  preemption_victims (:267 ExponentialBuckets(1, 2, 7)), pending_pods{queue}
"""

from __future__ import annotations

import math

from .registry import Histogram, Registry, exponential_buckets

#: the per-pod staged latency attribution vector (sched.flightrecorder):
#: the ONLY legal values of the {stage} label on
#: scheduler_e2e_scheduling_duration_seconds — declared at registration
#: (runtime check) and enforced at parse time by graftcheck MR004.
E2E_STAGES = (
    "api_ingest",       # REST create -> informer delivery (fullstack)
    "informer",         # delivery-handler wall (incl. pre-encode)
    "queue_wait",       # enqueue -> pop, summed across requeue hops
    "encode",           # owning cycle's host-encode wall
    "kernel",           # owning cycle's device-program wall
    "dispatch",         # bind enqueue -> micro-batch execution start
    "bind_rtt",         # bind execution -> completion
    "e2e",              # ingest (or delivery) -> bind ack
)

#: the engine registry (Scheduler(engine=…)): the ONLY legal values of the
#: {engine} label on the packing-objective metric family — declared at
#: registration and enforced at parse time by graftcheck MR004.
ENGINES = (
    "greedy",           # exact reference-semantics per-pod scan
    "batched",          # capacity-coupled rounds (throughput mode)
    "packing",          # constraint-based packing (cluster objectives)
)


def window_quantile_ms(
    hist: Histogram, baseline: Histogram | None = None, q: float = 0.99
) -> float | None:
    """A histogram quantile in MILLISECONDS scoped to the measurement
    window: with ``baseline`` (an earlier ``merged()`` snapshot) the
    quantile covers only the delta since it — a large init phase must not
    dominate the reported p99s (the perf runner's window-scoping rule,
    shared by both run modes and the staged percentiles). None when the
    window observed nothing."""
    delta = hist.since(baseline) if baseline is not None else hist.merged()
    if delta.total > 0:
        return float(delta.quantile(q) * 1000.0)
    return None


class SchedulerMetricsRegistry:
    """Owns a Registry pre-populated with the scheduler metric set; the
    Scheduler observes into it and /metrics exposes it."""

    def __init__(self) -> None:
        r = Registry()
        self.registry = r
        self.scheduling_attempt_duration = r.histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency in seconds (scheduling algorithm + binding)",
            labels=("result", "profile"),
            buckets=exponential_buckets(0.001, 2, 15),
        )
        self.scheduling_algorithm_duration = r.histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency in seconds",
            buckets=exponential_buckets(0.001, 2, 15),
        )
        self.pod_scheduling_sli_duration = r.histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e latency for a pod being scheduled, from the time the pod "
            "enters the scheduling queue and might involve multiple "
            "scheduling attempts.",
            labels=("attempts",),
            buckets=exponential_buckets(0.01, 2, 20),
        )
        self.pod_scheduling_attempts = r.histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=exponential_buckets(1, 2, 5),
        )
        self.framework_extension_point_duration = r.histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency for running all plugins of a specific extension point.",
            labels=("extension_point", "status", "profile"),
            buckets=exponential_buckets(0.0001, 2, 12),
        )
        self.plugin_execution_duration = r.histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Duration for running a plugin at a specific extension point.",
            labels=("plugin", "extension_point", "status"),
            buckets=exponential_buckets(0.00001, 1.5, 20),
        )
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by the result.",
            labels=("result", "profile"),
        )
        self.preemption_attempts = r.counter(
            "scheduler_preemption_attempts_total",
            "Total preemption attempts in the cluster till now",
        )
        self.preemption_victims = r.histogram(
            "scheduler_preemption_victims",
            "Number of selected preemption victims",
            buckets=exponential_buckets(1, 2, 7),
        )
        self.e2e_scheduling_duration = r.histogram(
            "scheduler_e2e_scheduling_duration_seconds",
            "Per-pod staged scheduling latency: where each pod's "
            "end-to-end time went, by attribution stage "
            "(sched.flightrecorder; stages: " + ", ".join(E2E_STAGES) + ").",
            labels=("stage",),
            buckets=exponential_buckets(0.0001, 2, 20),
            declared={"stage": E2E_STAGES},
        )
        self.pending_pods = r.gauge(
            "scheduler_pending_pods",
            "Number of pending pods, by the queue type.",
            labels=("queue",),
        )
        self.queue_incoming_pods = r.counter(
            "scheduler_queue_incoming_pods_total",
            "Number of pods added to scheduling queues by event and queue type.",
            labels=("queue", "event"),
        )
        # --- active-active federation (sched.federation) ------------------
        # conflicts: CAS-bind 409 losses + epoch-fenced stale-owner binds,
        # labeled by partition mode and replica id — the numerator of the
        # conflict/throughput curve ("none"/"r0" in single-scheduler mode)
        self.federation_conflicts = r.counter(
            "scheduler_federation_conflicts_total",
            "CAS-bind conflicts lost to another scheduler replica "
            "(409 losers and epoch-fenced stale-owner binds), by "
            "federation partition mode and replica id.",
            labels=("mode", "replica"),
        )
        self.federation_lease_transitions = r.counter(
            "scheduler_federation_lease_transitions_total",
            "Partition-lease ownership changes (acquisitions + losses) "
            "observed by this replica's lease manager.",
            labels=("mode", "replica"),
        )
        self.federation_partitions_owned = r.gauge(
            "scheduler_federation_partitions_owned",
            "Partition leases currently owned by this replica "
            "(lease mode; the ownership rebalance evidence).",
            labels=("mode", "replica"),
        )
        # --- packing engine (assign.packing) ------------------------------
        # cluster-objective telemetry, labeled by the engine that produced
        # it (today only "packing" reports; greedy/batched leave the whole
        # family unobserved, which keeps the sentinel's solver-iteration
        # rule dormant for them — an absent series extracts to None)
        self.packing_objective = r.gauge(
            "scheduler_packing_objective",
            "Last cycle's packing objective value: priority-weighted "
            "admission minus the alpha*nodes-opened and beta*fragmentation "
            "penalties (assign.packing), by engine.",
            labels=("engine",),
            declared={"engine": ENGINES},
        )
        self.nodes_used = r.gauge(
            "scheduler_nodes_used",
            "Nodes carrying at least one pod after the last scheduling "
            "cycle, as seen by the device solver, by engine.",
            labels=("engine",),
            declared={"engine": ENGINES},
        )
        self.packing_solver_iters = r.histogram(
            "scheduler_packing_solver_iters",
            "Solver iterations (projection-loop rounds) per scheduling "
            "cycle — the warm-start evidence: steady-state cycles should "
            "sit in the low buckets, spikes feed the sentinel's "
            "PackingSolverIterationSpike rule.",
            labels=("engine",),
            buckets=exponential_buckets(1, 2, 12),
            declared={"engine": ENGINES},
        )
        # --- gang admission (sched.podgroup) ------------------------------
        # quorum-met → fully-admitted latency, observed ONCE per group at
        # first admission. Labeled by engine like the packing family so a
        # run with no pod groups never creates the series — the sentinel's
        # gang-admission-stall rule stays dormant on gang-free clusters
        # (absent series extracts to None, same shape as
        # packing-solver-iteration-spike).
        self.gang_admission_duration = r.histogram(
            "scheduler_gang_admission_duration_seconds",
            "Latency from a pod group reaching quorum to its first full "
            "admission (all members of the winning attempt assumed), by "
            "engine. Observed once per group.",
            labels=("engine",),
            buckets=exponential_buckets(0.001, 2, 16),
            declared={"engine": ENGINES},
        )
        # API dispatcher lifetime counts, set at scrape time from
        # APIDispatcher.stats() (a gauge because the dispatcher owns the
        # monotonic counters; "errors" is the satellite's failed-API-write
        # signal, "batches"/"batched_calls" size the bulk micro-batches)
        self.api_dispatcher_calls = r.gauge(
            "scheduler_api_dispatcher_calls",
            "API dispatcher lifetime call counts by event: added, executed, "
            "errors, batches (bulk RPCs issued), batched_calls (calls that "
            "rode a bulk RPC).",
            labels=("event",),
        )

    def set_dispatcher_stats(self, stats: dict) -> None:
        for event, value in stats.items():
            self.api_dispatcher_calls.labels(event).set(value)

    def expose(self) -> str:
        return self.registry.expose()

    # --- convenience for the perf harness ---------------------------------
    def p99_attempt_latency_s(self) -> float:
        """p99 of pod_scheduling_sli_duration_seconds across attempt labels
        (histogram_quantile over the summed buckets)."""
        return self.pod_scheduling_sli_duration.quantile(0.99)

    def _attempts_by_result(self) -> dict:
        attempts: dict[str, int] = {}
        for key, child in self.schedule_attempts._children_snapshot():
            result = key[0] if key else "unknown"
            attempts[result] = attempts.get(result, 0) + int(child.value)
        return attempts

    def snapshot_baseline(self) -> dict:
        """Capture the current histogram/counter state; pass to
        ``snapshot(baseline=...)`` so the summary covers only the window
        since (the perf harness scopes to its measured phase — embedded
        numbers must describe the same population as the measurement
        fields beside them)."""
        return {
            "attempt_duration": self.scheduling_attempt_duration.merged(),
            "sli_duration": self.pod_scheduling_sli_duration.merged(),
            "algorithm_duration": self.scheduling_algorithm_duration.merged(),
            "schedule_attempts": self._attempts_by_result(),
            "e2e_stages": self._staged_children(),
        }

    def _staged_children(self) -> dict:
        """{stage: merged Histogram} for every stage observed so far."""
        return {
            key[0]: child.merged()
            for key, child in (
                self.e2e_scheduling_duration._children_snapshot()
            )
        }

    def staged_percentiles(self, baseline: dict | None = None) -> dict | None:
        """Per-stage p50/p99 (ms) of the staged latency vector, scoped to
        the window since ``baseline`` (a ``snapshot_baseline``) — the
        ``staged_latency_ms`` block every fullstack bench record carries.
        None when no stage observed anything in the window."""
        base = (baseline or {}).get("e2e_stages", {})
        out = {}
        for stage, child in self._staged_children().items():
            p50 = window_quantile_ms(child, base.get(stage), 0.50)
            p99 = window_quantile_ms(child, base.get(stage), 0.99)
            if p99 is None:
                continue
            out[stage] = {"p50": round(p50, 3), "p99": round(p99, 3)}
        return out or None

    def snapshot(self, baseline: dict | None = None) -> dict:
        """Post-run summary embedded in BENCH artifacts: p50/p99 from the
        histograms plus schedule_attempts by result — the numbers a
        dashboard would derive from a scrape, pre-derived so every bench
        JSON is self-describing. With ``baseline`` (a
        ``snapshot_baseline``), everything is the DELTA since it."""

        def q(hist, quantile: float) -> float | None:
            v = hist.quantile(quantile)
            return None if math.isnan(v) else round(float(v), 6)

        attempt_h = self.scheduling_attempt_duration
        sli_h = self.pod_scheduling_sli_duration
        algo_h = self.scheduling_algorithm_duration
        attempts = self._attempts_by_result()
        if baseline is not None:
            attempt_h = attempt_h.since(baseline["attempt_duration"])
            sli_h = sli_h.since(baseline["sli_duration"])
            algo_h = algo_h.since(baseline["algorithm_duration"])
            base_attempts = baseline["schedule_attempts"]
            attempts = {
                k: v - base_attempts.get(k, 0)
                for k, v in attempts.items()
                if v - base_attempts.get(k, 0)
            }
        return {
            "schedule_attempts": attempts,
            "attempt_duration_s": {
                "p50": q(attempt_h, 0.50),
                "p99": q(attempt_h, 0.99),
            },
            "sli_duration_s": {
                "p50": q(sli_h, 0.50),
                "p99": q(sli_h, 0.99),
            },
            "algorithm_duration_s": {
                "p50": q(algo_h, 0.50),
                "p99": q(algo_h, 0.99),
            },
        }
