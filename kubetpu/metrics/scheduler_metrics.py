"""The scheduler's metric set, with the reference's names and bucket
layouts (pkg/scheduler/metrics/metrics.go):

- scheduling_attempt_duration_seconds{result, profile} (:247, STABLE,
  ExponentialBuckets(0.001, 2, 15))
- scheduling_algorithm_duration_seconds (:252, same buckets)
- pod_scheduling_sli_duration_seconds{attempts} (:316, BETA,
  ExponentialBuckets(0.01, 2, 20)) — e2e from queue entry to bind dispatch
- pod_scheduling_attempts (:327, ExponentialBuckets(1, 2, 5))
- framework_extension_point_duration_seconds{extension_point, status,
  profile} (:344, ExponentialBuckets(0.0001, 2, 12))
- schedule_attempts_total{result, profile}, preemption_attempts_total,
  preemption_victims (:267 ExponentialBuckets(1, 2, 7)), pending_pods{queue}
"""

from __future__ import annotations

from .registry import Registry, exponential_buckets


class SchedulerMetricsRegistry:
    """Owns a Registry pre-populated with the scheduler metric set; the
    Scheduler observes into it and /metrics exposes it."""

    def __init__(self) -> None:
        r = Registry()
        self.registry = r
        self.scheduling_attempt_duration = r.histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency in seconds (scheduling algorithm + binding)",
            labels=("result", "profile"),
            buckets=exponential_buckets(0.001, 2, 15),
        )
        self.scheduling_algorithm_duration = r.histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency in seconds",
            buckets=exponential_buckets(0.001, 2, 15),
        )
        self.pod_scheduling_sli_duration = r.histogram(
            "scheduler_pod_scheduling_sli_duration_seconds",
            "E2e latency for a pod being scheduled, from the time the pod "
            "enters the scheduling queue and might involve multiple "
            "scheduling attempts.",
            labels=("attempts",),
            buckets=exponential_buckets(0.01, 2, 20),
        )
        self.pod_scheduling_attempts = r.histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=exponential_buckets(1, 2, 5),
        )
        self.framework_extension_point_duration = r.histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency for running all plugins of a specific extension point.",
            labels=("extension_point", "status", "profile"),
            buckets=exponential_buckets(0.0001, 2, 12),
        )
        self.schedule_attempts = r.counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by the result.",
            labels=("result", "profile"),
        )
        self.preemption_attempts = r.counter(
            "scheduler_preemption_attempts_total",
            "Total preemption attempts in the cluster till now",
        )
        self.preemption_victims = r.histogram(
            "scheduler_preemption_victims",
            "Number of selected preemption victims",
            buckets=exponential_buckets(1, 2, 7),
        )
        self.pending_pods = r.gauge(
            "scheduler_pending_pods",
            "Number of pending pods, by the queue type.",
            labels=("queue",),
        )
        self.queue_incoming_pods = r.counter(
            "scheduler_queue_incoming_pods_total",
            "Number of pods added to scheduling queues by event and queue type.",
            labels=("queue", "event"),
        )

    def expose(self) -> str:
        return self.registry.expose()

    # --- convenience for the perf harness ---------------------------------
    def p99_attempt_latency_s(self) -> float:
        """p99 of pod_scheduling_sli_duration_seconds across attempt labels
        (histogram_quantile over the summed buckets)."""
        return self.pod_scheduling_sli_duration.quantile(0.99)
