"""Snapshot → tensor encoding (the tensorization layer, SURVEY §7.2).

Replaces the reference's per-node object walks with a two-step scheme:

1. **Host (numpy)**: label keys/values, taints, ports and selectors are
   interned (``Vocab``); every *distinct* selector/toleration/port signature
   among the pending pods is evaluated once against all N nodes, vectorized
   over nodes, yielding per-signature ``(N,)`` masks. Pods gather their
   signature's mask — O(distinct_signatures × N), not O(pods × N) Python.
2. **Device (jnp)**: only integer/bool tensors cross the host↔device
   boundary: ``(N, R)`` allocatable/requested, ``(P, R)`` requests, ``(P, N)``
   static masks and static score addends. The dynamic kernels (resource fit,
   spread, inter-pod affinity) run entirely on device.

This file covers the *static* per-pod-per-node facts:
  - NodeName        (schedule_one's trivial predicate)
  - NodeUnschedulable (plugins/nodeunschedulable — toleration-aware)
  - TaintToleration Filter + Score raw counts (plugins/tainttoleration)
  - NodeAffinity Filter (required) + Score raw weights (plugins/nodeaffinity)
  - spec.nodeSelector (part of NodeAffinity plugin's Filter)
plus the NodePorts *dynamic*-filter tensors (interned port triples + conflict
matrix — usage evolves as the batch assigns pods, so the conflict check runs
on device, not here). Resource tensors for NodeResourcesFit/LeastAllocated/
BalancedAllocation are encoded here too; their kernels live in ``kubetpu.ops``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from .. import names
from ..api import types as t
from ..api.selectors import (
    count_intolerable_prefer_no_schedule,
    find_untolerated_taint,
    node_selector_term_matches,
    requirement_matches,
    tolerates,
)
from .snapshot import NodeInfo, Snapshot
from .vocab import Vocab

BASE_RESOURCES = (t.CPU, t.MEMORY, t.EPHEMERAL_STORAGE)

# the default static-score plugin set (profile=None callers)
DEFAULT_SCORES = frozenset({names.NODE_AFFINITY, names.TAINT_TOLERATION})

_UNSCHEDULABLE_TAINT = t.Taint(
    key="node.kubernetes.io/unschedulable", effect=t.TaintEffect.NO_SCHEDULE
)


def round_up(n: int, minimum: int = 8) -> int:
    """Pad to a compile-cache bucket (XLA static shapes; SURVEY §7 'Hard
    parts: dynamic shapes'): next power of two up to 1024, then next multiple
    of 1024 — power-of-two padding wastes up to 2× compute at cluster scale
    (10k pods → 16384 scan steps), and the cache-hit benefit saturates once
    shapes are large."""
    v = minimum
    while v < n and v < 1024:
        v <<= 1
    if n <= v:
        return v
    return (n + 1023) // 1024 * 1024


def shard_aligned(n: int, multiple: int) -> int:
    """Round a padded node capacity up to a per-shard bucket boundary: a
    mesh of ``multiple`` shards needs capacity % multiple == 0 or the
    sharded resident block degrades to replication. ONE place computes
    this (runtime.encode_batch_static and the bench's capacity planner
    both call it), so a mesh's bucket padding can never disagree with the
    encoder's — at 100k nodes a mismatched bucket re-pads ~100 MB of
    node-axis tensors per cycle."""
    if multiple <= 1:
        return n
    return (n + multiple - 1) // multiple * multiple


def bucket_ladder(n: int, minimum: int = 8) -> list[int]:
    """Every padded size ``round_up`` can produce for inputs in [1, n] —
    the compile-cache bucket ladder. Warming all of them at startup means a
    churning queue (whose batch sizes wander the ladder) never pays XLA
    compilation mid-cycle."""
    top = round_up(n, minimum)
    out = [minimum]
    while out[-1] < top:
        v = out[-1]
        out.append(v << 1 if v < 1024 else v + 1024)
    return out


def resource_axis(snapshot: Snapshot, pods: Sequence[t.Pod]) -> list[str]:
    """Fixed resource vocabulary: base resources then sorted scalars seen in
    node allocatable or pod requests."""
    scalars: set[str] = set()
    for info in snapshot.nodes.values():
        for k, _ in info.node.allocatable:
            if k not in BASE_RESOURCES and k != t.PODS:
                scalars.add(k)
    for p in pods:
        for k, _ in p.requests:
            if k not in BASE_RESOURCES and k != t.PODS:
                scalars.add(k)
    return list(BASE_RESOURCES) + sorted(scalars)


# singleton scalars stay dense while few (cheap; preserves full preemption
# semantics for the common handful-of-scalar-types cluster); past this many
# distinct singletons they ALL fold, keeping the resource axis STABLE
# across cycles (a per-cycle-varying axis would defeat encode_snapshot's
# prev-row reuse in exactly the per-node-unique workload folding targets)
FOLD_SINGLETON_THRESHOLD = 8


def batch_resource_axis(
    snapshot: Snapshot, pods: Sequence[t.Pod]
) -> tuple[list[str], frozenset]:
    """The BATCH's resource axis: base resources plus the scalars the batch
    actually requests (node-advertised-but-unrequested scalars never enter a
    fit comparison, so they would be dead columns — the DRA/extended
    per-node-unique resource shape advertises thousands).

    Returns ``(resource_names, folded)``: when a batch carries more than
    FOLD_SINGLETON_THRESHOLD distinct single-pod scalars, every singleton
    folds into the static mask — a singleton has no in-batch capacity
    contention by construction, so its availability check is a pure static
    per-node mask (encode_pod_batch), and the dense axis (base + multi-pod
    scalars) stays identical cycle to cycle. Known deviation: a pod blocked
    ONLY on a folded resource reads as statically infeasible, so preemption
    won't hunt victims for it (the reference can preempt to free extended
    resources); multi-pod scalars always keep full dense preemption
    semantics.
    """
    import collections

    counts: collections.Counter = collections.Counter()
    for p in pods:
        for k, v in p.requests:
            if k not in BASE_RESOURCES and k != t.PODS and v > 0:
                counts[k] += 1
    multi = sorted(k for k, c in counts.items() if c > 1)
    singles = sorted(k for k, c in counts.items() if c == 1)
    if len(singles) > FOLD_SINGLETON_THRESHOLD:
        folded = frozenset(singles)
        dense = multi
    else:
        folded = frozenset()
        dense = multi + singles
    return list(BASE_RESOURCES) + sorted(dense), folded


@dataclass
class NodeTensors:
    """Numpy-side encoded snapshot. Node-axis arrays may be allocated at a
    larger padded capacity (``encode_snapshot(pad_nodes=…)``); rows past
    ``num_nodes`` are zero (no allocatable → infeasible everywhere)."""

    resource_names: list[str]
    node_names: list[str]
    alloc: np.ndarray              # (≥N, R) int64
    requested: np.ndarray          # (≥N, R) int64 (exact, Fit filter view)
    nonzero_requested: np.ndarray  # (≥N, R) int64 (scoring view)
    pod_count: np.ndarray          # (≥N,) int32
    allowed_pods: np.ndarray       # (≥N,) int32
    # host-side helpers for signature evaluation
    infos: list[NodeInfo] = field(repr=False, default_factory=list)
    key_vocab: Vocab = field(repr=False, default_factory=Vocab)
    val_vocab: Vocab = field(repr=False, default_factory=Vocab)
    node_label: np.ndarray | None = field(repr=False, default=None)  # (N, K) int32
    # per-node cache generation each row was last encoded at — enables the
    # incremental ``encode_snapshot(…, prev=…)`` refresh (only rows whose
    # generation moved are rewritten, the UpdateSnapshot O(Δ) philosophy)
    node_gens: dict = field(repr=False, default_factory=dict)
    # node name → row index (maintained across the append-incremental
    # branch so dirty-candidate names resolve in O(1))
    name_to_idx: dict = field(repr=False, default_factory=dict)
    # --- O(Δ) informer-to-tensor sync bookkeeping ------------------------
    # the backing Cache these tensors were encoded from (snapshot.
    # cache_token), the cache's order epoch at that time, and the highest
    # cache generation folded in: together they let the incremental
    # refresh (a) skip the O(N) node-name list compare (order epoch pins
    # set+order), (b) scan only the recency index's Δ instead of all N
    # rows, and (c) extend in place when every structural change since was
    # an append (an autoscaler add-wave at 100k nodes must not pay a full
    # O(N) re-encode per cycle)
    src_token: object = field(repr=False, default=None)
    src_order_epoch: int = field(repr=False, default=-1)
    gens_watermark: int = field(repr=False, default=0)
    # --- delta-upload + pipeline-staleness bookkeeping -------------------
    # row indices re-encoded but not yet shipped to the device-resident
    # node block (runtime.ResidentNodeState consumes + clears); None means
    # "freshly (re)built — everything needs a full upload"
    pending_device_rows: set | None = field(repr=False, default=None)
    # outcome of the LAST encode_snapshot call on this object: which rows it
    # re-encoded, whether any re-encoded row's VALUES actually differ from
    # what was there before (a bind confirmation replaces a pod with
    # identical accounting → rows re-encode to the same values), and whether
    # any node OBJECT was replaced (labels/taints/images may differ — facts
    # outside the resource rows). The pipelined scheduler uses these to
    # decide whether a dispatched-but-unsynced cycle saw stale state.
    last_dirty_rows: tuple = field(repr=False, default=())
    last_values_changed: bool = field(repr=False, default=False)
    last_nodes_replaced: bool = field(repr=False, default=False)
    # a dirty row whose POD SET content (uids, labels, host ports) changed —
    # facts that feed affinity/spread/port tensors without moving the
    # resource rows (a bind confirmation replaces a pod with identical
    # content and does NOT set this)
    last_pods_mutated: bool = field(repr=False, default=False)
    # per-node content signature backing the check above
    pod_content_sigs: dict = field(repr=False, default_factory=dict)
    # row indices of nodes with any in-use host-port triple, maintained by
    # ``_encode_node_row`` (a pod add/remove touches its node's generation,
    # so every port change re-encodes the row) — the per-cycle port encode
    # walks THIS set, not all N nodes (an O(N)-python-per-cycle wall at
    # 100k nodes for the port-free steady state)
    nodes_with_ports: set = field(repr=False, default_factory=set)
    # memoized dense topology coordinates (state.topology.TopologyTensors);
    # cleared by ``_refresh_tensors`` whenever a node object was replaced
    # or appended, since labels may have moved under the coordinates
    topo_memo: object = field(repr=False, default=None)

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)

    @property
    def num_resources(self) -> int:
        return len(self.resource_names)

    def diff_rows(self, other: "NodeTensors") -> "list[int] | None":
        """Row indices whose resource/count values differ from ``other``
        (vectorized over the full padded capacity). None when the two are
        not comparable — different padded capacity or resource axis. The
        incremental-reshard path of ``runtime.ResidentNodeState`` uses this
        to turn a node add/delete (which rebuilds the NodeTensors object)
        into a dirty-row delta upload instead of a full re-upload."""
        if (
            other.alloc.shape != self.alloc.shape
            or other.resource_names != self.resource_names
        ):
            return None
        changed = (
            np.any(self.alloc != other.alloc, axis=1)
            | np.any(self.requested != other.requested, axis=1)
            | np.any(self.nonzero_requested != other.nonzero_requested, axis=1)
            | (self.pod_count != other.pod_count)
            | (self.allowed_pods != other.allowed_pods)
        )
        return np.flatnonzero(changed).tolist()

    # ---- label machinery -------------------------------------------------
    def _ensure_label_matrix(self) -> np.ndarray:
        if self.node_label is None or self.node_label.shape[1] < len(self.key_vocab):
            K = len(self.key_vocab)
            # allocated at the padded node CAPACITY (like the resource
            # arrays) so the append-incremental branch writes new rows in
            # place instead of forcing an O(N·K) rebuild per add-wave cycle
            mat = np.full((self.alloc.shape[0], K), -1, dtype=np.int32)
            for i, info in enumerate(self.infos):
                for k, v in info.node.labels:
                    mat[i, self.key_vocab.get(k)] = self.val_vocab.intern(v)
            self.node_label = mat
        return self.node_label

    def requirement_mask(self, req: t.Requirement) -> np.ndarray:
        """(N,) bool — vectorized over nodes via interned label ids."""
        kid = self.key_vocab.get(req.key)
        if kid < 0:
            # Key never appears on any node: In/Exists/Gt/Lt fail everywhere,
            # NotIn/DoesNotExist succeed everywhere.
            ok = req.operator in (t.Operator.NOT_IN, t.Operator.DOES_NOT_EXIST)
            return np.full(self.num_nodes, ok, dtype=bool)
        col = self._ensure_label_matrix()[: self.num_nodes, kid]
        op = req.operator
        if op == t.Operator.EXISTS:
            return col >= 0
        if op == t.Operator.DOES_NOT_EXIST:
            return col < 0
        if op == t.Operator.IN:
            vids = [self.val_vocab.get(v) for v in req.values]
            vids = np.array([v for v in vids if v >= 0], dtype=np.int32)
            return np.isin(col, vids) & (col >= 0)
        if op == t.Operator.NOT_IN:
            vids = [self.val_vocab.get(v) for v in req.values]
            vids = np.array([v for v in vids if v >= 0], dtype=np.int32)
            return ~np.isin(col, vids) | (col < 0)
        # Gt/Lt: rare — fall back to scalar evaluation per node.
        out = np.zeros(self.num_nodes, dtype=bool)
        for i, info in enumerate(self.infos):
            out[i] = requirement_matches(req, info.node.labels_dict())
        return out

    def term_mask(self, term: t.NodeSelectorTerm) -> np.ndarray:
        if not term.match_expressions and not term.match_fields:
            return np.zeros(self.num_nodes, dtype=bool)
        m = np.ones(self.num_nodes, dtype=bool)
        for req in term.match_expressions:
            m &= self.requirement_mask(req)
        if term.match_fields:
            names = np.array(
                [
                    node_selector_term_matches(
                        t.NodeSelectorTerm(match_fields=term.match_fields),
                        {},
                        n,
                    )
                    for n in self.node_names
                ],
                dtype=bool,
            )
            m &= names
        return m

    def node_selector_mask(self, sel: t.NodeSelector) -> np.ndarray:
        m = np.zeros(self.num_nodes, dtype=bool)
        for term in sel.terms:
            m |= self.term_mask(term)
        return m

    def topology_values(self, topo_key: str) -> np.ndarray:
        """(N,) int32 domain id per node for a topology label key; -1 absent."""
        kid = self.key_vocab.get(topo_key)
        if kid < 0:
            return np.full(self.num_nodes, -1, dtype=np.int32)
        return self._ensure_label_matrix()[: self.num_nodes, kid].copy()


def _encode_node_row(
    nt: NodeTensors, i: int, info: NodeInfo, ridx: dict
) -> None:
    """(Re)write row ``i`` of the resource/count arrays from ``info``."""
    nt.alloc[i, :] = 0
    nt.requested[i, :] = 0
    nt.nonzero_requested[i, :] = 0
    nt.allowed_pods[i] = 0
    for k, v in info.node.allocatable:
        if k == t.PODS:
            nt.allowed_pods[i] = v
        else:
            j = ridx.get(k)
            if j is not None:
                nt.alloc[i, j] = v
    for k, v in info.requested.items():
        j = ridx.get(k)
        if j is not None:
            nt.requested[i, j] = v
    for k, v in info.nonzero_requested.items():
        j = ridx.get(k)
        if j is not None:
            nt.nonzero_requested[i, j] = v
    nt.pod_count[i] = len(info.pods)
    if info.port_triples:
        nt.nodes_with_ports.add(i)
    else:
        nt.nodes_with_ports.discard(i)


def _pod_content_sig(info: NodeInfo) -> int:
    """Order-independent signature of the node's pod-set facts that feed
    tensors OUTSIDE the resource rows: uids (membership), labels (affinity/
    spread selectors) and ports (NodePorts). Resource changes are covered by
    the row-value diff; this catches a label or hostPort mutation on an
    otherwise resource-identical pod. XOR-combined so no sort is needed —
    the per-dirty-row cost is O(pods on the node) hashes flat."""
    h = 0
    for uid, p in info.pods.items():
        h ^= hash((uid, p.labels, p.ports))
    return h


def encode_snapshot(
    snapshot: Snapshot, resource_names: Sequence[str] | None = None,
    pods: Sequence[t.Pod] = (),
    pad_nodes: int | None = None,
    prev: NodeTensors | None = None,
    track_changes: bool = True,
) -> NodeTensors:
    """``pad_nodes``: allocate node-axis arrays at this capacity up front
    (rows past the real node count stay zero = infeasible), avoiding a
    full-array ``np.pad`` copy downstream.

    ``prev``: a NodeTensors from an earlier snapshot of the SAME cache —
    when the node order, resource axis and capacity still match, only rows
    whose cache generation moved are re-encoded (cache.go:190 UpdateSnapshot
    O(Δ) semantics on the tensor side). The returned object may BE ``prev``,
    mutated in place; device uploads copy, so this is safe once the previous
    cycle's arrays are on device.

    ``track_changes``: maintain the value-diff / pod-content-signature
    staleness flags (``last_values_changed`` / ``last_pods_mutated``) the
    PIPELINED scheduler consumes. The serial loop never reads them — False
    skips the per-dirty-row copies, comparisons and content hashing, and
    sets the flags conservatively True whenever any row was dirty."""
    rnames = list(resource_names) if resource_names else resource_axis(snapshot, pods)
    infos = snapshot.node_infos()
    N, R = len(infos), len(rnames)
    NP = max(pad_nodes or N, N)
    node_names: list[str] | None = None

    if (
        prev is not None
        and prev.resource_names == rnames
        and prev.alloc.shape[0] >= NP
        and prev.alloc.shape[1] == R
    ):
        n_prev = len(prev.node_names)
        cache_match = (
            prev.src_token is not None
            and prev.src_token is snapshot.cache_token
        )
        same_set = appended = False
        if N == n_prev:
            # order epoch pins node set + order: the O(N) name-list compare
            # only runs for cacheless (hand-built) snapshots
            if cache_match and prev.src_order_epoch == snapshot.order_epoch:
                same_set = True
                node_names = prev.node_names
            else:
                node_names = [info.node.name for info in infos]
                same_set = prev.node_names == node_names
        elif N > n_prev:
            if cache_match and snapshot.appends_only_since(
                prev.src_order_epoch
            ):
                appended = True
            else:
                node_names = [info.node.name for info in infos]
                appended = node_names[:n_prev] == prev.node_names
        if same_set or appended:
            return _refresh_tensors(
                snapshot, prev, infos, rnames,
                appended_from=n_prev if appended else None,
                track_changes=track_changes, cache_match=cache_match,
            )

    if node_names is None:
        node_names = [info.node.name for info in infos]
    ridx = {r: i for i, r in enumerate(rnames)}
    alloc = np.zeros((NP, R), dtype=np.int64)
    requested = np.zeros((NP, R), dtype=np.int64)
    nonzero = np.zeros((NP, R), dtype=np.int64)
    pod_count = np.zeros(NP, dtype=np.int32)
    allowed = np.zeros(NP, dtype=np.int32)
    key_vocab, val_vocab = Vocab(), Vocab()
    nt = NodeTensors(
        resource_names=rnames,
        node_names=node_names,
        alloc=alloc,
        requested=requested,
        nonzero_requested=nonzero,
        pod_count=pod_count,
        allowed_pods=allowed,
        infos=infos,
        key_vocab=key_vocab,
        val_vocab=val_vocab,
        node_gens={
            name: snapshot.node_generation.get(name) for name in node_names
        },
        name_to_idx={name: i for i, name in enumerate(node_names)},
        src_token=snapshot.cache_token,
        src_order_epoch=snapshot.order_epoch,
        gens_watermark=snapshot.cache_watermark,
    )
    for i, info in enumerate(infos):
        _encode_node_row(nt, i, info, ridx)
        if track_changes:
            # seed the content signatures so a post-rebuild bind
            # confirmation (identical content) doesn't read as a mutation
            nt.pod_content_sigs[info.node.name] = _pod_content_sig(info)
        for k, v in info.node.labels:
            key_vocab.intern(k)
            val_vocab.intern(v)
    return nt


def _refresh_tensors(
    snapshot: Snapshot,
    prev: NodeTensors,
    infos: "list[NodeInfo]",
    rnames: list[str],
    appended_from: int | None,
    track_changes: bool,
    cache_match: bool,
) -> NodeTensors:
    """Incremental refresh of ``prev`` in place (the returned object IS
    ``prev``): re-encode pre-existing rows whose cache generation moved,
    and — when ``appended_from`` is given — encode the freshly APPENDED
    node rows into the spare padded capacity (an autoscaler add-wave
    extends the tensors instead of paying a full O(N) rebuild per cycle).

    Dirty discovery is O(Δ) when the snapshot's backing cache is the one
    these tensors were built from: the cache's recency index names the
    candidates (``Snapshot.dirty_since``) instead of a full O(N) gen scan
    — each candidate is still gen-checked, so a superset is harmless."""
    ridx = {r: i for i, r in enumerate(rnames)}
    gens = prev.node_gens
    dirty: list[int] = []
    values_changed = False
    nodes_replaced = False
    pods_mutated = False
    N = len(infos)
    n_old = appended_from if appended_from is not None else N

    cand: list[int] | None = None
    if cache_match:
        names_c = snapshot.dirty_since(prev.gens_watermark)
        if names_c is not None:
            idx_of = prev.name_to_idx
            cand = sorted(
                i for i in (idx_of.get(nm, -1) for nm in names_c)
                if 0 <= i < n_old
            )
    for i in (range(n_old) if cand is None else cand):
        info = infos[i]
        name = info.node.name
        gen = snapshot.node_generation.get(name)
        if gens.get(name) == gen:
            continue
        dirty.append(i)
        old_row = None
        if track_changes:
            psig = _pod_content_sig(info)
            if prev.pod_content_sigs.get(name) != psig:
                pods_mutated = True
                prev.pod_content_sigs[name] = psig
            if not values_changed:
                old_row = (
                    prev.alloc[i].copy(), prev.requested[i].copy(),
                    prev.nonzero_requested[i].copy(),
                    int(prev.pod_count[i]), int(prev.allowed_pods[i]),
                )
        _encode_node_row(prev, i, info, ridx)
        if old_row is not None and not (
            int(prev.pod_count[i]) == old_row[3]
            and int(prev.allowed_pods[i]) == old_row[4]
            and np.array_equal(prev.alloc[i], old_row[0])
            and np.array_equal(prev.requested[i], old_row[1])
            and np.array_equal(prev.nonzero_requested[i], old_row[2])
        ):
            values_changed = True
        if prev.infos[i].node is not info.node:
            nodes_replaced = True
            # node object replaced: labels may differ — refresh vocab and
            # the label-matrix row (new keys force a lazy full rebuild)
            kv, vv = prev.key_vocab, prev.val_vocab
            before = len(kv)
            for k, v in info.node.labels:
                kv.intern(k)
                vv.intern(v)
            if prev.node_label is not None:
                if len(kv) > before or len(kv) > prev.node_label.shape[1]:
                    prev.node_label = None
                else:
                    prev.node_label[i, :] = -1
                    for k, v in info.node.labels:
                        prev.node_label[i, kv.get(k)] = vv.intern(v)
        gens[name] = gen

    if appended_from is not None:
        # the add-wave extension: encode ONLY the appended rows; existing
        # rows, vocab ids and the label matrix stay valid (node index is
        # position in the order, and appends preserve the prefix)
        kv, vv = prev.key_vocab, prev.val_vocab
        keys_before = len(kv)
        new_names: list[str] = []
        for i in range(appended_from, N):
            info = infos[i]
            name = info.node.name
            _encode_node_row(prev, i, info, ridx)
            gens[name] = snapshot.node_generation.get(name)
            prev.name_to_idx[name] = i
            new_names.append(name)
            if track_changes:
                prev.pod_content_sigs[name] = _pod_content_sig(info)
            for k, v in info.node.labels:
                kv.intern(k)
                vv.intern(v)
            dirty.append(i)
        prev.node_names.extend(new_names)
        if prev.node_label is not None:
            if len(kv) > keys_before or len(kv) > prev.node_label.shape[1]:
                prev.node_label = None   # new keys: lazy full rebuild
            else:
                for i in range(appended_from, N):
                    prev.node_label[i, :] = -1
                    for k, v in infos[i].node.labels:
                        prev.node_label[i, kv.get(k)] = vv.intern(v)
        # the node SET changed: a pipelined in-flight cycle must replay
        nodes_replaced = True

    prev.infos = infos
    prev.src_token = snapshot.cache_token
    prev.src_order_epoch = snapshot.order_epoch
    if cache_match:
        prev.gens_watermark = snapshot.cache_watermark
    else:
        # adopting a NEW backing cache: its generation space is unrelated
        # to the old watermark — reset so the next O(Δ) walk cannot skip
        # dirty rows that live below a stale-high watermark
        prev.gens_watermark = 0
    prev.last_dirty_rows = tuple(dirty)
    if not track_changes and dirty:
        # flags not maintained: report "changed" so a consumer that
        # does read them errs toward a replay, never toward staleness
        values_changed = True
        pods_mutated = True
    prev.last_values_changed = values_changed
    prev.last_nodes_replaced = nodes_replaced
    prev.last_pods_mutated = pods_mutated
    if nodes_replaced:
        # replaced/appended node objects may carry different topology
        # labels — the dense coordinate memo no longer describes them
        prev.topo_memo = None
    if prev.pending_device_rows is not None:
        prev.pending_device_rows.update(dirty)
    return prev


# --------------------------------------------------------------------------
# Pod batch encoding
# --------------------------------------------------------------------------

def _static_filter_signature(pod: t.Pod):
    """Everything that determines the pod's static (P,N) feasibility mask.
    NodePorts is NOT here: port usage changes as the batch assigns pods, so
    it is a dynamic filter (interned triples + conflict matrix below)."""
    na = pod.affinity.node_affinity if pod.affinity else None
    return (
        pod.node_selector,
        na.required if na else None,
        pod.tolerations,
    )


def _static_score_signature(pod: t.Pod):
    na = pod.affinity.node_affinity if pod.affinity else None
    return (na.preferred if na else (), pod.tolerations)


# --------------------------------------------------------------------------
# Template-keyed row builders — pure functions of (node static facts, pod
# signature), shared by the batch encoder and the event-time encode cache
# (state.encode_cache): one build per distinct TEMPLATE, gathered by every
# pod stamped from it, across pods and across cycles.
# --------------------------------------------------------------------------

def build_request_row(
    pod: t.Pod, ridx: dict, R: int, folded_resources: frozenset,
    dense_items: Sequence[tuple[int, int]] = (),
) -> tuple[np.ndarray, np.ndarray, bool]:
    """``(requests (R,), nonzero (R,), unknown)`` on the given resource
    axis. ``unknown``: the pod requests a resource absent from the axis
    (and not folded) — statically infeasible everywhere."""
    req_row = np.zeros(R, dtype=np.int64)
    nz_row = np.zeros(R, dtype=np.int64)
    unknown = False
    for k, v in pod.requests:
        j = ridx.get(k)
        if j is not None:
            req_row[j] = v
        elif v > 0 and k != t.PODS and k not in folded_resources:
            unknown = True
    for k, v in pod.nonzero_requests().items():
        j = ridx.get(k)
        if j is not None:
            nz_row[j] = v
    for pid, count in dense_items:
        j = ridx.get(f"dra/pool{pid}")
        if j is not None:
            req_row[j] = count
            nz_row[j] = count
    return req_row, nz_row, unknown


def build_static_filter_row(
    nt: "NodeTensors", ctx, pod: t.Pod, f: frozenset,
    feat_req: tuple, unknown: bool,
) -> np.ndarray:
    """The PURE-STATIC (N,) feasibility row for a pod signature: node
    selector + required node affinity, taints, unschedulable, declared
    features, spec.nodeName, unknown-resource. Batch-coupled parts
    (volumes, DRA, folded scalars, in-batch RWOP) are layered onto a COPY
    by the batch encoder — they never enter the cached row. ``ctx`` is an
    ``encode_cache.NodeCtx`` (taint/unschedulable/feature hoists)."""
    N = nt.num_nodes
    m = np.ones(N, dtype=bool)
    if names.NODE_AFFINITY in f:
        # spec.nodeSelector — ANDed equality terms (NodeAffinity Filter)
        for k, v in pod.node_selector:
            m &= nt.requirement_mask(t.Requirement(k, t.Operator.IN, (v,)))
        # required node affinity
        na = pod.affinity.node_affinity if pod.affinity else None
        if na and na.required is not None:
            m &= nt.node_selector_mask(na.required)
    if names.TAINT_TOLERATION in f and ctx.tainted_nodes:
        # taints (NoSchedule/NoExecute) — dedupe by node taint tuple
        taint_ok: dict[tuple, bool] = {}
        for n_i, taints in ctx.tainted_nodes:
            ok = taint_ok.get(taints)
            if ok is None:
                ok = find_untolerated_taint(taints, pod.tolerations) is None
                taint_ok[taints] = ok
            if not ok:
                m[n_i] = False
    if names.NODE_UNSCHEDULABLE in f and ctx.any_unsched:
        # unschedulable nodes pass only if the pod tolerates the taint
        tolerated = any(
            tolerates(tol, _UNSCHEDULABLE_TAINT) for tol in pod.tolerations
        )
        if not tolerated:
            m &= ~ctx.node_unsched
    if feat_req:
        # NodeDeclaredFeatures Filter (nodedeclaredfeatures.go:
        # reqs ⊆ node.status.declaredFeatures, failures
        # UnschedulableAndUnresolvable)
        want = set(feat_req)
        if ctx.node_feature_sets is None:
            m[:] = False   # no node declares anything
        else:
            m &= np.array(
                [want <= s for s in ctx.node_feature_sets], dtype=bool
            )
    # NodeName (spec.nodeName pre-assignment) — exact match only
    if pod.node_name and names.NODE_NAME in f:
        m &= np.array(
            [n == pod.node_name for n in nt.node_names], dtype=bool
        )
    if unknown:
        m[:] = False
    return m


def build_static_score_rows(
    nt: "NodeTensors", ctx, pod: t.Pod, want_na: bool, want_tt: bool,
) -> tuple[np.ndarray, np.ndarray]:
    """``(node_affinity_raw (N,), taint_prefer_raw (N,))`` for a static
    score signature."""
    N = nt.num_nodes
    na_vec = np.zeros(N, dtype=np.int64)
    na = pod.affinity.node_affinity if pod.affinity else None
    if na and want_na:
        for pref in na.preferred:
            tm = nt.term_mask(pref.term)
            na_vec += pref.weight * tm.astype(np.int64)
    tt_vec = np.zeros(N, dtype=np.int64)
    if want_tt and ctx.tainted_nodes:
        prefer_cache: dict[tuple, int] = {}
        for n_i, taints in ctx.tainted_nodes:
            c = prefer_cache.get(taints)
            if c is None:
                c = count_intolerable_prefer_no_schedule(
                    taints, pod.tolerations
                )
                prefer_cache[taints] = c
            tt_vec[n_i] = c
    return na_vec, tt_vec


@dataclass
class PodBatch:
    """Numpy-side encoded pending-pod batch.

    Static per-(pod,node) facts are **signature-compressed**: pods sharing a
    static-filter (or static-score) signature share one ``(N,)`` row, so the
    arrays are ``(S, N)`` with a per-pod ``(P,)`` row index — the device
    gathers rows inside the jitted program. Replicated workloads (the
    scheduler_perf shape, runtime/batch.go:61-64's identical-signature
    observation) have S ≪ P, which turns the dominant host→device transfer
    (O(P·N) int64) into O(S·N).

    Port tensors (NodePorts, plugins/nodeports — a *dynamic* filter because
    assignments during the batch occupy ports): distinct
    ``(hostPort, protocol, hostIP)`` triples across pending pods and node
    usage are interned to ids 0..K-1; ``port_conflict[k, l]`` says triple k
    conflicts with an in-use triple l (same port+protocol, and equal hostIP
    or either side the 0.0.0.0 wildcard). A pod fits a node iff
    ``~any(pod_ports @ port_conflict @ node_ports^T)``; the greedy scan ORs
    the winner's ``pod_ports`` row into the node's usage row.
    """

    pods: list[t.Pod]
    requests: np.ndarray            # (P, R) int64
    nonzero_requests: np.ndarray    # (P, R) int64
    priority: np.ndarray            # (P,) int32
    # None when no pod has any static constraint (= all-True over valid
    # rows). (S, N) bool, one row per distinct static-filter signature.
    static_mask: np.ndarray | None  # (S, N) bool — all static filters ANDed
    static_sig: np.ndarray | None   # (P,) int32 — row of static_mask per pod
    # None unless requested via enabled_scores. (S2, N), one row per
    # distinct static-score signature.
    node_affinity_raw: np.ndarray | None  # (S2, N) — Σ matched preferred weights
    taint_prefer_raw: np.ndarray | None   # (S2, N) — intolerable PreferNoSchedule
    score_sig: np.ndarray | None    # (P,) int32 — row per pod
    pod_ports: np.ndarray           # (P, K) bool — triples the pod wants
    node_ports: np.ndarray          # (N, K) bool — triples in use on the node
    port_conflict: np.ndarray       # (K, K) bool
    port_vocab: Vocab | None = None  # triple→id table (shared w/ preemption)

    @property
    def num_pods(self) -> int:
        return len(self.pods)

    # --- per-pod dense views (tests / host-side debugging) ---------------
    def static_row(self, i: int) -> np.ndarray | None:
        if self.static_mask is None:
            return None
        return self.static_mask[self.static_sig[i]]

    def na_row(self, i: int) -> np.ndarray | None:
        if self.node_affinity_raw is None:
            return None
        return self.node_affinity_raw[self.score_sig[i]]

    def tt_row(self, i: int) -> np.ndarray | None:
        if self.taint_prefer_raw is None:
            return None
        return self.taint_prefer_raw[self.score_sig[i]]


def _pod_port_triples(pod: t.Pod) -> list[tuple[int, str, str]]:
    return [
        (cp.host_port, cp.protocol or "TCP", cp.host_ip or "0.0.0.0")
        for cp in pod.ports
        if cp.host_port > 0
    ]


def _encode_ports(
    nt: NodeTensors, pods: Sequence[t.Pod],
    pad_pods: int | None = None, pad_nodes: int | None = None,
    extra_triples: Sequence[tuple[int, str, str]] = (),
) -> tuple[np.ndarray, np.ndarray, np.ndarray, Vocab]:
    """Intern port triples → (pod_ports (P,K), node_ports (N,K),
    port_conflict (K,K), vocab). K is at least 1 (all-False dummy) so
    downstream einsums never see a zero axis. ``extra_triples`` (e.g. from
    nominated pods not in this batch) join the vocab + conflict matrix so
    callers can build their own rows against it."""
    vocab = Vocab()
    P, N = len(pods), nt.num_nodes
    pod_rows: list[tuple[int, list[int]]] = []
    for i, p in enumerate(pods):
        if p.ports:
            row = vocab.intern_all(_pod_port_triples(p))
            if row:
                pod_rows.append((i, row))
    # NodeInfo refcounts its in-use triples incrementally (UsedPorts), and
    # ``nodes_with_ports`` indexes the bearing rows, so this is
    # O(nodes-with-ports × triples) flat — the port-free steady state pays
    # nothing per node (at 100k nodes even a truthiness sweep was a
    # per-cycle python wall)
    node_rows: list[tuple[int, list[int]]] = []
    for i in sorted(nt.nodes_with_ports):
        info = nt.infos[i]
        if info.port_triples:
            node_rows.append(
                (i, [vocab.intern(tr) for tr in info.port_triples])
            )
    for tr in extra_triples:
        vocab.intern(tr)

    K = max(len(vocab), 1)
    pod_ports = np.zeros((max(pad_pods or P, P), K), dtype=bool)
    node_ports = np.zeros((max(pad_nodes or N, N), K), dtype=bool)
    for i, row in pod_rows:
        pod_ports[i, row] = True
    for i, row in node_rows:
        node_ports[i, row] = True
    conflict = np.zeros((K, K), dtype=bool)
    if len(vocab):
        # vectorized triple-vs-triple conflict: same port+protocol, and
        # equal hostIP or either side the 0.0.0.0 wildcard
        items = [vocab.lookup(k) for k in range(len(vocab))]
        port_a = np.array([p_ for p_, _, _ in items])
        proto_a = np.array([r_ for _, r_, _ in items])
        ip_a = np.array([i_ for _, _, i_ in items])
        same = (port_a[:, None] == port_a[None, :]) & (
            proto_a[:, None] == proto_a[None, :]
        )
        wild = (
            (ip_a[:, None] == "0.0.0.0")
            | (ip_a[None, :] == "0.0.0.0")
            | (ip_a[:, None] == ip_a[None, :])
        )
        conflict[: len(items), : len(items)] = same & wild
    return pod_ports, node_ports, conflict, vocab


def encode_pod_batch(
    nt: NodeTensors,
    pods: Sequence[t.Pod],
    enabled_filters: frozenset[str] | None = None,
    pad_pods: int | None = None,
    enabled_scores: frozenset[str] | None = None,
    extra_port_triples: Sequence[tuple[int, str, str]] = (),
    volume_state=None,
    folded_resources: frozenset = frozenset(),
    folded_nominated: Sequence[tuple[str, Sequence[tuple[str, int]]]] = (),
    dra_state=None,
    cache=None,
) -> PodBatch:
    """``enabled_filters`` is the profile's Filter plugin set (names from
    ``kubetpu.names``); None enables everything. Disabled static predicates
    are left out of ``static_mask``, mirroring a KubeSchedulerConfiguration
    that disables the plugin. ``enabled_scores`` likewise gates the static
    raw-score tensors (NodeAffinity preferred, TaintToleration prefer-count).

    ``pad_pods``: allocate pod-axis arrays at this capacity (rows past the
    real pod count stay zero / all-False-mask = never assigned). The node
    axis inherits ``nt``'s capacity. Avoids ``np.pad`` copies downstream.

    ``cache``: an ``encode_cache.EncodeCache`` — static filter/score/request
    rows become gathers over template-keyed rows that persist across pods
    AND cycles (pre-built at informer delivery when the scheduler wires the
    event-time hooks). None = the original build-per-batch behavior; the
    per-batch signature dedupe below is retained either way, so cached and
    fresh encodes are bit-identical by construction.
    """
    f = names.ALL_FILTERS if enabled_filters is None else enabled_filters
    sc = DEFAULT_SCORES if enabled_scores is None else enabled_scores
    ridx = {r: i for i, r in enumerate(nt.resource_names)}
    P, N, R = len(pods), nt.num_nodes, nt.num_resources
    PP = max(pad_pods or P, P)
    NC = nt.alloc.shape[0]  # node capacity (≥ N)
    if cache is not None:
        cache.sync_nodes(nt)
        cache.sync_request_axis(tuple(nt.resource_names), folded_resources)
        ctx = cache.node_ctx(nt)
        sigs = [cache.pod_sigs(p) for p in pods]
    else:
        from .encode_cache import build_node_ctx

        ctx = build_node_ctx(nt)
        sigs = [
            (_static_filter_signature(p), _static_score_signature(p))
            for p in pods
        ]
    requests = np.zeros((PP, R), dtype=np.int64)
    nonzero = np.zeros((PP, R), dtype=np.int64)
    priority = np.zeros(PP, dtype=np.int32)
    # Pods requesting a resource absent from the snapshot's axis can fit
    # nowhere (no node advertises it: request > 0 - 0); mark them infeasible
    # everywhere instead of silently dropping the request.
    unknown_resource = np.zeros(P, dtype=bool)
    # DRA (state.dra): per-pod analyses are precomputed+cached by
    # encode_batch; dense pool requests join the request rows through
    # columns named "dra/pool<id>" already present in the resource axis
    want_dra = dra_state is not None and names.DYNAMIC_RESOURCES in f
    dra_of: dict[int, object] = {}
    if want_dra:
        for i, p in enumerate(pods):
            d = dra_state.analyze(p)
            if d.any_work:
                dra_of[i] = d
    # Request rows dedupe heavily across a batch (replicated workloads) —
    # build each distinct (requests, nonzero) row once per batch, and per
    # TEMPLATE across cycles when the encode cache is on (DRA-coupled rows
    # depend on the allocator state and stay per-batch).
    row_cache: dict[tuple, tuple[np.ndarray, np.ndarray, bool]] = {}
    for i, p in enumerate(pods):
        d = dra_of.get(i)
        dense_items = d.dense if d is not None else ()
        key = (p.requests, p.nonzero, dense_items)
        entry = row_cache.get(key)
        if entry is None:
            if cache is not None and not dense_items:
                entry = cache.request_row(
                    key,
                    lambda p=p: build_request_row(
                        p, ridx, R, folded_resources, ()
                    ),
                )
            else:
                entry = build_request_row(
                    p, ridx, R, folded_resources, dense_items
                )
            row_cache[key] = entry
        requests[i], nonzero[i], unknown_resource[i] = entry
        priority[i] = p.priority

    # distinct static-filter signatures → one (N,) mask ROW each; pods carry
    # the row index. Pod-specific deviations (spec.nodeName, unknown
    # resources) fold into the signature key so a row is a pure function of
    # its key. The PURE-STATIC part of the row (build_static_filter_row) is
    # cacheable across cycles; batch-coupled extras (volumes, DRA, folded
    # scalars, in-batch RWOP) are layered onto a copy.
    sig_ids: dict = {}
    sig_rows: list[np.ndarray] = []
    sig_trivial: list[bool] = []
    static_sig = np.zeros(PP, dtype=np.int32)
    any_nontrivial = False

    # folded-scalar availability: one pass over nodes builds per-resource
    # (node, available) occurrence lists — O(node scalar entries), not
    # O(folded × N). A folded resource is requested by exactly one batch
    # pod, so static masking is exact (no in-batch contention to couple).
    # Nominated preemptors' folded requests are charged to their nominated
    # node for EVERY batch pod (the dense path gates by priority via
    # resource_fit_mask_nominated; folding charges conservatively —
    # a higher-priority pod may be held off a unit a nominee reserved).
    fold_avail: dict[str, list[tuple[int, int]]] = {}
    if folded_resources:
        nom_charge: dict[tuple[str, str], int] = {}
        for node_name, reqs in folded_nominated:
            for k, v in reqs:
                if k in folded_resources:
                    nom_charge[(k, node_name)] = (
                        nom_charge.get((k, node_name), 0) + v
                    )
        for n_i, info in enumerate(nt.infos):
            for k, cap in info.node.allocatable:
                if k in folded_resources:
                    avail = cap - info.requested.get(k, 0)
                    avail -= nom_charge.get((k, info.node.name), 0)
                    fold_avail.setdefault(k, []).append((n_i, avail))

    # in-batch ReadWriteOncePod guard: an RWOP claim taken by an EARLIER pod
    # of this batch rejects later users this cycle (the reference's per-pod
    # loop sees the first pod's assume; the batch must not co-schedule them)
    seen_rwop: set[str] = set()
    for i, p in enumerate(pods):
        vol_sig = None
        rwop_dup = False
        folded_items: tuple = ()
        if folded_resources:
            folded_items = tuple(
                (k, v) for k, v in p.requests
                if k in folded_resources and v > 0
            )
        if volume_state is not None and p.volumes:
            vol_sig = (
                p.namespace,
                tuple(v.pvc_name for v in p.volumes if v.pvc_name),
            )
            if names.VOLUME_RESTRICTIONS in f:
                for v in p.volumes:
                    if not v.pvc_name:
                        continue
                    pk = f"{p.namespace}/{v.pvc_name}"
                    pvc = volume_state.pvcs.get(pk)
                    if pvc is not None and t.READ_WRITE_ONCE_POD in pvc.access_modes:
                        if pk in seen_rwop:
                            rwop_dup = True
                        seen_rwop.add(pk)
        d = dra_of.get(i)
        dra_sig = (
            (d.blocked, d.pin, d.host_specs) if d is not None else None
        )
        feat_req = (
            p.required_node_features
            if names.NODE_DECLARED_FEATURES in f else ()
        )
        # the cacheable half of the key: everything build_static_filter_row
        # consumes (pure function of node static facts + these parts)
        base_key = (
            sigs[i][0],
            feat_req,
            p.node_name if names.NODE_NAME in f else "",
            bool(unknown_resource[i]) and names.NODE_RESOURCES_FIT in f,
            f,
        )
        sig = (base_key, vol_sig, rwop_dup, folded_items, dra_sig)
        sid = sig_ids.get(sig)
        if sid is None:
            def build(p=p, base_key=base_key):
                return build_static_filter_row(
                    nt, ctx, p, f, base_key[1], base_key[3]
                )

            if cache is not None:
                base, base_trivial = cache.filter_row(base_key, build, p)
            else:
                base = build()
                base_trivial = bool(base.all())
            extras = (
                vol_sig is not None or rwop_dup or dra_sig is not None
                or (folded_items and names.NODE_RESOURCES_FIT in f)
            )
            if extras:
                m = base.copy()
                if vol_sig is not None:
                    # the volume plugin family (zone/binding/restrictions/
                    # limits)
                    vm = volume_state.mask_for(p.namespace, p.volumes, nt, f)
                    if vm is not None:
                        m &= vm
                if rwop_dup:
                    m[:] = False
                if dra_sig is not None:
                    # DynamicResources static contributions
                    # (dynamicresources.go Filter :734): blocked claims
                    # reject everywhere; an allocated claim pins to its
                    # node; host-path specs AND in the exact allocator's
                    # per-node feasibility
                    blocked_, pin_, host_specs_ = dra_sig
                    if blocked_:
                        m[:] = False
                    else:
                        if pin_:
                            m &= np.array(
                                [n == pin_ for n in nt.node_names], dtype=bool
                            )
                        for spec in host_specs_:
                            m &= dra_state.spec_mask(spec, nt)
                if folded_items and names.NODE_RESOURCES_FIT in f:
                    for k, v in folded_items:
                        fm = np.zeros(N, dtype=bool)
                        for n_i, avail in fold_avail.get(k, ()):
                            if avail >= v:
                                fm[n_i] = True
                        m &= fm
                trivial = bool(m.all())
            else:
                m = base
                trivial = base_trivial
            sid = len(sig_rows)
            sig_ids[sig] = sid
            sig_rows.append(m)
            sig_trivial.append(trivial)
        static_sig[i] = sid
        if not sig_trivial[sid]:
            any_nontrivial = True

    static_mask: np.ndarray | None = None
    if any_nontrivial:
        static_mask = np.zeros((len(sig_rows), NC), dtype=bool)
        for s, m in enumerate(sig_rows):
            static_mask[s, :N] = m
    else:
        static_sig = None

    # distinct static-score signatures → one (N,) raw-score ROW each
    want_na = names.NODE_AFFINITY in sc
    want_tt = names.TAINT_TOLERATION in sc
    na_raw = tt_raw = score_sig = None
    if want_na or want_tt:
        score_ids: dict = {}
        score_rows: list[tuple[np.ndarray, np.ndarray]] = []
        score_sig = np.zeros(PP, dtype=np.int32)
        for i, p in enumerate(pods):
            ssig = sigs[i][1]
            sid = score_ids.get(ssig)
            if sid is None:
                def build_sc(p=p):
                    return build_static_score_rows(nt, ctx, p, want_na, want_tt)

                if cache is not None:
                    entry = cache.score_row(
                        (ssig, want_na, want_tt), build_sc, p,
                    )
                else:
                    entry = build_sc()
                sid = len(score_rows)
                score_ids[ssig] = sid
                score_rows.append(entry)
            score_sig[i] = sid
        S2 = max(len(score_rows), 1)
        if want_na:
            na_raw = np.zeros((S2, NC), dtype=np.int64)
            for s, (nv, _) in enumerate(score_rows):
                na_raw[s, :N] = nv
        if want_tt:
            tt_raw = np.zeros((S2, NC), dtype=np.int64)
            for s, (_, tv) in enumerate(score_rows):
                tt_raw[s, :N] = tv

    pod_ports, node_ports, port_conflict, port_vocab = _encode_ports(
        nt, pods, pad_pods=PP, pad_nodes=NC,
        extra_triples=extra_port_triples,
    )
    return PodBatch(
        pods=list(pods),
        requests=requests,
        nonzero_requests=nonzero,
        priority=priority,
        static_mask=static_mask,
        static_sig=static_sig,
        node_affinity_raw=na_raw,
        taint_prefer_raw=tt_raw,
        score_sig=score_sig,
        pod_ports=pod_ports,
        node_ports=node_ports,
        port_conflict=port_conflict,
        port_vocab=port_vocab,
    )
