"""Volume plugin family tensorization: VolumeZone, VolumeBinding (Filter),
VolumeRestrictions (ReadWriteOncePod), NodeVolumeLimits — all as per-pod
``(N,)`` static masks computed once per distinct (namespace, PVC set)
signature and folded into the batch's static mask.

Reference semantics mirrored:

- VolumeZone (plugins/volumezone/volume_zone.go:197 Filter): every bound
  PV's zone/region topology labels must match the node's (beta keys
  translate to GA, :91 translateToGALabel); a node with NO topology labels
  passes everything (:226 single-zone escape); failures are
  UnschedulableAndUnresolvable (:240).
- VolumeBinding Filter (plugins/volumebinding/volume_binding.go:414):
  bound PVC → its PV's spec.nodeAffinity must match the node; unbound PVC
  with an Immediate-mode class → unschedulable everywhere (the PV binder
  owns it); unbound + WaitForFirstConsumer → the node passes iff some
  AVAILABLE PV matches (class, access modes, capacity, node affinity —
  the binder's findMatchingVolumes) or the class can dynamically provision
  (provisioner other than kubernetes.io/no-provisioner).
- VolumeRestrictions (plugins/volumerestrictions/volume_restrictions.go):
  a ReadWriteOncePod PVC already used by another pod rejects the pod
  (PreFilter conflict count > 0).
- NodeVolumeLimits (plugins/nodevolumelimits/csi.go): per CSI driver, the
  count of distinct volumes on the node plus the pod's NEW volumes must
  not exceed the node's ``attachable-volumes-csi-<driver>`` allocatable.

The masks depend on pod spec ONLY through (namespace, pvc names), so they
join the encoder's signature machinery; cluster volume state is read fresh
each encode (the snapshot's lister view).
"""

from __future__ import annotations

import numpy as np

from ..api import types as t
from ..api.selectors import node_selector_term_matches

ATTACHABLE_PREFIX = "attachable-volumes-csi-"

# VolumeZone's topologyLabels (volume_zone.go:83) with beta→GA translation
ZONE_LABELS = {
    "failure-domain.beta.kubernetes.io/zone": "topology.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region": "topology.kubernetes.io/region",
    "topology.kubernetes.io/zone": None,
    "topology.kubernetes.io/region": None,
}


def node_affinity_matches(
    sel: t.NodeSelector | None, labels: dict, node_name: str
) -> bool:
    """VolumeNodeAffinity required terms (ORed), like pod node affinity."""
    if sel is None:
        return True
    return any(
        node_selector_term_matches(term, labels, node_name)
        for term in sel.terms
    )


class VolumeState:
    """Per-encode view over the snapshot's pv/pvc/storageclass listers plus
    per-node usage aggregates (built lazily)."""

    def __init__(self, snapshot) -> None:
        self.snapshot = snapshot
        self.pvs = snapshot.pvs
        self.pvcs = snapshot.pvcs
        self.classes = snapshot.storage_classes
        self._usage = None          # (driver→(N,) counts, pv→node idx set, rwop)
        self._node_labels = None    # cached list[dict] per encode
        self._driver_limits: dict[str, np.ndarray] = {}

    def has_work(self, pods) -> bool:
        return any(v.pvc_name for p in pods for v in p.volumes)

    def _labels(self, nt) -> list[dict]:
        if self._node_labels is None:
            self._node_labels = [info.node.labels_dict() for info in nt.infos]
        return self._node_labels

    # --- usage aggregates -------------------------------------------------
    def _build_usage(self):
        """Once per VolumeState (= per encode): per-driver distinct-volume
        counts per node, each attached PV's node set, and the in-use RWOP
        claims."""
        if self._usage is not None:
            return self._usage
        infos = self.snapshot.node_infos()
        N = len(infos)
        counts: dict[str, np.ndarray] = {}
        pv_nodes: dict[str, set[int]] = {}
        rwop_used: set[str] = set()   # "ns/name" of RWOP PVCs in use
        for n_i, info in enumerate(infos):
            for pod in info.pods.values():
                for vol in pod.volumes:
                    if not vol.pvc_name:
                        continue
                    key = f"{pod.namespace}/{vol.pvc_name}"
                    pvc = self.pvcs.get(key)
                    if pvc is None:
                        continue
                    if t.READ_WRITE_ONCE_POD in pvc.access_modes:
                        rwop_used.add(key)
                    pv = self.pvs.get(pvc.volume_name) if pvc.volume_name else None
                    if pv is not None and pv.driver:
                        nodes = pv_nodes.setdefault(pv.name, set())
                        if n_i not in nodes:
                            nodes.add(n_i)
                            arr = counts.get(pv.driver)
                            if arr is None:
                                arr = np.zeros(N, dtype=np.int32)
                                counts[pv.driver] = arr
                            arr[n_i] += 1
        self._usage = (counts, pv_nodes, rwop_used)
        return self._usage

    def _limit_array(self, driver: str, nt) -> np.ndarray:
        """(N,) declared attach limit per node, -1 = no limit declared."""
        arr = self._driver_limits.get(driver)
        if arr is None:
            key = ATTACHABLE_PREFIX + driver
            arr = np.full(nt.num_nodes, -1, dtype=np.int64)
            for i, info in enumerate(nt.infos):
                v = info.node.allocatable_dict().get(key)
                if v is not None:
                    arr[i] = v
            self._driver_limits[driver] = arr
        return arr

    # --- the per-signature mask ------------------------------------------
    def mask_for(
        self, namespace: str, volumes, nt, enabled: frozenset
    ) -> np.ndarray | None:
        """(N,) bool or None when the pod has no PVC volumes (or none of the
        volume plugins are enabled). ``nt`` is the NodeTensors (node label
        access); ``enabled`` is the profile's Filter plugin-name set."""
        from .. import names as names_

        want_zone = names_.VOLUME_ZONE in enabled
        want_binding = names_.VOLUME_BINDING in enabled
        want_restrictions = names_.VOLUME_RESTRICTIONS in enabled
        want_limits = names_.NODE_VOLUME_LIMITS in enabled
        if not (want_zone or want_binding or want_restrictions or want_limits):
            return None
        pvc_keys = [
            f"{namespace}/{v.pvc_name}" for v in volumes if v.pvc_name
        ]
        if not pvc_keys:
            return None
        N = nt.num_nodes
        mask = np.ones(N, dtype=bool)
        counts, pv_nodes, rwop_used = self._build_usage()

        node_labels = self._labels(nt)
        new_per_driver: dict[str, set[str]] = {}

        for key in pvc_keys:
            pvc = self.pvcs.get(key)
            if pvc is None:
                # waiting for the PVC object (volume_binding.go PreFilter:
                # unbound claim lookup failure → UnschedulableAndUnresolvable)
                return np.zeros(N, dtype=bool)
            if (
                want_restrictions
                and t.READ_WRITE_ONCE_POD in pvc.access_modes
                and key in rwop_used
            ):
                # VolumeRestrictions: RWOP claim already in use
                return np.zeros(N, dtype=bool)
            if pvc.volume_name:
                pv = self.pvs.get(pvc.volume_name)
                if pv is None:
                    return np.zeros(N, dtype=bool)
                mask &= self._bound_pv_mask(
                    pv, node_labels, nt, want_zone, want_binding
                )
                if pv.driver:
                    new_per_driver.setdefault(pv.driver, set()).add(pv.name)
            elif want_binding:
                sc = self.classes.get(pvc.storage_class)
                if sc is None:
                    return np.zeros(N, dtype=bool)
                if sc.binding_mode != t.BINDING_WAIT_FOR_FIRST_CONSUMER:
                    # Immediate: the PV controller binds it off-scheduler;
                    # until then the pod is unschedulable everywhere
                    return np.zeros(N, dtype=bool)
                mask &= self._wffc_mask(pvc, sc, node_labels, nt)

        # NodeVolumeLimits: new distinct volumes per driver vs allocatable,
        # vectorized over nodes (a PV already attached to a node does not
        # count again — the reference counts unique volume handles)
        if want_limits and new_per_driver:
            for driver, new_pvs in new_per_driver.items():
                limit = self._limit_array(driver, nt)
                if (limit < 0).all():
                    continue   # no node declares a limit for this driver
                existing = counts.get(driver)
                total = (
                    existing.astype(np.int64).copy()
                    if existing is not None else np.zeros(N, dtype=np.int64)
                )
                for pv_name in new_pvs:
                    on_node = pv_nodes.get(pv_name)
                    if not on_node:
                        total += 1
                    else:
                        add = np.ones(N, dtype=np.int64)
                        add[list(on_node)] = 0
                        total += add
                mask &= (limit < 0) | (total <= limit)
        return mask

    def _bound_pv_mask(
        self, pv, node_labels, nt, want_zone: bool, want_binding: bool
    ) -> np.ndarray:
        N = nt.num_nodes
        mask = np.ones(N, dtype=bool)
        # VolumeZone
        pv_labels = pv.labels_dict()
        zone_constraints = [
            (k, v) for k, v in pv_labels.items() if k in ZONE_LABELS
        ]
        if want_zone and zone_constraints:
            for i, labels in enumerate(node_labels):
                if not any(k in labels for k in ZONE_LABELS):
                    continue   # unlabeled node: single-zone escape (:226)
                for k, v in zone_constraints:
                    got = labels.get(k)
                    if got is None and ZONE_LABELS[k]:
                        got = labels.get(ZONE_LABELS[k])   # beta → GA
                    if got != v:
                        mask[i] = False
                        break
        # VolumeBinding bound-PV node affinity
        if want_binding and pv.node_affinity is not None:
            for i, labels in enumerate(node_labels):
                if mask[i] and not node_affinity_matches(
                    pv.node_affinity, labels, nt.node_names[i]
                ):
                    mask[i] = False
        return mask

    def available_pvs_for(self, pvc: t.PersistentVolumeClaim) -> list:
        """The binder's findMatchingVolumes candidate set: unbound PVs of
        the claim's class with compatible access modes and enough capacity,
        smallest first (volume/persistentvolume util's smallest-match)."""
        out = []
        for pv in self.pvs.values():
            if pv.claim_ref and pv.claim_ref != pvc.key:
                continue
            if pv.storage_class != pvc.storage_class:
                continue
            if pvc.access_modes and not set(pvc.access_modes) <= set(pv.access_modes):
                continue
            if pv.capacity < pvc.request:
                continue
            out.append(pv)
        out.sort(key=lambda pv: (pv.capacity, pv.name))
        return out

    def _wffc_mask(self, pvc, sc, node_labels, nt) -> np.ndarray:
        N = nt.num_nodes
        candidates = self.available_pvs_for(pvc)
        mask = np.zeros(N, dtype=bool)
        if candidates:
            for i, labels in enumerate(node_labels):
                for pv in candidates:
                    if node_affinity_matches(
                        pv.node_affinity, labels, nt.node_names[i]
                    ):
                        mask[i] = True
                        break
        if not mask.all() and sc.provisioner and sc.provisioner != t.NO_PROVISIONER:
            # dynamic provisioning can satisfy any node (allowed topologies
            # not yet modeled)
            mask[:] = True
        return mask
